"""Network front-end for the file store: multi-host WITHOUT a shared mount.

Reference: ``hyperopt/mongoexp.py`` — MongoTrials speaks a network wire
protocol to mongod (SURVEY.md §2/§5.8), so driver and workers only need TCP
reachability.  The round-1..3 builds covered the shared-mount tier
(``filestore.py`` over NFS/GCS-fuse, blessed by SURVEY §5.8 for this
no-pymongo environment); this module closes the remaining parity gap: a
~300-line HTTP KV front-end that exposes the EXACT claim/heartbeat/requeue
semantics of the file store over localhost/DCN sockets.

Design — serialize, don't re-implement:

* ``StoreServer`` owns a store directory on ITS local disk and executes every
  verb against a real :class:`~.filestore.FileTrials` under one lock.  All of
  the race-safety machinery (exclusive-create claims, owner fencing, stale
  requeue) is the filestore's own code running server-side; the server adds
  only transport.  Single-writer serialization makes the network tier
  trivially linearizable — the same role mongod's document-level atomicity
  plays for the reference.
* ``NetTrials`` is a :class:`~..base.Trials` whose document IO is RPC calls;
  ``fmin`` drives it exactly like ``FileTrials`` (``asynchronous = True``).
* ``NetWorker`` is a :class:`~.filestore.FileWorker` bound to a ``NetTrials``
  — the reserve→evaluate→heartbeat→write loop is inherited unchanged.

Wire format: JSON verbs over HTTP POST (stdlib only — the environment has no
third-party RPC deps).  Transport is pooled keep-alive HTTP/1.1
(:class:`_ConnectionPool`): sockets are reused across verbs instead of
re-dialed per call, with the inherent stale-keep-alive race retried once
transparently.  Trial documents are already JSON (the filestore
persists them as such).  The Domain and attachments travel as base64
cloudpickle, like the reference ships objectives through GridFS — which
means the SAME trust model as the reference: only run a StoreServer for
workers you trust (unpickling is code execution).

Authentication: pass ``token=`` (or ``--token`` / the
``HYPEROPT_TPU_NETSTORE_TOKEN`` environment variable) to both server and
clients and every verb requires the shared secret in the
``X-Netstore-Token`` header, compared constant-time
(``hmac.compare_digest``) BEFORE dispatch — an unauthenticated peer can
neither read documents nor claim/write trials (it gets a 401 and no verb
executes).  Without a token the server remains open, preserving the
localhost-trusted default; set one whenever the socket is reachable
beyond the machines you trust.  The token authenticates the transport —
it does not change the unpickling trust model above.

Reference anchors: ``MongoJobs.reserve`` (find_and_modify ≙ server-side
exclusive claim), ``MongoTrials.refresh`` (cursor fetch ≙ ``docs`` verb),
``hyperopt-mongo-worker`` CLI (≙ ``python -m hyperopt_tpu.parallel.netstore
--worker URL``).
"""

from __future__ import annotations

import base64
import hmac
import io
import json
import logging
import os
import pickle
import random
import socket
import threading
import time
import uuid
import zlib
from collections import OrderedDict
from collections.abc import MutableMapping
from http import client as _http_client
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.error import URLError
from urllib.parse import urlsplit

from .filestore import FileTrials, FileWorker, _pickler
from ..base import JOB_STATE_RUNNING, Trials, docs_from_samples
from ..exceptions import (Backpressure, InjectedFault, NetstoreUnavailable,
                          QuotaExceeded, ShardFenced)
from ..obs import bundle as _obs_bundle
from ..obs import context as _context
from ..obs import costs as _obs_costs
from ..obs import device as _obs_device
from ..obs import export as _obs_export
from ..obs import flight as _flight
from ..obs import health as _obs_health
from ..obs import metrics as _metrics
from ..obs import slo as _obs_slo
from ..obs import timeseries as _obs_ts
from ..obs.events import EVENTS
from .. import faults as _faults
from .. import wire as _wire

logger = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# restricted attachment codec
# ---------------------------------------------------------------------------

#: Globals the attachment unpickler will resolve — stdlib scalar/container
#: constructors plus the numpy ndarray/scalar reconstruction machinery
#: (both the pre-2.x ``numpy.core`` and 2.x ``numpy._core`` module paths).
#: Everything else — os.system reduce payloads, arbitrary class
#: construction — is refused before any object is built.
_SAFE_GLOBALS = frozenset({
    ("builtins", "complex"), ("builtins", "set"), ("builtins", "frozenset"),
    ("builtins", "bytearray"), ("builtins", "range"), ("builtins", "slice"),
    ("numpy", "ndarray"), ("numpy", "dtype"),
    ("numpy.core.multiarray", "_reconstruct"),
    ("numpy.core.multiarray", "scalar"),
    ("numpy.core.numeric", "_frombuffer"),
    ("numpy._core.multiarray", "_reconstruct"),
    ("numpy._core.multiarray", "scalar"),
    ("numpy._core.numeric", "_frombuffer"),
})


class _RestrictedUnpickler(pickle.Unpickler):
    """Allowlist unpickler for wire-crossing attachment blobs.

    ``pickle.loads`` on bytes a network peer controls is arbitrary code
    execution; attachments only need plain data (numbers, strings,
    containers, numpy arrays), so anything outside :data:`_SAFE_GLOBALS`
    is rejected with ``UnpicklingError``.  Scalars, strings, dicts,
    lists and tuples never hit ``find_class`` at all — they decode from
    dedicated opcodes."""

    def find_class(self, module, name):
        if (module, name) in _SAFE_GLOBALS:
            return super().find_class(module, name)
        raise pickle.UnpicklingError(
            f"attachment blob requested forbidden global "
            f"{module}.{name} — only plain data and numpy arrays "
            f"cross this boundary")


def safe_loads(blob: bytes):
    """Decode an attachment blob through the restricted unpickler."""
    return _RestrictedUnpickler(io.BytesIO(blob)).load()


# ---------------------------------------------------------------------------
# auth
# ---------------------------------------------------------------------------


def _resolve_token(token: str | None) -> str | None:
    """Effective shared secret: the explicit argument wins, else the
    ``HYPEROPT_TPU_NETSTORE_TOKEN`` environment variable; empty/unset →
    no auth (open server, localhost-trusted default).  Shared by server
    and clients so one env var secures a whole deployment."""
    if token is None:
        token = os.environ.get("HYPEROPT_TPU_NETSTORE_TOKEN") or None
    return token or None


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------


class _KeepAliveHTTPServer(ThreadingHTTPServer):
    """:class:`ThreadingHTTPServer` that severs live keep-alive
    connections on close.  With HTTP/1.1 reuse, daemon handler threads
    would otherwise keep serving established sockets after the listener
    dies — a closed server must go dark, not half-alive (failover
    promotion and graceful SIGTERM both rely on it)."""

    def __init__(self, *args, **kwargs):
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        super().__init__(*args, **kwargs)

    def process_request(self, request, client_address):
        with self._conns_lock:
            self._conns.add(request)
        super().process_request(request, client_address)

    def shutdown_request(self, request):
        with self._conns_lock:
            self._conns.discard(request)
        super().shutdown_request(request)

    def server_close(self):
        super().server_close()
        with self._conns_lock:
            conns = list(self._conns)
            self._conns.clear()
        for sock in conns:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass


class _LeanHeaders:
    """Just-enough stand-in for ``email.message.Message`` on the
    server's request hot path: the verb handlers only ever ``.get`` a
    handful of plain headers."""

    __slots__ = ("_d",)

    def __init__(self, d: dict):
        self._d = d

    def get(self, name, default=None):
        return self._d.get(name.lower(), default)

    def __contains__(self, name):
        return name.lower() in self._d


class _LeanRequestHandler(BaseHTTPRequestHandler):
    """``BaseHTTPRequestHandler`` with a fast request-parse path.

    The stock ``parse_request`` routes every request's header block
    through ``email.parser`` — ~100 µs per verb, comparable to a whole
    cached-read dispatch.  Verb traffic is uniform ("POST /path
    HTTP/1.1" plus a few plain headers), so the common case is parsed
    with a handful of ``partition`` calls; anything unusual (HTTP/1.0,
    other versions, oversized lines) falls back to the stock parser
    for strictness."""

    def handle_one_request(self):
        try:
            self.raw_requestline = self.rfile.readline(65537)
            if len(self.raw_requestline) > 65536:
                self.requestline = ""
                self.request_version = ""
                self.command = ""
                self.send_error(414)
                return
            if not self.raw_requestline:
                self.close_connection = True
                return
            words = self.raw_requestline.split()
            if len(words) == 3 and words[2] == b"HTTP/1.1":
                self.command = words[0].decode("latin-1")
                self.path = words[1].decode("latin-1")
                self.request_version = "HTTP/1.1"
                self.requestline = self.raw_requestline.decode(
                    "latin-1").rstrip("\r\n")
                hdrs: dict = {}
                while True:
                    line = self.rfile.readline(65537)
                    if line in (b"\r\n", b"\n", b""):
                        break
                    if len(line) >= 65536 or len(hdrs) >= 100:
                        self.send_error(431)
                        return
                    key, sep, val = line.partition(b":")
                    if not sep or key != key.strip():
                        # Folded (obs-fold) or malformed header — no
                        # client of ours emits these, and the lines are
                        # already consumed, so reject rather than guess.
                        self.send_error(400, "Bad header line")
                        return
                    hdrs[key.lower().decode("latin-1")] = (
                        val.strip().decode("latin-1"))
                self.headers = _LeanHeaders(hdrs)
                self.close_connection = (
                    hdrs.get("connection", "").lower() == "close")
            elif not self.parse_request():
                return
            mname = "do_" + self.command
            if not hasattr(self, mname):
                self.send_error(
                    501, "Unsupported method (%r)" % self.command)
                return
            getattr(self, mname)()
            self.wfile.flush()
        except TimeoutError as e:
            self.log_error("Request timed out: %r", e)
            self.close_connection = True


class _ClaimGate:
    """Wake-up channel for long-poll ``reserve``: one condition variable
    plus a generation counter per ``(tenant, exp_key)``.  A reserver
    snapshots the generation, attempts the claim, and parks only if the
    generation is unchanged — :meth:`signal`'s bump-then-notify makes a
    wakeup that lands between attempt and park impossible to lose."""

    __slots__ = ("_cv", "_gen")

    def __init__(self):
        self._cv = threading.Condition()
        self._gen = 0

    def snapshot(self) -> int:
        with self._cv:
            return self._gen

    def wait(self, gen0: int, timeout: float) -> bool:
        """Park until a signal newer than ``gen0`` (or ``timeout``);
        True iff (possibly) signaled."""
        with self._cv:
            if self._gen != gen0:
                return True
            return self._cv.wait(timeout)

    def signal(self) -> None:
        with self._cv:
            self._gen += 1
            self._cv.notify_all()


def _is_plain_json(x) -> bool:
    """True iff ``x`` is already canonical plain-JSON data: exactly the
    builtin container/scalar types (subclasses like ``np.float64`` fail
    the ``type`` check and force the normalizing roundtrip)."""
    t = type(x)
    if t is dict:
        return all(type(k) is str and _is_plain_json(v)
                   for k, v in x.items())
    if t is list:
        return all(_is_plain_json(v) for v in x)
    return t in (str, int, float, bool) or x is None


def _canon_docs(docs: list) -> list:
    """Canonical plain-JSON form of proposal docs.

    The suggest hot path used to pay ``json.loads(json.dumps(docs))``
    on EVERY call — a third full JSON pass per suggest on top of the
    WAL record's and the reply's own encodes — although
    ``docs_from_samples`` already emits plain ``int``/``float``/``str``
    containers.  Skip the roundtrip when the tree is verifiably
    canonical; fall back to it when an algorithm hands back numpy
    scalars or tuples, so stored state stays byte-identical to what a
    WAL replay would re-insert."""
    if _is_plain_json(docs):
        return docs
    return json.loads(json.dumps(docs))


class StoreServer:
    """Serve a local store directory to remote drivers/workers.

    ``serve_forever`` blocks; ``start()`` runs in a daemon thread and
    returns the bound ``(host, port)`` — tests and same-process drivers use
    that.  One lock serializes all verbs: correctness needs no concurrency
    here (each verb is micro-seconds of local file IO; the objective
    evaluations — the actual work — happen client-side in the workers).
    """

    #: Bounds on the idempotency dedup cache (completed mutating calls
    #: kept for replay): LRU capacity + TTL, both env-tunable.  Retries
    #: arrive within seconds of the original, so thousands of entries /
    #: minutes of TTL are generations of headroom — the bound exists so
    #: a long-running fleet's cache cannot grow without limit.
    _IDEM_CAP = 4096
    _IDEM_TTL_S = 900.0

    #: Server-side ceiling on one long-poll ``reserve`` park (seconds);
    #: clients asking for more are clamped — a parked claim must not
    #: outlive intermediary idle timeouts by much.
    _LONGPOLL_CAP_S = 30.0

    #: Verbs read-only by construction: no WAL append, no write lock —
    #: served by ``_dispatch_read`` so a poll-heavy fleet never queues
    #: behind a mutating verb's fsync.  The wire-protocol analyzer's
    #: WP007 pins this catalog against the computed mutation ground
    #: truth of the dispatcher arms, so drift is impossible silently.
    _READONLY_VERBS = frozenset({
        "metrics", "health", "bundle", "docs", "fetch_since",
        "get_domain", "att_get", "att_keys", "stores", "store_export"})

    #: Verbs whose success may make a claim (or a claims-quota slot)
    #: available: each wakes the exp_key's parked long-poll reserves.
    #: ``store_fence`` wakes them for the opposite reason — a parked
    #: claimant on a store that just fenced for migration must surface
    #: the typed redirect NOW, not doze out its wait budget.
    _LONGPOLL_WAKE = frozenset({
        "insert_docs", "suggest", "requeue_stale", "write_result",
        "store_fence"})

    #: Verbs that ADMIT new work into the system (docs inserted, ids
    #: allocated, proposals computed).  These — and only these — are
    #: refused with a typed retriable :class:`Backpressure` while a
    #: shed directive is active: producers are throttled, while
    #: consumers (reserve / write_result / heartbeat) keep running so
    #: the backlog drains instead of wedging.
    _ADMISSION_VERBS = frozenset({"insert_docs", "new_trial_ids",
                                  "suggest"})

    def __init__(self, root: str, host: str = "127.0.0.1", port: int = 0,
                 token: str | None = None,
                 requeue_stale_every: float | None = None,
                 stale_timeout: float = 60.0,
                 tenants=None,
                 scrape_interval: float | None = None,
                 slos=None):
        self.root = os.path.abspath(root)
        self._trials: dict = {}          # (tenant_name, exp_key) -> store
        self._lock = threading.RLock()
        self._token = _resolve_token(token)
        # Multi-tenant mode: a service.tenancy.TenantTable (anything with
        # .resolve(token) -> tenant).  When set, every verb authenticates
        # as SOME tenant and the dispatch layer namespaces exp_keys into
        # the tenant's own store subtree — the store key derives from the
        # authenticated identity, never from the request body.
        self._tenants = tenants
        # Exactly-once under client retry: (tenant, exp_key, idem_key) ->
        # (t_monotonic, JSON reply) of the first execution.  Stored
        # serialized so a replay can never alias live server-side state;
        # LRU + TTL bounded (netstore.idem.evicted counts expulsions).
        self._idem: OrderedDict = OrderedDict()
        self._idem_lock = threading.Lock()
        # Keys whose first execution is still running: concurrent
        # duplicates park on the Event instead of running the verb again
        # (the check-then-act hole between cache probe and publish).
        self._idem_inflight: dict = {}
        self._idem_cap = int(os.environ.get(
            "HYPEROPT_TPU_NETSTORE_IDEM_CAP", "") or self._IDEM_CAP)
        self._idem_ttl = float(os.environ.get(
            "HYPEROPT_TPU_NETSTORE_IDEM_TTL", "") or self._IDEM_TTL_S)
        # Fleet metrics: worker_id -> {"t": last push wall time, "metrics":
        # the worker's cumulative registry snapshot}.  Workers piggyback
        # snapshots on heartbeats (NetTrials.heartbeat); last-write-wins
        # per worker, merged on read by metrics_payload().  Deliberately
        # NOT part of the local registry, so registry().snapshot(
        # reset=True) by a bench/test never drops the per-worker labels.
        self._fleet: dict = {}
        self._fleet_lock = threading.Lock()
        # Janitor: requeue crashed-worker claims every S seconds so the
        # recovery path runs unprompted (``--requeue-stale-every``).
        self.requeue_stale_every = requeue_stale_every
        self.stale_timeout = stale_timeout
        self._janitor: threading.Thread | None = None
        self._janitor_stop = threading.Event()
        # Observability interpretation layer (obs/): every server owns a
        # time-series store + SLO monitor; the periodic scrape loop that
        # feeds them only runs when ``scrape_interval`` is set (the
        # disabled path costs nothing — no hot-path hooks exist).
        self.scrape_interval = scrape_interval
        self.timeseries = _obs_ts.TimeSeriesStore()
        self.slo_monitor = _obs_slo.SloMonitor(
            slos if slos is not None else _obs_slo.default_slos(),
            self.timeseries)
        self._health_cache: dict | None = None
        self._scraper: threading.Thread | None = None
        self._scraper_stop = threading.Event()
        # Bounded per-tenant label set (LRU): tenant churn would
        # otherwise grow the netstore.tenant.<name>.* families forever.
        self._tenant_labels = _metrics.LabelLru()
        # Read-path concurrency (A/B knob): when on — the default —
        # verbs in _READONLY_VERBS bypass the write lock entirely and
        # rely on each store's own internal lock.
        self._read_dispatch = os.environ.get(
            "HYPEROPT_TPU_READ_DISPATCH", "1").lower() not in (
                "0", "off", "false")
        # Long-poll claim gates: (tenant, exp_key) -> _ClaimGate.  Grows
        # with the store table (same key space), never shrinks.
        self._claim_gates: dict = {}
        self._claim_gates_lock = threading.Lock()
        # Load-shed directive (autoscaler-driven graceful degradation):
        # {"level": 0..1, "retry_after_s": float, "until": monotonic
        # deadline} or None.  Ephemeral BY DESIGN — never WAL-logged,
        # never in snapshots: a restarted shard comes back accepting
        # traffic and the autoscaler re-sheds if the overload persists.
        self._shed: dict | None = None
        self._shed_rng = random.Random(0x5EED)
        # Flight-bundle sections owned by this server: the time-series
        # window, SLO alert states and cached health verdicts travel in
        # every postmortem dump while the server lives.
        _obs_bundle.register_provider("series", self.timeseries.export_series)
        _obs_bundle.register_provider("slo", self.slo_monitor.status)
        _obs_bundle.register_provider(
            "health", lambda: self._health_cache or {})
        self._started = False
        self._closed = False
        self._lifecycle_lock = threading.Lock()
        server = self

        class Handler(_LeanRequestHandler):
            # HTTP/1.1 so the client pool's sockets stay open between
            # verbs (the 1.0 default would close after every reply);
            # every response path sets Content-Length, which keep-alive
            # requires.
            protocol_version = "HTTP/1.1"
            # Nagle off: on a persistent connection a small reply would
            # otherwise sit in the kernel waiting for the client's
            # delayed ACK (~40 ms per verb — the classic small-write
            # stall; one-shot urlopen never saw it because close()
            # flushed).
            disable_nagle_algorithm = True

            def log_message(self, fmt, *args):   # quiet by default
                logger.debug("netstore: " + fmt, *args)

            def _send(self, code, body: bytes, ctype: str):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _send_json(self, code, body: bytes):
                self._send(code, body, "application/json")

            def _reject(self):
                _metrics.registry().counter("netstore.auth.rejected").inc()
                self.rfile.read(
                    int(self.headers.get("Content-Length", "0")))
                self._send_json(401, json.dumps(
                    {"error": "AuthError: missing or bad "
                     "X-Netstore-Token"}).encode())

            def _authed(self) -> bool:
                # Auth gate BEFORE the body is parsed or any verb runs:
                # constant-time compare so the secret can't be recovered
                # byte-by-byte from response timing.  The request body is
                # still drained (keep-alive correctness) but never
                # dispatched.  Multi-tenant mode resolves the token to a
                # Tenant (itself a full-table constant-time scan); the
                # tenant identity then namespaces every verb of this
                # request — it comes from the header, never the body.
                self._tenant = None
                if server._tenants is not None:
                    got = self.headers.get("X-Netstore-Token", "")
                    tenant = server._tenants.resolve(got)
                    if tenant is None:
                        self._reject()
                        return False
                    self._tenant = tenant
                    return True
                if server._token is None:
                    return True
                got = self.headers.get("X-Netstore-Token", "")
                if hmac.compare_digest(got.encode(),
                                       server._token.encode()):
                    return True
                self._reject()
                return False

            def do_POST(self):
                if not self._authed():
                    return
                framed = False
                try:
                    n = int(self.headers.get("Content-Length", "0"))
                    raw = self.rfile.read(n) or b"{}"
                    # Content negotiation by magic sniff (not by header):
                    # the shard router forwards opaque bodies with its
                    # own Content-Type, so the bytes themselves are the
                    # only trustworthy signal.  The reply is framed iff
                    # the request was — JSON peers never see a frame.
                    if _wire.is_frame(raw):
                        if _wire.mode() == "json":
                            raise _wire.WireError(
                                "binary frame refused "
                                "(HYPEROPT_TPU_WIRE=json)")
                        framed = True
                        reg = _metrics.registry()
                        reg.counter("wire.frames").inc()
                        reg.counter("wire.bytes_rx").inc(len(raw))
                        req = _wire.decode(bytes(raw))
                    else:
                        req = json.loads(raw)
                    out = server._dispatch(req, tenant=self._tenant)
                    if framed:
                        body = _wire.encode(out)
                        _metrics.registry().counter(
                            "wire.bytes_tx").inc(len(body))
                        self._send(200, body, _wire.CONTENT_TYPE)
                        return
                    body = json.dumps(out).encode()
                    code = 200
                except Backpressure as e:
                    # Deliberate load shed, not a server fault: a typed
                    # retriable refusal with the server's own price
                    # attached.  503, never 500 — well-behaved clients
                    # sleep retry_after_s and try again without burning
                    # transport retry budget.
                    body = json.dumps(
                        {"error": f"Backpressure: {e}",
                         "retry_after_s": e.retry_after_s}).encode()
                    code = 503
                except ShardFenced as e:
                    # Typed retriable redirect: the store/shard is mid-
                    # cutover; the client should refresh its shard map
                    # and re-place itself, not retry here.
                    body = json.dumps(
                        {"error": f"ShardFenced: {e}"}).encode()
                    code = 409
                except Exception as e:  # surface server faults to the client
                    body = json.dumps(
                        {"error": f"{type(e).__name__}: {e}"}).encode()
                    code = 500
                self._send_json(code, body)

            def do_GET(self):
                # Read-only metrics surface, token-gated like every verb:
                # ``GET /metrics`` returns the process-global registry
                # snapshot (counters/gauges/histograms/kernel_cache) plus
                # the ``fleet`` view (per-worker labeled snapshots pushed
                # on heartbeats + exactly-merged histograms) so an
                # operator can curl the server a driver and workers feed.
                if not self._authed():
                    return
                if self.path.split("?", 1)[0] == "/metrics":
                    payload = server.metrics_payload()
                    # Content negotiation: a standard Prometheus/
                    # OpenMetrics scraper announces itself via Accept
                    # and gets the wire-correct text exposition
                    # (local + fleet-merged series); everything else
                    # keeps the historical JSON document.
                    if _obs_export.wants_openmetrics(
                            self.headers.get("Accept", "")):
                        body = _obs_export.render_openmetrics(
                            payload).encode("utf-8")
                        self._send(200, body, _obs_export.CONTENT_TYPE)
                        return
                    body = json.dumps(payload).encode()
                    self._send_json(200, body)
                    return
                self._send_json(404, json.dumps(
                    {"error": f"NotFound: {self.path}"}).encode())

        self._httpd = _KeepAliveHTTPServer((host, port), Handler)
        self.host, self.port = self._httpd.server_address[:2]

    # -- lifecycle -----------------------------------------------------------

    def start(self):
        self._started = True
        self._start_janitor()
        self._start_scraper()
        t = threading.Thread(target=self._httpd.serve_forever, daemon=True,
                             name="netstore-server")
        t.start()
        return self.host, self.port

    def serve_forever(self):
        self._started = True
        self._start_janitor()
        self._start_scraper()
        self._httpd.serve_forever()

    def shutdown(self):
        """Stop serving and release the socket.

        Idempotent, and safe when ``start()``/``serve_forever()`` never
        ran (``ThreadingHTTPServer.shutdown`` would otherwise block
        forever waiting on a serve loop that does not exist).
        """
        with self._lifecycle_lock:
            if self._closed:
                return
            self._closed = True
        for section in ("series", "slo", "health"):
            _obs_bundle.unregister_provider(section)
        self._janitor_stop.set()
        self._scraper_stop.set()
        if self._janitor is not None:
            self._janitor.join(timeout=5.0)
        if self._scraper is not None:
            self._scraper.join(timeout=5.0)
        if self._started:
            self._httpd.shutdown()
        self._httpd.server_close()

    def _start_janitor(self):
        if not self.requeue_stale_every or self._janitor is not None:
            return
        self._janitor = threading.Thread(target=self._janitor_loop,
                                         daemon=True,
                                         name="netstore-janitor")
        self._janitor.start()

    def _start_scraper(self):
        if not self.scrape_interval or self._scraper is not None:
            return
        self._scraper = threading.Thread(target=self._scraper_loop,
                                         daemon=True,
                                         name="netstore-scraper")
        self._scraper.start()

    def _scraper_loop(self):
        while not self._scraper_stop.wait(self.scrape_interval):
            try:
                self.observe_pass()
            except Exception:    # scraper must outlive any bad series
                logger.exception("netstore scraper: observe pass failed")

    def observe_pass(self, now: float | None = None) -> list:
        """One interpretation tick (the scrape loop's body, callable
        directly by tests and benches): publish device-runtime and
        fleet-liveness gauges, scrape the registry into the time-series
        store, evaluate the SLO monitor, and refresh the cheap
        (history-only) health verdicts the live dashboard shows.
        Returns the SLO status list."""
        _obs_device.collect()
        self._fleet_liveness_gauge()
        self.timeseries.scrape(now=now)
        status = self.slo_monitor.evaluate(now=now)
        try:
            self._health_cache = self._assess_health(introspect=False)
        except Exception:
            logger.exception("netstore scraper: health pass failed")
        return status

    def _fleet_liveness_gauge(self) -> float:
        """Fraction of pushed workers whose last heartbeat is fresh
        (< 30 s, the dashboard's own STALE rule); 1.0 with no fleet.
        Feeds the ``worker_liveness`` SLO via the time-series store."""
        now = time.time()
        with self._fleet_lock:
            ages = [now - rec.get("t", now)
                    for rec in self._fleet.values()]
        live = sum(1 for a in ages if a < 30.0)
        frac = (live / len(ages)) if ages else 1.0
        _metrics.registry().gauge("fleet.live_fraction").set(frac)
        return frac

    def _janitor_loop(self):
        # wait() (not sleep) so shutdown() interrupts a long period
        # immediately; first pass only after one full period.
        while not self._janitor_stop.wait(self.requeue_stale_every):
            try:
                self._janitor_pass()
            except Exception:       # janitor must outlive any bad store
                logger.exception("netstore janitor: requeue_stale failed")

    def _janitor_pass(self):
        # Overridable: the WAL-backed ServiceServer routes these requeues
        # through its log so replay reproduces the janitor's decisions.
        with self._lock:
            stores = list(self._trials.items())
        for (tname, exp_key), ft in stores:
            with self._lock:
                n = ft.requeue_stale(self.stale_timeout)
            if n:
                logger.info("netstore janitor: requeued %d stale "
                            "trial(s) in %r", n, ft._exp_key)
                # Requeued claims are claimable again: wake this
                # store's parked long-poll reserves.
                self._signal_claims(tname, exp_key)

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    # -- verbs ---------------------------------------------------------------

    def _store(self, exp_key: str, tenant=None) -> FileTrials:
        """Caller holds ``self._lock`` (every site: the verb dispatcher
        and the cohort gate's snapshot section take the RLock first).

        Tenant namespacing happens HERE and only here: the store key
        pairs the authenticated tenant name with the client's exp_key,
        and each tenant's files live under their own subtree.  The
        exp_key inside the documents stays the client's own (the doc
        filter ``_exp_key in (None, d["exp_key"])`` must keep matching).
        """
        tname = getattr(tenant, "name", tenant)
        key = (tname, exp_key)
        ft = self._trials.get(key)
        if ft is None:
            root = os.path.join(self.root, tname) if tname else self.root
            ft = self._trials[key] = FileTrials(root, exp_key=exp_key)
        return ft

    def _idem_put(self, key, payload: str):
        evicted = 0
        with self._idem_lock:
            self._idem[key] = (time.monotonic(), payload)
            self._idem.move_to_end(key)
            # Expire from the cold end: TTL first, then LRU overflow.
            now = time.monotonic()
            while self._idem:
                k, (t, _) = next(iter(self._idem.items()))
                if now - t > self._idem_ttl or len(self._idem) > self._idem_cap:
                    self._idem.popitem(last=False)
                    evicted += 1
                else:
                    break
        if evicted:
            _metrics.registry().counter("netstore.idem.evicted").inc(evicted)

    def _idem_execute(self, key, run):
        """At-most-once execution of ``run()`` for idempotency ``key``.

        Returns ``(reply_dict, replayed)``.  The cache probe and the
        in-flight claim are one atomic step under ``_idem_lock``, so two
        concurrent retries of the same key cannot both miss and run the
        verb twice: the loser parks on the winner's Event and re-reads
        the cache once the winner publishes.  If the winner's verb
        raises, nothing is published and the waiter claims the key
        itself — ordinary retry semantics.
        """
        while True:
            with self._idem_lock:
                hit = self._idem.get(key)
                if hit is not None:
                    t, payload = hit
                    if time.monotonic() - t <= self._idem_ttl:
                        self._idem.move_to_end(key)      # LRU touch
                        return json.loads(payload), True
                    del self._idem[key]
                    _metrics.registry().counter("netstore.idem.evicted").inc()
                ev = self._idem_inflight.get(key)
                if ev is None:
                    ev = self._idem_inflight[key] = threading.Event()
                    break
            # A duplicate of an in-flight call: wait for its publish,
            # then loop — cache hit replays it, a failure re-claims.
            ev.wait()
        try:
            out = run()
            self._idem_put(key, json.dumps(out))
            return out, False
        finally:
            with self._idem_lock:
                self._idem_inflight.pop(key, None)
            ev.set()

    def _dispatch(self, req: dict, tenant=None) -> dict:
        verb = req["verb"]
        reg = _metrics.registry()
        t0 = time.perf_counter()
        # Trace context stamped by the client (obs/context.py wire form):
        # adopt it for the duration of the verb so every event this
        # dispatch emits — store_claim/store_write from the filestore,
        # fault injections, the rpc instant below — attaches to the
        # originating trial and trace.
        ctx = req.pop("ctx", None)
        tname = getattr(tenant, "name", None)
        try:
            with _context.adopt(ctx):
                EVENTS.emit("rpc", name=verb)
                if verb in self._ADMISSION_VERBS:
                    # Shed gate BEFORE idempotency / WAL / cohort
                    # machinery: a refused admission must leave no
                    # durable trace and cache no reply.
                    self._shed_gate(verb)
                idem = req.pop("idem", None)
                wait_s = req.pop("wait_s", None)
                if verb == "reserve" and wait_s:
                    # Long-poll claim: the park/retry loop runs INSIDE
                    # the idempotent execution below, so only the final
                    # answer is cached for client retries.
                    def run():
                        return self._reserve_longpoll(
                            req, tenant=tenant, wait_s=float(wait_s),
                            idem=idem)
                else:
                    def run():
                        return self._dispatch_verb(verb, req,
                                                   tenant=tenant,
                                                   idem=idem)
                if idem is None:
                    out = run()
                else:
                    # Mutating verb with an idempotency key: a retry of
                    # a call the server already executed must return the
                    # original reply, not run the verb twice (the client
                    # retries blind — it cannot know whether the loss
                    # was on the way in or out).
                    key = (tname, req.get("exp_key", "default"), idem)
                    out, replayed = self._idem_execute(key, run)
                    if replayed:
                        reg.counter("netstore.idem.hits").inc()
                if verb in self._LONGPOLL_WAKE:
                    # Outside every lock: this verb may have made a
                    # claim (or a quota slot) available — wake parked
                    # long-poll reserves for the store.
                    self._signal_claims(tname,
                                        req.get("exp_key", "default"),
                                        verb=verb, out=out)
                return out
        except Exception as e:
            # Black-box the failing dispatch before the error surfaces
            # to the client (one boolean when the recorder is disarmed).
            # Typed control-plane refusals (shed, fence) are deliberate
            # steady-state answers under overload/cutover, not crashes —
            # a backpressure storm must not spam flight bundles.
            if not isinstance(e, (Backpressure, ShardFenced)):
                _flight.on_crash("dispatch", e)
            raise
        finally:
            # Per-verb call count + latency histogram: the contention
            # signal for the single-writer lock under many workers.
            reg.counter(f"netstore.verb.{verb}.calls").inc()
            reg.histogram(f"netstore.verb.{verb}.s").observe(
                time.perf_counter() - t0)
            if tname is not None:
                # Per-tenant labels for `show live` and quota forensics.
                # The live label set is LRU-bounded: an evicted tenant's
                # whole series family is dropped (recreated from zero on
                # its next call) and obs.series_evicted counts it.
                for old in self._tenant_labels.touch(tname):
                    reg.remove_prefix(f"netstore.tenant.{old}.")
                reg.counter(
                    f"netstore.tenant.{tname}.verb.{verb}.calls").inc()
                reg.histogram(
                    f"netstore.tenant.{tname}.verb.{verb}.s").observe(
                    time.perf_counter() - t0)

    def metrics_payload(self) -> dict:
        """The ``GET /metrics`` document: local snapshot + fleet view.

        Top level keeps the historical registry-snapshot schema
        (enabled/counters/gauges/kernel_cache/histograms — now with
        mergeable ``state`` per histogram, including the server-side
        per-verb latency histograms ``netstore.verb.<verb>.s`` with
        p50/p95/p99) and adds ``fleet``:

        * ``workers`` — per-worker labels: each worker's last pushed
          cumulative snapshot plus ``age_s`` staleness (a worker whose
          age greatly exceeds its heartbeat interval is presumed dead),
        * ``merged`` — counters/gauges summed and histograms
          exactly merged (``obs.metrics.merge_snapshots``) across the
          server's own registry and every pushed worker snapshot.
        """
        snap = _metrics.registry().snapshot(states=True)
        now = time.time()
        with self._fleet_lock:
            fleet = {w: dict(rec) for w, rec in self._fleet.items()}
        workers = {}
        members = [snap]
        for w in sorted(fleet):
            rec = fleet[w]
            m = rec.get("metrics") or {}
            workers[w] = {
                "age_s": round(now - rec.get("t", now), 3),
                "counters": m.get("counters") or {},
                "gauges": m.get("gauges") or {},
                "histograms": m.get("histograms") or {},
            }
            members.append(m)
        snap["fleet"] = {
            "n_workers": len(workers),
            "workers": workers,
            "merged": _metrics.merge_snapshots(members),
        }
        # Interpretation layer: last computed health verdicts (scraper
        # pass or health verb) and current SLO alert state, so `show
        # live` can render HEALTH/ALERTS panels from this one payload.
        if self._health_cache is not None:
            snap["health"] = self._health_cache
        status = self.slo_monitor.status()
        if status:
            snap["alerts"] = status
        # Cost-attribution ledger (armed via HYPEROPT_TPU_COSTS): the
        # service-mode server compiles suggest kernels in-process, so
        # its ledger rows feed the `cost:` panel of `show live`.
        costs = _obs_costs.ledger_report(reg=_metrics.registry())
        if costs.get("entries") or costs.get("armed"):
            snap["costs"] = costs
        return snap

    # -- optimizer health ----------------------------------------------------

    def _assess_health(self, tenant_name=..., exp_key=None,
                       introspect=True) -> dict:
        """Health reports keyed ``"tenant/exp_key"`` (bare ``exp_key``
        in single-tenant mode).  ``tenant_name=...`` means every
        tenant (the scraper's view); a concrete name (or None in
        single-tenant mode) restricts to that namespace.  Store state
        is snapshotted under the server lock; the assessments — which
        may run a backend introspection fit — happen OUTSIDE it, so a
        health probe never stalls serving verbs."""
        items = []
        with self._lock:
            for (tn, ek), ft in list(self._trials.items()):
                if tenant_name is not ... and tn != tenant_name:
                    continue
                if exp_key is not None and ek != exp_key:
                    continue
                export = getattr(ft, "export_docs", None)
                if export is not None:
                    docs = export()
                else:
                    ft.refresh()
                    docs = list(ft._dynamic_trials)
                items.append((tn, ek, ft, docs,
                              getattr(ft, "_srv_last_algo", None)))
        reports = {}
        for tn, ek, ft, docs, algo_name in items:
            label = f"{tn}/{ek}" if tn else ek
            domain = suggest_fn = None
            if introspect and algo_name:
                suggest_fn = self._server_algos().get(algo_name)
                try:
                    domain = self._domain_for(ft)
                except Exception:
                    logger.debug("health: domain introspection failed "
                                 "for %s; assessing without it",
                                 ek, exc_info=True)
                    domain = None
            rep = _obs_health.assess(
                docs, domain=domain, trials=ft, suggest_fn=suggest_fn,
                introspect=introspect)
            rep["algo"] = algo_name
            _obs_health.publish(label, rep)
            reports[label] = rep
        return reports

    def _health_verb(self, req: dict, tenant=None) -> dict:
        """The read-only ``health`` verb body: fresh assessments
        (introspection included unless ``introspect: false``) for the
        caller's namespace — all of the tenant's experiments with
        ``all: true``, else just the request's ``exp_key``."""
        tname = getattr(tenant, "name", tenant)
        exp_key = None if req.get("all") else req.get("exp_key", "default")
        reports = self._assess_health(
            tenant_name=tname, exp_key=exp_key,
            introspect=bool(req.get("introspect", True)))
        self._health_cache = dict(self._health_cache or {}, **reports)
        return reports

    # -- tenant quotas -------------------------------------------------------

    def _charge_admission(self, tenant, n: int) -> None:
        """Charge ``n`` trial creations against the tenant's rate quota
        (token bucket); raises :class:`QuotaExceeded` on refusal.  Runs
        BEFORE any WAL append or execution — a refused call leaves no
        trace in durable state."""
        admit = getattr(tenant, "admit_trials", None)
        if admit is None or admit(int(n)):
            return
        tname = getattr(tenant, "name", "?")
        _metrics.registry().counter(
            f"netstore.tenant.{tname}.quota.rate_rejected").inc()
        raise QuotaExceeded(
            f"tenant {tname!r}: trials/s admission quota exceeded "
            f"(rate={getattr(tenant, 'trials_per_s', None)}, asked {n})")

    def _claims_quota_hit(self, tenant) -> bool:
        """True when the tenant already holds ``max_claims`` RUNNING
        trials across all of its experiments (reserve must answer
        queue-empty so stock workers back off via their poll loop)."""
        limit = getattr(tenant, "max_claims", None)
        if limit is None:
            return False
        tname = getattr(tenant, "name", None)
        held = 0
        for (tn, _), ft in self._trials.items():
            if tn != tname:
                continue
            ft.refresh()
            held += sum(1 for d in ft._dynamic_trials
                        if d["state"] == JOB_STATE_RUNNING)
        reg = _metrics.registry()
        reg.gauge(f"netstore.tenant.{tname}.claims_held").set(held)
        if held >= limit:
            reg.counter(
                f"netstore.tenant.{tname}.quota.claims_rejected").inc()
            return True
        return False

    def _shed_gate(self, verb: str) -> None:
        """Refuse an admission verb while a shed directive is active.

        Probabilistic by ``level`` (1.0 sheds everything) so partial
        degradation is possible; the directive self-expires at its
        monotonic deadline — a dead autoscaler can throttle the fleet
        for at most one TTL."""
        shed = self._shed
        if not shed:
            return
        if time.monotonic() >= shed["until"]:
            self._shed = None
            return
        level = float(shed["level"])
        if level >= 1.0 or self._shed_rng.random() < level:
            _metrics.registry().counter("backpressure.shed").inc()
            raise Backpressure(
                f"admission shed active (level={level:.2f}): {verb} "
                "refused, retry later",
                retry_after_s=float(shed["retry_after_s"]))

    def _dispatch_verb(self, verb: str, req: dict, tenant=None,
                       idem=None) -> dict:
        if verb in self._READONLY_VERBS:
            return self._dispatch_read(verb, req, tenant=tenant)
        if verb == "shed":
            # Admission-control directive (autoscaler / operator):
            # level<=0 lifts the shed, anything else arms it for ttl_s.
            level = float(req.get("level", 1.0))
            ttl = float(req.get("ttl_s", 30.0))
            if level <= 0.0:
                self._shed = None
            else:
                self._shed = {"level": min(level, 1.0),
                              "retry_after_s": float(
                                  req.get("retry_after_s", 1.0)),
                              "until": time.monotonic() + ttl}
            _metrics.registry().gauge("backpressure.shed_level").set(
                max(0.0, min(level, 1.0)))
            return {"ok": True, "level": max(0.0, min(level, 1.0)),
                    "ttl_s": ttl}
        with self._lock:
            ft = self._store(req.get("exp_key", "default"), tenant=tenant)
            if getattr(ft, "fenced", False) and verb not in (
                    "store_fence", "store_import"):
                _metrics.registry().counter("store.fenced").inc()
                raise ShardFenced(
                    f"store {req.get('exp_key', 'default')!r} is fenced "
                    f"(migrating): refusing {verb!r}")
            if verb == "store_fence":
                ft.fence(drop=bool(req.get("drop")),
                         lift=bool(req.get("lift")))
                return {"ok": True, "dropped": bool(req.get("drop")),
                        "lifted": bool(req.get("lift"))}
            if verb == "store_import":
                state = dict(req["state"])
                state["fenced"] = False
                ft.load_state(state)
                return {"ok": True, "docs": len(state.get("docs", []))}
            if verb == "insert_docs":
                self._charge_admission(tenant, len(req["docs"]))
                return {"tids": ft._insert_trial_docs(req["docs"])}
            if verb == "new_trial_ids":
                ft.refresh()
                return {"tids": ft.new_trial_ids(int(req["n"]))}
            if verb == "reserve":
                if self._claims_quota_hit(tenant):
                    return {"doc": None, "quota": "max_claims"}
                return {"doc": ft.reserve(req["owner"])}
            if verb == "suggest":
                return self._suggest_verb(ft, req, tenant)
            if verb == "heartbeat":
                # Piggybacked fleet metrics: a worker may attach its
                # cumulative registry snapshot (last-write-wins per
                # worker id; merged on read by metrics_payload).  The
                # reply carries the server wall clock so clients can
                # estimate their skew for trace stitching.
                w = req.get("worker")
                if w is not None and req.get("metrics") is not None:
                    with self._fleet_lock:
                        self._fleet[w] = {"t": time.time(),
                                          "metrics": req["metrics"]}
                    _metrics.registry().counter(
                        "netstore.fleet.pushes").inc()
                return {"ok": ft.heartbeat(req["doc"],
                                           owner=req.get("owner")),
                        "t_wall": time.time()}
            if verb == "write_result":
                return {"ok": ft.write_result(req["doc"],
                                              owner=req.get("owner"))}
            if verb == "requeue_stale":
                return {"n": ft.requeue_stale(float(req["timeout"]))}
            if verb == "delete_all":
                ft.delete_all()
                return {"ok": True}
            if verb == "put_domain":
                ft.put_domain_blob(base64.b64decode(req["blob"]))
                return {"ok": True}
            if verb == "att_set":
                ft.attachments[req["key"]] = safe_loads(
                    base64.b64decode(req["blob"]))
                return {"ok": True}
            if verb == "att_del":
                try:
                    del ft.attachments[req["key"]]
                    return {"ok": True}
                except KeyError:
                    return {"ok": False}
            raise ValueError(f"unknown verb {verb!r}")

    # -- read dispatch (no write lock) ---------------------------------------

    def _dispatch_read(self, verb: str, req: dict, tenant=None) -> dict:
        """Read-only verbs (the ``_READONLY_VERBS`` catalog), served
        WITHOUT queuing on the write lock: a poll-heavy fleet's ``docs``
        calls never wait behind a mutating verb's fsync.  Safe because
        every store serializes its own state behind an internal lock
        (``FileTrials``/``MemTrials``) and the store table is only
        probed, never mutated, on this path (:meth:`_store_ro`).
        ``HYPEROPT_TPU_READ_DISPATCH=0`` restores the classic
        reads-queue-on-the-write-lock behavior for A/B attribution."""
        if verb == "metrics":
            # Same payload as GET /metrics so RPC clients
            # (NetTrials.metrics) don't need a second transport.
            return {"metrics": self.metrics_payload()}
        if verb == "health":
            # Read-only interpretation verb: per-(tenant, exp_key)
            # optimizer-health verdicts.  Never WAL-logged (not in
            # ServiceServer._WAL_VERBS) and never mutates a store.
            return {"health": self._health_verb(req, tenant=tenant)}
        if verb == "bundle":
            # Read-only flight pull: the full postmortem payload (events
            # ring + meta anchor, metrics, provider sections, redacted
            # env) so an operator lands a remote shard's black box on
            # local disk (bundle.write_payload) without shelling in.
            # Never WAL-logged, never touches a store, token-gated like
            # every verb.
            return {"bundle": _obs_bundle.collect_payload(
                "verb", extra={"trigger": "verb",
                               "tenant": getattr(tenant, "name", None)})}
        if verb == "stores":
            # Control-plane inventory: every (tenant, exp_key) this
            # server hosts with coarse sizes — the autoscaler's hot-key
            # detector and the per-store migration planner read this.
            with self._lock:
                items = [
                    {"tenant": t, "exp_key": k,
                     "docs": len(getattr(ft, "_by_tid", ()) or ()),
                     "claims": len(getattr(ft, "_claims", ()) or ()),
                     "fenced": bool(getattr(ft, "fenced", False))}
                    for (t, k), ft in sorted(
                        self._trials.items(),
                        key=lambda kv: (kv[0][0] or "", kv[0][1]))]
            return {"stores": items}
        exp_key = req.get("exp_key", "default")
        if not self._read_dispatch:
            with self._lock:
                return self._dispatch_read_store(
                    verb, req, self._store(exp_key, tenant=tenant))
        return self._dispatch_read_store(
            verb, req, self._store_ro(exp_key, tenant=tenant))

    def _dispatch_read_store(self, verb: str, req: dict, ft) -> dict:
        """Store-backed read arms; ``ft`` resolves concurrency above
        (lock-free probe, or under the write lock in the A/B-off arm).
        """
        if verb == "store_export":
            # Migration read: the store's full canonical state, exactly
            # what the receiving shard's ``store_import`` replays.  The
            # ONE read a fenced store still answers — the fence is what
            # makes this snapshot final.
            fn = getattr(ft, "state_dict", None)
            if fn is None:
                raise ValueError("store_export requires a service store "
                                 "(MemTrials)")
            return {"state": fn()}
        if getattr(ft, "fenced", False):
            # A fenced store's documents are moving (or moved) away;
            # serving a read here would hand the client a stale or empty
            # view.  Same typed redirect as the mutating path.
            _metrics.registry().counter("store.fenced").inc()
            raise ShardFenced(
                f"store {req.get('exp_key', 'default')!r} is fenced "
                f"(migrating): refusing {verb!r}")
        if verb == "docs":
            export = getattr(ft, "export_docs", None)
            if export is not None:
                return {"docs": export()}
            ft.refresh()
            return {"docs": ft._dynamic_trials}
        if verb == "fetch_since":
            # Delta history pull: only rows touched since the client's
            # cursor.  Stores without delta bookkeeping (FileTrials)
            # answer with the full list and a null cursor — the client
            # then keeps using classic full fetches against this peer.
            fn = getattr(ft, "docs_since", None)
            if fn is None:
                export = getattr(ft, "export_docs", None)
                if export is not None:
                    docs = export()
                else:
                    ft.refresh()
                    docs = ft._dynamic_trials
                return {"docs": docs, "cursor": None, "full": True}
            docs, cursor, full = fn(req.get("cursor"))
            return {"docs": docs, "cursor": cursor, "full": full}
        if verb == "get_domain":
            blob = ft.get_domain_blob()
            if blob is None:
                return {"blob": None}
            return {"blob": base64.b64encode(blob).decode()}
        if verb == "att_get":
            try:
                val = ft.attachments[req["key"]]
            except KeyError:
                return {"blob": None}
            return {"blob": base64.b64encode(
                _pickler.dumps(val)).decode()}
        if verb == "att_keys":
            return {"keys": list(ft.attachments)}
        raise ValueError(f"unknown verb {verb!r}")

    def _store_ro(self, exp_key: str, tenant=None):
        """Store lookup for the read path: a lock-free probe of the
        table (dict reads are atomic under the GIL; stores are created
        once and never replaced), taking the write lock only to create
        a store that does not exist yet — ``_store`` re-probes under
        the lock, so the race is benign."""
        tname = getattr(tenant, "name", tenant)
        ft = self._trials.get((tname, exp_key))
        if ft is not None:
            return ft
        with self._lock:
            return self._store(exp_key, tenant=tenant)

    # -- long-poll claims ----------------------------------------------------

    def _claim_gate(self, tname, exp_key) -> _ClaimGate:
        key = (tname, exp_key)
        with self._claim_gates_lock:
            gate = self._claim_gates.get(key)
            if gate is None:
                gate = self._claim_gates[key] = _ClaimGate()
            return gate

    def _signal_claims(self, tname, exp_key, verb=None, out=None):
        """Wake the store's parked long-poll reserves.  With ``verb``/
        ``out`` the wake is gated on the verb actually having produced
        something claimable (inserted docs, requeued claims, a freed
        claims-quota slot); the janitor calls with no verb
        (unconditional).  Never creates a gate — nobody parked means
        nothing to wake."""
        if verb is not None:
            if verb == "suggest" and not (out or {}).get("inserted"):
                return
            key = {"insert_docs": "tids", "suggest": "tids",
                   "requeue_stale": "n", "write_result": "ok",
                   "store_fence": "ok"}[verb]
            if not (out or {}).get(key):
                return
        with self._claim_gates_lock:
            gate = self._claim_gates.get((tname, exp_key))
        if gate is not None:
            gate.signal()

    def _reserve_longpoll(self, req: dict, tenant=None,
                          wait_s: float = 0.0, idem=None) -> dict:
        """Server-side parked claim: retry ``reserve`` on every gate
        signal until a doc lands or the wait budget expires, replacing
        the workers' client-side 100 ms poll loop.  Each attempt is a
        full ``_dispatch_verb`` pass, so quota checks (and, in the
        WAL-backed service, append-before-execute) re-run at every wake
        exactly as a fresh client poll would."""
        reg = _metrics.registry()
        tname = getattr(tenant, "name", tenant)
        gate = self._claim_gate(tname, req.get("exp_key", "default"))
        deadline = time.monotonic() + min(float(wait_s),
                                          self._LONGPOLL_CAP_S)
        parked = False
        while True:
            # Generation snapshot BEFORE the attempt: a signal that
            # lands between attempt and park bumps it, so the wait
            # below returns immediately instead of losing the wakeup.
            gen0 = gate.snapshot()
            out = self._dispatch_verb("reserve", req, tenant=tenant,
                                      idem=idem)
            if out.get("doc") is not None:
                if parked:
                    reg.counter("store.longpoll.woken").inc()
                return out
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                reg.counter("store.longpoll.timeouts").inc()
                return out
            if not parked:
                parked = True
                reg.counter("store.longpoll.parked").inc()
            gate.wait(gen0, remaining)

    # -- server-side suggest -------------------------------------------------

    #: Keyword arguments a suggest request may forward to the algorithm.
    #: A whitelist, not **kw passthrough: the wire is untrusted relative
    #: to the algorithm signatures, and an unknown key should 500 here
    #: with a clear message rather than TypeError deep inside TPE.
    _SUGGEST_KW = frozenset({
        "prior_weight", "n_startup_jobs", "n_EI_candidates", "gamma",
        "linear_forgetting", "split", "multivariate", "startup",
        "cat_prior", "popsize", "sigma0", "lr", "rank_shaping"})

    _ALGOS = None

    @classmethod
    def _server_algos(cls):
        """Lazy algorithm table from the backend registry
        (``hyperopt_tpu.backends.contract.server_table``): every
        registered head — builtins and ``register_backend`` additions —
        is servable by name, with console verbosity suppressed where the
        head supports it.  Imports happen on first suggest, keeping
        plain-store servers free of the JAX import.

        Registry heads are dispatch + immediate materialize by the
        SuggestBackend contract, so server and client proposals are
        bit-identical for the same (history, seed).
        """
        if cls._ALGOS is None:
            from ..backends import contract as _backends

            cls._ALGOS = _backends.server_table()
        return cls._ALGOS

    @staticmethod
    def _domain_for(ft):
        """Unpickle the store's published domain, cached on the store by
        (len, crc32) of the blob so repeated suggests don't re-unpickle —
        but a re-published domain (new blob) invalidates naturally."""
        blob = ft.get_domain_blob()
        if blob is None:
            raise FileNotFoundError(
                "suggest: no domain published for "
                f"exp_key={ft._exp_key!r} (driver must save_domain first)")
        sig = (len(blob), zlib.crc32(blob))
        cached = getattr(ft, "_srv_domain", None)
        if cached is not None and cached[0] == sig:
            return cached[1]
        domain = pickle.loads(blob)
        ft._srv_domain = (sig, domain)
        return domain

    def _suggest_verb(self, ft, req: dict, tenant=None) -> dict:
        """Server-side proposal: run the algorithm against the server's
        own store (which feeds the device-resident history ring exactly
        like a client-side Trials would) and optionally insert the docs.

        Thin-client protocol: the driver only needs ``suggest`` (with
        insert), ``docs`` and the result verbs — no JAX client-side.

        ``_fleet_rows`` carries pre-computed proposal rows from the
        ServiceServer cohort gate's fleet dispatch, so this verb only
        packages docs instead of running the algorithm again.  A wire
        client supplying it merely dictates its own proposals — the same
        privilege ``insert_docs`` already grants — so it needs no trust
        boundary beyond the normal auth gate.
        """
        fleet_rows = req.pop("_fleet_rows", None)
        algo_name = req.get("algo", "tpe")
        # Memo for the health verb: which head last served this store
        # (its introspection hook is the one worth running).
        ft._srv_last_algo = algo_name
        algo = self._server_algos().get(algo_name)
        if algo is None:
            from ..backends import UnknownBackend

            raise UnknownBackend(
                f"suggest: unknown algo {algo_name!r} "
                f"(have {sorted(self._server_algos())})")
        if "seed" not in req:
            raise ValueError("suggest: 'seed' is required — the server "
                             "must not invent entropy the driver cannot "
                             "reproduce")
        kw = {k: req[k] for k in self._SUGGEST_KW if k in req}
        bad = set(req) - self._SUGGEST_KW - {
            "verb", "exp_key", "algo", "seed", "n", "new_ids", "insert"}
        if bad:
            raise ValueError(f"suggest: unknown argument(s) {sorted(bad)}")
        new_ids = req.get("new_ids")
        if new_ids is None:
            # Server-allocated ids default to inserting (the enqueue
            # form); explicit ids default to proposal-only (the driver
            # owns the insert, e.g. fmin's algo adapter).
            insert = bool(req.get("insert", True))
            ft.refresh()
            new_ids = ft.new_trial_ids(int(req.get("n", 1)))
        else:
            insert = bool(req.get("insert", False))
            new_ids = [int(t) for t in new_ids]
        if insert:
            self._charge_admission(tenant, len(new_ids))
        domain = self._domain_for(ft)
        ft.refresh()
        if fleet_rows is not None:
            import numpy as _np

            rows = _np.asarray(fleet_rows, _np.float32)[: len(new_ids)]
            acts = domain.cs.active_mask_host(rows)
            docs = docs_from_samples(domain.cs, new_ids, rows, acts,
                                     exp_key=getattr(ft, "exp_key", None))
        else:
            docs = algo(new_ids, domain, ft, int(req["seed"]), **kw)
        # Canonicalize now, inside the lock: the reply the client sees
        # is exactly what a WAL replay would re-insert, and the docs the
        # server stores are plain JSON types like every other doc.  The
        # common case (docs_from_samples output) is already canonical
        # and skips the encode/decode deep-copy entirely.
        docs = _canon_docs(docs)
        tids = list(new_ids)
        if insert and docs:
            tids = ft._insert_trial_docs(docs)
        return {"docs": docs, "tids": tids, "inserted": bool(insert)}


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------


#: Verbs that change server state: each call carries a fresh idempotency
#: key, reused verbatim across retries so the server executes it once.
_MUTATING_VERBS = frozenset(
    {"new_trial_ids", "insert_docs", "reserve", "write_result", "suggest"})

#: Mutating verbs that are retry-convergent without a key: re-executing
#: the request converges on the same durable state (heartbeat refreshes a
#: timestamp to the same pinned clock, requeue_stale is a fixpoint scan,
#: delete_all/put_domain/att_set/att_del overwrite or clear absolutely),
#: so retries need no idempotency cache entry.  Every mutating verb must
#: be in exactly one of these two catalogs (the WP004/WP006 analyzers
#: reconcile both directions against the dispatcher arms).
_IDEMPOTENT_VERBS = frozenset(
    {"heartbeat", "requeue_stale", "delete_all", "put_domain",
     "att_set", "att_del", "store_fence", "store_import"})

#: Fleet control-plane verbs (autoscaler / operator surface): ephemeral
#: server directives that never touch durable store state — ``shed``
#: arms admission control on a shard, ``fence`` quiesces a whole shard
#: for a bounded cutover.  Driven through ad-hoc RPC clients by the
#: router and autoscaler; cataloged here so the registry-drift checker
#: sees their client side.
_CONTROL_VERBS = frozenset({"shed", "fence"})

_BACKOFF_CAP_S = 2.0

#: Peers (by URL) that refused a binary frame in ``auto`` wire mode:
#: pinned to JSON for the rest of the process so every later call skips
#: the doomed framed attempt.  ``binary`` mode never pins (strict).
_JSON_ONLY_PEERS: set = set()
_JSON_ONLY_LOCK = threading.Lock()

#: Error-name prefixes in a non-200 reply that mean "the peer could not
#: parse the frame" (old server: json.loads on magic bytes; new server
#: in json mode: explicit WireError refusal) — the only failures that
#: should trigger the JSON fallback.  Anything else (quota, auth, a
#: verb-level fault) is a real answer and must surface unchanged.
_FRAME_REFUSED = ("WireError", "JSONDecodeError", "UnicodeDecodeError")

#: Env knob: per-host cap on idle keep-alive connections held by the
#: process-global pool (0 disables pooling — every call dials and
#: closes a fresh socket, the pre-pool behavior).
_POOL_ENV = "HYPEROPT_TPU_RPC_POOL"


class _ConnectionPool:
    """Bounded per-host pool of keep-alive ``http.client`` connections.

    Every RPC used to pay a fresh TCP handshake (``urlopen`` closes its
    socket after one reply); at fleet scale connection setup dominated
    per-verb latency.  :meth:`request` checks a connection out of the
    per-``(host, port)`` idle list (``rpc.pool.hits``; a miss dials a
    new socket — ``rpc.pool.misses``), runs one HTTP round-trip, and
    checks it back in for the next call; returns beyond the per-host
    cap close the socket (``rpc.pool.evicted``).

    A reused socket may have died between calls (the server closed an
    idle keep-alive connection — a race inherent to HTTP/1.1).  That
    failure is retried ONCE, transparently, on a freshly dialed
    connection (``rpc.pool.stale_reconnects``): it is a pool artifact,
    not a server fault, so it burns neither the caller's retry budget
    nor a second ``rpc.send`` fault-point draw.  A failure on a fresh
    connection is a real transport error and propagates as
    ``URLError``/``OSError`` into :class:`_Rpc`'s retry loop."""

    # Distinct (host, port) entries allowed to hold idle sockets at
    # once, LRU-evicted.  Long-lived deployments talk to a handful of
    # endpoints and never feel this; without it, anything cycling many
    # short-lived servers (the test suite spawns hundreds, each on a
    # fresh port) accumulates one dead socket fd per server forever.
    _HOST_CAP = 32

    def __init__(self, size: int):
        self.size = max(0, int(size))
        self._lock = threading.Lock()
        # (host, port) -> [HTTPConnection]; dict order is the LRU order
        # (entries are re-inserted on every check-in, dropped when
        # their last idle socket is checked out).
        self._idle: dict = {}

    def request(self, url: str, data, headers: dict, timeout: float):
        """One HTTP round-trip → ``(status, body_bytes)``.  ``data`` is
        the POST body; ``None`` sends a GET (the router's upstream
        metrics scrape)."""
        parts = urlsplit(url)
        host = parts.hostname or "127.0.0.1"
        port = parts.port or 80
        path = parts.path or "/"
        if parts.query:
            path = f"{path}?{parts.query}"
        key = (host, port)
        reg = _metrics.registry()
        conn = None
        if self.size:
            with self._lock:
                idle = self._idle.get(key)
                if idle:
                    conn = idle.pop()
                    if not idle:
                        del self._idle[key]
        reused = conn is not None
        if reused:
            reg.counter("rpc.pool.hits").inc()
        else:
            reg.counter("rpc.pool.misses").inc()
        if conn is None:
            _faults.maybe_fail("rpc.connect", host=host, port=port)
            conn = _http_client.HTTPConnection(host, port, timeout=timeout)
        else:
            conn.timeout = timeout
            if conn.sock is not None:
                conn.sock.settimeout(timeout)
        try:
            status, body, keep = self._roundtrip(conn, path, data, headers)
        except BaseException as e:
            conn.close()
            if not reused or not isinstance(
                    e, (OSError, _http_client.HTTPException)):
                # Fresh-dial failure (a real transport error), or a
                # non-transport exception (injected fault, interrupt):
                # nothing to transparently retry — but never leak the
                # half-used socket either.
                if isinstance(e, (OSError, _http_client.HTTPException)):
                    raise self._transport_error(e) from e
                raise
            # Stale keep-alive socket: one transparent redial.  If the
            # redial itself fails — connect refused, or the rpc.connect
            # fault point firing — the host is unreachable, and every
            # OTHER idle socket for this key predates the failure, so
            # they are presumed just as dead: flush them all.  Leaving
            # them would poison the pool — each future call would check
            # out a corpse, fail, redial, fail, one per socket.
            reg.counter("rpc.pool.stale_reconnects").inc()
            try:
                _faults.maybe_fail("rpc.connect", host=host, port=port,
                                   redial=True)
                conn = _http_client.HTTPConnection(host, port,
                                                   timeout=timeout)
                status, body, keep = self._roundtrip(conn, path, data,
                                                     headers)
            except BaseException as e2:
                conn.close()
                self._flush_host(key)
                if isinstance(e2, (OSError, _http_client.HTTPException)):
                    raise self._transport_error(e2) from e2
                raise
        if keep:
            self._checkin(key, conn)
        else:
            conn.close()
        return status, body

    def _flush_host(self, key) -> None:
        """Drop every idle socket for ``key`` (host unreachable: a
        failed redial proves anything older is dead too)."""
        with self._lock:
            stale = self._idle.pop(key, [])
        if stale:
            _metrics.registry().counter("rpc.pool.flushed").inc(
                len(stale))
        for c in stale:
            c.close()

    @staticmethod
    def _roundtrip(conn, path, data, headers):
        """One hand-rolled HTTP/1.1 exchange over ``conn``'s socket.

        ``http.client``'s request/response machinery costs ~200 µs per
        call on this path: headers and body go out as two separate
        small ``sendall``s (two GIL handoffs — and, with Nagle on, a
        ~40 ms delayed-ACK stall), and the reply headers are parsed
        through ``email.parser``.  Both servers guarantee a
        ``Content-Length`` on every response path (a keep-alive
        invariant), so one coalesced write plus a line-oriented reply
        reader is sufficient — and roughly halves the per-verb
        client-side cost."""
        if conn.sock is None:
            conn.connect()
            # Nagle off before the first byte, else each small write
            # waits out the peer's delayed ACK.
            conn.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn._ht_rfile = conn.sock.makefile("rb")
        method = "POST" if data is not None else "GET"
        req = [f"{method} {path} HTTP/1.1",
               f"Host: {conn.host}:{conn.port}"]
        req += [f"{k}: {v}" for k, v in headers.items()]
        if data is not None:
            req.append(f"Content-Length: {len(data)}")
        buf = ("\r\n".join(req) + "\r\n\r\n").encode("latin-1")
        if data:
            buf += data
        conn.sock.sendall(buf)

        rfile = conn._ht_rfile
        status_line = rfile.readline(65537)
        if not status_line:
            raise _http_client.RemoteDisconnected(
                "Remote end closed connection without response")
        parts = status_line.split(None, 2)
        if len(parts) < 2 or not parts[0].startswith(b"HTTP/"):
            raise _http_client.BadStatusLine(
                status_line.decode("latin-1", "replace"))
        status = int(parts[1])
        keep = parts[0] == b"HTTP/1.1"
        length = None
        while True:
            line = rfile.readline(65537)
            if line in (b"\r\n", b"\n", b""):
                break
            k, _, v = line.partition(b":")
            k = k.strip().lower()
            if k == b"content-length":
                length = int(v.strip())
            elif k == b"connection":
                keep = keep and v.strip().lower() != b"close"
        if length is None:
            # Both servers always frame with Content-Length; anything
            # else is a foreign endpoint we cannot safely keep alive.
            raise _http_client.BadStatusLine("response without Content-Length")
        body = rfile.read(length) if length else b""
        if length and len(body) < length:
            raise _http_client.IncompleteRead(body, length - len(body))
        return status, body, keep

    @staticmethod
    def _transport_error(e):
        # http.client's protocol errors (BadStatusLine,
        # CannotSendRequest, RemoteDisconnected-as-HTTPException
        # shapes) are not all OSError; fold them into URLError so the
        # caller's ``except (URLError, OSError, ...)`` clause sees one
        # shape, exactly like urlopen reported them.
        if isinstance(e, OSError):
            return e
        return URLError(e)

    def _checkin(self, key, conn):
        evicted = []
        if self.size:
            with self._lock:
                idle = self._idle.pop(key, [])
                self._idle[key] = idle      # re-insert: LRU touch
                if len(idle) < self.size:
                    idle.append(conn)
                    conn = None
                    while len(self._idle) > self._HOST_CAP:
                        oldest = next(iter(self._idle))
                        evicted.extend(self._idle.pop(oldest))
            if conn is not None:
                _metrics.registry().counter("rpc.pool.evicted").inc()
        for c in evicted:
            _metrics.registry().counter("rpc.pool.evicted").inc()
            c.close()
        if conn is not None:
            conn.close()

    def close_all(self):
        with self._lock:
            idle_lists, self._idle = list(self._idle.values()), {}
        for conns in idle_lists:
            for c in conns:
                c.close()


_POOL: _ConnectionPool | None = None
_POOL_LOCK = threading.Lock()


def _rpc_pool() -> _ConnectionPool:
    """Process-global pool, rebuilt when the env knob changes (the A/B
    bench toggles ``HYPEROPT_TPU_RPC_POOL`` between arms; the replaced
    pool's idle sockets are closed)."""
    global _POOL
    size = max(0, int(os.environ.get(_POOL_ENV, "8") or "8"))
    pool = _POOL
    if pool is not None and pool.size == size:
        return pool
    with _POOL_LOCK:
        pool = _POOL
        if pool is None or pool.size != size:
            if pool is not None:
                pool.close_all()
            pool = _POOL = _ConnectionPool(size)
    return pool


class _Rpc:
    """Pooled keep-alive JSON client (one logical POST per call; the
    socket persists across calls via :class:`_ConnectionPool`).

    Transport failures (socket refused/reset/timeout, i.e. ``URLError``
    without an HTTP reply) are retried up to ``retries`` times with
    exponential backoff + deterministic jitter; exhaustion raises the typed
    :class:`~hyperopt_tpu.exceptions.NetstoreUnavailable`.  Server-reported
    errors (the server answered, with a fault) stay ``RuntimeError`` and
    are never retried — retrying a deliberate refusal (auth, bad verb)
    only hammers the server.
    """

    def __init__(self, url: str, exp_key: str, timeout: float = 30.0,
                 token: str | None = None, retries: int | None = None,
                 backoff: float | None = None):
        self.url = url.rstrip("/")
        self.exp_key = exp_key
        self.timeout = timeout
        self.token = _resolve_token(token)
        if retries is None:
            retries = int(os.environ.get(
                "HYPEROPT_TPU_NETSTORE_RETRIES", "5") or "5")
        self.retries = max(0, int(retries))
        if backoff is None:
            backoff = float(os.environ.get(
                "HYPEROPT_TPU_NETSTORE_BACKOFF", "0.05") or "0.05")
        self.backoff = float(backoff)
        # Deterministic jitter stream per client identity: spreads thundering
        # retries across workers without making test runs irreproducible.
        self._jitter = random.Random(
            zlib.crc32(f"{self.url}|{exp_key}".encode()))

    def __call__(self, verb: str, _timeout: float | None = None,
                 **kw) -> dict:
        kw.update(verb=verb, exp_key=self.exp_key)
        if verb in _MUTATING_VERBS and "idem" not in kw:
            # One key per logical call, shared by every retry of it.
            # Routed callers pre-pin the key instead, so a retry that
            # crosses a shard failover still dedupes on the promoted
            # replica (the shipped WAL record repopulated its cache).
            kw["idem"] = uuid.uuid4().hex
        # Trace-context stamp (obs/context.py): when the caller runs
        # inside a bound context (a traced driver batch, a worker
        # evaluating a stamped doc), the compact wire string rides along
        # so the server's events attach to the same trial.  Disarmed
        # cost: one module-global boolean check.
        if _context.armed():
            ctx = _context.wire_current()
            if ctx is not None:
                kw["ctx"] = ctx
        wmode = _wire.mode()
        use_frames = (wmode != "json" and verb in _wire.FRAMED_VERBS
                      and (wmode == "binary"
                           or self.url not in _JSON_ONLY_PEERS))
        headers = {"Content-Type": (_wire.CONTENT_TYPE if use_frames
                                    else "application/json")}
        if self.token is not None:
            headers["X-Netstore-Token"] = self.token
        if use_frames:
            data = _wire.encode(kw)
            reg = _metrics.registry()
            reg.counter("wire.frames").inc()
            reg.counter("wire.bytes_tx").inc(len(data))
        else:
            data = json.dumps(kw).encode()
        timeout = self.timeout
        if _timeout is not None:
            # Long-poll verbs park server-side for their wait budget;
            # the HTTP read timeout must outlive it.
            timeout = max(timeout, float(_timeout))
        attempts = 0
        bp_honored = 0
        bp_budget = int(os.environ.get(
            "HYPEROPT_TPU_BACKPRESSURE_RETRIES", "8") or "8")
        t_start = time.perf_counter()
        while True:
            try:
                _faults.maybe_fail("rpc.send", verb=verb)
                status, raw = _rpc_pool().request(self.url, data,
                                                  headers, timeout)
                if status == 200:
                    _faults.maybe_fail("rpc.recv", verb=verb)
                    if _wire.is_frame(raw):
                        _metrics.registry().counter(
                            "wire.bytes_rx").inc(len(raw))
                        out = _wire.decode(bytes(raw))
                    else:
                        out = json.loads(raw)
                    break
                # Non-2xx (500 server fault, 401 auth) carries the JSON
                # error body; surface it as the RuntimeError the callers
                # expect.  The server DID answer — no retry.
                try:
                    out = json.loads(raw)
                except Exception:
                    out = {"error": f"HTTP {status}"}
                if (use_frames and wmode == "auto"
                        and str(out.get("error", "")).startswith(
                            _FRAME_REFUSED)):
                    # Old peer (or json-pinned server) could not parse
                    # the frame: pin this URL to JSON and re-send the
                    # SAME request (same idem key) as JSON — the
                    # fallback costs one extra round trip, once.
                    with _JSON_ONLY_LOCK:
                        _JSON_ONLY_PEERS.add(self.url)
                    _metrics.registry().counter(
                        "wire.json_fallbacks").inc()
                    use_frames = False
                    headers["Content-Type"] = "application/json"
                    data = json.dumps(kw).encode()
                    continue
                if (str(out.get("error", "")).startswith("Backpressure")
                        and bp_honored < bp_budget):
                    # The server is shedding load and named its own
                    # price.  Honor it: sleep a jittered fraction of
                    # retry_after_s and re-send the SAME request (same
                    # idem key) WITHOUT charging the transport retry
                    # budget — the bytes made it there and back, the
                    # server just said "not yet".
                    bp_honored += 1
                    try:
                        retry_after = float(out.get("retry_after_s", 1.0))
                    except (TypeError, ValueError):
                        retry_after = 1.0
                    reg = _metrics.registry()
                    reg.counter("backpressure.client.honored").inc()
                    reg.histogram("backpressure.client.retry_after.s"
                                  ).observe(retry_after)
                    time.sleep(retry_after
                               * (0.5 + self._jitter.random()))
                    continue
                break
            except (URLError, OSError, InjectedFault) as e:
                attempts += 1
                _metrics.registry().counter("netstore.rpc.retry").inc()
                if attempts > self.retries:
                    _metrics.registry().counter(
                        "netstore.rpc.unavailable").inc()
                    raise NetstoreUnavailable(
                        f"netstore {self.url} unreachable after "
                        f"{attempts} attempt(s) ({verb}): {e}",
                        attempts=attempts) from e
                delay = min(self.backoff * (2 ** (attempts - 1)),
                            _BACKOFF_CAP_S)
                time.sleep(delay * (0.5 + self._jitter.random()))
        # Client-observed RPC latency (retries and backoff included) —
        # the worker-side twin of the server's per-verb histograms;
        # piggybacked to the server with the fleet snapshots.
        _metrics.registry().histogram("netstore.client.rpc.s").observe(
            time.perf_counter() - t_start)
        if "error" in out:
            if out["error"].startswith("QuotaExceeded"):
                # Typed so drivers can back off deliberately; NOT in
                # TRANSIENT_ERRORS — blind retry of a rate refusal is
                # exactly the traffic the quota exists to shed.
                raise QuotaExceeded(f"netstore server: {out['error']}")
            if out["error"].startswith("Backpressure"):
                # The shed outlived the honor budget: surface the typed
                # error so the caller can decide (a routed client has
                # already been told N times to come back later).
                try:
                    _ra = float(out.get("retry_after_s", 1.0))
                except (TypeError, ValueError):
                    _ra = 1.0
                raise Backpressure(f"netstore server: {out['error']}",
                                   retry_after_s=_ra)
            if out["error"].startswith("ShardFenced"):
                # Typed retriable redirect — a routed client refreshes
                # its shard map and re-places itself (_RoutedRpc).
                raise ShardFenced(f"netstore server: {out['error']}")
            raise RuntimeError(f"netstore server: {out['error']}")
        return out


class _NetAttachments(MutableMapping):
    """RPC-backed attachments mapping (GridFS-over-HTTP analog)."""

    def __init__(self, rpc: _Rpc):
        self._rpc = rpc

    def __setitem__(self, key, value):
        self._rpc("att_set", key=str(key),
                  blob=base64.b64encode(_pickler.dumps(value)).decode())

    def __getitem__(self, key):
        blob = self._rpc("att_get", key=str(key))["blob"]
        if blob is None:
            raise KeyError(key)
        return safe_loads(base64.b64decode(blob))

    def __delitem__(self, key):
        if not self._rpc("att_del", key=str(key))["ok"]:
            raise KeyError(key)

    def __iter__(self):
        return iter(self._rpc("att_keys")["keys"])

    def __len__(self):
        return len(self._rpc("att_keys")["keys"])


class NetTrials(Trials):
    """Async ``Trials`` over a :class:`StoreServer` URL (MongoTrials analog:
    same surface as :class:`~.filestore.FileTrials`, transport swapped from
    shared mount to HTTP)."""

    asynchronous = True

    #: Minimum seconds between cumulative-snapshot piggybacks on heartbeat
    #: calls (the fleet-metrics push cadence; tests shrink it).  Snapshots
    #: are cumulative — the server keeps last-write-wins per worker — so
    #: a lost push costs staleness, never data.
    metrics_push_interval = 2.0

    def __init__(self, url: str, exp_key: str = "default", refresh=True,
                 timeout: float = 30.0, token: str | None = None,
                 retries: int | None = None):
        self._rpc = _Rpc(url, exp_key, timeout=timeout, token=token,
                         retries=retries)
        self._last_metrics_push = float("-inf")
        # Delta-refresh state: server-issued [epoch, seq] cursor plus a
        # tid -> index map into _dynamic_trials so fetch_since rows merge
        # in place.  _delta_ok flips off permanently against peers that
        # don't speak the verb (the RuntimeError answer pins it).
        self._cursor = None
        self._net_pos: dict = {}
        self._delta_ok = True
        super().__init__(exp_key=exp_key, refresh=refresh)
        self.attachments = _NetAttachments(self._rpc)

    # -- document IO over RPC ------------------------------------------------

    def refresh(self):
        with self._lock:
            docs = None
            if self._delta_ok and _wire.mode() != "json":
                try:
                    out = self._rpc("fetch_since", cursor=self._cursor)
                except (NetstoreUnavailable, QuotaExceeded):
                    raise
                except RuntimeError:
                    # Old peer without the verb: classic full fetches
                    # from here on (one failed probe per process).
                    self._delta_ok = False
                else:
                    self._cursor = out.get("cursor")
                    if out.get("full", True) or self._cursor is None:
                        docs = out.get("docs", [])
                    else:
                        self._merge_delta(out.get("docs", []))
                        return
            if docs is None:
                docs = self._rpc("docs")["docs"]
            docs.sort(key=lambda d: d["tid"])
            self._dynamic_trials = docs
            self._net_pos = {d["tid"]: i for i, d in enumerate(docs)}
            self._ids = {d["tid"] for d in docs}
            self._trials = [d for d in docs
                            if self._exp_key in (None, d.get("exp_key"))]

    def _merge_delta(self, delta: list) -> None:
        """Apply a fetch_since row set: replace known tids in place,
        append unknown ones (re-sorting only if an append lands out of
        tid order — servers allocate tids monotonically, so appends are
        ordered in practice)."""
        if not delta:
            return
        resort = False
        for d in sorted(delta, key=lambda d: d["tid"]):
            i = self._net_pos.get(d["tid"])
            if i is not None:
                self._dynamic_trials[i] = d
            else:
                if (self._dynamic_trials
                        and d["tid"] < self._dynamic_trials[-1]["tid"]):
                    resort = True
                self._net_pos[d["tid"]] = len(self._dynamic_trials)
                self._dynamic_trials.append(d)
                self._ids.add(d["tid"])
        if resort:
            self._dynamic_trials.sort(key=lambda d: d["tid"])
            self._net_pos = {d["tid"]: i
                             for i, d in enumerate(self._dynamic_trials)}
        self._trials = [d for d in self._dynamic_trials
                        if self._exp_key in (None, d.get("exp_key"))]

    def _insert_trial_docs(self, docs):
        return self._rpc("insert_docs", docs=list(docs))["tids"]

    def new_trial_ids(self, n):
        return self._rpc("new_trial_ids", n=int(n))["tids"]

    def delete_all(self):
        self._rpc("delete_all")
        self._cursor = None
        self._net_pos = {}
        super().delete_all()
        self.attachments = _NetAttachments(self._rpc)

    # -- worker/claim surface (server-side atomicity) ------------------------

    def reserve(self, owner: str, wait_s: float | None = None):
        """Claim one NEW trial; ``None`` if none is claimable.

        ``wait_s`` > 0 long-polls: the server parks the call on its
        claim condition variable and answers the moment an insert or
        requeue makes a doc claimable (or the wait expires), replacing
        the client-side 100 ms poll loop — one idle RPC per wait budget
        instead of ten per second.  Default from
        ``HYPEROPT_TPU_RESERVE_WAIT_S`` (unset/0 = classic immediate
        answer); the server clamps the park to its own ceiling."""
        if wait_s is None:
            wait_s = float(os.environ.get(
                "HYPEROPT_TPU_RESERVE_WAIT_S", "0") or "0")
        if wait_s and wait_s > 0:
            return self._rpc("reserve", owner=owner, wait_s=float(wait_s),
                             _timeout=float(wait_s) + 10.0)["doc"]
        return self._rpc("reserve", owner=owner)["doc"]

    def heartbeat(self, doc, owner=None) -> bool:
        kw = {"doc": doc, "owner": owner}
        now = time.monotonic()
        if (owner is not None
                and now - self._last_metrics_push
                >= self.metrics_push_interval):
            # Piggyback this process's cumulative metrics snapshot
            # (histograms in mergeable state form) on the beat — no
            # extra RPC, and the push cadence is bounded by the
            # heartbeat interval itself.
            self._last_metrics_push = now
            kw["worker"] = owner
            kw["metrics"] = _metrics.registry().snapshot(states=True)
        t0 = time.time()
        out = self._rpc("heartbeat", **kw)
        t_server = out.get("t_wall")
        if t_server is not None:
            # NTP-style midpoint estimate of this process's wall-clock
            # offset from the server (positive = we are ahead).  Stamped
            # into the event-log header so `show trace --merge` can
            # normalize this process's lane onto the server clock.
            skew = 0.5 * (t0 + time.time()) - t_server
            _metrics.registry().gauge("clock.skew_s").set(skew)
            EVENTS.set_meta(skew_s=skew)
        return out["ok"]

    def write_result(self, doc, owner=None) -> bool:
        return self._rpc("write_result", doc=doc, owner=owner)["ok"]

    def requeue_stale(self, timeout: float) -> int:
        return self._rpc("requeue_stale", timeout=float(timeout))["n"]

    def metrics(self) -> dict:
        """Server-side metrics registry snapshot (``GET /metrics`` twin)."""
        return self._rpc("metrics")["metrics"]

    def health(self, all: bool = False, introspect: bool = True) -> dict:
        """Per-experiment optimizer-health verdicts (read-only verb):
        ``{label: report}`` with ``report["verdict"]`` in
        ``obs.health.VERDICTS``.  ``all=True`` widens from this client's
        exp_key to every experiment in the caller's tenant namespace;
        ``introspect=False`` skips the backend surrogate diagnostics."""
        kw = {"introspect": introspect}
        if all:
            kw["all"] = True
        return self._rpc("health", **kw)["health"]

    def bundle(self, out_dir: str | None = None) -> dict:
        """Pull the server's flight-recorder payload (read-only verb).

        Returns the bundle payload dict; with ``out_dir`` also writes it
        as an on-disk bundle directory (the exact form a local flight
        dump produces, so ``show bundle`` / ``show trace --merge``
        consume it unchanged)."""
        payload = self._rpc("bundle")["bundle"]
        if out_dir:
            _obs_bundle.write_payload(out_dir, payload)
        return payload

    # -- server-side suggest -------------------------------------------------

    def suggest(self, seed: int, n: int | None = None, new_ids=None,
                algo: str = "tpe", insert: bool | None = None, **kw):
        """Ask the SERVER to propose trials (thin-client protocol).

        The server runs the algorithm against its own store — for TPE,
        ``suggest_dispatch`` + materialize over the device-resident
        history ring, bit-identical to client-side ``tpe.suggest`` for
        the same (history, seed).  Two forms:

        * ``suggest(seed, n=8)`` — server allocates ids and INSERTS the
          proposals (one RPC enqueues a whole batch); returns the docs.
        * ``suggest(seed, new_ids=[...], insert=False)`` — proposal
          only, driver owns the insert (what :func:`server_suggest`
          uses to slot into ``fmin`` as an algo).
        """
        req = dict(seed=int(seed), algo=algo, **kw)
        if new_ids is not None:
            req["new_ids"] = [int(t) for t in new_ids]
        elif n is not None:
            req["n"] = int(n)
        if insert is not None:
            req["insert"] = bool(insert)
        return self._rpc("suggest", **req)["docs"]

    # -- domain shipping -----------------------------------------------------

    def save_domain(self, domain) -> None:
        self._rpc("put_domain",
                  blob=base64.b64encode(_pickler.dumps(domain)).decode())

    def load_domain(self):
        blob = self._rpc("get_domain")["blob"]
        if blob is None:
            raise FileNotFoundError("no domain published for "
                                    f"exp_key={self._exp_key!r}")
        return pickle.loads(base64.b64decode(blob))

    def fmin(self, fn, space, algo, max_evals, **kwargs):
        from ..base import Domain
        try:
            self.save_domain(Domain(fn, space,
                                    pass_expr_memo_ctrl=kwargs.get(
                                        "pass_expr_memo_ctrl")))
        except (pickle.PicklingError, AttributeError, TypeError) as e:
            logger.warning("objective not picklable (%s); workers must be "
                           "given the domain explicitly", e)
        return super().fmin(fn, space, algo, max_evals, **kwargs)


def server_suggest(new_ids, domain, trials, seed, algo: str = "tpe", **kw):
    """``fmin``-shaped algo that delegates the proposal to the server.

    Drop-in for ``algo=`` against a :class:`NetTrials`: the domain
    argument is ignored (the server uses the blob the driver published
    via ``save_domain``), ids and seed flow through unchanged, and the
    returned docs are exactly what the server computed — so a pinned
    seeded run matches client-side ``tpe.suggest`` document-for-document.
    """
    if not isinstance(trials, NetTrials):
        raise TypeError("server_suggest needs a NetTrials "
                        f"(got {type(trials).__name__})")
    return trials.suggest(seed, new_ids=new_ids, algo=algo, insert=False,
                          **kw)


class NetWorker(FileWorker):
    """`FileWorker` over the network store: the identical
    reserve→evaluate→heartbeat→write loop, claims arbitrated server-side.
    ``token`` (or the env secret) authenticates every verb against a
    token-protected :class:`StoreServer`."""

    def __init__(self, url, exp_key="default", token: str | None = None,
                 **kwargs):
        # Resolved before super().__init__ — which calls _make_trials.
        self._token = _resolve_token(token)
        super().__init__(url, exp_key=exp_key, **kwargs)

    def _make_trials(self, url, exp_key):
        return NetTrials(url, exp_key=exp_key, token=self._token)


# ---------------------------------------------------------------------------
# Router-aware client (sharded fleet, service/router.py)
# ---------------------------------------------------------------------------


class _RoutedRpc:
    """:class:`_Rpc` facade that places itself via a router's shard map.

    Fetches the ``shard_map`` verb from the router at construction and
    re-fetches every ``HYPEROPT_TPU_SHARDMAP_REFRESH_S`` seconds (or on
    transport failure), computes the owning shard for this client's
    ``(tenant, exp_key)`` with the same pinned hash the router uses
    (``service/cluster.py``), and then speaks to the owning primary
    **directly** — the router serves topology, not the data path.

    Failover: a :class:`NetstoreUnavailable` from the shard forces a map
    refresh (the router promotes the replica on its side) and one retry
    against the new primary, with the **same** idempotency key pinned
    before the first attempt — the promoted replica either replays the
    shipped record's cached reply or executes the verb for the first
    time, so the retry is exactly-once either way.
    """

    def __init__(self, router_url: str, exp_key: str,
                 timeout: float = 30.0, token: str | None = None,
                 retries: int | None = None,
                 map_refresh_s: float | None = None):
        self._router = _Rpc(router_url, exp_key, timeout=timeout,
                            token=token, retries=retries)
        self.exp_key = exp_key
        self.timeout = timeout
        self.token = _resolve_token(token)
        self._retries = retries
        if map_refresh_s is None:
            map_refresh_s = float(os.environ.get(
                "HYPEROPT_TPU_SHARDMAP_REFRESH_S", "30") or "30")
        self.map_refresh_s = float(map_refresh_s)
        self._lock = threading.Lock()
        self._shard_rpc = None
        self.shard_id = None
        self.tenant = None
        self.map_version = None
        self._map_t = float("-inf")
        self._refresh_map(force=True)

    @property
    def url(self) -> str:
        """The owning shard primary's URL (moves under failover)."""
        with self._lock:
            return self._shard_rpc.url

    def _refresh_map(self, force: bool = False) -> None:
        with self._lock:
            if (not force and time.monotonic() - self._map_t
                    < self.map_refresh_s):
                return
            out = self._router("shard_map")
            from ..service.cluster import ShardMap
            smap = ShardMap.from_dict(out["map"])
            self.tenant = out.get("tenant")
            sid, ent = smap.owner(self.tenant, self.exp_key)
            self._map_t = time.monotonic()
            self.map_version = smap.version
            if (self._shard_rpc is None or self.shard_id != sid
                    or self._shard_rpc.url != ent["primary"]):
                self._shard_rpc = _Rpc(ent["primary"], self.exp_key,
                                       timeout=self.timeout,
                                       token=self.token,
                                       retries=self._retries)
                self.shard_id = sid

    def __call__(self, verb: str, **kw) -> dict:
        self._refresh_map()
        if verb in _MUTATING_VERBS and "idem" not in kw:
            # Pinned HERE so the post-failover retry below reuses it.
            kw["idem"] = uuid.uuid4().hex
        with self._lock:
            rpc = self._shard_rpc
        try:
            return rpc(verb, **kw)
        except ShardFenced:
            return self._redirect(verb, kw)
        except NetstoreUnavailable:
            # Primary gone — and since the data path is direct, the
            # router may not know yet.  Push this very verb THROUGH the
            # router: its forward path retries, promotes the warm
            # replica and answers from the new primary.  The idem key
            # pinned above rides both attempts, so the retry dedupes if
            # the dead primary shipped the record before the kill.
            _metrics.registry().counter("netstore.client.reroutes").inc()
            out = self._router(verb, **kw)
            try:
                self._refresh_map(force=True)    # re-place future calls
            except (NetstoreUnavailable, RuntimeError, OSError):
                pass                 # best effort; next call retries it
            return out

    def _redirect(self, verb: str, kw: dict) -> dict:
        """Typed retriable redirect: the owning store (or its whole
        shard) fenced for a bounded cutover — rebalance, promotion, or
        a per-store migration.  Refresh the map and re-place; the fence
        lifts by the MAP changing, not by waiting it out, so each retry
        re-fetches topology first.  Bounded by the client timeout: a
        fence that outlives it is an operator problem and the typed
        error surfaces."""
        _metrics.registry().counter("netstore.client.redirects").inc()
        deadline = time.monotonic() + max(float(self.timeout), 5.0)
        delay = 0.05
        while True:
            try:
                self._refresh_map(force=True)
            except (NetstoreUnavailable, RuntimeError, OSError):
                pass             # router briefly busy: retry below
            with self._lock:
                rpc = self._shard_rpc
            try:
                return rpc(verb, **kw)
            except ShardFenced:
                if time.monotonic() >= deadline:
                    raise
                time.sleep(delay * (0.5 + rpc._jitter.random()))
                delay = min(delay * 2.0, 0.5)


class RouterTrials(NetTrials):
    """:class:`NetTrials` behind the fleet router (``service/router.py``).

    Same surface, different placement: ``url`` is the ROUTER's URL; the
    client pulls the shard map from it, hashes its own ``(tenant,
    exp_key)`` onto the ring, and talks to the owning shard primary
    directly, re-placing itself after failover or rebalance (see
    :class:`_RoutedRpc`).  ``token`` authenticates against both the
    router (edge) and the shard (authority).
    """

    def __init__(self, url: str, exp_key: str = "default", refresh=True,
                 timeout: float = 30.0, token: str | None = None,
                 retries: int | None = None,
                 map_refresh_s: float | None = None):
        self._rpc = _RoutedRpc(url, exp_key, timeout=timeout,
                               token=token, retries=retries,
                               map_refresh_s=map_refresh_s)
        self._last_metrics_push = float("-inf")
        # Delta-refresh state (see NetTrials.__init__).  Safe across
        # failover/rebalance: the promoted/receiving shard mints a fresh
        # store epoch, so a cursor from the old placement is rejected by
        # docs_since and answered with a full resend.
        self._cursor = None
        self._net_pos = {}
        self._delta_ok = True
        Trials.__init__(self, exp_key=exp_key, refresh=refresh)
        self.attachments = _NetAttachments(self._rpc)

    @property
    def shard_id(self):
        """The shard currently owning this client's store."""
        return self._rpc.shard_id


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def main(argv=None):
    """``--serve``: host a store directory; ``--worker URL``: evaluate jobs
    from a remote store (reference: ``hyperopt-mongo-worker`` against a
    mongod URL)."""
    import argparse

    p = argparse.ArgumentParser(description="hyperopt_tpu network store")
    sub = p.add_mutually_exclusive_group(required=True)
    sub.add_argument("--serve", action="store_true",
                     help="serve --root on --host:--port")
    sub.add_argument("--worker", metavar="URL",
                     help="run a worker against a StoreServer URL")
    p.add_argument("--root", default=None, help="store dir (server mode)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8417)
    p.add_argument("--exp-key", default="default")
    p.add_argument("--token", default=None,
                   help="shared secret for every verb (default: the "
                        "HYPEROPT_TPU_NETSTORE_TOKEN env var; unset = "
                        "open server)")
    p.add_argument("--poll-interval", type=float, default=0.1)
    p.add_argument("--reserve-timeout", type=float, default=None)
    p.add_argument("--max-consecutive-failures", type=int, default=4)
    p.add_argument("--max-trial-retries", type=int, default=0,
                   help="worker mode: in-place re-evaluations of a trial "
                        "after a transient failure before it is marked "
                        "ERROR (default 0 = fail fast)")
    p.add_argument("--requeue-stale-every", type=float, default=None,
                   metavar="S",
                   help="server mode: janitor period — requeue claims whose "
                        "heartbeat went stale, every S seconds (default: "
                        "janitor off; clients may still call requeue_stale)")
    p.add_argument("--stale-timeout", type=float, default=60.0,
                   help="server mode: heartbeat age beyond which the "
                        "janitor treats a claim as crashed (default 60s; "
                        "keep well above the workers' heartbeat interval)")
    p.add_argument("--workdir", default=None)
    p.add_argument("--trace-dir", default=None,
                   help="arm the structured event log and write "
                        "loop_events.jsonl (+ chrome trace) here on exit; "
                        "feed several processes' dirs to "
                        "`hyperopt-tpu-show trace --merge`")
    p.add_argument("--flight-dir", default=None,
                   help="arm the flight recorder: freeze a postmortem "
                        "bundle here on SLO alert fire, unhandled verb "
                        "error or SIGTERM (default: the "
                        "HYPEROPT_TPU_FLIGHT_DIR env var; unset = off)")
    args = p.parse_args(argv)

    if args.serve:
        if not args.root:
            p.error("--serve requires --root")
        tracer = None
        if args.trace_dir:
            from ..obs.trace import Tracer
            tracer = Tracer(args.trace_dir)
            EVENTS.set_meta(role="server")
        server = StoreServer(args.root, host=args.host, port=args.port,
                             token=args.token,
                             requeue_stale_every=args.requeue_stale_every,
                             stale_timeout=args.stale_timeout)
        print(f"netstore: serving {args.root} at {server.url}", flush=True)

        # Graceful stop on SIGTERM (systemd/k8s default kill signal):
        # raise out of serve_forever on the main thread, then shut down in
        # the finally.  shutdown() must not run inside the handler — it
        # joins the serve loop that the handler interrupted.
        import signal

        def _on_sigterm(signo, frame):
            raise SystemExit(0)

        try:
            signal.signal(signal.SIGTERM, _on_sigterm)
        except ValueError:          # not the main thread (embedded use)
            pass
        # Arm AFTER the SIGTERM handler so the flight handler chains it:
        # a TERM first freezes the bundle, then the graceful exit runs.
        flight_dir = _flight.install(args.flight_dir)
        if flight_dir:
            print(f"netstore: flight recorder armed -> {flight_dir}",
                  flush=True)
        try:
            server.serve_forever()
        except (KeyboardInterrupt, SystemExit):
            pass
        finally:
            server.shutdown()
            if tracer is not None:
                tracer.dump()
            print("netstore: shut down", flush=True)
        return 0

    worker = NetWorker(args.worker, exp_key=args.exp_key, token=args.token,
                       poll_interval=args.poll_interval,
                       reserve_timeout=args.reserve_timeout,
                       max_consecutive_failures=args.max_consecutive_failures,
                       max_trial_retries=args.max_trial_retries,
                       workdir=args.workdir, trace_dir=args.trace_dir)
    n = worker.run()
    logger.info("net worker done: %d trials evaluated", n)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
