"""Network front-end for the file store: multi-host WITHOUT a shared mount.

Reference: ``hyperopt/mongoexp.py`` — MongoTrials speaks a network wire
protocol to mongod (SURVEY.md §2/§5.8), so driver and workers only need TCP
reachability.  The round-1..3 builds covered the shared-mount tier
(``filestore.py`` over NFS/GCS-fuse, blessed by SURVEY §5.8 for this
no-pymongo environment); this module closes the remaining parity gap: a
~300-line HTTP KV front-end that exposes the EXACT claim/heartbeat/requeue
semantics of the file store over localhost/DCN sockets.

Design — serialize, don't re-implement:

* ``StoreServer`` owns a store directory on ITS local disk and executes every
  verb against a real :class:`~.filestore.FileTrials` under one lock.  All of
  the race-safety machinery (exclusive-create claims, owner fencing, stale
  requeue) is the filestore's own code running server-side; the server adds
  only transport.  Single-writer serialization makes the network tier
  trivially linearizable — the same role mongod's document-level atomicity
  plays for the reference.
* ``NetTrials`` is a :class:`~..base.Trials` whose document IO is RPC calls;
  ``fmin`` drives it exactly like ``FileTrials`` (``asynchronous = True``).
* ``NetWorker`` is a :class:`~.filestore.FileWorker` bound to a ``NetTrials``
  — the reserve→evaluate→heartbeat→write loop is inherited unchanged.

Wire format: JSON verbs over HTTP POST (stdlib only — the environment has no
third-party RPC deps).  Trial documents are already JSON (the filestore
persists them as such).  The Domain and attachments travel as base64
cloudpickle, like the reference ships objectives through GridFS — which
means the SAME trust model as the reference: only run a StoreServer for
workers you trust (unpickling is code execution).

Authentication: pass ``token=`` (or ``--token`` / the
``HYPEROPT_TPU_NETSTORE_TOKEN`` environment variable) to both server and
clients and every verb requires the shared secret in the
``X-Netstore-Token`` header, compared constant-time
(``hmac.compare_digest``) BEFORE dispatch — an unauthenticated peer can
neither read documents nor claim/write trials (it gets a 401 and no verb
executes).  Without a token the server remains open, preserving the
localhost-trusted default; set one whenever the socket is reachable
beyond the machines you trust.  The token authenticates the transport —
it does not change the unpickling trust model above.

Reference anchors: ``MongoJobs.reserve`` (find_and_modify ≙ server-side
exclusive claim), ``MongoTrials.refresh`` (cursor fetch ≙ ``docs`` verb),
``hyperopt-mongo-worker`` CLI (≙ ``python -m hyperopt_tpu.parallel.netstore
--worker URL``).
"""

from __future__ import annotations

import base64
import hmac
import json
import logging
import os
import pickle
import random
import threading
import time
import uuid
import zlib
from collections import OrderedDict
from collections.abc import MutableMapping
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.error import HTTPError, URLError
from urllib.request import Request, urlopen

from .filestore import FileTrials, FileWorker, _pickler
from ..base import Trials
from ..exceptions import InjectedFault, NetstoreUnavailable
from ..obs import context as _context
from ..obs import metrics as _metrics
from ..obs.events import EVENTS
from .. import faults as _faults

logger = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# auth
# ---------------------------------------------------------------------------


def _resolve_token(token: str | None) -> str | None:
    """Effective shared secret: the explicit argument wins, else the
    ``HYPEROPT_TPU_NETSTORE_TOKEN`` environment variable; empty/unset →
    no auth (open server, localhost-trusted default).  Shared by server
    and clients so one env var secures a whole deployment."""
    if token is None:
        token = os.environ.get("HYPEROPT_TPU_NETSTORE_TOKEN") or None
    return token or None


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------


class StoreServer:
    """Serve a local store directory to remote drivers/workers.

    ``serve_forever`` blocks; ``start()`` runs in a daemon thread and
    returns the bound ``(host, port)`` — tests and same-process drivers use
    that.  One lock serializes all verbs: correctness needs no concurrency
    here (each verb is micro-seconds of local file IO; the objective
    evaluations — the actual work — happen client-side in the workers).
    """

    #: Bound on the idempotency dedup cache (completed mutating calls kept
    #: for replay).  Retries arrive within seconds of the original, so a
    #: few thousand entries is generations of headroom.
    _IDEM_CAP = 4096

    def __init__(self, root: str, host: str = "127.0.0.1", port: int = 0,
                 token: str | None = None,
                 requeue_stale_every: float | None = None,
                 stale_timeout: float = 60.0):
        self.root = os.path.abspath(root)
        self._trials: dict = {}          # exp_key -> FileTrials
        self._lock = threading.Lock()
        self._token = _resolve_token(token)
        # Exactly-once under client retry: (exp_key, idem_key) -> the JSON
        # reply of the first execution.  Stored serialized so a replay can
        # never alias live server-side state.
        self._idem: OrderedDict = OrderedDict()
        self._idem_lock = threading.Lock()
        # Fleet metrics: worker_id -> {"t": last push wall time, "metrics":
        # the worker's cumulative registry snapshot}.  Workers piggyback
        # snapshots on heartbeats (NetTrials.heartbeat); last-write-wins
        # per worker, merged on read by metrics_payload().  Deliberately
        # NOT part of the local registry, so registry().snapshot(
        # reset=True) by a bench/test never drops the per-worker labels.
        self._fleet: dict = {}
        self._fleet_lock = threading.Lock()
        # Janitor: requeue crashed-worker claims every S seconds so the
        # recovery path runs unprompted (``--requeue-stale-every``).
        self.requeue_stale_every = requeue_stale_every
        self.stale_timeout = stale_timeout
        self._janitor: threading.Thread | None = None
        self._janitor_stop = threading.Event()
        self._started = False
        self._closed = False
        self._lifecycle_lock = threading.Lock()
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):   # quiet by default
                logger.debug("netstore: " + fmt, *args)

            def _send_json(self, code, body: bytes):
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _authed(self) -> bool:
                # Auth gate BEFORE the body is parsed or any verb runs:
                # constant-time compare so the secret can't be recovered
                # byte-by-byte from response timing.  The request body is
                # still drained (keep-alive correctness) but never
                # dispatched.
                if server._token is None:
                    return True
                got = self.headers.get("X-Netstore-Token", "")
                if hmac.compare_digest(got.encode(),
                                       server._token.encode()):
                    return True
                _metrics.registry().counter("netstore.auth.rejected").inc()
                self.rfile.read(
                    int(self.headers.get("Content-Length", "0")))
                self._send_json(401, json.dumps(
                    {"error": "AuthError: missing or bad "
                     "X-Netstore-Token"}).encode())
                return False

            def do_POST(self):
                if not self._authed():
                    return
                try:
                    n = int(self.headers.get("Content-Length", "0"))
                    req = json.loads(self.rfile.read(n) or b"{}")
                    out = server._dispatch(req)
                    body = json.dumps(out).encode()
                    code = 200
                except Exception as e:  # surface server faults to the client
                    body = json.dumps(
                        {"error": f"{type(e).__name__}: {e}"}).encode()
                    code = 500
                self._send_json(code, body)

            def do_GET(self):
                # Read-only metrics surface, token-gated like every verb:
                # ``GET /metrics`` returns the process-global registry
                # snapshot (counters/gauges/histograms/kernel_cache) plus
                # the ``fleet`` view (per-worker labeled snapshots pushed
                # on heartbeats + exactly-merged histograms) so an
                # operator can curl the server a driver and workers feed.
                if not self._authed():
                    return
                if self.path.split("?", 1)[0] == "/metrics":
                    body = json.dumps(server.metrics_payload()).encode()
                    self._send_json(200, body)
                    return
                self._send_json(404, json.dumps(
                    {"error": f"NotFound: {self.path}"}).encode())

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._httpd.server_address[:2]

    # -- lifecycle -----------------------------------------------------------

    def start(self):
        self._started = True
        self._start_janitor()
        t = threading.Thread(target=self._httpd.serve_forever, daemon=True,
                             name="netstore-server")
        t.start()
        return self.host, self.port

    def serve_forever(self):
        self._started = True
        self._start_janitor()
        self._httpd.serve_forever()

    def shutdown(self):
        """Stop serving and release the socket.

        Idempotent, and safe when ``start()``/``serve_forever()`` never
        ran (``ThreadingHTTPServer.shutdown`` would otherwise block
        forever waiting on a serve loop that does not exist).
        """
        with self._lifecycle_lock:
            if self._closed:
                return
            self._closed = True
        self._janitor_stop.set()
        if self._janitor is not None:
            self._janitor.join(timeout=5.0)
        if self._started:
            self._httpd.shutdown()
        self._httpd.server_close()

    def _start_janitor(self):
        if not self.requeue_stale_every or self._janitor is not None:
            return
        self._janitor = threading.Thread(target=self._janitor_loop,
                                         daemon=True,
                                         name="netstore-janitor")
        self._janitor.start()

    def _janitor_loop(self):
        # wait() (not sleep) so shutdown() interrupts a long period
        # immediately; first pass only after one full period.
        while not self._janitor_stop.wait(self.requeue_stale_every):
            try:
                with self._lock:
                    stores = list(self._trials.values())
                for ft in stores:
                    with self._lock:
                        n = ft.requeue_stale(self.stale_timeout)
                    if n:
                        logger.info("netstore janitor: requeued %d stale "
                                    "trial(s) in %r", n, ft._exp_key)
            except Exception:       # janitor must outlive any bad store
                logger.exception("netstore janitor: requeue_stale failed")

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    # -- verbs ---------------------------------------------------------------

    def _store(self, exp_key: str) -> FileTrials:
        ft = self._trials.get(exp_key)
        if ft is None:
            ft = self._trials[exp_key] = FileTrials(self.root,
                                                    exp_key=exp_key)
        return ft

    def _dispatch(self, req: dict) -> dict:
        verb = req["verb"]
        reg = _metrics.registry()
        t0 = time.perf_counter()
        # Trace context stamped by the client (obs/context.py wire form):
        # adopt it for the duration of the verb so every event this
        # dispatch emits — store_claim/store_write from the filestore,
        # fault injections, the rpc instant below — attaches to the
        # originating trial and trace.
        ctx = req.pop("ctx", None)
        try:
            with _context.adopt(ctx):
                EVENTS.emit("rpc", name=verb)
                idem = req.pop("idem", None)
                if idem is None:
                    return self._dispatch_verb(verb, req)
                # Mutating verb with an idempotency key: a retry of a call
                # the server already executed must return the original
                # reply, not run the verb twice (the client retries blind
                # — it cannot know whether the loss was on the way in or
                # out).
                key = (req.get("exp_key", "default"), idem)
                with self._idem_lock:
                    cached = self._idem.get(key)
                if cached is not None:
                    reg.counter("netstore.idem.hits").inc()
                    return json.loads(cached)
                out = self._dispatch_verb(verb, req)
                with self._idem_lock:
                    self._idem[key] = json.dumps(out)
                    while len(self._idem) > self._IDEM_CAP:
                        self._idem.popitem(last=False)
                return out
        finally:
            # Per-verb call count + latency histogram: the contention
            # signal for the single-writer lock under many workers.
            reg.counter(f"netstore.verb.{verb}.calls").inc()
            reg.histogram(f"netstore.verb.{verb}.s").observe(
                time.perf_counter() - t0)

    def metrics_payload(self) -> dict:
        """The ``GET /metrics`` document: local snapshot + fleet view.

        Top level keeps the historical registry-snapshot schema
        (enabled/counters/gauges/kernel_cache/histograms — now with
        mergeable ``state`` per histogram, including the server-side
        per-verb latency histograms ``netstore.verb.<verb>.s`` with
        p50/p95/p99) and adds ``fleet``:

        * ``workers`` — per-worker labels: each worker's last pushed
          cumulative snapshot plus ``age_s`` staleness (a worker whose
          age greatly exceeds its heartbeat interval is presumed dead),
        * ``merged`` — counters/gauges summed and histograms
          exactly merged (``obs.metrics.merge_snapshots``) across the
          server's own registry and every pushed worker snapshot.
        """
        snap = _metrics.registry().snapshot(states=True)
        now = time.time()
        with self._fleet_lock:
            fleet = {w: dict(rec) for w, rec in self._fleet.items()}
        workers = {}
        members = [snap]
        for w in sorted(fleet):
            rec = fleet[w]
            m = rec.get("metrics") or {}
            workers[w] = {
                "age_s": round(now - rec.get("t", now), 3),
                "counters": m.get("counters") or {},
                "gauges": m.get("gauges") or {},
                "histograms": m.get("histograms") or {},
            }
            members.append(m)
        snap["fleet"] = {
            "n_workers": len(workers),
            "workers": workers,
            "merged": _metrics.merge_snapshots(members),
        }
        return snap

    def _dispatch_verb(self, verb: str, req: dict) -> dict:
        if verb == "metrics":
            # Same payload as GET /metrics so RPC clients
            # (NetTrials.metrics) don't need a second transport.
            return {"metrics": self.metrics_payload()}
        with self._lock:
            ft = self._store(req.get("exp_key", "default"))
            if verb == "docs":
                ft.refresh()
                return {"docs": ft._dynamic_trials}
            if verb == "insert_docs":
                return {"tids": ft._insert_trial_docs(req["docs"])}
            if verb == "new_trial_ids":
                ft.refresh()
                return {"tids": ft.new_trial_ids(int(req["n"]))}
            if verb == "reserve":
                return {"doc": ft.reserve(req["owner"])}
            if verb == "heartbeat":
                # Piggybacked fleet metrics: a worker may attach its
                # cumulative registry snapshot (last-write-wins per
                # worker id; merged on read by metrics_payload).  The
                # reply carries the server wall clock so clients can
                # estimate their skew for trace stitching.
                w = req.get("worker")
                if w is not None and req.get("metrics") is not None:
                    with self._fleet_lock:
                        self._fleet[w] = {"t": time.time(),
                                          "metrics": req["metrics"]}
                    _metrics.registry().counter(
                        "netstore.fleet.pushes").inc()
                return {"ok": ft.heartbeat(req["doc"],
                                           owner=req.get("owner")),
                        "t_wall": time.time()}
            if verb == "write_result":
                return {"ok": ft.write_result(req["doc"],
                                              owner=req.get("owner"))}
            if verb == "requeue_stale":
                return {"n": ft.requeue_stale(float(req["timeout"]))}
            if verb == "delete_all":
                ft.delete_all()
                return {"ok": True}
            if verb == "put_domain":
                path = os.path.join(ft._exp_dir, "domain.pkl")
                tmp = f"{path}.tmp.{os.getpid()}"
                with open(tmp, "wb") as f:
                    f.write(base64.b64decode(req["blob"]))
                os.replace(tmp, path)
                return {"ok": True}
            if verb == "get_domain":
                path = os.path.join(ft._exp_dir, "domain.pkl")
                try:
                    with open(path, "rb") as f:
                        return {"blob": base64.b64encode(f.read()).decode()}
                except FileNotFoundError:
                    return {"blob": None}
            if verb == "att_set":
                ft.attachments[req["key"]] = pickle.loads(
                    base64.b64decode(req["blob"]))
                return {"ok": True}
            if verb == "att_get":
                try:
                    val = ft.attachments[req["key"]]
                except KeyError:
                    return {"blob": None}
                return {"blob": base64.b64encode(
                    _pickler.dumps(val)).decode()}
            if verb == "att_del":
                try:
                    del ft.attachments[req["key"]]
                    return {"ok": True}
                except KeyError:
                    return {"ok": False}
            if verb == "att_keys":
                return {"keys": list(ft.attachments)}
            raise ValueError(f"unknown verb {verb!r}")


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------


#: Verbs that change server state: each call carries a fresh idempotency
#: key, reused verbatim across retries so the server executes it once.
_MUTATING_VERBS = frozenset(
    {"new_trial_ids", "insert_docs", "reserve", "write_result"})

_BACKOFF_CAP_S = 2.0


class _Rpc:
    """One-POST-per-call JSON client (stdlib urllib; connection reuse is not
    worth a dependency at this call volume).

    Transport failures (socket refused/reset/timeout, i.e. ``URLError``
    without an HTTP reply) are retried up to ``retries`` times with
    exponential backoff + deterministic jitter; exhaustion raises the typed
    :class:`~hyperopt_tpu.exceptions.NetstoreUnavailable`.  Server-reported
    errors (the server answered, with a fault) stay ``RuntimeError`` and
    are never retried — retrying a deliberate refusal (auth, bad verb)
    only hammers the server.
    """

    def __init__(self, url: str, exp_key: str, timeout: float = 30.0,
                 token: str | None = None, retries: int | None = None,
                 backoff: float | None = None):
        self.url = url.rstrip("/")
        self.exp_key = exp_key
        self.timeout = timeout
        self.token = _resolve_token(token)
        if retries is None:
            retries = int(os.environ.get(
                "HYPEROPT_TPU_NETSTORE_RETRIES", "5") or "5")
        self.retries = max(0, int(retries))
        if backoff is None:
            backoff = float(os.environ.get(
                "HYPEROPT_TPU_NETSTORE_BACKOFF", "0.05") or "0.05")
        self.backoff = float(backoff)
        # Deterministic jitter stream per client identity: spreads thundering
        # retries across workers without making test runs irreproducible.
        self._jitter = random.Random(
            zlib.crc32(f"{self.url}|{exp_key}".encode()))

    def __call__(self, verb: str, **kw) -> dict:
        kw.update(verb=verb, exp_key=self.exp_key)
        if verb in _MUTATING_VERBS:
            # One key per logical call, shared by every retry of it.
            kw["idem"] = uuid.uuid4().hex
        # Trace-context stamp (obs/context.py): when the caller runs
        # inside a bound context (a traced driver batch, a worker
        # evaluating a stamped doc), the compact wire string rides along
        # so the server's events attach to the same trial.  Disarmed
        # cost: one module-global boolean check.
        if _context.armed():
            ctx = _context.wire_current()
            if ctx is not None:
                kw["ctx"] = ctx
        headers = {"Content-Type": "application/json"}
        if self.token is not None:
            headers["X-Netstore-Token"] = self.token
        data = json.dumps(kw).encode()
        attempts = 0
        t_start = time.perf_counter()
        while True:
            try:
                _faults.maybe_fail("rpc.send", verb=verb)
                req = Request(self.url, data=data, headers=headers)
                with urlopen(req, timeout=self.timeout) as resp:
                    raw = resp.read()
                _faults.maybe_fail("rpc.recv", verb=verb)
                out = json.loads(raw)
                break
            except HTTPError as e:
                # Non-2xx (500 server fault, 401 auth) carries the JSON
                # error body; surface it as the RuntimeError the callers
                # expect.  The server DID answer — no retry.
                try:
                    out = json.loads(e.read())
                except Exception:
                    out = {"error": f"HTTP {e.code}"}
                break
            except (URLError, OSError, InjectedFault) as e:
                attempts += 1
                _metrics.registry().counter("netstore.rpc.retry").inc()
                if attempts > self.retries:
                    _metrics.registry().counter(
                        "netstore.rpc.unavailable").inc()
                    raise NetstoreUnavailable(
                        f"netstore {self.url} unreachable after "
                        f"{attempts} attempt(s) ({verb}): {e}",
                        attempts=attempts) from e
                delay = min(self.backoff * (2 ** (attempts - 1)),
                            _BACKOFF_CAP_S)
                time.sleep(delay * (0.5 + self._jitter.random()))
        # Client-observed RPC latency (retries and backoff included) —
        # the worker-side twin of the server's per-verb histograms;
        # piggybacked to the server with the fleet snapshots.
        _metrics.registry().histogram("netstore.client.rpc.s").observe(
            time.perf_counter() - t_start)
        if "error" in out:
            raise RuntimeError(f"netstore server: {out['error']}")
        return out


class _NetAttachments(MutableMapping):
    """RPC-backed attachments mapping (GridFS-over-HTTP analog)."""

    def __init__(self, rpc: _Rpc):
        self._rpc = rpc

    def __setitem__(self, key, value):
        self._rpc("att_set", key=str(key),
                  blob=base64.b64encode(_pickler.dumps(value)).decode())

    def __getitem__(self, key):
        blob = self._rpc("att_get", key=str(key))["blob"]
        if blob is None:
            raise KeyError(key)
        return pickle.loads(base64.b64decode(blob))

    def __delitem__(self, key):
        if not self._rpc("att_del", key=str(key))["ok"]:
            raise KeyError(key)

    def __iter__(self):
        return iter(self._rpc("att_keys")["keys"])

    def __len__(self):
        return len(self._rpc("att_keys")["keys"])


class NetTrials(Trials):
    """Async ``Trials`` over a :class:`StoreServer` URL (MongoTrials analog:
    same surface as :class:`~.filestore.FileTrials`, transport swapped from
    shared mount to HTTP)."""

    asynchronous = True

    #: Minimum seconds between cumulative-snapshot piggybacks on heartbeat
    #: calls (the fleet-metrics push cadence; tests shrink it).  Snapshots
    #: are cumulative — the server keeps last-write-wins per worker — so
    #: a lost push costs staleness, never data.
    metrics_push_interval = 2.0

    def __init__(self, url: str, exp_key: str = "default", refresh=True,
                 timeout: float = 30.0, token: str | None = None,
                 retries: int | None = None):
        self._rpc = _Rpc(url, exp_key, timeout=timeout, token=token,
                         retries=retries)
        self._last_metrics_push = float("-inf")
        super().__init__(exp_key=exp_key, refresh=refresh)
        self.attachments = _NetAttachments(self._rpc)

    # -- document IO over RPC ------------------------------------------------

    def refresh(self):
        with self._lock:
            docs = self._rpc("docs")["docs"]
            docs.sort(key=lambda d: d["tid"])
            self._dynamic_trials = docs
            self._ids = {d["tid"] for d in docs}
            self._trials = [d for d in docs
                            if self._exp_key in (None, d.get("exp_key"))]

    def _insert_trial_docs(self, docs):
        return self._rpc("insert_docs", docs=list(docs))["tids"]

    def new_trial_ids(self, n):
        return self._rpc("new_trial_ids", n=int(n))["tids"]

    def delete_all(self):
        self._rpc("delete_all")
        super().delete_all()
        self.attachments = _NetAttachments(self._rpc)

    # -- worker/claim surface (server-side atomicity) ------------------------

    def reserve(self, owner: str):
        return self._rpc("reserve", owner=owner)["doc"]

    def heartbeat(self, doc, owner=None) -> bool:
        kw = {"doc": doc, "owner": owner}
        now = time.monotonic()
        if (owner is not None
                and now - self._last_metrics_push
                >= self.metrics_push_interval):
            # Piggyback this process's cumulative metrics snapshot
            # (histograms in mergeable state form) on the beat — no
            # extra RPC, and the push cadence is bounded by the
            # heartbeat interval itself.
            self._last_metrics_push = now
            kw["worker"] = owner
            kw["metrics"] = _metrics.registry().snapshot(states=True)
        t0 = time.time()
        out = self._rpc("heartbeat", **kw)
        t_server = out.get("t_wall")
        if t_server is not None:
            # NTP-style midpoint estimate of this process's wall-clock
            # offset from the server (positive = we are ahead).  Stamped
            # into the event-log header so `show trace --merge` can
            # normalize this process's lane onto the server clock.
            skew = 0.5 * (t0 + time.time()) - t_server
            _metrics.registry().gauge("clock.skew_s").set(skew)
            EVENTS.set_meta(skew_s=skew)
        return out["ok"]

    def write_result(self, doc, owner=None) -> bool:
        return self._rpc("write_result", doc=doc, owner=owner)["ok"]

    def requeue_stale(self, timeout: float) -> int:
        return self._rpc("requeue_stale", timeout=float(timeout))["n"]

    def metrics(self) -> dict:
        """Server-side metrics registry snapshot (``GET /metrics`` twin)."""
        return self._rpc("metrics")["metrics"]

    # -- domain shipping -----------------------------------------------------

    def save_domain(self, domain) -> None:
        self._rpc("put_domain",
                  blob=base64.b64encode(_pickler.dumps(domain)).decode())

    def load_domain(self):
        blob = self._rpc("get_domain")["blob"]
        if blob is None:
            raise FileNotFoundError("no domain published for "
                                    f"exp_key={self._exp_key!r}")
        return pickle.loads(base64.b64decode(blob))

    def fmin(self, fn, space, algo, max_evals, **kwargs):
        from ..base import Domain
        try:
            self.save_domain(Domain(fn, space,
                                    pass_expr_memo_ctrl=kwargs.get(
                                        "pass_expr_memo_ctrl")))
        except (pickle.PicklingError, AttributeError, TypeError) as e:
            logger.warning("objective not picklable (%s); workers must be "
                           "given the domain explicitly", e)
        return super().fmin(fn, space, algo, max_evals, **kwargs)


class NetWorker(FileWorker):
    """`FileWorker` over the network store: the identical
    reserve→evaluate→heartbeat→write loop, claims arbitrated server-side.
    ``token`` (or the env secret) authenticates every verb against a
    token-protected :class:`StoreServer`."""

    def __init__(self, url, exp_key="default", token: str | None = None,
                 **kwargs):
        # Resolved before super().__init__ — which calls _make_trials.
        self._token = _resolve_token(token)
        super().__init__(url, exp_key=exp_key, **kwargs)

    def _make_trials(self, url, exp_key):
        return NetTrials(url, exp_key=exp_key, token=self._token)


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def main(argv=None):
    """``--serve``: host a store directory; ``--worker URL``: evaluate jobs
    from a remote store (reference: ``hyperopt-mongo-worker`` against a
    mongod URL)."""
    import argparse

    p = argparse.ArgumentParser(description="hyperopt_tpu network store")
    sub = p.add_mutually_exclusive_group(required=True)
    sub.add_argument("--serve", action="store_true",
                     help="serve --root on --host:--port")
    sub.add_argument("--worker", metavar="URL",
                     help="run a worker against a StoreServer URL")
    p.add_argument("--root", default=None, help="store dir (server mode)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8417)
    p.add_argument("--exp-key", default="default")
    p.add_argument("--token", default=None,
                   help="shared secret for every verb (default: the "
                        "HYPEROPT_TPU_NETSTORE_TOKEN env var; unset = "
                        "open server)")
    p.add_argument("--poll-interval", type=float, default=0.1)
    p.add_argument("--reserve-timeout", type=float, default=None)
    p.add_argument("--max-consecutive-failures", type=int, default=4)
    p.add_argument("--max-trial-retries", type=int, default=0,
                   help="worker mode: in-place re-evaluations of a trial "
                        "after a transient failure before it is marked "
                        "ERROR (default 0 = fail fast)")
    p.add_argument("--requeue-stale-every", type=float, default=None,
                   metavar="S",
                   help="server mode: janitor period — requeue claims whose "
                        "heartbeat went stale, every S seconds (default: "
                        "janitor off; clients may still call requeue_stale)")
    p.add_argument("--stale-timeout", type=float, default=60.0,
                   help="server mode: heartbeat age beyond which the "
                        "janitor treats a claim as crashed (default 60s; "
                        "keep well above the workers' heartbeat interval)")
    p.add_argument("--workdir", default=None)
    p.add_argument("--trace-dir", default=None,
                   help="arm the structured event log and write "
                        "loop_events.jsonl (+ chrome trace) here on exit; "
                        "feed several processes' dirs to "
                        "`hyperopt-tpu-show trace --merge`")
    args = p.parse_args(argv)

    if args.serve:
        if not args.root:
            p.error("--serve requires --root")
        tracer = None
        if args.trace_dir:
            from ..obs.trace import Tracer
            tracer = Tracer(args.trace_dir)
            EVENTS.set_meta(role="server")
        server = StoreServer(args.root, host=args.host, port=args.port,
                             token=args.token,
                             requeue_stale_every=args.requeue_stale_every,
                             stale_timeout=args.stale_timeout)
        print(f"netstore: serving {args.root} at {server.url}", flush=True)

        # Graceful stop on SIGTERM (systemd/k8s default kill signal):
        # raise out of serve_forever on the main thread, then shut down in
        # the finally.  shutdown() must not run inside the handler — it
        # joins the serve loop that the handler interrupted.
        import signal

        def _on_sigterm(signo, frame):
            raise SystemExit(0)

        try:
            signal.signal(signal.SIGTERM, _on_sigterm)
        except ValueError:          # not the main thread (embedded use)
            pass
        try:
            server.serve_forever()
        except (KeyboardInterrupt, SystemExit):
            pass
        finally:
            server.shutdown()
            if tracer is not None:
                tracer.dump()
            print("netstore: shut down", flush=True)
        return 0

    worker = NetWorker(args.worker, exp_key=args.exp_key, token=args.token,
                       poll_interval=args.poll_interval,
                       reserve_timeout=args.reserve_timeout,
                       max_consecutive_failures=args.max_consecutive_failures,
                       max_trial_retries=args.max_trial_retries,
                       workdir=args.workdir, trace_dir=args.trace_dir)
    n = worker.run()
    logger.info("net worker done: %d trials evaluated", n)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
