"""Network front-end for the file store: multi-host WITHOUT a shared mount.

Reference: ``hyperopt/mongoexp.py`` — MongoTrials speaks a network wire
protocol to mongod (SURVEY.md §2/§5.8), so driver and workers only need TCP
reachability.  The round-1..3 builds covered the shared-mount tier
(``filestore.py`` over NFS/GCS-fuse, blessed by SURVEY §5.8 for this
no-pymongo environment); this module closes the remaining parity gap: a
~300-line HTTP KV front-end that exposes the EXACT claim/heartbeat/requeue
semantics of the file store over localhost/DCN sockets.

Design — serialize, don't re-implement:

* ``StoreServer`` owns a store directory on ITS local disk and executes every
  verb against a real :class:`~.filestore.FileTrials` under one lock.  All of
  the race-safety machinery (exclusive-create claims, owner fencing, stale
  requeue) is the filestore's own code running server-side; the server adds
  only transport.  Single-writer serialization makes the network tier
  trivially linearizable — the same role mongod's document-level atomicity
  plays for the reference.
* ``NetTrials`` is a :class:`~..base.Trials` whose document IO is RPC calls;
  ``fmin`` drives it exactly like ``FileTrials`` (``asynchronous = True``).
* ``NetWorker`` is a :class:`~.filestore.FileWorker` bound to a ``NetTrials``
  — the reserve→evaluate→heartbeat→write loop is inherited unchanged.

Wire format: JSON verbs over HTTP POST (stdlib only — the environment has no
third-party RPC deps).  Trial documents are already JSON (the filestore
persists them as such).  The Domain and attachments travel as base64
cloudpickle, like the reference ships objectives through GridFS — which
means the SAME trust model as the reference: only run a StoreServer for
workers you trust (unpickling is code execution).

Authentication: pass ``token=`` (or ``--token`` / the
``HYPEROPT_TPU_NETSTORE_TOKEN`` environment variable) to both server and
clients and every verb requires the shared secret in the
``X-Netstore-Token`` header, compared constant-time
(``hmac.compare_digest``) BEFORE dispatch — an unauthenticated peer can
neither read documents nor claim/write trials (it gets a 401 and no verb
executes).  Without a token the server remains open, preserving the
localhost-trusted default; set one whenever the socket is reachable
beyond the machines you trust.  The token authenticates the transport —
it does not change the unpickling trust model above.

Reference anchors: ``MongoJobs.reserve`` (find_and_modify ≙ server-side
exclusive claim), ``MongoTrials.refresh`` (cursor fetch ≙ ``docs`` verb),
``hyperopt-mongo-worker`` CLI (≙ ``python -m hyperopt_tpu.parallel.netstore
--worker URL``).
"""

from __future__ import annotations

import base64
import hmac
import json
import logging
import os
import pickle
import random
import threading
import time
import uuid
import zlib
from collections import OrderedDict
from collections.abc import MutableMapping
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.error import HTTPError, URLError
from urllib.request import Request, urlopen

from .filestore import FileTrials, FileWorker, _pickler
from ..base import JOB_STATE_RUNNING, Trials, docs_from_samples
from ..exceptions import InjectedFault, NetstoreUnavailable, QuotaExceeded
from ..obs import bundle as _obs_bundle
from ..obs import context as _context
from ..obs import costs as _obs_costs
from ..obs import device as _obs_device
from ..obs import export as _obs_export
from ..obs import flight as _flight
from ..obs import health as _obs_health
from ..obs import metrics as _metrics
from ..obs import slo as _obs_slo
from ..obs import timeseries as _obs_ts
from ..obs.events import EVENTS
from .. import faults as _faults

logger = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# auth
# ---------------------------------------------------------------------------


def _resolve_token(token: str | None) -> str | None:
    """Effective shared secret: the explicit argument wins, else the
    ``HYPEROPT_TPU_NETSTORE_TOKEN`` environment variable; empty/unset →
    no auth (open server, localhost-trusted default).  Shared by server
    and clients so one env var secures a whole deployment."""
    if token is None:
        token = os.environ.get("HYPEROPT_TPU_NETSTORE_TOKEN") or None
    return token or None


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------


class StoreServer:
    """Serve a local store directory to remote drivers/workers.

    ``serve_forever`` blocks; ``start()`` runs in a daemon thread and
    returns the bound ``(host, port)`` — tests and same-process drivers use
    that.  One lock serializes all verbs: correctness needs no concurrency
    here (each verb is micro-seconds of local file IO; the objective
    evaluations — the actual work — happen client-side in the workers).
    """

    #: Bounds on the idempotency dedup cache (completed mutating calls
    #: kept for replay): LRU capacity + TTL, both env-tunable.  Retries
    #: arrive within seconds of the original, so thousands of entries /
    #: minutes of TTL are generations of headroom — the bound exists so
    #: a long-running fleet's cache cannot grow without limit.
    _IDEM_CAP = 4096
    _IDEM_TTL_S = 900.0

    def __init__(self, root: str, host: str = "127.0.0.1", port: int = 0,
                 token: str | None = None,
                 requeue_stale_every: float | None = None,
                 stale_timeout: float = 60.0,
                 tenants=None,
                 scrape_interval: float | None = None,
                 slos=None):
        self.root = os.path.abspath(root)
        self._trials: dict = {}          # (tenant_name, exp_key) -> store
        self._lock = threading.RLock()
        self._token = _resolve_token(token)
        # Multi-tenant mode: a service.tenancy.TenantTable (anything with
        # .resolve(token) -> tenant).  When set, every verb authenticates
        # as SOME tenant and the dispatch layer namespaces exp_keys into
        # the tenant's own store subtree — the store key derives from the
        # authenticated identity, never from the request body.
        self._tenants = tenants
        # Exactly-once under client retry: (tenant, exp_key, idem_key) ->
        # (t_monotonic, JSON reply) of the first execution.  Stored
        # serialized so a replay can never alias live server-side state;
        # LRU + TTL bounded (netstore.idem.evicted counts expulsions).
        self._idem: OrderedDict = OrderedDict()
        self._idem_lock = threading.Lock()
        # Keys whose first execution is still running: concurrent
        # duplicates park on the Event instead of running the verb again
        # (the check-then-act hole between cache probe and publish).
        self._idem_inflight: dict = {}
        self._idem_cap = int(os.environ.get(
            "HYPEROPT_TPU_NETSTORE_IDEM_CAP", "") or self._IDEM_CAP)
        self._idem_ttl = float(os.environ.get(
            "HYPEROPT_TPU_NETSTORE_IDEM_TTL", "") or self._IDEM_TTL_S)
        # Fleet metrics: worker_id -> {"t": last push wall time, "metrics":
        # the worker's cumulative registry snapshot}.  Workers piggyback
        # snapshots on heartbeats (NetTrials.heartbeat); last-write-wins
        # per worker, merged on read by metrics_payload().  Deliberately
        # NOT part of the local registry, so registry().snapshot(
        # reset=True) by a bench/test never drops the per-worker labels.
        self._fleet: dict = {}
        self._fleet_lock = threading.Lock()
        # Janitor: requeue crashed-worker claims every S seconds so the
        # recovery path runs unprompted (``--requeue-stale-every``).
        self.requeue_stale_every = requeue_stale_every
        self.stale_timeout = stale_timeout
        self._janitor: threading.Thread | None = None
        self._janitor_stop = threading.Event()
        # Observability interpretation layer (obs/): every server owns a
        # time-series store + SLO monitor; the periodic scrape loop that
        # feeds them only runs when ``scrape_interval`` is set (the
        # disabled path costs nothing — no hot-path hooks exist).
        self.scrape_interval = scrape_interval
        self.timeseries = _obs_ts.TimeSeriesStore()
        self.slo_monitor = _obs_slo.SloMonitor(
            slos if slos is not None else _obs_slo.default_slos(),
            self.timeseries)
        self._health_cache: dict | None = None
        self._scraper: threading.Thread | None = None
        self._scraper_stop = threading.Event()
        # Bounded per-tenant label set (LRU): tenant churn would
        # otherwise grow the netstore.tenant.<name>.* families forever.
        self._tenant_labels = _metrics.LabelLru()
        # Flight-bundle sections owned by this server: the time-series
        # window, SLO alert states and cached health verdicts travel in
        # every postmortem dump while the server lives.
        _obs_bundle.register_provider("series", self.timeseries.export_series)
        _obs_bundle.register_provider("slo", self.slo_monitor.status)
        _obs_bundle.register_provider(
            "health", lambda: self._health_cache or {})
        self._started = False
        self._closed = False
        self._lifecycle_lock = threading.Lock()
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):   # quiet by default
                logger.debug("netstore: " + fmt, *args)

            def _send(self, code, body: bytes, ctype: str):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _send_json(self, code, body: bytes):
                self._send(code, body, "application/json")

            def _reject(self):
                _metrics.registry().counter("netstore.auth.rejected").inc()
                self.rfile.read(
                    int(self.headers.get("Content-Length", "0")))
                self._send_json(401, json.dumps(
                    {"error": "AuthError: missing or bad "
                     "X-Netstore-Token"}).encode())

            def _authed(self) -> bool:
                # Auth gate BEFORE the body is parsed or any verb runs:
                # constant-time compare so the secret can't be recovered
                # byte-by-byte from response timing.  The request body is
                # still drained (keep-alive correctness) but never
                # dispatched.  Multi-tenant mode resolves the token to a
                # Tenant (itself a full-table constant-time scan); the
                # tenant identity then namespaces every verb of this
                # request — it comes from the header, never the body.
                self._tenant = None
                if server._tenants is not None:
                    got = self.headers.get("X-Netstore-Token", "")
                    tenant = server._tenants.resolve(got)
                    if tenant is None:
                        self._reject()
                        return False
                    self._tenant = tenant
                    return True
                if server._token is None:
                    return True
                got = self.headers.get("X-Netstore-Token", "")
                if hmac.compare_digest(got.encode(),
                                       server._token.encode()):
                    return True
                self._reject()
                return False

            def do_POST(self):
                if not self._authed():
                    return
                try:
                    n = int(self.headers.get("Content-Length", "0"))
                    req = json.loads(self.rfile.read(n) or b"{}")
                    out = server._dispatch(req, tenant=self._tenant)
                    body = json.dumps(out).encode()
                    code = 200
                except Exception as e:  # surface server faults to the client
                    body = json.dumps(
                        {"error": f"{type(e).__name__}: {e}"}).encode()
                    code = 500
                self._send_json(code, body)

            def do_GET(self):
                # Read-only metrics surface, token-gated like every verb:
                # ``GET /metrics`` returns the process-global registry
                # snapshot (counters/gauges/histograms/kernel_cache) plus
                # the ``fleet`` view (per-worker labeled snapshots pushed
                # on heartbeats + exactly-merged histograms) so an
                # operator can curl the server a driver and workers feed.
                if not self._authed():
                    return
                if self.path.split("?", 1)[0] == "/metrics":
                    payload = server.metrics_payload()
                    # Content negotiation: a standard Prometheus/
                    # OpenMetrics scraper announces itself via Accept
                    # and gets the wire-correct text exposition
                    # (local + fleet-merged series); everything else
                    # keeps the historical JSON document.
                    if _obs_export.wants_openmetrics(
                            self.headers.get("Accept", "")):
                        body = _obs_export.render_openmetrics(
                            payload).encode("utf-8")
                        self._send(200, body, _obs_export.CONTENT_TYPE)
                        return
                    body = json.dumps(payload).encode()
                    self._send_json(200, body)
                    return
                self._send_json(404, json.dumps(
                    {"error": f"NotFound: {self.path}"}).encode())

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._httpd.server_address[:2]

    # -- lifecycle -----------------------------------------------------------

    def start(self):
        self._started = True
        self._start_janitor()
        self._start_scraper()
        t = threading.Thread(target=self._httpd.serve_forever, daemon=True,
                             name="netstore-server")
        t.start()
        return self.host, self.port

    def serve_forever(self):
        self._started = True
        self._start_janitor()
        self._start_scraper()
        self._httpd.serve_forever()

    def shutdown(self):
        """Stop serving and release the socket.

        Idempotent, and safe when ``start()``/``serve_forever()`` never
        ran (``ThreadingHTTPServer.shutdown`` would otherwise block
        forever waiting on a serve loop that does not exist).
        """
        with self._lifecycle_lock:
            if self._closed:
                return
            self._closed = True
        for section in ("series", "slo", "health"):
            _obs_bundle.unregister_provider(section)
        self._janitor_stop.set()
        self._scraper_stop.set()
        if self._janitor is not None:
            self._janitor.join(timeout=5.0)
        if self._scraper is not None:
            self._scraper.join(timeout=5.0)
        if self._started:
            self._httpd.shutdown()
        self._httpd.server_close()

    def _start_janitor(self):
        if not self.requeue_stale_every or self._janitor is not None:
            return
        self._janitor = threading.Thread(target=self._janitor_loop,
                                         daemon=True,
                                         name="netstore-janitor")
        self._janitor.start()

    def _start_scraper(self):
        if not self.scrape_interval or self._scraper is not None:
            return
        self._scraper = threading.Thread(target=self._scraper_loop,
                                         daemon=True,
                                         name="netstore-scraper")
        self._scraper.start()

    def _scraper_loop(self):
        while not self._scraper_stop.wait(self.scrape_interval):
            try:
                self.observe_pass()
            except Exception:    # scraper must outlive any bad series
                logger.exception("netstore scraper: observe pass failed")

    def observe_pass(self, now: float | None = None) -> list:
        """One interpretation tick (the scrape loop's body, callable
        directly by tests and benches): publish device-runtime and
        fleet-liveness gauges, scrape the registry into the time-series
        store, evaluate the SLO monitor, and refresh the cheap
        (history-only) health verdicts the live dashboard shows.
        Returns the SLO status list."""
        _obs_device.collect()
        self._fleet_liveness_gauge()
        self.timeseries.scrape(now=now)
        status = self.slo_monitor.evaluate(now=now)
        try:
            self._health_cache = self._assess_health(introspect=False)
        except Exception:
            logger.exception("netstore scraper: health pass failed")
        return status

    def _fleet_liveness_gauge(self) -> float:
        """Fraction of pushed workers whose last heartbeat is fresh
        (< 30 s, the dashboard's own STALE rule); 1.0 with no fleet.
        Feeds the ``worker_liveness`` SLO via the time-series store."""
        now = time.time()
        with self._fleet_lock:
            ages = [now - rec.get("t", now)
                    for rec in self._fleet.values()]
        live = sum(1 for a in ages if a < 30.0)
        frac = (live / len(ages)) if ages else 1.0
        _metrics.registry().gauge("fleet.live_fraction").set(frac)
        return frac

    def _janitor_loop(self):
        # wait() (not sleep) so shutdown() interrupts a long period
        # immediately; first pass only after one full period.
        while not self._janitor_stop.wait(self.requeue_stale_every):
            try:
                self._janitor_pass()
            except Exception:       # janitor must outlive any bad store
                logger.exception("netstore janitor: requeue_stale failed")

    def _janitor_pass(self):
        # Overridable: the WAL-backed ServiceServer routes these requeues
        # through its log so replay reproduces the janitor's decisions.
        with self._lock:
            stores = list(self._trials.values())
        for ft in stores:
            with self._lock:
                n = ft.requeue_stale(self.stale_timeout)
            if n:
                logger.info("netstore janitor: requeued %d stale "
                            "trial(s) in %r", n, ft._exp_key)

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    # -- verbs ---------------------------------------------------------------

    def _store(self, exp_key: str, tenant=None) -> FileTrials:
        """Caller holds ``self._lock`` (every site: the verb dispatcher
        and the cohort gate's snapshot section take the RLock first).

        Tenant namespacing happens HERE and only here: the store key
        pairs the authenticated tenant name with the client's exp_key,
        and each tenant's files live under their own subtree.  The
        exp_key inside the documents stays the client's own (the doc
        filter ``_exp_key in (None, d["exp_key"])`` must keep matching).
        """
        tname = getattr(tenant, "name", tenant)
        key = (tname, exp_key)
        ft = self._trials.get(key)
        if ft is None:
            root = os.path.join(self.root, tname) if tname else self.root
            ft = self._trials[key] = FileTrials(root, exp_key=exp_key)
        return ft

    def _idem_put(self, key, payload: str):
        evicted = 0
        with self._idem_lock:
            self._idem[key] = (time.monotonic(), payload)
            self._idem.move_to_end(key)
            # Expire from the cold end: TTL first, then LRU overflow.
            now = time.monotonic()
            while self._idem:
                k, (t, _) = next(iter(self._idem.items()))
                if now - t > self._idem_ttl or len(self._idem) > self._idem_cap:
                    self._idem.popitem(last=False)
                    evicted += 1
                else:
                    break
        if evicted:
            _metrics.registry().counter("netstore.idem.evicted").inc(evicted)

    def _idem_execute(self, key, run):
        """At-most-once execution of ``run()`` for idempotency ``key``.

        Returns ``(reply_dict, replayed)``.  The cache probe and the
        in-flight claim are one atomic step under ``_idem_lock``, so two
        concurrent retries of the same key cannot both miss and run the
        verb twice: the loser parks on the winner's Event and re-reads
        the cache once the winner publishes.  If the winner's verb
        raises, nothing is published and the waiter claims the key
        itself — ordinary retry semantics.
        """
        while True:
            with self._idem_lock:
                hit = self._idem.get(key)
                if hit is not None:
                    t, payload = hit
                    if time.monotonic() - t <= self._idem_ttl:
                        self._idem.move_to_end(key)      # LRU touch
                        return json.loads(payload), True
                    del self._idem[key]
                    _metrics.registry().counter("netstore.idem.evicted").inc()
                ev = self._idem_inflight.get(key)
                if ev is None:
                    ev = self._idem_inflight[key] = threading.Event()
                    break
            # A duplicate of an in-flight call: wait for its publish,
            # then loop — cache hit replays it, a failure re-claims.
            ev.wait()
        try:
            out = run()
            self._idem_put(key, json.dumps(out))
            return out, False
        finally:
            with self._idem_lock:
                self._idem_inflight.pop(key, None)
            ev.set()

    def _dispatch(self, req: dict, tenant=None) -> dict:
        verb = req["verb"]
        reg = _metrics.registry()
        t0 = time.perf_counter()
        # Trace context stamped by the client (obs/context.py wire form):
        # adopt it for the duration of the verb so every event this
        # dispatch emits — store_claim/store_write from the filestore,
        # fault injections, the rpc instant below — attaches to the
        # originating trial and trace.
        ctx = req.pop("ctx", None)
        tname = getattr(tenant, "name", None)
        try:
            with _context.adopt(ctx):
                EVENTS.emit("rpc", name=verb)
                idem = req.pop("idem", None)
                if idem is None:
                    return self._dispatch_verb(verb, req, tenant=tenant)
                # Mutating verb with an idempotency key: a retry of a call
                # the server already executed must return the original
                # reply, not run the verb twice (the client retries blind
                # — it cannot know whether the loss was on the way in or
                # out).
                key = (tname, req.get("exp_key", "default"), idem)
                out, replayed = self._idem_execute(
                    key, lambda: self._dispatch_verb(verb, req,
                                                     tenant=tenant,
                                                     idem=idem))
                if replayed:
                    reg.counter("netstore.idem.hits").inc()
                return out
        except Exception as e:
            # Black-box the failing dispatch before the error surfaces
            # to the client (one boolean when the recorder is disarmed).
            _flight.on_crash("dispatch", e)
            raise
        finally:
            # Per-verb call count + latency histogram: the contention
            # signal for the single-writer lock under many workers.
            reg.counter(f"netstore.verb.{verb}.calls").inc()
            reg.histogram(f"netstore.verb.{verb}.s").observe(
                time.perf_counter() - t0)
            if tname is not None:
                # Per-tenant labels for `show live` and quota forensics.
                # The live label set is LRU-bounded: an evicted tenant's
                # whole series family is dropped (recreated from zero on
                # its next call) and obs.series_evicted counts it.
                for old in self._tenant_labels.touch(tname):
                    reg.remove_prefix(f"netstore.tenant.{old}.")
                reg.counter(
                    f"netstore.tenant.{tname}.verb.{verb}.calls").inc()
                reg.histogram(
                    f"netstore.tenant.{tname}.verb.{verb}.s").observe(
                    time.perf_counter() - t0)

    def metrics_payload(self) -> dict:
        """The ``GET /metrics`` document: local snapshot + fleet view.

        Top level keeps the historical registry-snapshot schema
        (enabled/counters/gauges/kernel_cache/histograms — now with
        mergeable ``state`` per histogram, including the server-side
        per-verb latency histograms ``netstore.verb.<verb>.s`` with
        p50/p95/p99) and adds ``fleet``:

        * ``workers`` — per-worker labels: each worker's last pushed
          cumulative snapshot plus ``age_s`` staleness (a worker whose
          age greatly exceeds its heartbeat interval is presumed dead),
        * ``merged`` — counters/gauges summed and histograms
          exactly merged (``obs.metrics.merge_snapshots``) across the
          server's own registry and every pushed worker snapshot.
        """
        snap = _metrics.registry().snapshot(states=True)
        now = time.time()
        with self._fleet_lock:
            fleet = {w: dict(rec) for w, rec in self._fleet.items()}
        workers = {}
        members = [snap]
        for w in sorted(fleet):
            rec = fleet[w]
            m = rec.get("metrics") or {}
            workers[w] = {
                "age_s": round(now - rec.get("t", now), 3),
                "counters": m.get("counters") or {},
                "gauges": m.get("gauges") or {},
                "histograms": m.get("histograms") or {},
            }
            members.append(m)
        snap["fleet"] = {
            "n_workers": len(workers),
            "workers": workers,
            "merged": _metrics.merge_snapshots(members),
        }
        # Interpretation layer: last computed health verdicts (scraper
        # pass or health verb) and current SLO alert state, so `show
        # live` can render HEALTH/ALERTS panels from this one payload.
        if self._health_cache is not None:
            snap["health"] = self._health_cache
        status = self.slo_monitor.status()
        if status:
            snap["alerts"] = status
        # Cost-attribution ledger (armed via HYPEROPT_TPU_COSTS): the
        # service-mode server compiles suggest kernels in-process, so
        # its ledger rows feed the `cost:` panel of `show live`.
        costs = _obs_costs.ledger_report(reg=_metrics.registry())
        if costs.get("entries") or costs.get("armed"):
            snap["costs"] = costs
        return snap

    # -- optimizer health ----------------------------------------------------

    def _assess_health(self, tenant_name=..., exp_key=None,
                       introspect=True) -> dict:
        """Health reports keyed ``"tenant/exp_key"`` (bare ``exp_key``
        in single-tenant mode).  ``tenant_name=...`` means every
        tenant (the scraper's view); a concrete name (or None in
        single-tenant mode) restricts to that namespace.  Store state
        is snapshotted under the server lock; the assessments — which
        may run a backend introspection fit — happen OUTSIDE it, so a
        health probe never stalls serving verbs."""
        items = []
        with self._lock:
            for (tn, ek), ft in list(self._trials.items()):
                if tenant_name is not ... and tn != tenant_name:
                    continue
                if exp_key is not None and ek != exp_key:
                    continue
                export = getattr(ft, "export_docs", None)
                if export is not None:
                    docs = export()
                else:
                    ft.refresh()
                    docs = list(ft._dynamic_trials)
                items.append((tn, ek, ft, docs,
                              getattr(ft, "_srv_last_algo", None)))
        reports = {}
        for tn, ek, ft, docs, algo_name in items:
            label = f"{tn}/{ek}" if tn else ek
            domain = suggest_fn = None
            if introspect and algo_name:
                suggest_fn = self._server_algos().get(algo_name)
                try:
                    domain = self._domain_for(ft)
                except Exception:
                    logger.debug("health: domain introspection failed "
                                 "for %s; assessing without it",
                                 ek, exc_info=True)
                    domain = None
            rep = _obs_health.assess(
                docs, domain=domain, trials=ft, suggest_fn=suggest_fn,
                introspect=introspect)
            rep["algo"] = algo_name
            _obs_health.publish(label, rep)
            reports[label] = rep
        return reports

    def _health_verb(self, req: dict, tenant=None) -> dict:
        """The read-only ``health`` verb body: fresh assessments
        (introspection included unless ``introspect: false``) for the
        caller's namespace — all of the tenant's experiments with
        ``all: true``, else just the request's ``exp_key``."""
        tname = getattr(tenant, "name", tenant)
        exp_key = None if req.get("all") else req.get("exp_key", "default")
        reports = self._assess_health(
            tenant_name=tname, exp_key=exp_key,
            introspect=bool(req.get("introspect", True)))
        self._health_cache = dict(self._health_cache or {}, **reports)
        return reports

    # -- tenant quotas -------------------------------------------------------

    def _charge_admission(self, tenant, n: int) -> None:
        """Charge ``n`` trial creations against the tenant's rate quota
        (token bucket); raises :class:`QuotaExceeded` on refusal.  Runs
        BEFORE any WAL append or execution — a refused call leaves no
        trace in durable state."""
        admit = getattr(tenant, "admit_trials", None)
        if admit is None or admit(int(n)):
            return
        tname = getattr(tenant, "name", "?")
        _metrics.registry().counter(
            f"netstore.tenant.{tname}.quota.rate_rejected").inc()
        raise QuotaExceeded(
            f"tenant {tname!r}: trials/s admission quota exceeded "
            f"(rate={getattr(tenant, 'trials_per_s', None)}, asked {n})")

    def _claims_quota_hit(self, tenant) -> bool:
        """True when the tenant already holds ``max_claims`` RUNNING
        trials across all of its experiments (reserve must answer
        queue-empty so stock workers back off via their poll loop)."""
        limit = getattr(tenant, "max_claims", None)
        if limit is None:
            return False
        tname = getattr(tenant, "name", None)
        held = 0
        for (tn, _), ft in self._trials.items():
            if tn != tname:
                continue
            ft.refresh()
            held += sum(1 for d in ft._dynamic_trials
                        if d["state"] == JOB_STATE_RUNNING)
        reg = _metrics.registry()
        reg.gauge(f"netstore.tenant.{tname}.claims_held").set(held)
        if held >= limit:
            reg.counter(
                f"netstore.tenant.{tname}.quota.claims_rejected").inc()
            return True
        return False

    def _dispatch_verb(self, verb: str, req: dict, tenant=None,
                       idem=None) -> dict:
        if verb == "metrics":
            # Same payload as GET /metrics so RPC clients
            # (NetTrials.metrics) don't need a second transport.
            return {"metrics": self.metrics_payload()}
        if verb == "health":
            # Read-only interpretation verb: per-(tenant, exp_key)
            # optimizer-health verdicts.  Never WAL-logged (not in
            # ServiceServer._WAL_VERBS) and never mutates a store.
            return {"health": self._health_verb(req, tenant=tenant)}
        if verb == "bundle":
            # Read-only flight pull: the full postmortem payload (events
            # ring + meta anchor, metrics, provider sections, redacted
            # env) so an operator lands a remote shard's black box on
            # local disk (bundle.write_payload) without shelling in.
            # Never WAL-logged, never touches a store, token-gated like
            # every verb.
            return {"bundle": _obs_bundle.collect_payload(
                "verb", extra={"trigger": "verb",
                               "tenant": getattr(tenant, "name", None)})}
        with self._lock:
            ft = self._store(req.get("exp_key", "default"), tenant=tenant)
            if verb == "docs":
                export = getattr(ft, "export_docs", None)
                if export is not None:
                    return {"docs": export()}
                ft.refresh()
                return {"docs": ft._dynamic_trials}
            if verb == "insert_docs":
                self._charge_admission(tenant, len(req["docs"]))
                return {"tids": ft._insert_trial_docs(req["docs"])}
            if verb == "new_trial_ids":
                ft.refresh()
                return {"tids": ft.new_trial_ids(int(req["n"]))}
            if verb == "reserve":
                if self._claims_quota_hit(tenant):
                    return {"doc": None, "quota": "max_claims"}
                return {"doc": ft.reserve(req["owner"])}
            if verb == "suggest":
                return self._suggest_verb(ft, req, tenant)
            if verb == "heartbeat":
                # Piggybacked fleet metrics: a worker may attach its
                # cumulative registry snapshot (last-write-wins per
                # worker id; merged on read by metrics_payload).  The
                # reply carries the server wall clock so clients can
                # estimate their skew for trace stitching.
                w = req.get("worker")
                if w is not None and req.get("metrics") is not None:
                    with self._fleet_lock:
                        self._fleet[w] = {"t": time.time(),
                                          "metrics": req["metrics"]}
                    _metrics.registry().counter(
                        "netstore.fleet.pushes").inc()
                return {"ok": ft.heartbeat(req["doc"],
                                           owner=req.get("owner")),
                        "t_wall": time.time()}
            if verb == "write_result":
                return {"ok": ft.write_result(req["doc"],
                                              owner=req.get("owner"))}
            if verb == "requeue_stale":
                return {"n": ft.requeue_stale(float(req["timeout"]))}
            if verb == "delete_all":
                ft.delete_all()
                return {"ok": True}
            if verb == "put_domain":
                ft.put_domain_blob(base64.b64decode(req["blob"]))
                return {"ok": True}
            if verb == "get_domain":
                blob = ft.get_domain_blob()
                if blob is None:
                    return {"blob": None}
                return {"blob": base64.b64encode(blob).decode()}
            if verb == "att_set":
                ft.attachments[req["key"]] = pickle.loads(
                    base64.b64decode(req["blob"]))
                return {"ok": True}
            if verb == "att_get":
                try:
                    val = ft.attachments[req["key"]]
                except KeyError:
                    return {"blob": None}
                return {"blob": base64.b64encode(
                    _pickler.dumps(val)).decode()}
            if verb == "att_del":
                try:
                    del ft.attachments[req["key"]]
                    return {"ok": True}
                except KeyError:
                    return {"ok": False}
            if verb == "att_keys":
                return {"keys": list(ft.attachments)}
            raise ValueError(f"unknown verb {verb!r}")

    # -- server-side suggest -------------------------------------------------

    #: Keyword arguments a suggest request may forward to the algorithm.
    #: A whitelist, not **kw passthrough: the wire is untrusted relative
    #: to the algorithm signatures, and an unknown key should 500 here
    #: with a clear message rather than TypeError deep inside TPE.
    _SUGGEST_KW = frozenset({
        "prior_weight", "n_startup_jobs", "n_EI_candidates", "gamma",
        "linear_forgetting", "split", "multivariate", "startup",
        "cat_prior", "popsize", "sigma0", "lr", "rank_shaping"})

    _ALGOS = None

    @classmethod
    def _server_algos(cls):
        """Lazy algorithm table from the backend registry
        (``hyperopt_tpu.backends.contract.server_table``): every
        registered head — builtins and ``register_backend`` additions —
        is servable by name, with console verbosity suppressed where the
        head supports it.  Imports happen on first suggest, keeping
        plain-store servers free of the JAX import.

        Registry heads are dispatch + immediate materialize by the
        SuggestBackend contract, so server and client proposals are
        bit-identical for the same (history, seed).
        """
        if cls._ALGOS is None:
            from ..backends import contract as _backends

            cls._ALGOS = _backends.server_table()
        return cls._ALGOS

    @staticmethod
    def _domain_for(ft):
        """Unpickle the store's published domain, cached on the store by
        (len, crc32) of the blob so repeated suggests don't re-unpickle —
        but a re-published domain (new blob) invalidates naturally."""
        blob = ft.get_domain_blob()
        if blob is None:
            raise FileNotFoundError(
                "suggest: no domain published for "
                f"exp_key={ft._exp_key!r} (driver must save_domain first)")
        sig = (len(blob), zlib.crc32(blob))
        cached = getattr(ft, "_srv_domain", None)
        if cached is not None and cached[0] == sig:
            return cached[1]
        domain = pickle.loads(blob)
        ft._srv_domain = (sig, domain)
        return domain

    def _suggest_verb(self, ft, req: dict, tenant=None) -> dict:
        """Server-side proposal: run the algorithm against the server's
        own store (which feeds the device-resident history ring exactly
        like a client-side Trials would) and optionally insert the docs.

        Thin-client protocol: the driver only needs ``suggest`` (with
        insert), ``docs`` and the result verbs — no JAX client-side.

        ``_fleet_rows`` carries pre-computed proposal rows from the
        ServiceServer cohort gate's fleet dispatch, so this verb only
        packages docs instead of running the algorithm again.  A wire
        client supplying it merely dictates its own proposals — the same
        privilege ``insert_docs`` already grants — so it needs no trust
        boundary beyond the normal auth gate.
        """
        fleet_rows = req.pop("_fleet_rows", None)
        algo_name = req.get("algo", "tpe")
        # Memo for the health verb: which head last served this store
        # (its introspection hook is the one worth running).
        ft._srv_last_algo = algo_name
        algo = self._server_algos().get(algo_name)
        if algo is None:
            from ..backends import UnknownBackend

            raise UnknownBackend(
                f"suggest: unknown algo {algo_name!r} "
                f"(have {sorted(self._server_algos())})")
        if "seed" not in req:
            raise ValueError("suggest: 'seed' is required — the server "
                             "must not invent entropy the driver cannot "
                             "reproduce")
        kw = {k: req[k] for k in self._SUGGEST_KW if k in req}
        bad = set(req) - self._SUGGEST_KW - {
            "verb", "exp_key", "algo", "seed", "n", "new_ids", "insert"}
        if bad:
            raise ValueError(f"suggest: unknown argument(s) {sorted(bad)}")
        new_ids = req.get("new_ids")
        if new_ids is None:
            # Server-allocated ids default to inserting (the enqueue
            # form); explicit ids default to proposal-only (the driver
            # owns the insert, e.g. fmin's algo adapter).
            insert = bool(req.get("insert", True))
            ft.refresh()
            new_ids = ft.new_trial_ids(int(req.get("n", 1)))
        else:
            insert = bool(req.get("insert", False))
            new_ids = [int(t) for t in new_ids]
        if insert:
            self._charge_admission(tenant, len(new_ids))
        domain = self._domain_for(ft)
        ft.refresh()
        if fleet_rows is not None:
            import numpy as _np

            rows = _np.asarray(fleet_rows, _np.float32)[: len(new_ids)]
            acts = domain.cs.active_mask_host(rows)
            docs = docs_from_samples(domain.cs, new_ids, rows, acts,
                                     exp_key=getattr(ft, "exp_key", None))
        else:
            docs = algo(new_ids, domain, ft, int(req["seed"]), **kw)
        # JSON roundtrip now, inside the lock: the reply the client sees
        # is exactly what a WAL replay would re-insert, and the docs the
        # server stores are plain JSON types like every other doc.
        docs = json.loads(json.dumps(docs))
        tids = list(new_ids)
        if insert and docs:
            tids = ft._insert_trial_docs(docs)
        return {"docs": docs, "tids": tids, "inserted": bool(insert)}


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------


#: Verbs that change server state: each call carries a fresh idempotency
#: key, reused verbatim across retries so the server executes it once.
_MUTATING_VERBS = frozenset(
    {"new_trial_ids", "insert_docs", "reserve", "write_result", "suggest"})

#: Mutating verbs that are retry-convergent without a key: re-executing
#: the request converges on the same durable state (heartbeat refreshes a
#: timestamp to the same pinned clock, requeue_stale is a fixpoint scan,
#: delete_all/put_domain/att_set/att_del overwrite or clear absolutely),
#: so retries need no idempotency cache entry.  Every mutating verb must
#: be in exactly one of these two catalogs (the WP004/WP006 analyzers
#: reconcile both directions against the dispatcher arms).
_IDEMPOTENT_VERBS = frozenset(
    {"heartbeat", "requeue_stale", "delete_all", "put_domain",
     "att_set", "att_del"})

_BACKOFF_CAP_S = 2.0


class _Rpc:
    """One-POST-per-call JSON client (stdlib urllib; connection reuse is not
    worth a dependency at this call volume).

    Transport failures (socket refused/reset/timeout, i.e. ``URLError``
    without an HTTP reply) are retried up to ``retries`` times with
    exponential backoff + deterministic jitter; exhaustion raises the typed
    :class:`~hyperopt_tpu.exceptions.NetstoreUnavailable`.  Server-reported
    errors (the server answered, with a fault) stay ``RuntimeError`` and
    are never retried — retrying a deliberate refusal (auth, bad verb)
    only hammers the server.
    """

    def __init__(self, url: str, exp_key: str, timeout: float = 30.0,
                 token: str | None = None, retries: int | None = None,
                 backoff: float | None = None):
        self.url = url.rstrip("/")
        self.exp_key = exp_key
        self.timeout = timeout
        self.token = _resolve_token(token)
        if retries is None:
            retries = int(os.environ.get(
                "HYPEROPT_TPU_NETSTORE_RETRIES", "5") or "5")
        self.retries = max(0, int(retries))
        if backoff is None:
            backoff = float(os.environ.get(
                "HYPEROPT_TPU_NETSTORE_BACKOFF", "0.05") or "0.05")
        self.backoff = float(backoff)
        # Deterministic jitter stream per client identity: spreads thundering
        # retries across workers without making test runs irreproducible.
        self._jitter = random.Random(
            zlib.crc32(f"{self.url}|{exp_key}".encode()))

    def __call__(self, verb: str, **kw) -> dict:
        kw.update(verb=verb, exp_key=self.exp_key)
        if verb in _MUTATING_VERBS and "idem" not in kw:
            # One key per logical call, shared by every retry of it.
            # Routed callers pre-pin the key instead, so a retry that
            # crosses a shard failover still dedupes on the promoted
            # replica (the shipped WAL record repopulated its cache).
            kw["idem"] = uuid.uuid4().hex
        # Trace-context stamp (obs/context.py): when the caller runs
        # inside a bound context (a traced driver batch, a worker
        # evaluating a stamped doc), the compact wire string rides along
        # so the server's events attach to the same trial.  Disarmed
        # cost: one module-global boolean check.
        if _context.armed():
            ctx = _context.wire_current()
            if ctx is not None:
                kw["ctx"] = ctx
        headers = {"Content-Type": "application/json"}
        if self.token is not None:
            headers["X-Netstore-Token"] = self.token
        data = json.dumps(kw).encode()
        attempts = 0
        t_start = time.perf_counter()
        while True:
            try:
                _faults.maybe_fail("rpc.send", verb=verb)
                req = Request(self.url, data=data, headers=headers)
                with urlopen(req, timeout=self.timeout) as resp:
                    raw = resp.read()
                _faults.maybe_fail("rpc.recv", verb=verb)
                out = json.loads(raw)
                break
            except HTTPError as e:
                # Non-2xx (500 server fault, 401 auth) carries the JSON
                # error body; surface it as the RuntimeError the callers
                # expect.  The server DID answer — no retry.
                try:
                    out = json.loads(e.read())
                except Exception:
                    out = {"error": f"HTTP {e.code}"}
                break
            except (URLError, OSError, InjectedFault) as e:
                attempts += 1
                _metrics.registry().counter("netstore.rpc.retry").inc()
                if attempts > self.retries:
                    _metrics.registry().counter(
                        "netstore.rpc.unavailable").inc()
                    raise NetstoreUnavailable(
                        f"netstore {self.url} unreachable after "
                        f"{attempts} attempt(s) ({verb}): {e}",
                        attempts=attempts) from e
                delay = min(self.backoff * (2 ** (attempts - 1)),
                            _BACKOFF_CAP_S)
                time.sleep(delay * (0.5 + self._jitter.random()))
        # Client-observed RPC latency (retries and backoff included) —
        # the worker-side twin of the server's per-verb histograms;
        # piggybacked to the server with the fleet snapshots.
        _metrics.registry().histogram("netstore.client.rpc.s").observe(
            time.perf_counter() - t_start)
        if "error" in out:
            if out["error"].startswith("QuotaExceeded"):
                # Typed so drivers can back off deliberately; NOT in
                # TRANSIENT_ERRORS — blind retry of a rate refusal is
                # exactly the traffic the quota exists to shed.
                raise QuotaExceeded(f"netstore server: {out['error']}")
            raise RuntimeError(f"netstore server: {out['error']}")
        return out


class _NetAttachments(MutableMapping):
    """RPC-backed attachments mapping (GridFS-over-HTTP analog)."""

    def __init__(self, rpc: _Rpc):
        self._rpc = rpc

    def __setitem__(self, key, value):
        self._rpc("att_set", key=str(key),
                  blob=base64.b64encode(_pickler.dumps(value)).decode())

    def __getitem__(self, key):
        blob = self._rpc("att_get", key=str(key))["blob"]
        if blob is None:
            raise KeyError(key)
        return pickle.loads(base64.b64decode(blob))

    def __delitem__(self, key):
        if not self._rpc("att_del", key=str(key))["ok"]:
            raise KeyError(key)

    def __iter__(self):
        return iter(self._rpc("att_keys")["keys"])

    def __len__(self):
        return len(self._rpc("att_keys")["keys"])


class NetTrials(Trials):
    """Async ``Trials`` over a :class:`StoreServer` URL (MongoTrials analog:
    same surface as :class:`~.filestore.FileTrials`, transport swapped from
    shared mount to HTTP)."""

    asynchronous = True

    #: Minimum seconds between cumulative-snapshot piggybacks on heartbeat
    #: calls (the fleet-metrics push cadence; tests shrink it).  Snapshots
    #: are cumulative — the server keeps last-write-wins per worker — so
    #: a lost push costs staleness, never data.
    metrics_push_interval = 2.0

    def __init__(self, url: str, exp_key: str = "default", refresh=True,
                 timeout: float = 30.0, token: str | None = None,
                 retries: int | None = None):
        self._rpc = _Rpc(url, exp_key, timeout=timeout, token=token,
                         retries=retries)
        self._last_metrics_push = float("-inf")
        super().__init__(exp_key=exp_key, refresh=refresh)
        self.attachments = _NetAttachments(self._rpc)

    # -- document IO over RPC ------------------------------------------------

    def refresh(self):
        with self._lock:
            docs = self._rpc("docs")["docs"]
            docs.sort(key=lambda d: d["tid"])
            self._dynamic_trials = docs
            self._ids = {d["tid"] for d in docs}
            self._trials = [d for d in docs
                            if self._exp_key in (None, d.get("exp_key"))]

    def _insert_trial_docs(self, docs):
        return self._rpc("insert_docs", docs=list(docs))["tids"]

    def new_trial_ids(self, n):
        return self._rpc("new_trial_ids", n=int(n))["tids"]

    def delete_all(self):
        self._rpc("delete_all")
        super().delete_all()
        self.attachments = _NetAttachments(self._rpc)

    # -- worker/claim surface (server-side atomicity) ------------------------

    def reserve(self, owner: str):
        return self._rpc("reserve", owner=owner)["doc"]

    def heartbeat(self, doc, owner=None) -> bool:
        kw = {"doc": doc, "owner": owner}
        now = time.monotonic()
        if (owner is not None
                and now - self._last_metrics_push
                >= self.metrics_push_interval):
            # Piggyback this process's cumulative metrics snapshot
            # (histograms in mergeable state form) on the beat — no
            # extra RPC, and the push cadence is bounded by the
            # heartbeat interval itself.
            self._last_metrics_push = now
            kw["worker"] = owner
            kw["metrics"] = _metrics.registry().snapshot(states=True)
        t0 = time.time()
        out = self._rpc("heartbeat", **kw)
        t_server = out.get("t_wall")
        if t_server is not None:
            # NTP-style midpoint estimate of this process's wall-clock
            # offset from the server (positive = we are ahead).  Stamped
            # into the event-log header so `show trace --merge` can
            # normalize this process's lane onto the server clock.
            skew = 0.5 * (t0 + time.time()) - t_server
            _metrics.registry().gauge("clock.skew_s").set(skew)
            EVENTS.set_meta(skew_s=skew)
        return out["ok"]

    def write_result(self, doc, owner=None) -> bool:
        return self._rpc("write_result", doc=doc, owner=owner)["ok"]

    def requeue_stale(self, timeout: float) -> int:
        return self._rpc("requeue_stale", timeout=float(timeout))["n"]

    def metrics(self) -> dict:
        """Server-side metrics registry snapshot (``GET /metrics`` twin)."""
        return self._rpc("metrics")["metrics"]

    def health(self, all: bool = False, introspect: bool = True) -> dict:
        """Per-experiment optimizer-health verdicts (read-only verb):
        ``{label: report}`` with ``report["verdict"]`` in
        ``obs.health.VERDICTS``.  ``all=True`` widens from this client's
        exp_key to every experiment in the caller's tenant namespace;
        ``introspect=False`` skips the backend surrogate diagnostics."""
        kw = {"introspect": introspect}
        if all:
            kw["all"] = True
        return self._rpc("health", **kw)["health"]

    def bundle(self, out_dir: str | None = None) -> dict:
        """Pull the server's flight-recorder payload (read-only verb).

        Returns the bundle payload dict; with ``out_dir`` also writes it
        as an on-disk bundle directory (the exact form a local flight
        dump produces, so ``show bundle`` / ``show trace --merge``
        consume it unchanged)."""
        payload = self._rpc("bundle")["bundle"]
        if out_dir:
            _obs_bundle.write_payload(out_dir, payload)
        return payload

    # -- server-side suggest -------------------------------------------------

    def suggest(self, seed: int, n: int | None = None, new_ids=None,
                algo: str = "tpe", insert: bool | None = None, **kw):
        """Ask the SERVER to propose trials (thin-client protocol).

        The server runs the algorithm against its own store — for TPE,
        ``suggest_dispatch`` + materialize over the device-resident
        history ring, bit-identical to client-side ``tpe.suggest`` for
        the same (history, seed).  Two forms:

        * ``suggest(seed, n=8)`` — server allocates ids and INSERTS the
          proposals (one RPC enqueues a whole batch); returns the docs.
        * ``suggest(seed, new_ids=[...], insert=False)`` — proposal
          only, driver owns the insert (what :func:`server_suggest`
          uses to slot into ``fmin`` as an algo).
        """
        req = dict(seed=int(seed), algo=algo, **kw)
        if new_ids is not None:
            req["new_ids"] = [int(t) for t in new_ids]
        elif n is not None:
            req["n"] = int(n)
        if insert is not None:
            req["insert"] = bool(insert)
        return self._rpc("suggest", **req)["docs"]

    # -- domain shipping -----------------------------------------------------

    def save_domain(self, domain) -> None:
        self._rpc("put_domain",
                  blob=base64.b64encode(_pickler.dumps(domain)).decode())

    def load_domain(self):
        blob = self._rpc("get_domain")["blob"]
        if blob is None:
            raise FileNotFoundError("no domain published for "
                                    f"exp_key={self._exp_key!r}")
        return pickle.loads(base64.b64decode(blob))

    def fmin(self, fn, space, algo, max_evals, **kwargs):
        from ..base import Domain
        try:
            self.save_domain(Domain(fn, space,
                                    pass_expr_memo_ctrl=kwargs.get(
                                        "pass_expr_memo_ctrl")))
        except (pickle.PicklingError, AttributeError, TypeError) as e:
            logger.warning("objective not picklable (%s); workers must be "
                           "given the domain explicitly", e)
        return super().fmin(fn, space, algo, max_evals, **kwargs)


def server_suggest(new_ids, domain, trials, seed, algo: str = "tpe", **kw):
    """``fmin``-shaped algo that delegates the proposal to the server.

    Drop-in for ``algo=`` against a :class:`NetTrials`: the domain
    argument is ignored (the server uses the blob the driver published
    via ``save_domain``), ids and seed flow through unchanged, and the
    returned docs are exactly what the server computed — so a pinned
    seeded run matches client-side ``tpe.suggest`` document-for-document.
    """
    if not isinstance(trials, NetTrials):
        raise TypeError("server_suggest needs a NetTrials "
                        f"(got {type(trials).__name__})")
    return trials.suggest(seed, new_ids=new_ids, algo=algo, insert=False,
                          **kw)


class NetWorker(FileWorker):
    """`FileWorker` over the network store: the identical
    reserve→evaluate→heartbeat→write loop, claims arbitrated server-side.
    ``token`` (or the env secret) authenticates every verb against a
    token-protected :class:`StoreServer`."""

    def __init__(self, url, exp_key="default", token: str | None = None,
                 **kwargs):
        # Resolved before super().__init__ — which calls _make_trials.
        self._token = _resolve_token(token)
        super().__init__(url, exp_key=exp_key, **kwargs)

    def _make_trials(self, url, exp_key):
        return NetTrials(url, exp_key=exp_key, token=self._token)


# ---------------------------------------------------------------------------
# Router-aware client (sharded fleet, service/router.py)
# ---------------------------------------------------------------------------


class _RoutedRpc:
    """:class:`_Rpc` facade that places itself via a router's shard map.

    Fetches the ``shard_map`` verb from the router at construction and
    re-fetches every ``HYPEROPT_TPU_SHARDMAP_REFRESH_S`` seconds (or on
    transport failure), computes the owning shard for this client's
    ``(tenant, exp_key)`` with the same pinned hash the router uses
    (``service/cluster.py``), and then speaks to the owning primary
    **directly** — the router serves topology, not the data path.

    Failover: a :class:`NetstoreUnavailable` from the shard forces a map
    refresh (the router promotes the replica on its side) and one retry
    against the new primary, with the **same** idempotency key pinned
    before the first attempt — the promoted replica either replays the
    shipped record's cached reply or executes the verb for the first
    time, so the retry is exactly-once either way.
    """

    def __init__(self, router_url: str, exp_key: str,
                 timeout: float = 30.0, token: str | None = None,
                 retries: int | None = None,
                 map_refresh_s: float | None = None):
        self._router = _Rpc(router_url, exp_key, timeout=timeout,
                            token=token, retries=retries)
        self.exp_key = exp_key
        self.timeout = timeout
        self.token = _resolve_token(token)
        self._retries = retries
        if map_refresh_s is None:
            map_refresh_s = float(os.environ.get(
                "HYPEROPT_TPU_SHARDMAP_REFRESH_S", "30") or "30")
        self.map_refresh_s = float(map_refresh_s)
        self._lock = threading.Lock()
        self._shard_rpc = None
        self.shard_id = None
        self.tenant = None
        self.map_version = None
        self._map_t = float("-inf")
        self._refresh_map(force=True)

    @property
    def url(self) -> str:
        """The owning shard primary's URL (moves under failover)."""
        with self._lock:
            return self._shard_rpc.url

    def _refresh_map(self, force: bool = False) -> None:
        with self._lock:
            if (not force and time.monotonic() - self._map_t
                    < self.map_refresh_s):
                return
            out = self._router("shard_map")
            from ..service.cluster import ShardMap
            smap = ShardMap.from_dict(out["map"])
            self.tenant = out.get("tenant")
            sid, ent = smap.owner(self.tenant, self.exp_key)
            self._map_t = time.monotonic()
            self.map_version = smap.version
            if (self._shard_rpc is None or self.shard_id != sid
                    or self._shard_rpc.url != ent["primary"]):
                self._shard_rpc = _Rpc(ent["primary"], self.exp_key,
                                       timeout=self.timeout,
                                       token=self.token,
                                       retries=self._retries)
                self.shard_id = sid

    def __call__(self, verb: str, **kw) -> dict:
        self._refresh_map()
        if verb in _MUTATING_VERBS and "idem" not in kw:
            # Pinned HERE so the post-failover retry below reuses it.
            kw["idem"] = uuid.uuid4().hex
        with self._lock:
            rpc = self._shard_rpc
        try:
            return rpc(verb, **kw)
        except NetstoreUnavailable:
            # Primary gone — and since the data path is direct, the
            # router may not know yet.  Push this very verb THROUGH the
            # router: its forward path retries, promotes the warm
            # replica and answers from the new primary.  The idem key
            # pinned above rides both attempts, so the retry dedupes if
            # the dead primary shipped the record before the kill.
            _metrics.registry().counter("netstore.client.reroutes").inc()
            out = self._router(verb, **kw)
            try:
                self._refresh_map(force=True)    # re-place future calls
            except (NetstoreUnavailable, RuntimeError, OSError):
                pass                 # best effort; next call retries it
            return out


class RouterTrials(NetTrials):
    """:class:`NetTrials` behind the fleet router (``service/router.py``).

    Same surface, different placement: ``url`` is the ROUTER's URL; the
    client pulls the shard map from it, hashes its own ``(tenant,
    exp_key)`` onto the ring, and talks to the owning shard primary
    directly, re-placing itself after failover or rebalance (see
    :class:`_RoutedRpc`).  ``token`` authenticates against both the
    router (edge) and the shard (authority).
    """

    def __init__(self, url: str, exp_key: str = "default", refresh=True,
                 timeout: float = 30.0, token: str | None = None,
                 retries: int | None = None,
                 map_refresh_s: float | None = None):
        self._rpc = _RoutedRpc(url, exp_key, timeout=timeout,
                               token=token, retries=retries,
                               map_refresh_s=map_refresh_s)
        self._last_metrics_push = float("-inf")
        Trials.__init__(self, exp_key=exp_key, refresh=refresh)
        self.attachments = _NetAttachments(self._rpc)

    @property
    def shard_id(self):
        """The shard currently owning this client's store."""
        return self._rpc.shard_id


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def main(argv=None):
    """``--serve``: host a store directory; ``--worker URL``: evaluate jobs
    from a remote store (reference: ``hyperopt-mongo-worker`` against a
    mongod URL)."""
    import argparse

    p = argparse.ArgumentParser(description="hyperopt_tpu network store")
    sub = p.add_mutually_exclusive_group(required=True)
    sub.add_argument("--serve", action="store_true",
                     help="serve --root on --host:--port")
    sub.add_argument("--worker", metavar="URL",
                     help="run a worker against a StoreServer URL")
    p.add_argument("--root", default=None, help="store dir (server mode)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8417)
    p.add_argument("--exp-key", default="default")
    p.add_argument("--token", default=None,
                   help="shared secret for every verb (default: the "
                        "HYPEROPT_TPU_NETSTORE_TOKEN env var; unset = "
                        "open server)")
    p.add_argument("--poll-interval", type=float, default=0.1)
    p.add_argument("--reserve-timeout", type=float, default=None)
    p.add_argument("--max-consecutive-failures", type=int, default=4)
    p.add_argument("--max-trial-retries", type=int, default=0,
                   help="worker mode: in-place re-evaluations of a trial "
                        "after a transient failure before it is marked "
                        "ERROR (default 0 = fail fast)")
    p.add_argument("--requeue-stale-every", type=float, default=None,
                   metavar="S",
                   help="server mode: janitor period — requeue claims whose "
                        "heartbeat went stale, every S seconds (default: "
                        "janitor off; clients may still call requeue_stale)")
    p.add_argument("--stale-timeout", type=float, default=60.0,
                   help="server mode: heartbeat age beyond which the "
                        "janitor treats a claim as crashed (default 60s; "
                        "keep well above the workers' heartbeat interval)")
    p.add_argument("--workdir", default=None)
    p.add_argument("--trace-dir", default=None,
                   help="arm the structured event log and write "
                        "loop_events.jsonl (+ chrome trace) here on exit; "
                        "feed several processes' dirs to "
                        "`hyperopt-tpu-show trace --merge`")
    p.add_argument("--flight-dir", default=None,
                   help="arm the flight recorder: freeze a postmortem "
                        "bundle here on SLO alert fire, unhandled verb "
                        "error or SIGTERM (default: the "
                        "HYPEROPT_TPU_FLIGHT_DIR env var; unset = off)")
    args = p.parse_args(argv)

    if args.serve:
        if not args.root:
            p.error("--serve requires --root")
        tracer = None
        if args.trace_dir:
            from ..obs.trace import Tracer
            tracer = Tracer(args.trace_dir)
            EVENTS.set_meta(role="server")
        server = StoreServer(args.root, host=args.host, port=args.port,
                             token=args.token,
                             requeue_stale_every=args.requeue_stale_every,
                             stale_timeout=args.stale_timeout)
        print(f"netstore: serving {args.root} at {server.url}", flush=True)

        # Graceful stop on SIGTERM (systemd/k8s default kill signal):
        # raise out of serve_forever on the main thread, then shut down in
        # the finally.  shutdown() must not run inside the handler — it
        # joins the serve loop that the handler interrupted.
        import signal

        def _on_sigterm(signo, frame):
            raise SystemExit(0)

        try:
            signal.signal(signal.SIGTERM, _on_sigterm)
        except ValueError:          # not the main thread (embedded use)
            pass
        # Arm AFTER the SIGTERM handler so the flight handler chains it:
        # a TERM first freezes the bundle, then the graceful exit runs.
        flight_dir = _flight.install(args.flight_dir)
        if flight_dir:
            print(f"netstore: flight recorder armed -> {flight_dir}",
                  flush=True)
        try:
            server.serve_forever()
        except (KeyboardInterrupt, SystemExit):
            pass
        finally:
            server.shutdown()
            if tracer is not None:
                tracer.dump()
            print("netstore: shut down", flush=True)
        return 0

    worker = NetWorker(args.worker, exp_key=args.exp_key, token=args.token,
                       poll_interval=args.poll_interval,
                       reserve_timeout=args.reserve_timeout,
                       max_consecutive_failures=args.max_consecutive_failures,
                       max_trial_retries=args.max_trial_retries,
                       workdir=args.workdir, trace_dir=args.trace_dir)
    n = worker.run()
    logger.info("net worker done: %d trials evaluated", n)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
