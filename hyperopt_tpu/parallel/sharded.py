"""Device-mesh execution of the TPE suggest step.

Two axes of scale (SURVEY.md §5.7-5.8 — the "long axis" of this framework is
the EI candidate batch, and the data-parallel axis is independent posteriors):

* ``ShardedTpeKernel`` — shards the **candidate axis** of the EI sweep over a
  ``jax.sharding.Mesh``: candidates are drawn, scored ([n_cand, K] logsumexp
  blocks) and arg-maxed with the candidate axis split across devices; XLA
  inserts the ICI collectives for the final argmax reduce.  This is how a
  100k-candidate × 50-dim sweep (BASELINE.md config 5) fits in per-chip HBM
  and scales across a slice.

* ``multi_start_suggest`` — runs **K independent TPE posteriors** (distinct
  RNG streams over the same history) one per mesh slot via ``shard_map``,
  yielding K diverse proposals in one device program: the TPU-native
  equivalent of the reference's parallel-trial backends for batched
  ``fmin(max_queue_len=K)`` (BASELINE.md config 4; reference analog:
  ``SparkTrials`` thread-per-trial, SURVEY.md §3.5 — but here the *suggest*
  itself is parallel, which the reference never does).

Works identically on a real TPU slice and on the virtual 8-device CPU mesh
used by tests (``--xla_force_host_platform_device_count``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import base
from .. import history as _rhist
from ..space import CompiledSpace, prng_key
from ..tpe import (
    _TpeKernel,
    _batch_size_for,
    _bucket,
    _inflight_fantasy_rows,
    _with_inflight_fantasies,
    _default_gamma,
    _default_linear_forgetting,
    _default_n_EI_candidates,
    _default_n_startup_jobs,
    _default_prior_weight,
    _padded_history,
)
from .. import rand

CAND_AXIS = "sp"    # candidate (sequence-like long) axis
START_AXIS = "dp"   # independent-posterior (data-parallel) axis


def _shard_map(f, mesh, in_specs, out_specs):
    """``jax.shard_map`` with a jax-0.4.x fallback.

    ``shard_map`` graduated from ``jax.experimental`` only in jax 0.5;
    on 0.4.x the top-level symbol is absent and the replication-check
    kwarg is still spelled ``check_rep``.  Feature-detect rather than
    version-parse so pre-release builds resolve correctly."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=False)
    from jax.experimental.shard_map import shard_map as sm

    return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
              check_rep=False)


def default_mesh(devices=None, n_starts=1):
    """Build a ``(dp=n_starts, sp=rest)`` mesh over the available devices."""
    devices = np.asarray(devices if devices is not None else jax.devices())
    n = devices.size
    if n % n_starts:
        raise ValueError(f"{n} devices not divisible by n_starts={n_starts}")
    return Mesh(devices.reshape(n_starts, n // n_starts),
                (START_AXIS, CAND_AXIS))


class ShardedTpeKernel(_TpeKernel):
    """TPE suggest step with the candidate axis sharded over a mesh.

    Same math as :class:`~hyperopt_tpu.tpe._TpeKernel`; the only difference
    is a ``with_sharding_constraint`` on every candidate-axis array, which
    makes XLA partition the EI sweep across ``mesh[CAND_AXIS]`` and reduce
    the argmax over ICI.
    """

    def __init__(self, cs: CompiledSpace, n_cap, n_cand, lf, mesh,
                 split="sqrt", multivariate=False, cat_prior=None):
        self.mesh = mesh
        n_shards = mesh.shape[CAND_AXIS]
        if n_cand % n_shards:
            raise ValueError(
                f"n_EI_candidates={n_cand} not divisible by the "
                f"{n_shards}-way candidate mesh axis")
        # Chunked scoring would fight the sharding constraint; per-device
        # candidate counts are modest, so score in one block.
        self.score_chunk = n_cand + 1
        super().__init__(cs, n_cap, n_cand, lf, split,
                         multivariate=multivariate, cat_prior=cat_prior)

    def _constrain_cand(self, x, axis=-1):
        spec = [None] * x.ndim
        spec[axis if axis >= 0 else x.ndim + axis] = CAND_AXIS
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(self.mesh, P(*spec)))


def _mesh_key(mesh):
    """Stable cache key for a mesh — device ids + layout, not ``id(mesh)``
    (a garbage-collected mesh's id can be recycled by a new mesh, handing
    back a kernel bound to the dead mesh's sharding)."""
    return (mesh.axis_names, mesh.devices.shape,
            tuple(d.id for d in mesh.devices.flat))


def _get_sharded_kernel(cs, n_cap, n_cand, lf, mesh, split,
                        multivariate=False, cat_prior=None):
    from ..ops.gmm import _comp_sampler
    from ..tpe import (
        _cat_prior_default,
        _pallas_mode,
        _pallas_tile,
        _split_impl,
    )

    cache = getattr(cs, "_sharded_tpe_kernels", None)
    if cache is None:
        cache = cs._sharded_tpe_kernels = {}
    cat_prior = cat_prior or _cat_prior_default()
    # Same key discipline as tpe.get_kernel: cat_prior, pallas mode, and
    # the component-sampler lowering are baked into the compiled program,
    # so they MUST key the cache — otherwise an env toggle mid-process
    # hands back a stale kernel.
    k = (n_cap, n_cand, lf, _mesh_key(mesh), split, multivariate,
         cat_prior, _pallas_mode(), _comp_sampler(), _pallas_tile(),
         _split_impl(), _rhist.enabled())
    if k not in cache:
        cache[k] = ShardedTpeKernel(cs, n_cap, n_cand, lf, mesh, split,
                                    multivariate=multivariate,
                                    cat_prior=cat_prior)
    return cache[k]


def sharded_suggest(new_ids, domain, trials, seed, mesh=None,
                    prior_weight=_default_prior_weight,
                    n_startup_jobs=_default_n_startup_jobs,
                    n_EI_candidates=4096,
                    gamma=_default_gamma,
                    linear_forgetting=_default_linear_forgetting,
                    split="sqrt", multivariate=False, startup=None,
                    cat_prior=None):
    """Drop-in ``algo=`` callable: TPE with mesh-sharded EI scoring.

    Defaults to a 4096-candidate sweep (vs the reference's 24 — the headroom
    SURVEY.md §5.7 identifies): on TPU the wider sweep is nearly free and
    sharded over the mesh's candidate axis.  Accepts the same tuning
    kwargs as ``tpe.suggest`` (``multivariate``, ``startup``,
    ``cat_prior`` — round-3 verdict ask #4), so a quality-tuned config
    ports to the mesh unchanged.
    """
    from ..tpe import _startup_batch

    cs = domain.cs
    if mesh is None:
        mesh = default_mesh()
    h = trials.history(cs)
    if cs.n_params == 0:
        return rand.suggest(new_ids, domain, trials, seed)
    if int(h["ok"].sum()) < n_startup_jobs:
        v, a = _startup_batch(startup, new_ids, domain, trials, seed)
        if not isinstance(a, np.ndarray):
            v = np.asarray(v)
            a = cs.active_mask_host(v)
        return base.docs_from_samples(cs, new_ids, np.asarray(v),
                                      np.asarray(a),
                                      exp_key=getattr(trials, "exp_key",
                                                      None))
    n = len(new_ids)
    resident = _rhist.enabled()
    fant = None
    if resident:
        fant = _inflight_fantasy_rows(h, trials, cs)
        n_rows = h["vals"].shape[0] + (fant[0].shape[0] if fant else 0)
    else:
        h = _with_inflight_fantasies(h, trials, cs)
        n_rows = h["vals"].shape[0]
    # Batched proposals run the inherited constant-liar scan (the sharding
    # constraints live inside _suggest_one, so each scan step's EI sweep
    # is still mesh-sharded): one dispatch + one fetch for all n, with
    # m = pow2(n) rows of bucket slack for the fantasy cursor.
    m = _batch_size_for(n)
    kern = _get_sharded_kernel(cs, _bucket(n_rows + (m if n > 1 else 0)),
                               int(n_EI_candidates), int(linear_forgetting),
                               mesh, split, multivariate=multivariate,
                               cat_prior=cat_prior)
    if resident:
        # Resident history replicated over the mesh (P() = no sharded
        # dims); placement keys the store so a plain-jit path on the same
        # trials keeps its own canonical buffers.
        hv, ha, hl, hok = _rhist.device_history(
            trials, cs, h, kern.n_cap, fantasies=fant,
            sharding=NamedSharding(mesh, P()), shard_key=_mesh_key(mesh))
    else:
        hv, ha, hl, hok = _padded_history(h, kern.n_cap)
    seed32 = int(seed) % (2 ** 32)
    with mesh:
        if n == 1:
            # Seeded entry: key construction is compiled into the sharded
            # program (one jit dispatch, no un-jitted random_seed/fold_in
            # primitives on the host).
            r, _ = kern.suggest_seeded(seed32, hv, ha, hl, hok,
                                       gamma, prior_weight)
            rows = np.asarray(r)[None, :]
        else:
            r, _ = kern.suggest_many_seeded(seed32, m, n_rows, hv, ha,
                                            hl, hok, gamma, prior_weight)
            rows = np.asarray(r)[:n]
    # Values only (one fetch); masks rebuilt on host.
    return base.docs_from_samples(cs, new_ids, rows,
                                  cs.active_mask_host(rows),
                                  exp_key=getattr(trials, "exp_key", None))


# ---------------------------------------------------------------------------
# multi-start: K independent posteriors across the mesh
# ---------------------------------------------------------------------------


def _multi_start_fn(kern, mesh):
    """Build the shard_mapped K-start suggest step (cached per kernel;
    shape-polymorphic in the number of starts via jit retracing).

    Each start gets its OWN γ (``gammas`` is sharded like ``keys``): K
    EI-argmax draws against one posterior at a single γ collapse onto the
    same EI peak (the batch-collapse defect tpe._liar_scan fixes
    sequentially), but the sequential liar would serialize the mesh.  A
    per-start γ spread diversifies in parallel instead — different
    below/above splits give genuinely different posteriors, so the K
    argmax winners spread while every start still exploits the history."""

    def one_host(keys, gammas, vals, active, loss, ok, prior_weight):
        # keys/gammas: [local] — this device's share of the K starts.
        return jax.vmap(
            lambda k, g: kern._suggest_one(k, vals, active, loss, ok,
                                           g, prior_weight))(keys, gammas)

    return jax.jit(_shard_map(
        one_host, mesh=mesh,
        in_specs=(P(START_AXIS), P(START_AXIS), P(), P(), P(), P(), P()),
        out_specs=P(START_AXIS)))


def _gamma_spread(gamma, n_starts):
    """Per-start γ ladder: ``γ·2**linspace(-1, 1, K)`` clipped to a sane
    split range; K=1 degenerates to the base γ."""
    if n_starts == 1:
        return np.asarray([gamma], np.float32)
    return np.clip(gamma * np.exp2(np.linspace(-1.0, 1.0, n_starts)),
                   0.05, 0.75).astype(np.float32)


def multi_start_suggest(new_ids, domain, trials, seed, mesh=None,
                        prior_weight=_default_prior_weight,
                        n_startup_jobs=_default_n_startup_jobs,
                        n_EI_candidates=_default_n_EI_candidates,
                        gamma=_default_gamma,
                        linear_forgetting=_default_linear_forgetting,
                        split="sqrt", multivariate=False, startup=None,
                        cat_prior=None):
    """``algo=`` callable proposing ``len(new_ids)`` configs in ONE device
    program: each new trial gets its own RNG stream AND its own γ from a
    ``2**linspace(-1,1,K)`` ladder (see ``_gamma_spread``) — the
    mesh-parallel answer to batch collapse, laid out one-per-mesh-slot
    along the ``dp`` axis.

    Use with ``fmin(..., max_queue_len=K)`` (or an async Trials backend) to
    evaluate K proposals in parallel — BASELINE.md config 4.
    """
    from ..tpe import _startup_batch, get_kernel

    cs = domain.cs
    if mesh is None:
        mesh = Mesh(np.asarray(jax.devices()), (START_AXIS,))
    h = trials.history(cs)
    if cs.n_params == 0:
        return rand.suggest(new_ids, domain, trials, seed)
    if int(h["ok"].sum()) < n_startup_jobs:
        v, a = _startup_batch(startup, new_ids, domain, trials, seed)
        if not isinstance(a, np.ndarray):
            v = np.asarray(v)
            a = cs.active_mask_host(v)
        return base.docs_from_samples(cs, new_ids, np.asarray(v),
                                      np.asarray(a),
                                      exp_key=getattr(trials, "exp_key",
                                                      None))
    n = len(new_ids)
    resident = _rhist.enabled()
    fant = None
    if resident:
        fant = _inflight_fantasy_rows(h, trials, cs)
        n_rows = h["vals"].shape[0] + (fant[0].shape[0] if fant else 0)
    else:
        h = _with_inflight_fantasies(h, trials, cs)
        n_rows = h["vals"].shape[0]
    n_dev = mesh.shape[START_AXIS]
    n_starts = -(-n // n_dev) * n_dev  # round up to fill the mesh axis
    kern = get_kernel(cs, _bucket(n_rows), int(n_EI_candidates),
                      int(linear_forgetting), split,
                      multivariate=multivariate, cat_prior=cat_prior)
    cache = getattr(cs, "_multi_start_fns", None)
    if cache is None:
        cache = cs._multi_start_fns = {}
    ck = (id(kern), _mesh_key(mesh))
    if ck not in cache:
        cache[ck] = _multi_start_fn(kern, mesh)
    fn = cache[ck]

    if resident:
        hv, ha, hl, hok = _rhist.device_history(
            trials, cs, h, kern.n_cap, fantasies=fant,
            sharding=NamedSharding(mesh, P()), shard_key=_mesh_key(mesh))
    else:
        hv, ha, hl, hok = _padded_history(h, kern.n_cap)
    keys = jax.random.split(prng_key(int(seed) % (2 ** 32)), n_starts)
    with mesh:
        rows, _ = fn(keys, _gamma_spread(gamma, n_starts), hv, ha, hl, hok,
                     np.float32(prior_weight))
    rows = np.asarray(rows)[:n]
    return base.docs_from_samples(cs, new_ids, rows,
                                  cs.active_mask_host(rows),
                                  exp_key=getattr(trials, "exp_key", None))
