"""Compat shim over :mod:`hyperopt_tpu.dispatch` (deprecated import path).

.. deprecated:: PR 15
    The mesh machinery that lived here — ``ShardedTpeKernel``, the
    ``(dp, sp)`` ``default_mesh``, the shard_mapped multi-start step —
    moved into :mod:`hyperopt_tpu.dispatch`, the one substrate where
    sharding × fleet lanes × pipeline depth × backend head compose.
    Mesh-sharded suggest is no longer an opt-in side path: with a mesh
    registered (``dispatch.set_default_mesh`` /
    ``HYPEROPT_TPU_DISPATCH=sharded``) plain ``tpe.suggest`` IS the
    sharded path.  This module keeps the historical names importable and
    the legacy ``algo=`` callables (``sharded_suggest``,
    ``multi_start_suggest``) working unchanged; new code should pass a
    mesh to the substrate instead of calling these directly.

Works identically on a real TPU slice and on the virtual 8-device CPU mesh
used by tests (``--xla_force_host_platform_device_count``).
"""

from __future__ import annotations

from .. import rand
from .. import tpe as _tpe
from ..dispatch import (          # noqa: F401  (compat re-exports)
    CAND_AXIS,
    START_AXIS,
    ShardedTpeKernel,
    _gamma_spread,
    _mesh_key,
    _multi_start_fn,
    _shard_map,
    default_mesh,
    multi_start_suggest,
)
from ..tpe import (               # noqa: F401  (compat re-exports)
    _batch_size_for,
    _bucket,
    _default_gamma,
    _default_linear_forgetting,
    _default_n_EI_candidates,
    _default_n_startup_jobs,
    _default_prior_weight,
)


def _get_sharded_kernel(cs, n_cap, n_cand, lf, mesh, split,
                        multivariate=False, cat_prior=None):
    """Legacy kernel accessor — now a view into the unified substrate
    cache (``cs._dispatch_kernels``), which keys ALL env toggles the
    local cache does (the old ``_sharded_tpe_kernels`` cache omitted the
    prng/EI toggles and could hand back a stale kernel)."""
    from .. import dispatch as _dispatch

    return _dispatch.get_kernel(cs, n_cap, n_cand, lf, split,
                                multivariate=multivariate,
                                cat_prior=cat_prior, mesh=mesh, strict=True)


def sharded_suggest(new_ids, domain, trials, seed, mesh=None,
                    prior_weight=_default_prior_weight,
                    n_startup_jobs=_default_n_startup_jobs,
                    n_EI_candidates=4096,
                    gamma=_default_gamma,
                    linear_forgetting=_default_linear_forgetting,
                    split="sqrt", multivariate=False, startup=None,
                    cat_prior=None):
    """Drop-in ``algo=`` callable: TPE with mesh-sharded EI scoring.

    .. deprecated:: PR 15 — a thin wrapper over
        ``dispatch.suggest_dispatch`` + ``tpe.suggest_materialize``; the
        substrate shards plain ``tpe.suggest`` whenever a mesh is active,
        so this wrapper only remains for callers pinning the explicit
        ``mesh=`` / 4096-candidate legacy defaults.

    Defaults to a 4096-candidate sweep (vs the reference's 24 — the
    headroom SURVEY.md §5.7 identifies): on TPU the wider sweep is nearly
    free and sharded over the mesh's candidate axis.  Accepts the same
    tuning kwargs as ``tpe.suggest`` (``multivariate``, ``startup``,
    ``cat_prior`` — round-3 verdict ask #4), so a quality-tuned config
    ports to the mesh unchanged.
    """
    from .. import dispatch as _dispatch

    cs = domain.cs
    if cs.n_params == 0:
        return rand.suggest(new_ids, domain, trials, seed)
    if mesh is None:
        mesh = _dispatch.active_mesh() or default_mesh()
    handle = _dispatch.suggest_dispatch(
        new_ids, domain, trials, seed, mesh=mesh, strict=True,
        prior_weight=prior_weight, n_startup_jobs=n_startup_jobs,
        n_EI_candidates=n_EI_candidates, gamma=gamma,
        linear_forgetting=linear_forgetting, split=split,
        multivariate=multivariate, startup=startup, cat_prior=cat_prior)
    return _tpe.suggest_materialize(handle)
