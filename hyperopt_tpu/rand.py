"""Random-search suggest algorithm.

Reference: ``hyperopt/rand.py::suggest`` (SURVEY.md §2 L3): seed an RNG, draw
one sample of the space per new trial id, package into trial docs.

TPU-native: all ``len(new_ids)`` configurations are drawn in ONE jitted,
batched device call via :meth:`CompiledSpace.sample` — no per-node graph
interpretation.
"""

from __future__ import annotations

import jax
import numpy as np

from . import base
from .space import prng_key


def suggest(new_ids, domain, trials, seed):
    """Uniform-prior sampling: the reference's random search."""
    n = len(new_ids)
    if n == 0:
        return []
    key = prng_key(int(seed) % (2 ** 32))
    vals, _ = domain.cs.sample(key, n)
    # Fetch only the values (one device sync); the mask is a pure host
    # function of them (space.py::active_mask_host).
    vals = np.asarray(vals)
    return base.docs_from_samples(domain.cs, new_ids,
                                  vals, domain.cs.active_mask_host(vals),
                                  exp_key=getattr(trials, "exp_key", None))


def suggest_batch(new_ids, domain, trials, seed):
    """Return raw (vals, active) arrays for ``new_ids`` without packaging."""
    key = prng_key(int(seed) % (2 ** 32))
    return domain.cs.sample(key, len(new_ids))


#: registry hook (hyperopt_tpu.backends.contract resolves through this)
BACKENDS = {"rand": suggest, "random": suggest}
