"""Pluggable suggest backends: the contract, the registry, and the
model-based heads that live outside the Parzen family.

Import surface is deliberately tiny and JAX-free: ``contract`` (and the
re-exports below) never import jax or any algo module — heads load
lazily on first :func:`resolve`, so plain-store netstore servers and
analysis tooling keep their no-JAX property.  See
:mod:`hyperopt_tpu.backends.contract` for the SuggestBackend protocol.
"""

from .contract import (  # noqa: F401
    UnknownBackend,
    names,
    register_backend,
    resolve,
    run_conformance,
)
