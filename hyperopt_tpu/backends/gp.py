"""GP-EI suggest backend: jitted Gaussian-process surrogate on the
shared batched-kernel substrate.

The canonical Bayesian-optimization head (Snoek et al., "Practical
Bayesian Optimization of Machine Learning Algorithms"): a Matérn-5/2 GP
over the unit-cube encoding of the search space, fit by Cholesky solve,
proposing the argmax of analytic expected improvement over a candidate
sweep drawn from the prior sampler.  Everything from history feed to
proposal row runs in ONE jitted XLA program per (bucket, candidate
count, batch size) triple, cached on ``cs._gp_kernels`` exactly like
the TPE kernel cache.

Substrate reuse (the point of the backends/ contract):

* History arrives through the SAME feed as TPE — the device-resident
  ring (``history.device_history``, delta-upload) when enabled, the
  host-padded form otherwise, bucketed by ``tpe._bucket`` so programs
  are shared across runs.
* In-flight trials (depth-D pipeline, pool workers) enter as
  constant-liar fantasy rows through the ring's overlay slots
  (``tpe._inflight_fantasy_rows``) — the GP fits them at the mean
  observed loss like every other head, so it pipelines at depth D
  unchanged.
* Within one batched dispatch the liar idea repeats in-program: a
  ``lax.scan`` proposes, fantasizes the proposal at the lie (exactly 0
  in standardized-loss space, since the lie IS the mean), refits, and
  proposes again — m proposals, m Cholesky factorizations, zero host
  round-trips.
* The handle layout and materialize/transfer/ready halves are
  literally ``tpe``'s — GP only supplies a different dispatch.

Model details: columns encoded to [0, 1] per family (log-space for
log-scaled params, ±3σ core for normals); categorical columns use an
index encoding with a Hamming-style kernel distance (0.25 per mismatch)
so one categorical flip costs half a length-scale, not a continuum
move; inactive params impute distance-neutrally.  Hyperparameters are
selected per dispatch by log-marginal-likelihood over a small
(length-scale × noise) grid, vmapped so the whole grid is one batched
Cholesky.  Fit cost is bounded by ``HYPEROPT_TPU_GP_MAX_N`` (default
256): past that many observations the fit gathers the lowest-loss rows
— O(max_n³) per dispatch forever, the standard subset-of-data
sparsification.
"""

from __future__ import annotations

import os
from time import perf_counter

import numpy as np

import jax
import jax.numpy as jnp

from .. import tpe as _tpe
from .. import history as _rhist
from . import _codec
from ..obs import costs as _costs
from ..obs.metrics import kernel_cache_event
from ..obs.metrics import registry as _metrics_registry

_default_n_startup_jobs = 10
_default_n_EI_candidates = 64

#: (length-scale, noise) grid scored by log marginal likelihood each
#: dispatch.  Length-scales are in unit-cube units.
_LS_GRID = np.asarray([0.1, 0.2, 0.4, 0.8], np.float32)
_NOISE_GRID = np.asarray([1e-4, 1e-2], np.float32)

_SQ2PI = np.sqrt(2.0 * np.pi)   # host constant, out of every trace


def _max_fit_rows() -> int:
    raw = os.environ.get("HYPEROPT_TPU_GP_MAX_N", "")
    try:
        return max(16, int(raw)) if raw else 256
    except ValueError:
        return 256


def _build_suggest_fn(cs, n_cap, n_cand, m, max_n):
    """Compile the full GP-EI dispatch for one (bucket, sweep, batch)
    shape.  All host-side meta (codec constants, hyper grid, static
    sizes) is closed over here, OUTSIDE the traced function — the
    jit-purity discipline every kernel in the repo follows."""
    meta = _codec.unit_meta(cs)
    is_cat = np.asarray(meta["kind"] == _codec.K_CAT)
    n_eff = min(n_cap, max_n)
    ls_grid, noise_grid = np.meshgrid(_LS_GRID, _NOISE_GRID)
    ls_grid = np.ascontiguousarray(ls_grid.ravel())
    noise_grid = np.ascontiguousarray(noise_grid.ravel())

    def matern52(zi, zj, ls):
        d = zi[:, None, :] - zj[None, :, :]
        d2 = jnp.where(jnp.asarray(is_cat), 0.25 * (d != 0.0), d * d)
        r2 = jnp.sum(d2, axis=-1) / (ls * ls)
        s = jnp.sqrt(5.0 * r2 + 1e-12)
        return (1.0 + s + (5.0 / 3.0) * r2) * jnp.exp(-s)

    def run(seed, hv, ha, hl, hok):
        key = jax.random.PRNGKey(seed)
        z_all = _codec.encode(meta, hv, ha, cat="index")
        mk = hok
        if n_cap > n_eff:
            # Subset-of-data cap: keep the n_eff lowest-loss rows (the
            # region EI cares about).  Static shapes — the gather is the
            # only data-dependent step and it stays in-program.
            sel = jnp.argsort(jnp.where(mk, hl, jnp.inf))[:n_eff]
            z_all = z_all[sel]
            hl_eff = hl[sel]
            mk = mk[sel]
        else:
            hl_eff = hl
        mf = mk.astype(jnp.float32)
        cnt = jnp.maximum(mf.sum(), 1.0)
        y0 = jnp.where(mk, hl_eff, 0.0)
        mu_y = y0.sum() / cnt
        sd_y = jnp.sqrt((mf * (y0 - mu_y) ** 2).sum() / cnt) + 1e-6
        y = mf * (y0 - mu_y) / sd_y

        # Hyperparameter selection: one vmapped Cholesky over the grid.
        def logml(ls, noise):
            kf = matern52(z_all, z_all, ls)
            kmat = kf * jnp.outer(mf, mf) \
                + jnp.diag((1.0 - mf) + 1e-6 + noise * mf)
            chol = jnp.linalg.cholesky(kmat)
            alpha = jax.scipy.linalg.cho_solve((chol, True), y)
            return -0.5 * jnp.dot(y, alpha) \
                - jnp.sum(jnp.log(jnp.diagonal(chol)))

        scores = jax.vmap(logml)(jnp.asarray(ls_grid),
                                 jnp.asarray(noise_grid))
        bi = jnp.argmax(scores)
        ls = jnp.asarray(ls_grid)[bi]
        noise = jnp.asarray(noise_grid)[bi]

        # Liar-scan: m proposals, each fantasized into slot n_eff + i at
        # the lie (standardized 0 — the lie is the mean) before the next
        # refit.  Candidates are fresh prior draws per step.
        z2_0 = jnp.concatenate(
            [z_all, jnp.zeros((m, z_all.shape[1]), z_all.dtype)])
        mf2_0 = jnp.concatenate([mf, jnp.zeros((m,), mf.dtype)])
        y2 = jnp.concatenate([y, jnp.zeros((m,), y.dtype)])

        def step(carry, i):
            z2, mf2 = carry
            kc = jax.random.fold_in(key, i)
            cv, ca = cs.sample_traced(kc, n_cand)
            zc = _codec.encode(meta, cv, ca, cat="index")
            kf = matern52(z2, z2, ls)
            kmat = kf * jnp.outer(mf2, mf2) \
                + jnp.diag((1.0 - mf2) + 1e-6 + noise * mf2)
            chol = jnp.linalg.cholesky(kmat)
            alpha = jax.scipy.linalg.cho_solve((chol, True), y2 * mf2)
            kstar = matern52(zc, z2, ls) * mf2[None, :]
            mu = kstar @ alpha
            v = jax.scipy.linalg.solve_triangular(chol, kstar.T, lower=True)
            var = jnp.clip(1.0 + noise - jnp.sum(v * v, axis=0), 1e-9)
            sigma = jnp.sqrt(var)
            best = jnp.min(jnp.where(mf2 > 0, y2, jnp.inf))
            zs = (best - mu) / sigma
            cdf = 0.5 * (1.0 + jax.scipy.special.erf(zs / np.sqrt(2.0)))
            pdf = jnp.exp(-0.5 * zs * zs) / _SQ2PI
            ei = (best - mu) * cdf + sigma * pdf
            pick = jnp.argmax(ei)
            z2 = z2.at[n_eff + i].set(zc[pick])
            mf2 = mf2.at[n_eff + i].set(1.0)
            return (z2, mf2), cv[pick]

        (_, _), rows = jax.lax.scan(step, (z2_0, mf2_0), jnp.arange(m))
        return rows

    return jax.jit(run)


def _get_suggest_fn(cs, n_cap, n_cand, m, max_n):
    cache = getattr(cs, "_gp_kernels", None)
    if cache is None:
        cache = {}
        cs._gp_kernels = cache
    key = (n_cap, n_cand, m, max_n)
    fn = cache.get(key)
    hit = fn is not None
    if not hit:
        fn = _build_suggest_fn(cs, n_cap, n_cand, m, max_n)
        fn._cost_key = ("gp",) + key
        cache[key] = fn
    # GP programs share the kernel-cache compile-shape accounting (and
    # through it the cost ledger's request join) with the TPE heads.
    kernel_cache_event(fn._cost_key, hit)
    if not hit:
        def _lower(fn=fn):
            f32 = jnp.float32
            sd = jax.ShapeDtypeStruct
            p = cs.n_params
            return fn.lower(
                sd((), jnp.uint32),
                sd((n_cap, p), f32), sd((n_cap, p), jnp.bool_),
                sd((n_cap,), f32), sd((n_cap,), jnp.bool_)).compile()
        _costs.record_compile("gp", fn._cost_key, _lower, n_cap=n_cap,
                              P=cs.n_params, m=m)
    return fn


def suggest_dispatch(new_ids, domain, trials, seed,
                     n_startup_jobs=_default_n_startup_jobs,
                     n_EI_candidates=_default_n_EI_candidates,
                     startup=None):
    """Enqueue the GP-EI proposal program; returns a tpe-layout handle
    (``("pending", cs, new_ids, (rows, None), exp_key)``) consumed by
    ``tpe.suggest_materialize`` and friends — the four halves are shared
    with TPE by construction."""
    cs = domain.cs
    n = len(new_ids)
    exp_key = getattr(trials, "exp_key", None)
    reg = _metrics_registry()
    reg.counter("backend.gp.suggest.calls").inc()
    if n == 0 or cs.n_params == 0:
        return ("ready", cs, list(new_ids),
                (np.zeros((n, cs.n_params), np.float32),
                 np.ones((n, cs.n_params), bool)), exp_key)
    h = trials.history(cs)
    if int(h["ok"].sum()) < n_startup_jobs:
        v, a = _tpe._startup_batch(startup, new_ids, domain, trials, seed)
        if not isinstance(a, np.ndarray):
            v = np.asarray(v)
            a = cs.active_mask_host(v)
        return ("ready", cs, list(new_ids),
                (np.asarray(v), np.asarray(a)), exp_key)
    resident = _rhist.enabled()
    if resident:
        fant = _tpe._inflight_fantasy_rows(h, trials, cs)
        n_rows = h["vals"].shape[0] + (fant[0].shape[0] if fant else 0)
    else:
        h = _tpe._with_inflight_fantasies(h, trials, cs)
        fant = None
        n_rows = h["vals"].shape[0]
    n_cap = _tpe._bucket(n_rows)
    m = _tpe._batch_size_for(n)
    fn = _get_suggest_fn(cs, n_cap, int(n_EI_candidates), m, _max_fit_rows())
    t_feed = perf_counter()
    if resident:
        hv, ha, hl, hok = _rhist.device_history(trials, cs, h, n_cap,
                                                fantasies=fant)
    else:
        hv, ha, hl, hok = _tpe._padded_history(h, n_cap)
    _tpe._obs_ms(reg, "suggest.upload_ms",
                 (perf_counter() - t_feed) * 1e3)
    t_disp = perf_counter()
    rows = fn(np.uint32(int(seed) % (2 ** 32)), hv, ha, hl, hok)
    dms = (perf_counter() - t_disp) * 1e3
    _tpe._obs_ms(reg, "backend.gp.dispatch_ms", dms)
    _costs.observe_dispatch(fn._cost_key, dms)
    return ("pending", cs, list(new_ids), (rows, None), exp_key)


def suggest(new_ids, domain, trials, seed, **kwargs):
    """GP-EI proposals for ``new_ids`` — dispatch + immediate force, so
    the sync and pipelined paths share one implementation (the contract
    ``check_sync_parity`` pins)."""
    return _tpe.suggest_materialize(
        suggest_dispatch(new_ids, domain, trials, seed, **kwargs))


def introspect(domain, trials, seed=0, n_candidates=64):
    """Health-hook diagnostics (``obs.health``): refit the same
    Matérn-5/2 grid host-side in numpy and report log-marginal-
    likelihood plus candidate-sweep EI statistics.

    Runs eagerly (no new XLA programs compiled) on at most
    ``HYPEROPT_TPU_GP_MAX_N`` rows, so a health probe never perturbs
    the kernel caches the serving path depends on.  ``ei_rel`` is the
    best candidate EI converted back to raw loss units and divided by
    the observed loss scale — ~0 means the acquisition surface is flat
    (EI collapse) regardless of the standardized-space magnitude.
    """
    cs = domain.cs
    h = trials.history(cs)
    ok = np.asarray(h["ok"], bool)
    n_ok = int(ok.sum())
    out = {"backend": "gp", "n_obs": n_ok}
    if n_ok < 4 or cs.n_params == 0:
        out["insufficient"] = True
        return out
    vals = np.asarray(h["vals"], np.float64)[ok]
    act = np.asarray(h["active"], bool)[ok]
    loss = np.asarray(h["loss"], np.float64)[ok]
    max_n = _max_fit_rows()
    if n_ok > max_n:
        sel = np.argsort(loss)[:max_n]
        vals, act, loss = vals[sel], act[sel], loss[sel]
    meta = _codec.unit_meta(cs)
    is_cat = np.asarray(meta["kind"] == _codec.K_CAT)
    z = np.asarray(_codec.encode(meta, jnp.asarray(vals, jnp.float32),
                                 jnp.asarray(act), cat="index"),
                   np.float64)
    n = z.shape[0]
    mu_y = loss.mean()
    sd_y = loss.std() + 1e-6
    y = (loss - mu_y) / sd_y

    def matk(zi, zj, ls):
        d = zi[:, None, :] - zj[None, :, :]
        d2 = np.where(is_cat, 0.25 * (d != 0.0), d * d)
        r2 = d2.sum(-1) / (ls * ls)
        s = np.sqrt(5.0 * r2 + 1e-12)
        return (1.0 + s + (5.0 / 3.0) * r2) * np.exp(-s)

    best = None
    for ls in _LS_GRID:
        for noise in _NOISE_GRID:
            km = matk(z, z, float(ls)) \
                + (1e-6 + float(noise)) * np.eye(n)
            try:
                chol = np.linalg.cholesky(km)
            except np.linalg.LinAlgError:   # pragma: no cover - jittered
                continue
            alpha = np.linalg.solve(km, y)
            lml = float(-0.5 * y @ alpha
                        - np.log(np.diag(chol)).sum())
            if best is None or lml > best[0]:
                best = (lml, float(ls), float(noise), alpha, km)
    if best is None:        # pragma: no cover - grid fully singular
        out["insufficient"] = True
        return out
    lml, ls, noise, alpha, km = best
    cv, ca = cs.sample_traced(jax.random.PRNGKey(int(seed)),
                              int(n_candidates))
    zc = np.asarray(_codec.encode(meta, cv, ca, cat="index"), np.float64)
    kstar = matk(zc, z, ls)
    mu = kstar @ alpha
    w = np.linalg.solve(km, kstar.T)
    var = np.clip(1.0 + noise - np.einsum("ij,ji->i", kstar, w), 1e-12,
                  None)
    sigma = np.sqrt(var)
    best_y = y.min()
    zs = (best_y - mu) / sigma
    cdf = 0.5 * (1.0 + np.asarray(
        jax.scipy.special.erf(jnp.asarray(zs / np.sqrt(2.0)))))
    pdf = np.exp(-0.5 * zs * zs) / np.sqrt(2.0 * np.pi)
    ei = (best_y - mu) * cdf + sigma * pdf          # standardized units
    ei_max = float(ei.max())
    ei_raw = float(ei_max * sd_y)
    scale = max(float(loss.max() - loss.min()),
                1e-3 * abs(float(loss.min())), 1e-9)
    out.update({
        "logml": lml, "ls": ls, "noise": noise, "sd_y": float(sd_y),
        "ei_max": ei_max, "ei_mean": float(ei.mean()), "ei_raw": ei_raw,
        "ei_rel": float(ei_raw / scale),
    })
    return out


suggest.dispatch = suggest_dispatch
suggest.materialize = _tpe.suggest_materialize
suggest.start_transfer = _tpe.suggest_start_transfer
suggest.handle_ready = _tpe.suggest_handle_ready
suggest.introspect = introspect

#: registry hook (hyperopt_tpu.backends.contract resolves through this)
BACKENDS = {"gp": suggest}
