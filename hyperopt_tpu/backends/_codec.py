"""Shared unit-cube codec for the model-based suggest backends.

GP and ES both model the search space as ``[0, 1]^P``: history rows are
*encoded* into the cube before fitting, and proposals are *decoded* back
to raw parameter values that round-trip through the same
quantize/clip/exp rules as :meth:`CompiledSpace.sample_traced` (so a
decoded row is always a row the prior sampler could have produced, and
``base.docs_from_samples`` / ``active_mask_host`` treat it identically).

The per-pid metadata is plain host numpy built ONCE per CompiledSpace
(outside any traced function — the jit-purity JP003 discipline); the
encode/decode helpers are pure jnp and safe to close over inside jitted
programs.

Column conventions by parameter family:

* uniform family — affine in *fit space* (log space for loguniform):
  ``z = (t - a) / (b - a)`` with ``t = log(x)`` where ``is_log``.
* normal family — affine over the ±3σ core, clipped to [0, 1].
* categorical / probabilistic randint — ``encode(..., cat="index")``
  keeps the raw option index (the GP's Hamming-style kernel distance);
  ``cat="unit"`` maps index k of K to ``(k + 0.5) / K`` (the ES
  continuous relaxation).  Decode inverts the latter via
  ``floor(z·K)``.
* wide randint — affine over [low, high); decode floors back to the
  integer lattice.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

#: kind codes in the per-pid ``kind`` array
K_UF, K_NF, K_CAT, K_WIDE = 0, 1, 2, 3


def unit_meta(cs):
    """Per-pid codec constants for ``cs`` as a dict of host numpy arrays.

    Keys: ``kind`` (int32 family code), ``a``/``b`` (fit-space affine
    bounds; for cat columns ``b - a`` is unused), ``is_log``, ``q``
    (quantization step, 0 = none), ``clip_lo``/``clip_hi`` (raw-space
    clip after decode), ``cat_k`` (option count, 1 for non-cat),
    ``cat_off`` (randint low offset for probabilistic-randint columns).
    """
    P = cs.n_params
    kind = np.zeros(P, np.int32)
    a = np.zeros(P, np.float32)
    b = np.ones(P, np.float32)
    is_log = np.zeros(P, bool)
    q = np.zeros(P, np.float32)
    clip_lo = np.full(P, -np.inf, np.float32)
    clip_hi = np.full(P, np.inf, np.float32)
    cat_k = np.ones(P, np.float32)
    cat_off = np.zeros(P, np.float32)
    for i, p in enumerate(cs._uf):
        pid = p.pid
        kind[pid] = K_UF
        a[pid], b[pid] = cs._uf_a[i], cs._uf_b[i]
        is_log[pid] = cs._uf_log[i]
        q[pid] = cs._uf_q[i]
        clip_lo[pid], clip_hi[pid] = cs._uf_clip_lo[i], cs._uf_clip_hi[i]
    for i, p in enumerate(cs._nf):
        pid = p.pid
        kind[pid] = K_NF
        mu, sg = float(cs._nf_mu[i]), float(cs._nf_sigma[i])
        a[pid], b[pid] = mu - 3.0 * sg, mu + 3.0 * sg
        is_log[pid] = cs._nf_log[i]
        q[pid] = cs._nf_q[i]
        clip_lo[pid], clip_hi[pid] = -cs._nf_clip[i], cs._nf_clip[i]
    for i, p in enumerate(cs._cat):
        pid = p.pid
        kind[pid] = K_CAT
        cat_k[pid] = float(p.n_options)
        cat_off[pid] = cs._cat_offset[i]
    for i, p in enumerate(cs._wide):
        pid = p.pid
        kind[pid] = K_WIDE
        a[pid], b[pid] = float(cs._wide_low[i]), float(cs._wide_high[i])
    # Degenerate spans (single-point uniforms, K=1 randints) would divide
    # by zero in encode; widen to a unit span — z is constant either way.
    span = b - a
    b = np.where(span > 0, b, a + 1.0).astype(np.float32)
    return dict(kind=kind, a=a, b=b, is_log=is_log, q=q,
                clip_lo=clip_lo, clip_hi=clip_hi,
                cat_k=cat_k, cat_off=cat_off)


def encode(meta, vals, active, cat="index"):
    """Raw rows ``vals f32[N, P]`` → unit-cube rows (traceable).

    Inactive numeric entries impute to 0.5 (the cube center — distance-
    neutral for the GP, update-neutral for ES); inactive categorical
    entries impute to -1 under ``cat="index"`` (a pseudo-level no real
    row matches) and to 0.5 under ``cat="unit"``.
    """
    kind = jnp.asarray(meta["kind"])
    t = jnp.where(jnp.asarray(meta["is_log"]),
                  jnp.log(jnp.maximum(vals, 1e-12)), vals)
    z_num = (t - jnp.asarray(meta["a"])) \
        / (jnp.asarray(meta["b"]) - jnp.asarray(meta["a"]))
    z_num = jnp.clip(z_num, 0.0, 1.0)
    idx = vals - jnp.asarray(meta["cat_off"])
    if cat == "index":
        z_cat = idx
        fill = jnp.where(kind == K_CAT, -1.0, 0.5)
    else:
        z_cat = (idx + 0.5) / jnp.asarray(meta["cat_k"])
        fill = jnp.full((vals.shape[1],), 0.5, vals.dtype)
    z = jnp.where(kind == K_CAT, z_cat, z_num)
    return jnp.where(active, z, fill)


def decode(meta, z):
    """Unit-cube rows ``z f32[n, P]`` → raw parameter rows (traceable).

    Applies the family-exact inverse transforms — exp for log-scaled
    columns, q-lattice rounding, clip — so decoded rows land on the same
    value lattice as prior samples.
    """
    kind = jnp.asarray(meta["kind"])
    a, b = jnp.asarray(meta["a"]), jnp.asarray(meta["b"])
    t = a + z * (b - a)
    x = jnp.where(jnp.asarray(meta["is_log"]), jnp.exp(t), t)
    q = jnp.asarray(meta["q"])
    x = jnp.where(q > 0, jnp.round(x / jnp.where(q > 0, q, 1.0)) * q, x)
    x = jnp.clip(x, jnp.asarray(meta["clip_lo"]), jnp.asarray(meta["clip_hi"]))
    cat_k = jnp.asarray(meta["cat_k"])
    x_cat = jnp.asarray(meta["cat_off"]) \
        + jnp.clip(jnp.floor(z * cat_k), 0.0, cat_k - 1.0)
    span = jnp.maximum(b - a, 1.0)
    x_wide = a + jnp.clip(jnp.floor(z * span), 0.0, span - 1.0)
    return jnp.where(kind == K_CAT, x_cat,
                     jnp.where(kind == K_WIDE, x_wide, x))
