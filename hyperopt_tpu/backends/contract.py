"""The SuggestBackend contract: protocol, registry, conformance suite.

ROADMAP item 3: the dispatch/materialize split that ``fmin``,
``pipeline.py`` and ``fleet.py`` consume was implicit folklore inside
``tpe.py``.  This module makes it a real plugin boundary.

The protocol (four halves + two substrate conventions)
------------------------------------------------------

A *suggest backend* is a callable with the reference plugin signature::

    suggest(new_ids, domain, trials, seed, **kw) -> [trial docs]

Dispatch-capable backends additionally attach four attributes on the
callable — the halves the depth-D pipeline drives
(:class:`hyperopt_tpu.pipeline.PipelinedExecutor`):

``suggest.dispatch(new_ids, domain, trials, seed, **kw) -> handle``
    Enqueue the proposal computation on device and return an *opaque*
    handle WITHOUT forcing it.  History must be snapshotted at dispatch
    time (the one-step-stale posterior every async optimizer accepts).
    The canonical handle layout — shared by TPE, GP and ES so their
    materialize/transfer/ready halves are one implementation — is
    ``(tag, cs, new_ids, (rows, acts), exp_key)`` with ``tag`` either
    ``"ready"`` (host arrays, e.g. startup draws) or ``"pending"``
    (unforced device arrays; ``acts`` may be None — the activity mask
    is rebuilt host-side from the forced rows, which keeps the
    materialize at ONE device sync).
``suggest.materialize(handle) -> [trial docs]``
    Block on the handle and package trial documents
    (``base.docs_from_samples``).  ``suggest(...)`` itself must equal
    dispatch + immediate materialize for the same arguments — the sync
    and overlapped paths may not drift apart (pinned per head by the
    conformance suite below).
``suggest.start_transfer(handle) -> handle``
    Begin the device→host copy without blocking
    (``jax.Array.copy_to_host_async``); a no-op on ready handles.
``suggest.handle_ready(handle) -> bool``
    True when materialize will not block (``jax.Array.is_ready``).
    Must never itself block: the executor polls it for stall
    attribution.

Backends without the attributes are *sync-only*: ``fmin`` degrades to
the synchronous loop (``rand``, ``qmc``, ``anneal``, ``atpe``).  All
four halves must be present together or absent together.

Substrate conventions every model-based head follows:

* **History feed** — read the dense SoA history ``trials.history(cs)``
  and, when ``history.enabled()``, feed the jitted program through the
  device-resident ring ``history.device_history(trials, cs, h, n_cap,
  fantasies=...)`` so each trial uploads O(P) bytes, not O(N·P).
  Bucket ``n_cap`` with ``tpe._bucket`` so programs are shared across
  runs.
* **Constant-liar overlay** — trials currently NEW/RUNNING enter the
  snapshot as fantasy rows at the mean observed loss
  (``tpe._inflight_fantasy_rows`` → the ring's overlay slots), so a
  depth-D pipeline's concurrent dispatches repel each other's pending
  points.  Within one batched dispatch the same lie value drives the
  liar-scan (propose → fantasize → refit, ``lax.scan``).

The registry
------------

:func:`resolve` maps ``fmin``'s ``algo="..."`` strings (and the service
``suggest`` verb's ``algo`` field) to registered callables.  Builtin
heads live in lazy per-module ``BACKENDS`` dicts — nothing is imported
until its name is first resolved, so plain-store netstore servers keep
their no-JAX-until-suggest property.  :func:`register_backend` adds
third-party heads at runtime; unknown names raise the typed
:class:`UnknownBackend` (a ``ValueError``, which is what the service
verb serializes over the wire).

The conformance suite
---------------------

``check_sync_parity`` / ``check_handle_protocol`` /
``check_pipeline_depth2`` / ``check_transient_retry`` are reusable
checks any head must pass; ``tests/test_backends.py`` parametrizes them
over every registered head.  They are ordinary functions raising
``AssertionError`` so external backend authors can run them against
their own heads without pytest.
"""

from __future__ import annotations

import importlib
import threading

from ..obs.metrics import registry as _metrics_registry

#: name -> module path holding a ``BACKENDS`` dict with that name.
#: Lazy by construction: resolving one name imports one module.
_BUILTIN_SPECS = {
    "tpe": "hyperopt_tpu.tpe",
    "tpe_quantile": "hyperopt_tpu.tpe",
    "tpe_sobol": "hyperopt_tpu.tpe",
    "tpe_mv": "hyperopt_tpu.tpe",
    "rand": "hyperopt_tpu.rand",
    "random": "hyperopt_tpu.rand",
    "qmc": "hyperopt_tpu.qmc",
    "sobol": "hyperopt_tpu.qmc",
    "halton": "hyperopt_tpu.qmc",
    "anneal": "hyperopt_tpu.anneal",
    "atpe": "hyperopt_tpu.atpe",
    "gp": "hyperopt_tpu.backends.gp",
    "es": "hyperopt_tpu.backends.es",
}

_REGISTRY: dict = {}            # name -> suggest callable (resolved)
_REGISTRY_LOCK = threading.Lock()


class UnknownBackend(ValueError):
    """``algo`` name with no registered backend.  Subclasses ValueError
    so the service ``suggest`` verb's wire behavior (a server-reported
    ValueError) is unchanged by the registry refactor."""


def register_backend(name: str, fn, replace: bool = False) -> None:
    """Register ``fn`` as the suggest backend for ``algo=name``.

    ``fn`` must follow the plugin signature above; attach the four
    dispatch halves for pipeline capability.  Re-registering an existing
    name requires ``replace=True`` (guards against alias collisions with
    the builtins).
    """
    if not callable(fn):
        raise TypeError(f"backend {name!r} must be callable, got "
                        f"{type(fn).__name__}")
    with _REGISTRY_LOCK:
        if not replace and name in _REGISTRY or \
                not replace and name in _BUILTIN_SPECS:
            raise ValueError(f"backend {name!r} already registered "
                             "(pass replace=True to override)")
        _REGISTRY[name] = fn


def _load_builtin(name: str):
    """Import the builtin module owning ``name`` and cache every head its
    ``BACKENDS`` dict declares (one import populates all its aliases)."""
    module = importlib.import_module(_BUILTIN_SPECS[name])
    table = module.BACKENDS
    with _REGISTRY_LOCK:
        for alias, fn in table.items():
            _REGISTRY.setdefault(alias, fn)
    return table[name]


def resolve(name: str):
    """Resolve an ``algo=`` string to its suggest callable.

    Raises :class:`UnknownBackend` (a ValueError) for unregistered
    names, listing what is available.
    """
    fn = _REGISTRY.get(name)
    if fn is None:
        if name not in _BUILTIN_SPECS:
            raise UnknownBackend(
                f"unknown algo {name!r} (have {names()}) — register new "
                "heads with hyperopt_tpu.backends.register_backend or "
                "pass a suggest callable")
        fn = _load_builtin(name)
    _metrics_registry().counter(f"backend.{name}.resolved").inc()
    return fn


def names() -> list:
    """Every resolvable backend name (builtins + runtime-registered),
    sorted.  Imports nothing: builtin names are known statically."""
    with _REGISTRY_LOCK:
        dynamic = set(_REGISTRY)
    return sorted(dynamic | set(_BUILTIN_SPECS))


def server_table() -> dict:
    """``{name: callable}`` for the netstore ``suggest`` verb: every
    registered head, with console verbosity suppressed where the head
    supports it (a server must not chat on a driver's behalf)."""
    import functools
    import inspect

    table = {}
    for name in names():
        fn = resolve(name)
        try:
            params = inspect.signature(fn).parameters
        except (TypeError, ValueError):  # pragma: no cover - exotic callables
            params = {}
        if "verbose" in params:
            fn = functools.partial(fn, verbose=False)
        table[name] = fn
    return table


# ---------------------------------------------------------------------------
# conformance suite
# ---------------------------------------------------------------------------

#: The checks every head must pass (tests/test_backends.py parametrizes
#: them over all registered names).
CONFORMANCE_CHECKS = ("sync_parity", "handle_protocol",
                      "pipeline_depth2", "transient_retry")

_HALVES = ("dispatch", "materialize", "start_transfer", "handle_ready")


def halves_of(fn):
    """``(dispatch, materialize, start_transfer, handle_ready)`` of a
    head, or ``(None,)*4`` for sync-only heads.  Unwraps keyword-only
    ``functools.partial`` the same way ``FMinIter`` does, re-binding the
    partial's keywords onto the dispatch half, so configured variants
    (``tpe_sobol``, ``tpe_mv``) keep their pipeline capability."""
    import functools

    kw = {}
    if isinstance(fn, functools.partial) and not fn.args:
        kw = dict(fn.keywords or {})
        fn = fn.func
    halves = [getattr(fn, a, None) for a in _HALVES]
    if halves[0] is not None and kw:
        halves[0] = functools.partial(halves[0], **kw)
    return tuple(halves)


def introspect_of(fn):
    """The head's optional health-introspection hook, or None.

    A head may attach ``suggest.introspect(domain, trials, seed=0) ->
    dict`` — pure host-side diagnostics (surrogate fit quality,
    acquisition statistics, split shape) that ``obs.health`` turns into
    per-experiment verdicts.  Like :func:`halves_of`, keyword-only
    ``functools.partial`` variants unwrap to the carrying callable; the
    hook must never mutate trials, touch kernel caches, or require an
    accelerator.
    """
    import functools

    while isinstance(fn, functools.partial):
        fn = fn.func
    return getattr(fn, "introspect", None)


def conformance_domain():
    """Small mixed space (continuous + categorical) every check runs on."""
    from .. import base, hp

    space = {"x": hp.uniform("x", -2.0, 2.0),
             "c": hp.choice("c", [0, 1, 2])}
    return base.Domain(_conformance_objective, space)


def _conformance_objective(p):
    return (p["x"] - 0.5) ** 2 + 0.1 * p["c"]


def seeded_trials(domain, n=24, seed=0, exp_key=None):
    """A Trials pre-loaded with ``n`` completed random trials — enough to
    put every model-based head past its startup phase.  Deterministic in
    ``seed`` so two calls produce identical histories (the sync-parity
    check's precondition)."""
    from .. import base, rand

    t = base.Trials(exp_key=exp_key)
    docs = rand.suggest(list(range(n)), domain, t, seed)
    for d in docs:
        vals = d["misc"]["vals"]
        x = vals["x"][0]
        c = vals["c"][0] if vals["c"] else 0
        d["state"] = base.JOB_STATE_DONE
        d["result"] = {"status": base.STATUS_OK,
                       "loss": float(_conformance_objective(
                           {"x": x, "c": c}))}
    t.insert_trial_docs(docs)
    t.refresh()
    return t


def check_sync_parity(fn, n=4, seed=1234):
    """``suggest(...)`` equals its own dispatch + materialize (when the
    halves exist) and is deterministic in ``(history, seed)`` — compared
    through the JSON wire form like the service contract test."""
    import json

    domain = conformance_domain()
    ids = list(range(24, 24 + n))
    docs_sync = fn(ids, domain, seeded_trials(domain), seed)
    dispatch, materialize = halves_of(fn)[:2]
    if dispatch is not None:
        handle = dispatch(ids, domain, seeded_trials(domain), seed)
        docs_async = materialize(handle)
    else:
        docs_async = fn(ids, domain, seeded_trials(domain), seed)
    assert json.loads(json.dumps(docs_sync)) == \
        json.loads(json.dumps(docs_async)), \
        "sync suggest and dispatch+materialize (or a re-run on an " \
        "identical history) disagree"
    assert [d["tid"] for d in docs_sync] == ids


def check_handle_protocol(fn, n=3, seed=77):
    """Dispatch handles obey the four-halves protocol: all four
    attributes present together (or none), ``handle_ready`` returns a
    bool without blocking, ``start_transfer`` never raises, materialize
    yields exactly ``len(new_ids)`` docs."""
    dispatch, materialize, start_transfer, handle_ready = halves_of(fn)
    halves = (dispatch, materialize, start_transfer, handle_ready)
    if all(h is None for h in halves):
        return "sync-only"
    assert all(h is not None for h in halves), \
        f"partial protocol: need all of {_HALVES} or none"
    domain = conformance_domain()
    ids = list(range(24, 24 + n))
    handle = dispatch(ids, domain, seeded_trials(domain), seed)
    ready = handle_ready(handle)
    assert isinstance(ready, bool)
    start_transfer(handle)
    docs = materialize(handle)
    assert len(docs) == n
    assert bool(handle_ready(handle)) is True  # forced => ready
    # The startup path must produce an immediately-ready handle.
    from .. import base
    cold = dispatch([0, 1], domain, base.Trials(), seed)
    assert handle_ready(cold) is True
    return "dispatch-capable"


def check_pipeline_depth2(fn, max_evals=26, seed=5):
    """A depth-2 pipelined fmin completes with every trial recorded —
    the head runs unmodified under overlapped dispatch (sync-only heads
    exercise the graceful degradation path)."""
    from .. import base
    from ..fmin import fmin
    import numpy as np

    domain = conformance_domain()
    t = base.Trials()
    fmin(_conformance_objective, domain.expr, algo=fn,
         max_evals=max_evals, trials=t,
         rstate=np.random.default_rng(seed), overlap_depth=2,
         show_progressbar=False, verbose=False)
    t.refresh()
    assert len(t.trials) == max_evals
    states = [d["state"] for d in t.trials]
    assert all(s == base.JOB_STATE_DONE for s in states), states
    assert t.best_trial["result"]["loss"] is not None


def check_transient_retry(fn, max_evals=6, seed=9):
    """Transient objective faults are retried in place: with an armed
    ``objective.call`` schedule and a retry budget, the run still
    completes every trial."""
    from .. import base, faults
    from ..fmin import fmin
    import numpy as np

    domain = conformance_domain()
    t = base.Trials()
    with faults.injected("objective.call", prob=1.0, times=2, seed=3):
        fmin(_conformance_objective, domain.expr, algo=fn,
             max_evals=max_evals, trials=t,
             rstate=np.random.default_rng(seed), max_trial_retries=3,
             show_progressbar=False, verbose=False)
    t.refresh()
    assert len(t.trials) == max_evals
    assert all(d["state"] == base.JOB_STATE_DONE for d in t.trials)
    retried = [d for d in t.trials if d["misc"].get("fail_count")]
    assert retried, "no trial recorded a retried transient fault"


def run_conformance(fn) -> dict:
    """Run the full suite against one head; returns per-check outcomes.
    External backend authors: ``run_conformance(my_suggest)`` raising
    nothing means the head composes with fmin, the pipeline and the
    faults harness."""
    return {
        "sync_parity": check_sync_parity(fn) or "ok",
        "handle_protocol": check_handle_protocol(fn),
        "pipeline_depth2": check_pipeline_depth2(fn) or "ok",
        "transient_retry": check_transient_retry(fn) or "ok",
    }
