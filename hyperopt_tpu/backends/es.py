"""Evolution-strategies suggest backend (OpenES-style, population-as-array).

A model-free head in the evosax idiom: the search distribution is an
isotropic Gaussian over the unit cube whose mean evolves by the OpenES
natural-gradient estimate, and a *generation* is one population of
``popsize`` trials.  Proposals are antithetic pairs ``mean ± σ·ε`` —
the variance-reduction trick OpenES ships with — decoded back to raw
parameter rows in-program.

State lives NOWHERE on the host: the head is *stateless by replay*.
Each dispatch reconstructs the strategy state inside one jitted program
from the device-resident history feed — completed trials, taken in
insertion order, ARE the generations, and a ``lax.scan`` over them
replays every completed generation's mean update (centered-rank shaped
by default).  Replay is O(generations) fused device work per dispatch;
in exchange the head inherits every substrate property for free —
fault-injected retries, service-side suggest, WAL recovery, and
process restarts all resume the strategy exactly, because the history
IS the state.  Partial generations (the tail ``n_ok % popsize`` trials)
don't move the mean; in-flight fantasy rows are ignored entirely (a
model-free update has no posterior to fantasize into — proposals within
one generation are independent draws by design, which is ES's native
batch parallelism).

Handle layout and the materialize/transfer/ready halves are shared with
``tpe``; only dispatch differs.  Population state (the generation
matrix ``[G, popsize, P]``) is a batched device array throughout —
never a per-individual host loop.
"""

from __future__ import annotations

import os
from time import perf_counter

import numpy as np

import jax
import jax.numpy as jnp

from .. import tpe as _tpe
from .. import history as _rhist
from . import _codec
from ..obs import costs as _costs
from ..obs.metrics import kernel_cache_event
from ..obs.metrics import registry as _metrics_registry

_default_sigma0 = 0.25
_default_lr = 0.5
_SIGMA_DECAY = 0.97


def _default_popsize() -> int:
    raw = os.environ.get("HYPEROPT_TPU_ES_POPSIZE", "")
    try:
        return max(2, int(raw)) if raw else 8
    except ValueError:
        return 8


def _build_suggest_fn(cs, n_cap, m, popsize, sigma0, lr, rank_shaping):
    """Compile replay + proposal for one (bucket, batch, strategy-config)
    shape.  Codec meta and static sizes close over here, outside the
    traced function."""
    meta = _codec.unit_meta(cs)
    n_gens = max(1, n_cap // popsize)
    n_take = n_gens * popsize
    half = (m + 1) // 2

    def run(seed, hv, ha, hl, hok):
        key = jax.random.PRNGKey(seed)
        z = _codec.encode(meta, hv, ha, cat="unit")
        # Completed trials in insertion order are the generations: a
        # stable argsort moves ok rows to the front without changing
        # their relative order (indices are unique, so the sort key
        # ``ok ? i : n_cap`` is a strict total order).
        order = jnp.argsort(jnp.where(hok, jnp.arange(n_cap), n_cap))
        take = order[:n_take]
        zg = z[take].reshape(n_gens, popsize, -1)
        ag = ha[take].astype(jnp.float32).reshape(n_gens, popsize, -1)
        lg = jnp.where(hok, hl, 0.0)[take].reshape(n_gens, popsize)
        full = jnp.sum(hok.astype(jnp.int32)) // popsize

        def step(mean, inp):
            g, zgen, agen, lgen = inp
            live = (g < full).astype(jnp.float32)
            if rank_shaping:
                # Centered ranks of fitness (-loss): best → +0.5,
                # worst → -0.5; invariant to loss scale and outliers.
                ranks = jnp.argsort(jnp.argsort(-lgen))
                w = ranks.astype(jnp.float32) / (popsize - 1) - 0.5
            else:
                f = -lgen
                w = (f - f.mean()) / (f.std() + 1e-8) / 2.0
            upd = (2.0 / popsize) * jnp.sum(
                w[:, None] * agen * (zgen - mean), axis=0)
            mean = jnp.clip(mean + live * lr * upd, 0.0, 1.0)
            return mean, None

        mean0 = jnp.full((z.shape[1],), 0.5, z.dtype)
        mean, _ = jax.lax.scan(step, mean0,
                               (jnp.arange(n_gens), zg, ag, lg))
        sigma = sigma0 * jnp.power(_SIGMA_DECAY, full.astype(jnp.float32))
        eps = jax.random.normal(key, (half, z.shape[1]), z.dtype)
        eps = jnp.concatenate([eps, -eps], axis=0)[:m]
        zprop = jnp.clip(mean[None, :] + sigma * eps, 0.0, 1.0)
        return _codec.decode(meta, zprop)

    return jax.jit(run)


def _get_suggest_fn(cs, n_cap, m, popsize, sigma0, lr, rank_shaping):
    cache = getattr(cs, "_es_kernels", None)
    if cache is None:
        cache = {}
        cs._es_kernels = cache
    key = (n_cap, m, popsize, float(sigma0), float(lr), bool(rank_shaping))
    fn = cache.get(key)
    hit = fn is not None
    if not hit:
        fn = _build_suggest_fn(cs, n_cap, m, popsize, sigma0, lr,
                               rank_shaping)
        fn._cost_key = ("es",) + key
        cache[key] = fn
    # ES programs join the shared compile-shape + cost-ledger accounting.
    kernel_cache_event(fn._cost_key, hit)
    if not hit:
        def _lower(fn=fn):
            f32 = jnp.float32
            sd = jax.ShapeDtypeStruct
            p = cs.n_params
            return fn.lower(
                sd((), jnp.uint32),
                sd((n_cap, p), f32), sd((n_cap, p), jnp.bool_),
                sd((n_cap,), f32), sd((n_cap,), jnp.bool_)).compile()
        _costs.record_compile("es", fn._cost_key, _lower, n_cap=n_cap,
                              P=cs.n_params, m=m)
    return fn


def suggest_dispatch(new_ids, domain, trials, seed, n_startup_jobs=None,
                     popsize=None, sigma0=_default_sigma0, lr=_default_lr,
                     rank_shaping=True, startup=None):
    """Enqueue the ES replay + proposal program; tpe-layout handle."""
    cs = domain.cs
    n = len(new_ids)
    exp_key = getattr(trials, "exp_key", None)
    reg = _metrics_registry()
    reg.counter("backend.es.suggest.calls").inc()
    popsize = _default_popsize() if popsize is None else max(2, int(popsize))
    if n_startup_jobs is None:
        n_startup_jobs = popsize
    if n == 0 or cs.n_params == 0:
        return ("ready", cs, list(new_ids),
                (np.zeros((n, cs.n_params), np.float32),
                 np.ones((n, cs.n_params), bool)), exp_key)
    h = trials.history(cs)
    if int(h["ok"].sum()) < n_startup_jobs:
        v, a = _tpe._startup_batch(startup, new_ids, domain, trials, seed)
        if not isinstance(a, np.ndarray):
            v = np.asarray(v)
            a = cs.active_mask_host(v)
        return ("ready", cs, list(new_ids),
                (np.asarray(v), np.asarray(a)), exp_key)
    n_rows = h["vals"].shape[0]
    n_cap = _tpe._bucket(n_rows)
    m = _tpe._batch_size_for(n)
    fn = _get_suggest_fn(cs, n_cap, m, popsize, sigma0, lr, rank_shaping)
    t_feed = perf_counter()
    if _rhist.enabled():
        hv, ha, hl, hok = _rhist.device_history(trials, cs, h, n_cap)
    else:
        hv, ha, hl, hok = _tpe._padded_history(h, n_cap)
    _tpe._obs_ms(reg, "suggest.upload_ms", (perf_counter() - t_feed) * 1e3)
    t_disp = perf_counter()
    rows = fn(np.uint32(int(seed) % (2 ** 32)), hv, ha, hl, hok)
    dms = (perf_counter() - t_disp) * 1e3
    _tpe._obs_ms(reg, "backend.es.dispatch_ms", dms)
    _costs.observe_dispatch(fn._cost_key, dms)
    return ("pending", cs, list(new_ids), (rows, None), exp_key)


def suggest(new_ids, domain, trials, seed, **kwargs):
    """OpenES proposals for ``new_ids`` — dispatch + immediate force."""
    return _tpe.suggest_materialize(
        suggest_dispatch(new_ids, domain, trials, seed, **kwargs))


suggest.dispatch = suggest_dispatch
suggest.materialize = _tpe.suggest_materialize
suggest.start_transfer = _tpe.suggest_start_transfer
suggest.handle_ready = _tpe.suggest_handle_ready

#: registry hook (hyperopt_tpu.backends.contract resolves through this)
BACKENDS = {"es": suggest}
