"""``pyll``-compat shim for reference-code migration.

Reference surface covered (``hyperopt/pyll/__init__.py`` re-exports,
SURVEY.md §2 L0): ``scope`` (expression namespace),
``stochastic.sample(space, rng)`` (draw one concrete configuration), and
the graph-interpreter surface reference code uses for graph surgery —
``rec_eval`` (memoized lazy evaluator), ``dfs``/``toposort`` (node
enumeration), ``clone`` (substituting copy), ``clone_merge``
(common-subexpression-merging copy), ``use_obj_for_literal_in_memo``
(sentinel-literal substitution), ``Literal``/``as_apply``.

These operate on THIS framework's expression graph
(:class:`hyperopt_tpu.space.Expr` trees: ``Param``/``Choice`` stochastic
leaves, ``Apply`` deterministic nodes, plain dict/list/tuple containers).
The *hot path* never interprets: spaces compile once to an XLA sampler
(:mod:`hyperopt_tpu.space`) and the interpreter exists purely so
migration-era host code (``rec_eval(expr, memo=...)`` idioms,
``clone``-based space rewrites) keeps working.

Importable as ``hyperopt_tpu.pyll``::

    from hyperopt_tpu import pyll
    cfg = pyll.stochastic.sample(space, rng=np.random.default_rng(0))
    val = pyll.rec_eval(expr, memo={"x": 0.5})
"""

from __future__ import annotations

import numpy as np

from .scope import scope  # noqa: F401
from .space import (
    _SCOPE_IMPLS,
    CATEGORICAL,
    LOGNORMAL,
    LOGUNIFORM,
    NORMAL,
    QLOGNORMAL,
    QLOGUNIFORM,
    QNORMAL,
    QUNIFORM,
    RANDINT,
    UNIFORM,
    UNIFORMINT,
    Apply,
    Choice,
    Expr,
    Param,
    compile_space,
    prng_key,
)


class Literal(Expr):
    """A constant wrapped as a graph node (reference: ``pyll.Literal``).

    Plain Python values embedded in a space already act as literals; this
    class exists for reference code that constructs/inspects ``Literal``
    nodes explicitly (e.g. during ``clone``-based rewrites).
    """

    __slots__ = ("obj",)

    def __init__(self, obj=None):
        self.obj = obj

    def __repr__(self):
        return f"Literal({self.obj!r})"


def as_apply(obj):
    """Identity shim for the reference's ``pyll.as_apply``.

    Reference code wraps spaces with ``as_apply`` before handing them to
    hyperopt (``pyll/base.py::as_apply`` builds Apply/Literal nodes); here
    nested dict/list/``hp.*`` structures ARE the space representation and
    every entry point accepts them directly, so migration code calling
    ``pyll.as_apply(space)`` gets its input back unchanged.
    """
    return obj


# ---------------------------------------------------------------------------
# graph interpretation (reference: pyll/base.py::rec_eval ~L550-700)
# ---------------------------------------------------------------------------


def _memo_get(memo, node):
    """Memo lookup by node identity first (the reference's convention),
    then by label (the natural spelling for this framework's users)."""
    if memo is None:
        return False, None
    try:
        if node in memo:
            return True, memo[node]
    except TypeError:       # unhashable memo key types — label path below
        pass
    label = getattr(node, "label", None)
    if label is not None and label in memo:
        return True, memo[label]
    return False, None


def _draw_leaf(p: Param, rng: np.random.Generator):
    """One numpy draw from a stochastic leaf's marginal (the generative
    semantics ``pyll/stochastic.py``'s samplers implement per node)."""
    k = p.kind
    if k == UNIFORM:
        return float(rng.uniform(p.low, p.high))
    if k == LOGUNIFORM:
        return float(np.exp(rng.uniform(p.low, p.high)))
    if k == QUNIFORM:
        return float(np.round(rng.uniform(p.low, p.high) / p.q) * p.q)
    if k == QLOGUNIFORM:
        return float(np.round(np.exp(rng.uniform(p.low, p.high)) / p.q) * p.q)
    if k == NORMAL:
        return float(rng.normal(p.mu, p.sigma))
    if k == LOGNORMAL:
        return float(np.exp(rng.normal(p.mu, p.sigma)))
    if k == QNORMAL:
        return float(np.round(rng.normal(p.mu, p.sigma) / p.q) * p.q)
    if k == QLOGNORMAL:
        return float(np.round(np.exp(rng.normal(p.mu, p.sigma)) / p.q) * p.q)
    if k == RANDINT:
        if p.probs is not None:
            return int(p.low) + int(rng.choice(len(p.probs), p=p.probs))
        return int(rng.integers(p.low, p.high))
    if k == UNIFORMINT:
        return int(rng.integers(p.low, int(p.high) + 1))
    if k == CATEGORICAL:
        return int(rng.choice(len(p.probs), p=p.probs))
    raise ValueError(f"cannot draw from {p!r}")


def rec_eval(expr, memo=None, rng=None):
    """Evaluate an expression tree to a concrete value.

    Reference: ``pyll/base.py::rec_eval(expr, memo=...)`` — the memoized
    post-order interpreter.  ``memo`` maps nodes (by identity, the
    reference convention) or labels to concrete values; stochastic leaves
    not covered by the memo are drawn with ``rng`` (a
    ``numpy.random.Generator``) or raise.  ``scope.switch`` is lazy: only
    the selected branch is evaluated, exactly like the reference builtin.
    """

    def rec(node):
        if isinstance(node, Choice):
            # A memo entry for a Choice holds the BRANCH INDEX (the value
            # stored in trials' misc.vals), not the branch's final value.
            hit_i, idx = _memo_get(memo, node)
            if not hit_i:
                if rng is None:
                    raise KeyError(
                        f"rec_eval: no memo value (and no rng) for {node!r}")
                probs = node.probs or \
                    [1.0 / len(node.options)] * len(node.options)
                idx = int(rng.choice(len(node.options), p=probs))
            return rec(node.options[int(idx)])
        # The memo applies to GRAPH NODES only — a plain literal that
        # happens to equal a label key (e.g. option string "c" vs label
        # "c") must never be substituted.
        if isinstance(node, Expr):
            hit, v = _memo_get(memo, node)
            if hit:
                return v
        if isinstance(node, Literal):
            return node.obj
        if isinstance(node, Param):
            if rng is not None:
                return _draw_leaf(node, rng)
            raise KeyError(
                f"rec_eval: no memo value (and no rng) for {node!r}")
        if isinstance(node, Apply):
            if node.op == "switch":
                sel = int(rec(node.args[0]))
                options = node.args[1:]
                if not 0 <= sel < len(options):
                    raise IndexError(
                        f"scope.switch index {sel} out of range for "
                        f"{len(options)} options")
                return rec(options[sel])
            return _SCOPE_IMPLS[node.op](*(rec(a) for a in node.args))
        if isinstance(node, dict):
            return {k: rec(v) for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            return type(node)(rec(v) for v in node)
        return node      # plain literal

    return rec(expr)


def dfs(expr):
    """Post-order list of the UNIQUE graph nodes under ``expr`` (children
    before parents).  Reference: ``pyll/base.py::dfs``.  Only ``Expr``
    nodes are returned; container structure is traversed through."""
    seen: set = set()
    out: list = []

    def rec(node):
        if isinstance(node, Expr):
            if id(node) in seen:
                return
            seen.add(id(node))
            if isinstance(node, Apply):
                for a in node.args:
                    rec(a)
            elif isinstance(node, Choice):
                for o in node.options:
                    rec(o)
            out.append(node)
        elif isinstance(node, dict):
            for v in node.values():
                rec(v)
        elif isinstance(node, (list, tuple)):
            for v in node:
                rec(v)

    rec(expr)
    return out


def toposort(expr):
    """Topological order of the expression DAG (every node after all of its
    inputs).  Reference: ``pyll/base.py::toposort`` (networkx there; the
    deduplicated post-order is the same ordering for these graphs)."""
    return dfs(expr)


def clone(expr, memo=None):
    """Deep-copy an expression graph, substituting via ``memo``
    (node → replacement).  Reference: ``pyll/base.py::clone`` — the graph-
    surgery primitive behind space rewrites.  Shared subgraphs stay shared
    in the copy (identity-memoized like the reference)."""
    memo = dict(memo or {})

    def rec(node):
        if isinstance(node, Expr):
            if id(node) in _copies:
                return _copies[id(node)]
            if memo:
                try:
                    if node in memo:
                        return memo[node]
                except TypeError:
                    pass
            if isinstance(node, Literal):
                new = Literal(node.obj)
            elif isinstance(node, Param):
                new = Param(node.label, node.kind, low=node.low,
                            high=node.high, mu=node.mu, sigma=node.sigma,
                            q=node.q, probs=node.probs)
            elif isinstance(node, Choice):
                new = Choice(node.label, [rec(o) for o in node.options],
                             probs=node.probs)
            elif isinstance(node, Apply):
                new = Apply(node.op, tuple(rec(a) for a in node.args))
            else:       # pragma: no cover - future Expr subclasses
                raise TypeError(f"clone: unknown node type {type(node)!r}")
            _copies[id(node)] = new
            return new
        if isinstance(node, dict):
            return {k: rec(v) for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            return type(node)(rec(v) for v in node)
        return node

    _copies: dict = {}
    return rec(expr)


def clone_merge(expr, memo=None, merge_literals=False):
    """Clone with common-subexpression merging.

    Reference: ``pyll/base.py::clone_merge`` — like :func:`clone`, but
    structurally identical nodes in the copy collapse onto one shared
    node (two ``scope.add(x, 1)`` applications of the same ``x`` become
    one).  ``merge_literals`` additionally merges equal-valued
    :class:`Literal` nodes (off by default, like the reference: literal
    identity can be load-bearing for memo-based substitution).  ``memo``
    pre-seeds node replacements exactly as in :func:`clone`.
    """
    memo = dict(memo or {})
    _copies: dict = {}
    _table: dict = {}

    def ckey(c):
        # Children are merged before parents, so structural equality of
        # Expr children has become object identity by the time a parent's
        # key is computed; plain values compare by value when hashable.
        if isinstance(c, Expr):
            return ("n", id(c))
        try:
            hash(c)
        except TypeError:
            return ("u", id(c))
        return ("v", type(c).__name__, c)

    def skey(new):
        if isinstance(new, Literal):
            if not merge_literals:
                return None
            try:
                hash(new.obj)
            except TypeError:
                return None
            return ("lit", type(new.obj).__name__, new.obj)
        if isinstance(new, Param):
            probs = None if new.probs is None else tuple(map(float,
                                                             new.probs))
            return ("param", new.label, new.kind, new.low, new.high,
                    new.mu, new.sigma, new.q, probs)
        if isinstance(new, Choice):
            probs = None if new.probs is None else tuple(map(float,
                                                             new.probs))
            return ("choice", new.label,
                    tuple(ckey(o) for o in new.options), probs)
        if isinstance(new, Apply):
            return ("apply", new.op, tuple(ckey(a) for a in new.args))
        return None

    def rec(node):
        if isinstance(node, Expr):
            if id(node) in _copies:
                return _copies[id(node)]
            if memo:
                try:
                    if node in memo:
                        return memo[node]
                except TypeError:
                    pass
            if isinstance(node, Literal):
                new = Literal(node.obj)
            elif isinstance(node, Param):
                new = Param(node.label, node.kind, low=node.low,
                            high=node.high, mu=node.mu, sigma=node.sigma,
                            q=node.q, probs=node.probs)
            elif isinstance(node, Choice):
                new = Choice(node.label, [rec(o) for o in node.options],
                             probs=node.probs)
            elif isinstance(node, Apply):
                new = Apply(node.op, tuple(rec(a) for a in node.args))
            else:       # pragma: no cover - future Expr subclasses
                raise TypeError(
                    f"clone_merge: unknown node type {type(node)!r}")
            k = skey(new)
            if k is not None:
                new = _table.setdefault(k, new)
            _copies[id(node)] = new
            return new
        if isinstance(node, dict):
            return {k: rec(v) for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            return type(node)(rec(v) for v in node)
        return node

    return rec(expr)


def use_obj_for_literal_in_memo(expr, obj, lit, memo):
    """Set ``memo[node] = obj`` for every ``Literal`` equal to ``lit``.

    Reference: ``pyll/base.py::use_obj_for_literal_in_memo`` — the idiom
    behind ``fmin_pass_expr_memo_ctrl`` objectives: plant a sentinel
    literal in the space, then substitute the live object (e.g. a
    ``Ctrl``) at evaluation time.  Existing memo entries are preserved;
    the (mutated) memo is returned for chaining.
    """
    for node in dfs(expr):
        if isinstance(node, Literal):
            try:
                match = node.obj == lit
            except Exception:
                match = False
            if match and node not in memo:
                memo[node] = obj
    return memo


class stochastic:
    """Namespace mirror of ``hyperopt.pyll.stochastic``."""

    @staticmethod
    def sample(space, rng=None, seed=None):
        """Draw ONE concrete configuration from ``space``.

        Reference: ``pyll/stochastic.py::sample(expr, rng)`` — there it
        interprets the graph with numpy RNG; here it is one jitted batched
        draw (n=1) + host decode.
        """
        import jax

        if seed is None:
            if rng is None:
                seed = np.random.default_rng().integers(2 ** 31 - 1)
            elif isinstance(rng, np.random.Generator):
                seed = rng.integers(2 ** 31 - 1)
            else:  # legacy RandomState
                seed = rng.randint(2 ** 31 - 1)
        cs = compile_space(space)
        vals, active = cs.sample(prng_key(int(seed)), 1)
        return cs.decode_row(np.asarray(vals)[0], np.asarray(active)[0])
