"""``pyll``-compat shim for reference-code migration.

Reference surface covered (``hyperopt/pyll/__init__.py`` re-exports,
SURVEY.md §2 L0): ``scope`` (expression namespace) and
``stochastic.sample(space, rng)`` (draw one concrete configuration).  The
graph-interpreter internals (``rec_eval``, ``toposort``, ``clone``) have no
equivalent by design — spaces compile once to an XLA sampler
(:mod:`hyperopt_tpu.space`), there is no per-call graph to interpret.

Importable as ``hyperopt_tpu.pyll``::

    from hyperopt_tpu import pyll
    cfg = pyll.stochastic.sample(space, rng=np.random.default_rng(0))
"""

from __future__ import annotations

import numpy as np

from .scope import scope  # noqa: F401
from .space import compile_space


def as_apply(obj):
    """Identity shim for the reference's ``pyll.as_apply``.

    Reference code wraps spaces with ``as_apply`` before handing them to
    hyperopt (``pyll/base.py::as_apply`` builds Apply/Literal nodes); here
    nested dict/list/``hp.*`` structures ARE the space representation and
    every entry point accepts them directly, so migration code calling
    ``pyll.as_apply(space)`` gets its input back unchanged.
    """
    return obj


class stochastic:
    """Namespace mirror of ``hyperopt.pyll.stochastic``."""

    @staticmethod
    def sample(space, rng=None, seed=None):
        """Draw ONE concrete configuration from ``space``.

        Reference: ``pyll/stochastic.py::sample(expr, rng)`` — there it
        interprets the graph with numpy RNG; here it is one jitted batched
        draw (n=1) + host decode.
        """
        import jax

        if seed is None:
            if rng is None:
                seed = np.random.default_rng().integers(2 ** 31 - 1)
            elif isinstance(rng, np.random.Generator):
                seed = rng.integers(2 ** 31 - 1)
            else:  # legacy RandomState
                seed = rng.randint(2 ** 31 - 1)
        cs = compile_space(space)
        vals, active = cs.sample(jax.random.key(int(seed)), 1)
        return cs.decode_row(np.asarray(vals)[0], np.asarray(active)[0])
