"""Post-hoc matplotlib visualizations of an experiment.

Reference: ``hyperopt/plotting.py`` (~650 LoC, SURVEY.md §2):
``main_plot_history`` (loss vs trial), ``main_plot_histogram`` (loss dist),
``main_plot_vars`` (per-variable loss scatter).  Same entry points, driven by
the dense SoA history instead of per-doc dict walks.

Import is lazy and headless-safe: callers in batch jobs get the Agg backend
automatically when no display is configured.
"""

from __future__ import annotations

import os

import numpy as np

from .base import JOB_STATE_DONE, STATUS_OK, Trials


def _plt():
    import matplotlib

    if not os.environ.get("DISPLAY") and os.name != "nt":
        matplotlib.use("Agg", force=False)
    import matplotlib.pyplot as plt

    return plt


def _ok_losses(trials: Trials):
    xs, ys = [], []
    for t in trials:
        r = t["result"]
        if t["state"] == JOB_STATE_DONE and r.get("status") == STATUS_OK \
                and r.get("loss") is not None:
            xs.append(t["tid"])
            ys.append(float(r["loss"]))
    return np.asarray(xs), np.asarray(ys)


def main_plot_history(trials, do_show=True, status_colors=None,
                      title="Loss History"):
    """Loss vs trial id, with the running best overlaid
    (reference: plotting.py::main_plot_history)."""
    plt = _plt()
    xs, ys = _ok_losses(trials)
    fig, ax = plt.subplots()
    ax.scatter(xs, ys, s=12, alpha=0.6, label="trial loss")
    if len(ys):
        ax.plot(xs, np.minimum.accumulate(ys), color="C1", lw=1.5,
                label="best so far")
        best = ys.min()
        ax.axhline(best, ls=":", color="C1", alpha=0.5)
    ax.set_xlabel("trial")
    ax.set_ylabel("loss")
    ax.set_title(title)
    ax.legend()
    if do_show:
        plt.show()
    return ax


def main_plot_histogram(trials, do_show=True, title="Loss Histogram"):
    """Histogram of finished-trial losses
    (reference: plotting.py::main_plot_histogram)."""
    plt = _plt()
    _, ys = _ok_losses(trials)
    fig, ax = plt.subplots()
    ax.hist(ys, bins=min(30, max(3, len(ys) // 3 or 3)))
    ax.set_xlabel("loss")
    ax.set_ylabel("count")
    ax.set_title(title)
    if do_show:
        plt.show()
    return ax


def main_plot_vars(trials, domain=None, space=None, do_show=True,
                   colorize_best=10, columns=5):
    """Per-hyperparameter scatter of value vs loss — the at-a-glance
    sensitivity view (reference: plotting.py::main_plot_vars).

    One panel per parameter; the ``colorize_best`` lowest-loss trials are
    highlighted.  Conditional parameters only show trials where they were
    active (ragged idxs/vals in the reference; the activity mask here).
    """
    plt = _plt()
    if domain is not None:
        cs = domain.cs
    elif space is not None:
        from .space import compile_space
        cs = compile_space(space)
    else:
        raise ValueError("pass domain= or space=")
    h = trials.history(cs)
    ok = h["ok"]
    loss = h["loss"]
    best_cut = np.sort(loss[ok])[:colorize_best][-1] if ok.any() else np.inf

    n = cs.n_params
    cols = min(columns, max(n, 1))
    rows = -(-n // cols) if n else 1
    fig, axes = plt.subplots(rows, cols, figsize=(3 * cols, 2.5 * rows),
                             squeeze=False)
    for spec in cs.params:
        ax = axes[spec.pid // cols][spec.pid % cols]
        m = ok & h["active"][:, spec.pid]
        v = h["vals"][m, spec.pid]
        l = loss[m]
        is_best = l <= best_cut
        ax.scatter(v[~is_best], l[~is_best], s=8, alpha=0.5)
        ax.scatter(v[is_best], l[is_best], s=14, color="C1")
        ax.set_title(spec.label, fontsize=9)
        if spec.is_log:
            ax.set_xscale("log")
    for i in range(n, rows * cols):
        axes[i // cols][i % cols].axis("off")
    fig.tight_layout()
    if do_show:
        plt.show()
    return axes
