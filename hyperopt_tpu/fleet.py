"""Fleet mode: vmap-batched TPE cohorts serving many experiments per dispatch.

One TPU dispatch per *experiment* leaves the chip idle between
single-experiment steps — r5 measured 6.2 ms/suggest solo, and PR 7's
service serves tenants strictly one at a time.  This module applies the
population-as-array idiom of evosax (PAPERS.md, arXiv:2212.04180) at the
**experiment axis**: same-shape experiments stack their padded history
rings along a leading cohort dimension
(:func:`history.device_history_batched`) and one jitted
``vmap(_seeded_one)`` / ``vmap(liar-scan)`` call produces every
experiment's proposal — per-lane seeds, per-lane ``n`` cursors via the
active masks already in the buffers, one kernel-cache entry per
``(n_cap, P, m, B-tier)``.  That turns the PR 7 service into the
many-tenant tuning runtime of Tran et al. (PAPERS.md, arXiv:1811.02091):
one dispatch, N tenants' proposals.

:class:`CohortScheduler` is the planning layer: it buckets concurrent
suggest requests by structural space signature + history bucket + batch
size, rounds cohorts up to pow2 lane tiers (bounding compiles to
O(log fleet)), pads the spare lanes with empty histories, and falls back
to the solo :func:`tpe.suggest_dispatch` path for requests that cannot
batch (startup phase, empty spaces, singleton cohorts).  Every member's
proposal is **bit-identical** to its solo run (tests/test_fleet.py pins
this), so fleet mode is a pure throughput optimization.

The scheduler exposes the same four pipeline halves as ``tpe.suggest``
(``dispatch / start_transfer / handle_ready / materialize``); fleet
handles carry a shared lazily-forced cohort result so the whole cohort
pays ONE device sync, while solo-fallback handles delegate to the tpe
halves unchanged.
"""

from __future__ import annotations

import threading
import weakref
from time import perf_counter

import numpy as np

from . import base, tpe
from . import history as _rhist
from .obs import bundle as _bundle
from .obs.events import EVENTS
from .obs.metrics import registry as _registry

__all__ = ["CohortScheduler", "fleet_report", "space_signature",
           "cohort_tier", "suggest_materialize", "suggest_start_transfer",
           "suggest_handle_ready"]

#: Live schedulers, for the flight-bundle ``fleet`` section.
_SCHEDULERS: "weakref.WeakSet" = weakref.WeakSet()

#: Live fmin_fleet lane stacks (one handle per running call), for
#: obs.device HBM accounting — the vmapped history buffers are plain
#: arrays invisible to the resident-history walk.
_LANE_STACKS: "weakref.WeakSet" = weakref.WeakSet()


class _LaneStackHandle:
    """Size marker for one live :func:`fmin_fleet` lane stack.

    The fleet loop's ``hv/ha/hl/hok`` buffers (``[B, n_cap, P]`` etc.)
    live as locals in the loop frame, so ``obs/device.py::report()``
    cannot find them by walking ``history._STORE``.  The loop keeps one
    of these alive for its duration; the WeakSet drops it when the run
    returns, so ``lane_stacks`` goes back to zero without any explicit
    release call."""

    __slots__ = ("n_lanes", "n_cap", "p_dim", "__weakref__")

    def __init__(self, n_lanes, n_cap, p_dim):
        self.n_lanes = n_lanes
        self.n_cap = n_cap
        self.p_dim = p_dim

    def nbytes(self) -> int:
        # hv f32 + ha bool per [B, cap, P] cell; hl f32 + hok bool per
        # [B, cap] cell.
        return self.n_lanes * self.n_cap * (self.p_dim * 5 + 5)


def fleet_report() -> dict:
    """Cohort-state snapshot for postmortem bundles: per scheduler, each
    cohort's lane tier and live occupancy — the context a bundle needs
    to read its ``fleet_dispatch`` events and per-tier cost rows."""
    scheds = []
    for s in list(_SCHEDULERS):
        with s._lock:
            cohorts = []
            for (sig, n_cap, m), st in s._states.items():
                occ = sum(1 for w in st.lanes if w is not None and
                          w() is not None)
                cohorts.append({"n_cap": n_cap, "m": m,
                                "tier": len(st.lanes), "occupied": occ,
                                "resident": st.store is not None})
        scheds.append({"cohorts": cohorts,
                       "n_spaces": len(s._rep_cs)})
    return {"n_schedulers": len(scheds), "schedulers": scheds}


def space_signature(cs) -> tuple:
    """Structural fingerprint of a compiled space: every
    :class:`~hyperopt_tpu.space.ParamSpec` field that reaches the traced
    suggest program (distribution family + parameters + conditional
    wiring), EXCLUDING labels — two tenants tuning the same architecture
    under different parameter names share one cohort and one compiled
    kernel.  Cached on the space object (specs are frozen)."""
    sig = getattr(cs, "_fleet_sig", None)
    if sig is None:
        sig = tuple(
            (p.pid, p.kind, p.low, p.high, p.mu, p.sigma, p.q,
             tuple(p.probs) if p.probs is not None else None,
             p.n_options, tuple(p.conditions))
            for p in cs.params)
        cs._fleet_sig = sig
    return sig


def cohort_tier(b: int) -> int:
    """Pow2 lane-count tier for ``b`` cohort members.  Every distinct
    lane count is a separate XLA trace of the vmapped program; rounding
    to powers of two canonicalizes all cohorts in (t/2, t] onto one
    program, the exact argument behind :func:`tpe._batch_size_for`."""
    if b <= 1:
        return 1
    return 1 << (b - 1).bit_length()


class _CohortResult:
    """Shared device-side result of one cohort dispatch.

    Every member handle references the same instance, so the first
    materialize pays the single device→host sync and the rest read the
    cached host array — the cohort-wide analog of the one-sync contract
    in :func:`tpe._force_rows` (values only; activity masks are rebuilt
    host-side per member)."""

    __slots__ = ("rows_b", "_host", "_lock")

    def __init__(self, rows_b):
        self.rows_b = rows_b        # device [B, m, P]
        self._host = None
        self._lock = threading.Lock()

    def force(self):
        with self._lock:
            if self._host is None:
                t0 = perf_counter()
                self._host = np.asarray(self.rows_b)
                tpe._obs_ms(_registry(), "suggest.fetch_sync_ms",
                            (perf_counter() - t0) * 1e3)
            return self._host

    def start_transfer(self):
        try:
            self.rows_b.copy_to_host_async()
        except AttributeError:
            pass

    def ready(self) -> bool:
        if self._host is not None:
            return True
        try:
            return bool(self.rows_b.is_ready())
        except AttributeError:
            return True


class _CohortState:
    """Persistent per-cohort-key device state: the stacked
    :class:`~hyperopt_tpu.history.BatchedResident` buffers plus the
    stable experiment→lane assignment (stable lanes keep the tids-prefix
    delta-append hitting across dispatches)."""

    __slots__ = ("store", "lanes")

    def __init__(self):
        self.store = None
        self.lanes: list = []       # lane -> weakref(trials) | None


class _Prep:
    """One planned cohort member (the per-request half of
    ``tpe.suggest_dispatch`` up to — but excluding — the device call)."""

    __slots__ = ("idx", "new_ids", "cs", "trials", "seed32", "h", "fant",
                 "n_rows", "m", "exp_key")

    def __init__(self, idx, new_ids, cs, trials, seed32, h, fant, n_rows,
                 m, exp_key):
        self.idx = idx
        self.new_ids = new_ids
        self.cs = cs
        self.trials = trials
        self.seed32 = seed32
        self.h = h
        self.fant = fant
        self.n_rows = n_rows
        self.m = m
        self.exp_key = exp_key


class CohortScheduler:
    """Bucket concurrent suggest requests into vmapped cohort dispatches.

    One scheduler serves one algorithm configuration (the same knobs as
    :func:`tpe.suggest`); requests are ``(new_ids, domain, trials,
    seed)`` tuples.  :meth:`suggest_dispatch` returns one handle per
    request — cohort members share a device program, non-batchable
    requests fall back to the solo path — and the module-level halves
    (:func:`suggest_materialize` etc.) resolve either kind, so callers
    plug the scheduler into the pipeline contract unchanged.
    """

    def __init__(self, prior_weight=tpe._default_prior_weight,
                 n_startup_jobs=tpe._default_n_startup_jobs,
                 n_EI_candidates=tpe._default_n_EI_candidates,
                 gamma=tpe._default_gamma,
                 linear_forgetting=tpe._default_linear_forgetting,
                 split="sqrt", multivariate=False, startup=None,
                 cat_prior=None):
        self.prior_weight = float(prior_weight)
        self.n_startup_jobs = int(n_startup_jobs)
        self.n_EI_candidates = int(n_EI_candidates)
        self.gamma = float(gamma)
        self.linear_forgetting = int(linear_forgetting)
        self.split = split
        self.multivariate = bool(multivariate)
        self.startup = startup
        self.cat_prior = cat_prior
        self._lock = threading.Lock()
        self._states: dict = {}      # cohort key -> _CohortState
        self._rep_cs: dict = {}      # space signature -> representative cs
        self._kwargs = dict(
            prior_weight=self.prior_weight,
            n_startup_jobs=self.n_startup_jobs,
            n_EI_candidates=self.n_EI_candidates, gamma=self.gamma,
            linear_forgetting=self.linear_forgetting, split=self.split,
            multivariate=self.multivariate, startup=self.startup,
            cat_prior=self.cat_prior)
        _SCHEDULERS.add(self)
        _bundle.register_provider("fleet", fleet_report)

    # -- planning ------------------------------------------------------------

    def _plan(self, idx, new_ids, domain, trials, seed):
        """Replicate ``tpe.suggest_dispatch``'s control decisions for one
        request.  Returns ``(cohort_key, _Prep)`` when the request can
        join a cohort, else ``None`` (solo fallback): empty requests,
        empty spaces and warm-start draws never reach the TPE program, so
        there is nothing to batch."""
        cs = domain.cs
        n = len(new_ids)
        if n == 0 or cs.n_params == 0:
            return None
        h = trials.history(cs)
        if int(h["ok"].sum()) < self.n_startup_jobs:
            return None
        fant = tpe._inflight_fantasy_rows(h, trials, cs)
        n_rows = h["vals"].shape[0] + (fant[0].shape[0] if fant else 0)
        m = tpe._batch_size_for(n)
        n_cap = tpe._bucket(n_rows + (m if n > 1 else 0))
        sig = space_signature(cs)
        key = (sig, n_cap, m)
        prep = _Prep(idx, list(new_ids), cs, trials,
                     int(seed) % (2 ** 32), h, fant, n_rows, m,
                     getattr(trials, "exp_key", None))
        return key, prep

    def _rep(self, sig, cs):
        """Representative space for a signature: all structurally equal
        spaces compile against ONE CompiledSpace so the kernel cache
        (keyed on ``id(cs)``) cannot fragment across tenants.
        Caller holds ``self._lock`` (``suggest_dispatch`` only)."""
        rep = self._rep_cs.get(sig)
        if rep is None:
            rep = self._rep_cs[sig] = cs
        return rep

    # -- dispatch ------------------------------------------------------------

    def suggest_dispatch(self, requests):
        """Plan + dispatch every request; returns one handle per request
        (order preserved).  Cohorts of ≥2 members share one vmapped
        device call; everything else takes ``tpe.suggest_dispatch``."""
        handles = [None] * len(requests)
        groups: dict = {}
        seen: set = set()
        with self._lock:
            for idx, (new_ids, domain, trials, seed) in enumerate(requests):
                planned = self._plan(idx, new_ids, domain, trials, seed)
                # A second request against the SAME trials in one batch
                # cannot share the first's lane (one lane = one history
                # snapshot) — it runs solo, exactly as it would have
                # without fleet mode.
                if planned is None or id(trials) in seen:
                    handles[idx] = tpe.suggest_dispatch(
                        new_ids, domain, trials, seed, **self._kwargs)
                    continue
                seen.add(id(trials))
                key, prep = planned
                groups.setdefault(key, []).append(prep)
            for key, members in groups.items():
                if len(members) < 2:
                    for prep in members:
                        handles[prep.idx] = tpe.suggest_dispatch(
                            prep.new_ids, _DomainShim(prep.cs),
                            prep.trials, prep.seed32, **self._kwargs)
                    continue
                self._dispatch_cohort(key, members, handles)
        return handles

    def _dispatch_cohort(self, key, members, handles):
        """Caller holds ``self._lock`` (``suggest_dispatch`` only) —
        ``_states``/``_rep``/lane bookkeeping all mutate under it."""
        sig, n_cap, m = key
        state = self._states.get(key)
        if state is None:
            state = self._states[key] = _CohortState()
        rep = self._rep(sig, members[0].cs)
        # Kernel via the dispatch substrate: with an active mesh the
        # cohort's vmapped lane stack runs against the candidate-sharded
        # kernel (fleet lanes × sharding compose); without one this is
        # exactly tpe.get_kernel.  Non-strict — an indivisible candidate
        # count falls back to the local kernel rather than failing the
        # whole cohort.
        from . import dispatch as _dispatch

        mesh = _dispatch.active_mesh()
        kern = _dispatch.get_kernel(rep, n_cap, self.n_EI_candidates,
                                    self.linear_forgetting, self.split,
                                    self.multivariate, self.cat_prior,
                                    mesh=mesh)

        # Stable lane assignment: returning experiments keep their lane
        # (tids-prefix delta-append stays hot), dead lanes free up,
        # newcomers take free ones, the tier pads up to pow2.
        lanes = state.lanes
        live = {}
        for i, w in enumerate(lanes):
            t = w() if w is not None else None
            if t is None:
                lanes[i] = None
            else:
                live[id(t)] = i
        assigned = {}
        for prep in members:
            lane = live.get(id(prep.trials))
            if lane is not None:
                assigned[lane] = prep
        free = [i for i in range(len(lanes)) if lanes[i] is None]
        for prep in members:
            if id(prep.trials) in live:
                continue
            lane = free.pop(0) if free else len(lanes)
            if lane == len(lanes):
                lanes.append(None)
            lanes[lane] = weakref.ref(prep.trials)
            assigned[lane] = prep
        occupied = sum(1 for w in lanes if w is not None)
        tier = cohort_tier(occupied)
        if tier < cohort_tier(len(lanes)):
            # The fleet shrank past a pow2 boundary: compact occupied
            # lanes down and drop the store (rebuilt at the new width on
            # the next feed) so steady-state cohorts stop paying
            # burst-era padding lanes forever.
            by_trial = {id(p.trials): p for p in members}
            state.lanes = lanes = [w for w in lanes if w is not None]
            state.store = None
            assigned = {}
            for i, w in enumerate(lanes):
                t = w()
                prep = by_trial.get(id(t)) if t is not None else None
                if prep is not None:
                    assigned[i] = prep
        while len(lanes) < tier:
            lanes.append(None)
        b = len(lanes)

        lane_hist = [None] * b
        fants = [None] * b
        gens = [0] * b
        seeds = [0] * b
        n_rows = [0] * b
        for i, w in enumerate(lanes):
            prep = assigned.get(i)
            if prep is not None:
                lane_hist[i] = prep.h
                fants[i] = prep.fant
                gens[i] = _rhist.generation(prep.trials)
                seeds[i] = prep.seed32
                n_rows[i] = prep.n_rows
            elif w is not None:
                # Live experiment sitting out this dispatch: leave its
                # resident rows in place, ignore its output lane.
                lane_hist[i] = _rhist.KEEP

        resident = _rhist.enabled()
        t_feed = perf_counter()
        store, bufs = _rhist.device_history_batched(
            state.store if resident else None, lane_hist, n_cap,
            fantasies=fants, gens=gens)
        state.store = store if resident else None
        reg = _registry()
        tpe._obs_ms(reg, "suggest.upload_ms",
                    (perf_counter() - t_feed) * 1e3)
        if resident and max(n_rows) >= 0.75 * n_cap:
            _rhist.pregrow_batched(state.store, n_cap * 2)

        t_disp = perf_counter()
        from contextlib import nullcontext

        kern_mesh = getattr(kern, "mesh", None)
        with (kern_mesh if kern_mesh is not None else nullcontext()):
            rows_b, _acts_b = kern.suggest_fleet_seeded(
                seeds, m, n_rows, *bufs,
                [self.gamma] * b, [self.prior_weight] * b)
        tpe._obs_ms(reg, "suggest.dispatch_ms",
                    (perf_counter() - t_disp) * 1e3)

        n_real = len(members)
        waste = (b - n_real) / b
        reg.counter("fleet.dispatches").inc()
        reg.counter("fleet.suggestions").inc(
            sum(len(p.new_ids) for p in members))
        reg.histogram("fleet.cohort_size").observe(n_real)
        reg.gauge("fleet.cohort_size_last").set(n_real)
        reg.gauge("fleet.cohort_tier_last").set(b)
        reg.gauge("fleet.padding_waste").set(waste)
        EVENTS.emit("fleet_dispatch", name=f"cohort[{n_real}/{b}]",
                    cohort=n_real, tier=b, n_cap=n_cap, m=m,
                    padding_waste=round(waste, 4))

        result = _CohortResult(rows_b)
        for lane, prep in assigned.items():
            handles[prep.idx] = ("fleet", prep.cs, prep.new_ids,
                                 (result, lane), prep.exp_key)

    # -- convenience ---------------------------------------------------------

    def suggest(self, requests):
        """Dispatch + materialize in one call: a list of per-request
        trial-doc lists (the blocking, non-pipelined entry)."""
        return [suggest_materialize(hd)
                for hd in self.suggest_dispatch(requests)]

    def algo(self):
        """A ``tpe.suggest``-style algorithm bound to this scheduler,
        carrying the four pipeline halves (``dispatch / materialize /
        start_transfer / handle_ready``) so it drops into ``fmin``'s
        ``algo=`` slot and the depth-D pipelined executor unchanged.
        Each call routes through :meth:`suggest_dispatch` as a
        single-request batch — several concurrently-driven loops sharing
        one scheduler still land in one planning pass each, and the
        solo fallback keeps lone loops at exact ``tpe.suggest``
        behavior."""

        def _dispatch(new_ids, domain, trials, seed, **_kw):
            return self.suggest_dispatch(
                [(new_ids, domain, trials, seed)])[0]

        def _suggest(new_ids, domain, trials, seed, **_kw):
            return suggest_materialize(
                _dispatch(new_ids, domain, trials, seed))

        _suggest.dispatch = _dispatch
        _suggest.materialize = suggest_materialize
        _suggest.start_transfer = suggest_start_transfer
        _suggest.handle_ready = suggest_handle_ready
        return _suggest


class _DomainShim:
    """Minimal domain stand-in for re-dispatching an already-planned
    request down the solo path (which only reads ``domain.cs``)."""

    __slots__ = ("cs",)

    def __init__(self, cs):
        self.cs = cs


# -- pipeline halves (fleet-aware; delegate solo handles to tpe) ------------


def suggest_materialize(handle):
    """Materialize a fleet or solo handle into trial docs.  Fleet lanes
    read the shared cohort result (one sync for the whole cohort) and
    rebuild the activity mask host-side with the member's OWN space, so
    doc packaging (labels, exp_key) is per-tenant even when the compute
    was shared."""
    if handle[0] != "fleet":
        return tpe.suggest_materialize(handle)
    _, cs, new_ids, (result, lane), exp_key = handle
    rows = result.force()[lane][: len(new_ids)]
    acts = cs.active_mask_host(rows)
    return base.docs_from_samples(cs, new_ids, rows, acts, exp_key=exp_key)


def suggest_start_transfer(handle):
    if handle[0] != "fleet":
        return tpe.suggest_start_transfer(handle)
    handle[3][0].start_transfer()
    return handle


def suggest_handle_ready(handle) -> bool:
    if handle[0] != "fleet":
        return tpe.suggest_handle_ready(handle)
    return handle[3][0].ready()


# -- whole-loop fleet: vmapped device-resident fmin lanes -------------------


def fmin_fleet(fn, space, n_lanes, max_evals, seed=0, sync_stride=None,
               trials_list=None, mesh=None,
               n_startup_jobs=tpe._default_n_startup_jobs,
               n_EI_candidates=tpe._default_n_EI_candidates,
               gamma=tpe._default_gamma,
               prior_weight=tpe._default_prior_weight,
               linear_forgetting=tpe._default_linear_forgetting,
               split="sqrt", multivariate=False, cat_prior=None):
    """Run ``n_lanes`` independent device-resident fmin loops in lockstep.

    The population-as-array idiom applied to WHOLE optimizations: the
    segmented scan behind ``fmin(mode='device')``
    (``device._build_segment``) is ``vmap``-ed over a leading lane axis,
    so every ``sync_stride``-trial segment is ONE dispatch and ONE slab
    fetch for all lanes together — ``ceil(max_evals / stride)`` host
    round trips for the entire fleet, regardless of lane count.  Lane
    ``j`` draws its per-trial seeds from ``default_rng(seed + j)`` with
    the hosted cadence, so each lane is seeded-bit-parity with a solo
    ``fmin(mode='device')`` run under that rstate (pinned by
    tests/test_fleet.py).

    With a ``mesh``, lanes shard over its ``dp`` axis (restarts are
    embarrassingly parallel; per-lane candidate axes stay local) — the
    orthogonal composition with ``dispatch``'s candidate-axis sharding,
    which applies to single-lane runs instead.

    ``trials_list`` (optional, one ``Trials`` per lane) receives each
    lane's slab as completed docs every segment, so per-tenant hooks and
    stores see the run at stride granularity.  Early stopping is a
    per-lane host decision and does not compose with lockstep lanes; use
    solo device mode when you need it.

    Returns a list of per-lane ``info`` dicts (``best``, ``best_loss``,
    ``losses``, ``vals``, ``active``) in lane order.
    """
    import jax
    import jax.numpy as jnp

    from . import device as _device
    from . import dispatch as _dispatch
    from .base import JOB_STATE_DONE, STATUS_OK, coarse_utcnow
    from .space import CompiledSpace, compile_space, prng_impl
    from .tpe import _bucket, _pallas_tile

    cs = space if isinstance(space, CompiledSpace) else compile_space(space)
    n_lanes = int(n_lanes)
    max_evals = int(max_evals)
    if n_lanes < 1:
        raise ValueError("n_lanes must be >= 1")
    if max_evals < 1:
        raise ValueError("max_evals must be >= 1")
    if trials_list is not None and len(trials_list) != n_lanes:
        raise ValueError(f"trials_list has {len(trials_list)} entries "
                         f"for {n_lanes} lanes")
    if sync_stride is not None:
        sync_stride = int(sync_stride)
        if sync_stride < 1:
            raise ValueError("sync_stride must be >= 1 or None")
    n_cap = _bucket(max_evals)
    if mesh is not None:
        from .dispatch import START_AXIS

        if START_AXIS not in mesh.shape:
            raise ValueError(
                f"fmin_fleet shards lanes over the mesh's '{START_AXIS}' "
                f"axis, but this mesh has axes {tuple(mesh.shape)}")
        if n_lanes % mesh.shape[START_AXIS]:
            raise ValueError(
                f"n_lanes={n_lanes} not divisible by the "
                f"{mesh.shape[START_AXIS]}-way '{START_AXIS}' mesh axis")
    # Lanes shard over dp; per-lane suggests use the local kernel so the
    # two partitionings cannot fight (same rule as fmin_device n_runs>1).
    kern = _dispatch.get_kernel(cs, n_cap, int(n_EI_candidates),
                                int(linear_forgetting), split,
                                multivariate, cat_prior, mesh=None)
    eval_one = _device._wrap_objective(fn, cs)
    # Same toggle/cache discipline as device.fmin_trials: the slab
    # changes the traced program, so it keys the run cache; the vmap
    # carries a per-lane slab twin at zero extra sync boundaries.
    from .obs import devtel as _devtel

    telemetry = _devtel.enabled()
    stride_label = "inf" if sync_stride is None else str(sync_stride)
    segment = _device._build_segment(cs, kern, eval_one,
                                     int(n_startup_jobs), gamma,
                                     prior_weight, telemetry=telemetry)

    cache = getattr(cs, "_device_fmin_cache", None)
    if cache is None:
        from collections import OrderedDict

        cache = cs._device_fmin_cache = OrderedDict()
    base_key = ("fleet_seg", id(fn), n_lanes, n_cap, int(n_startup_jobs),
                float(gamma), float(prior_weight), int(linear_forgetting),
                int(n_EI_candidates), split, multivariate, kern.cat_prior,
                kern.comp_sampler, kern.split_impl, kern.pallas,
                kern.pallas_ei, kern.ei_precision, kern.ei_topm,
                kern.fused_step, _pallas_tile(),
                _device._mesh_key_of(mesh), prng_impl(), telemetry)
    reg = _registry()
    fresh_strides: set = set()

    def seg_fn(s):
        key = base_key + (s,)
        run = cache.get(key)
        if run is None:
            reg.counter("device.run_cache.misses").inc()
            fresh_strides.add(s)
            run = cache[key] = jax.jit(
                jax.vmap(segment, in_axes=(0, 0, 0, 0, 0, None)))
            while len(cache) > _device._RUN_CACHE_CAP:
                cache.popitem(last=False)
        else:
            cache.move_to_end(key)
            reg.counter("device.run_cache.hits").inc()
        return run

    p_dim = cs.n_params
    hv = jnp.zeros((n_lanes, n_cap, p_dim), jnp.float32)
    ha = jnp.zeros((n_lanes, n_cap, p_dim), bool)
    hl = jnp.full((n_lanes, n_cap), jnp.inf, jnp.float32)
    hok = jnp.zeros((n_lanes, n_cap), bool)
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec

        from .dispatch import START_AXIS

        def _lane_sharded(x):
            spec = [None] * x.ndim
            spec[0] = START_AXIS
            return jax.device_put(x, NamedSharding(mesh,
                                                   PartitionSpec(*spec)))

        hv, ha, hl, hok = (_lane_sharded(a) for a in (hv, ha, hl, hok))
    # Live lane-stack marker for obs.device HBM accounting; freed with
    # this frame when the run returns.
    _stack = _LaneStackHandle(n_lanes, n_cap, p_dim)
    _LANE_STACKS.add(_stack)
    rstates = [np.random.default_rng(int(seed) + j) for j in range(n_lanes)]

    all_rows = []
    all_acts = []
    all_losses = []
    slab_hs = []                         # per-segment lane-stacked slabs
    i = 0
    seg_index = 0
    while i < max_evals:
        s = (max_evals - i if sync_stride is None
             else min(sync_stride, max_evals - i))
        seeds = np.asarray(
            [[r.integers(2 ** 31 - 1) for _ in range(s)] for r in rstates],
            np.uint32)
        t0_mono = perf_counter()
        out = seg_fn(s)(seeds, hv, ha, hl, hok, np.int32(i))
        if telemetry:
            (hv, ha, hl, hok, _), (rows, acts, losses), slab = out
        else:
            (hv, ha, hl, hok, _), (rows, acts, losses) = out
            slab = None
        rows_h = np.asarray(rows)        # [B, s, P] — ONE fetch, all lanes
        acts_h = np.asarray(acts)
        losses_h = np.asarray(losses)
        t1_mono = perf_counter()
        reg.counter("device.fetch_syncs").inc()
        reg.counter("device.segments").inc()
        if slab is not None:
            from .obs import costs as _costs

            _devtel.bump_labeled(reg, "fleet", stride_label)
            cost_key = ("device", "fleet", s, n_lanes)
            if s in fresh_strides:
                fresh_strides.discard(s)
                _costs.record_compile(
                    "device", cost_key, compile_s=t1_mono - t0_mono,
                    n_cap=n_cap, P=p_dim, m=s, tier=n_lanes)
            slab_h = _devtel.slab_host(slab)
            slab_hs.append(slab_h)
            # Fleet segments backfill the span + aggregates; per-trial
            # anchors are a solo-mode feature (B×s instants per boundary
            # would swamp the ring at fleet scale).
            _devtel.backfill_segment(
                reg, mode="fleet", stride=stride_label, slab_h=slab_h,
                n_trials=s, n_lanes=n_lanes, t0_mono=t0_mono,
                t1_mono=t1_mono, seg_index=seg_index, cost_key=cost_key)
        seg_index += 1
        all_rows.append(rows_h)
        all_acts.append(acts_h)
        all_losses.append(losses_h)
        if trials_list is not None:
            now = coarse_utcnow()
            for j, trials in enumerate(trials_list):
                new_ids = trials.new_trial_ids(s)
                docs = base.docs_from_samples(
                    cs, new_ids, rows_h[j], acts_h[j],
                    exp_key=getattr(trials, "exp_key", None))
                for doc, loss in zip(docs, losses_h[j]):
                    doc["state"] = JOB_STATE_DONE
                    doc["result"] = {"loss": float(loss),
                                     "status": STATUS_OK}
                    doc["book_time"] = now
                    doc["refresh_time"] = now
                trials.insert_trial_docs(docs)
                trials.refresh()
            reg.counter("device.trials_landed").inc(s * n_lanes)
        i += s

    vals = np.concatenate(all_rows, axis=1)      # [B, max_evals, P]
    active = np.concatenate(all_acts, axis=1)
    losses = np.concatenate(all_losses, axis=1)  # [B, max_evals]
    out = []
    for j in range(n_lanes):
        order = np.where(np.isnan(losses[j]), np.inf, losses[j])
        bi = int(np.argmin(order))
        best = {p.label: cs._param_value(p, vals[j, bi, p.pid])
                for p in cs.params if active[j, bi, p.pid]}
        info = {"best": best, "best_loss": float(losses[j, bi]),
                "best_index": bi, "losses": losses[j],
                "vals": vals[j], "active": active[j]}
        if slab_hs:
            # Per-lane telemetry twin, reduced across segments (min/max
            # for levels, sums for counts; trajectory = final segment's
            # reservoir — it already tracks run-level best-so-far).
            n_tpe = sum(int(sh["tpe_steps"][j]) for sh in slab_hs)
            ei_sum = sum(float(sh["ei_sum"][j]) for sh in slab_hs)
            info["telemetry"] = {
                "best_loss": min(float(sh["best_loss"][j])
                                 for sh in slab_hs),
                "ei_max": max(float(sh["ei_max"][j]) for sh in slab_hs),
                "ei_mean": (ei_sum / n_tpe) if n_tpe else None,
                "tpe_steps": n_tpe,
                "nonfinite": sum(int(sh["nonfinite"][j])
                                 for sh in slab_hs),
                "argmax_ties": sum(int(sh["argmax_ties"][j])
                                   for sh in slab_hs),
                "best_trajectory": slab_hs[-1]["best_trajectory"][j],
            }
        out.append(info)
    del _stack
    return out
