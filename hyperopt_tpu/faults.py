"""Deterministic, seeded fault injection for the distributed trial loop.

A process-global registry of named **fault points**.  Production code calls
:func:`maybe_fail` at each point; when a schedule is armed for that point the
call raises a typed :class:`~hyperopt_tpu.exceptions.InjectedFault`, otherwise
it returns immediately.  The disabled path is a single module-global boolean
check — cheap enough to leave the hooks in shipping code (measured in
``benchmarks/faults_overhead.py``; budget note in DESIGN.md §6).

Fault points wired into the core::

    rpc.send          before a netstore request leaves the client
    rpc.recv          after the server executed the verb, before the client
                      reads the reply (the request DID happen — exercises
                      idempotent replay)
    rpc.connect       when the pooled client dials a TCP connection —
                      covers both the fresh dial and the transparent
                      stale-socket redial inside ``_ConnectionPool``
    store.write       inside FileTrials' atomic document write
    worker.evaluate   around a worker's domain.evaluate call
    objective.call    at the top of Domain.evaluate (every execution path)
    pipeline.dispatch before PipelinedExecutor dispatches a suggest slot
    wal.write         before a service-server WAL record is appended
    wal.fsync         before a group-commit leader fsyncs a WAL batch
    wal.replay        per record during WAL replay at server recovery
    flight.dump       inside a flight-recorder bundle dump
    replica.ship      before a WAL batch/snapshot ships to a warm replica
    router.forward    before the fleet router forwards a verb to a shard

Configuration — programmatic::

    from hyperopt_tpu import faults
    faults.configure({"rpc.send": {"prob": 0.5, "times": 3}}, seed=7)
    ...
    faults.clear()

    with faults.injected("objective.call", prob=1.0, times=2, seed=0):
        ...   # scoped: cleared on exit

or via the environment (read once at import; re-read with
:func:`configure_from_env`)::

    HYPEROPT_TPU_FAULTS="rpc.send=0.3,rpc.recv=0.3:5,objective.call=1.0:2@10"
    HYPEROPT_TPU_FAULTS_SEED=7

Per-point spec is ``prob[:times][@after]``: fire with probability ``prob``
per call, at most ``times`` injections total (default unlimited), skipping
the first ``after`` calls (default 0).  Each point draws from its own
``random.Random`` seeded by ``seed`` + the point name, so one point's call
pattern never perturbs another's schedule and a fixed seed replays the same
fault sequence exactly.

Every injection increments ``faults.injected`` plus a per-point
``faults.injected.<point>`` counter in :mod:`hyperopt_tpu.obs.metrics` and
emits a ``fault_injected`` event.
"""

from __future__ import annotations

import os
import threading
import zlib

from .exceptions import InjectedFault
from .obs import events as _events
from .obs import metrics as _metrics

__all__ = [
    "FAULT_POINTS",
    "maybe_fail",
    "configure",
    "configure_from_env",
    "clear",
    "is_active",
    "injected",
    "injection_counts",
]

#: Advisory catalog of the points the core instruments.  ``configure``
#: accepts unknown names (a library user may instrument their own code),
#: but tests pin the core set against this.
FAULT_POINTS = frozenset(
    {
        "rpc.send",
        "rpc.recv",
        "rpc.connect",
        "store.write",
        "worker.evaluate",
        "objective.call",
        "pipeline.dispatch",
        "wal.write",
        "wal.fsync",
        "wal.replay",
        "flight.dump",
        "replica.ship",
        "router.forward",
    }
)

_ENV_VAR = "HYPEROPT_TPU_FAULTS"
_ENV_SEED = "HYPEROPT_TPU_FAULTS_SEED"


class _Point:
    """One armed fault point: seeded RNG + probability/schedule + tallies."""

    __slots__ = ("name", "prob", "times", "after", "calls", "fired", "_rng")

    def __init__(self, name, prob, times=None, after=0, seed=0):
        import random

        if not 0.0 <= float(prob) <= 1.0:
            raise ValueError(f"fault prob for {name!r} must be in [0,1], "
                             f"got {prob}")
        self.name = name
        self.prob = float(prob)
        self.times = None if times is None else int(times)
        self.after = int(after)
        self.calls = 0
        self.fired = 0
        # Per-point stream: the seed is mixed with a stable hash of the
        # name so schedules replay exactly regardless of which other
        # points are armed or how often they are hit.
        self._rng = random.Random(
            (int(seed) << 32) ^ zlib.crc32(name.encode()))

    def should_fire(self) -> bool:
        self.calls += 1
        if self.calls <= self.after:
            return False
        if self.times is not None and self.fired >= self.times:
            return False
        if self._rng.random() >= self.prob:
            return False
        self.fired += 1
        return True


_lock = threading.Lock()
_points: dict = {}
_active = False          # fast-path gate: False ⇒ maybe_fail is a no-op


def maybe_fail(point: str, **ctx) -> None:
    """Raise :class:`InjectedFault` if a schedule armed for ``point`` fires.

    ``ctx`` (e.g. ``verb=``, ``tid=``) is attached to the telemetry event,
    never inspected for the firing decision — determinism depends only on
    the per-point call count and seeded RNG stream.
    """
    if not _active:
        return
    with _lock:
        p = _points.get(point)
        if p is None or not p.should_fire():
            return
        call_no = p.calls
    _metrics.registry().counter("faults.injected").inc()
    _metrics.registry().counter(f"faults.injected.{point}").inc()
    # Callers pass the trial id as ``tid=``; the event schema's trial key
    # is ``trial`` — normalize so fault events attach to trial lanes in
    # merged traces (obs/events.events_to_chrome anchors on "trial").
    tid = ctx.pop("tid", None)
    if tid is not None and "trial" not in ctx:
        ctx["trial"] = tid
    _events.EVENTS.emit("fault_injected", name=point, call_no=call_no, **ctx)
    raise InjectedFault(point, call_no=call_no)


def configure(spec, seed: int = 0) -> None:
    """Arm fault points from ``spec`` (replaces any previous schedule).

    ``spec`` is either the ``HYPEROPT_TPU_FAULTS`` string form or a dict
    ``{point: {"prob": p[, "times": n][, "after": k]}}`` (a bare float is
    shorthand for ``{"prob": p}``).  An empty spec disarms everything.
    """
    global _active
    if isinstance(spec, str):
        spec = _parse(spec)
    new = {}
    for name, cfg in (spec or {}).items():
        if isinstance(cfg, (int, float)):
            cfg = {"prob": cfg}
        new[name] = _Point(name, seed=seed, **cfg)
    with _lock:
        _points.clear()
        _points.update(new)
        _active = bool(new)


def configure_from_env() -> None:
    """(Re-)read ``HYPEROPT_TPU_FAULTS`` / ``HYPEROPT_TPU_FAULTS_SEED``."""
    raw = os.environ.get(_ENV_VAR, "")
    try:
        seed = int(os.environ.get(_ENV_SEED, "0") or "0")
    except ValueError:
        seed = 0
    configure(raw, seed=seed)


def clear() -> None:
    """Disarm every fault point and reset tallies."""
    global _active
    with _lock:
        _points.clear()
        _active = False


def is_active() -> bool:
    """True when at least one fault point is armed."""
    return _active


def injection_counts() -> dict:
    """``{point: {"calls": n, "fired": m}}`` for every armed point."""
    with _lock:
        return {name: {"calls": p.calls, "fired": p.fired}
                for name, p in _points.items()}


class injected:
    """Context manager arming a single point for a ``with`` block.

    Restores the previously armed schedule (if any) on exit, so chaos
    tests can nest/scope without clobbering each other.
    """

    def __init__(self, point, prob=1.0, times=None, after=0, seed=0):
        self._spec = {point: {"prob": prob, "times": times, "after": after}}
        self._seed = seed
        self._saved = None

    def __enter__(self):
        with _lock:
            self._saved = dict(_points)
        configure(self._spec, seed=self._seed)
        return self

    def __exit__(self, *exc):
        global _active
        with _lock:
            _points.clear()
            _points.update(self._saved)
            _active = bool(_points)
        return False


def _parse(raw: str) -> dict:
    """Parse ``"point=prob[:times][@after],..."`` into a spec dict."""
    spec = {}
    for item in raw.split(","):
        item = item.strip()
        if not item:
            continue
        try:
            name, rhs = item.split("=", 1)
            after = 0
            if "@" in rhs:
                rhs, after_s = rhs.rsplit("@", 1)
                after = int(after_s)
            times = None
            if ":" in rhs:
                rhs, times_s = rhs.split(":", 1)
                times = int(times_s)
            spec[name.strip()] = {"prob": float(rhs), "times": times,
                                  "after": after}
        except (ValueError, TypeError) as e:
            raise ValueError(
                f"bad {_ENV_VAR} entry {item!r} "
                "(want point=prob[:times][@after])") from e
    return spec


# Arm from the environment at import so worker subprocesses spawned with
# HYPEROPT_TPU_FAULTS set participate in the chaos schedule without any
# code change.  No env var ⇒ configure("") ⇒ stays disarmed.
configure_from_env()
