"""Mixture suggest algorithm: route each suggest call to a sub-algorithm.

Reference: ``hyperopt/mix.py::suggest`` (SURVEY.md §2): given
``p_suggest=[(p, algo), ...]``, pick one sub-algorithm per call with
probability ``p`` — e.g. an ε-greedy blend of random search and TPE::

    fmin(fn, space, max_evals=100,
         algo=partial(mix.suggest,
                      p_suggest=[(0.1, rand.suggest), (0.9, tpe.suggest)]))

Sub-algorithms may also be backend-registry names (TPU-first addition),
so mixes compose with every registered head — including ``gp`` and
``es`` — without importing the algo modules::

    algo=partial(mix.suggest, p_suggest=[(0.2, "rand"), (0.8, "gp")])
"""

from __future__ import annotations

import numpy as np


def suggest(new_ids, domain, trials, seed, p_suggest):
    """Call one of ``p_suggest``'s algorithms, chosen with its probability.

    Each entry is ``(p, algo)`` with ``algo`` a suggest callable or a
    backend-registry name (resolved via
    :func:`hyperopt_tpu.backends.resolve`, so unknown names raise the
    registry's typed error)."""
    ps = [p for p, _ in p_suggest]
    if not np.isclose(sum(ps), 1.0, atol=1e-3):
        raise ValueError(f"p_suggest probabilities sum to {sum(ps)}, not 1")
    rng = np.random.default_rng(int(seed) % (2 ** 32))
    idx = rng.choice(len(ps), p=np.asarray(ps) / sum(ps))
    _, algo = p_suggest[idx]
    if isinstance(algo, str):
        from .backends import contract as _backends

        algo = _backends.resolve(algo)
    return algo(new_ids, domain, trials, seed=int(rng.integers(2 ** 31 - 1)))
