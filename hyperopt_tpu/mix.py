"""Mixture suggest algorithm: route each suggest call to a sub-algorithm.

Reference: ``hyperopt/mix.py::suggest`` (SURVEY.md §2): given
``p_suggest=[(p, algo), ...]``, pick one sub-algorithm per call with
probability ``p`` — e.g. an ε-greedy blend of random search and TPE::

    fmin(fn, space, max_evals=100,
         algo=partial(mix.suggest,
                      p_suggest=[(0.1, rand.suggest), (0.9, tpe.suggest)]))
"""

from __future__ import annotations

import numpy as np


def suggest(new_ids, domain, trials, seed, p_suggest):
    """Call one of ``p_suggest``'s algorithms, chosen with its probability."""
    ps = [p for p, _ in p_suggest]
    if not np.isclose(sum(ps), 1.0, atol=1e-3):
        raise ValueError(f"p_suggest probabilities sum to {sum(ps)}, not 1")
    rng = np.random.default_rng(int(seed) % (2 ** 32))
    idx = rng.choice(len(ps), p=np.asarray(ps) / sum(ps))
    _, algo = p_suggest[idx]
    return algo(new_ids, domain, trials, seed=int(rng.integers(2 ** 31 - 1)))
