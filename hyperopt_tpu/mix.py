def suggest(new_ids, domain, trials, seed):
    raise NotImplementedError('mix: coming next')
