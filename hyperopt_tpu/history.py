"""Device-resident history feed: O(P) per-trial host→device transfer.

``tpe.suggest_dispatch`` used to rebuild the full padded history on host
(``_padded_history`` — fresh ``n_cap×P`` numpy allocs) and re-upload all
of it every call: O(n_cap·P) bytes across the axon tunnel per step for a
delta of one row.  This module keeps the padded ``(hv, ha, hl, hok)``
buffers RESIDENT on device, per ``(trials, space, mesh-placement)``, with
an append cursor:

* **Append** — only the newly completed ``[k, P]`` rows (+ losses/flags)
  cross host→device, through a jitted ``dynamic_update_slice`` program
  whose history operands are donated (in-place XLA aliasing) on
  accelerator backends.
* **Coherence** — the same tids-prefix check ``Trials.history()`` uses:
  the store remembers the tids of the rows it holds, and any mismatch
  (deletions, warm-start injection, multi-process stores rewriting the
  log) falls back to ONE full re-upload.  Never wrong answers; the
  fallback is counted, not silent.
* **Bucket rollover** — a single on-device pad-copy to the next
  power-of-two capacity, pre-triggered from ``suggest_dispatch``'s
  ``_prewarm_async`` boundary check so the switchover call doesn't pay
  it; zero host→device bytes.
* **In-flight fantasies** — ``_with_inflight_fantasies``'s host-side
  concat would dirty the buffers every overlapped step, so constant-liar
  rows are instead OVERLAID device-side into the slack rows past
  ``n_real`` (a non-donating program: the canonical buffers survive
  untouched for the next append).

Gate: ``HYPEROPT_TPU_RESIDENT_HISTORY`` (default on; ``=0`` restores the
legacy host-padded feed).  The buffer CONTENT is bit-identical to
``_padded_history`` either way — tests/test_history.py pins seeded
proposal parity — so the toggle is a transfer-path choice, not a math
choice.

Instrumentation (``obs.metrics``): ``history.upload_bytes`` (every
host→device byte this module moves), ``history.append_hits`` (calls
served by the delta path), ``history.rebuilds`` (full re-uploads),
``history.order_violations`` (true tid reorders — these raise
:class:`HistoryOrderError` instead of silently rebuilding).  The
steady-state per-trial upload contract — O(P) bytes, not O(n_cap·P) —
is asserted from these counters in the tier-1 suite.

Two extensions for fleet mode (PR 8):

* **Bounded store** — ``_Resident`` state is keyed by trials identity
  and historically only ``forget()`` freed it, so a long-lived
  ``ServiceServer`` with churning tenants leaked device buffers.
  ``HYPEROPT_TPU_RESIDENT_HISTORY_CAP`` (0/unset = unbounded) caps the
  number of resident entries process-wide with LRU eviction
  (``history.evicted`` counter); an evicted experiment's next suggest
  pays one full re-upload, never a wrong answer.
* **Batched rings** — :class:`BatchedResident` /
  :func:`device_history_batched` stack the per-bucket ``(hv, ha, hl,
  hok)`` rings of N same-shape experiments along a leading axis so a
  cohort's whole history feed is one set of ``[B, n_cap, ...]`` device
  buffers: delta-append, constant-liar overlay and pregrow all gain a
  batch dim (``fleet.CohortScheduler`` drives them).  Per-lane content
  is bit-identical to the solo buffers — tests/test_fleet.py pins it.
"""

from __future__ import annotations

import os
import threading
import weakref
from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np

from .obs.events import EVENTS
from .obs.metrics import registry as _registry

__all__ = ["enabled", "device_history", "pregrow", "forget", "generation",
           "BatchedResident", "device_history_batched", "pregrow_batched",
           "resident_cap", "KEEP"]


class _Keep:
    """Sentinel lane marker for :func:`device_history_batched`: the lane
    belongs to a live experiment that is NOT part of this dispatch —
    leave its resident rows and metadata untouched (its output lane is
    simply unused) instead of clearing it like a padding lane."""

    __slots__ = ()

    def __repr__(self):  # pragma: no cover - debug nicety
        return "history.KEEP"


KEEP = _Keep()


def enabled() -> bool:
    """Resident-history gate (``HYPEROPT_TPU_RESIDENT_HISTORY``, default on)."""
    return os.environ.get("HYPEROPT_TPU_RESIDENT_HISTORY", "1").lower() \
        not in ("0", "off", "false")


def resident_cap() -> int:
    """Process-wide resident-entry cap (``HYPEROPT_TPU_RESIDENT_HISTORY_CAP``,
    0/unset/invalid = unbounded).  Read per call so a long-lived server
    can be retuned without a restart."""
    try:
        cap = int(os.environ.get("HYPEROPT_TPU_RESIDENT_HISTORY_CAP", "0"))
    except ValueError:
        return 0
    return max(cap, 0)


def _row_bytes(p: int) -> int:
    """Host→device bytes per history row: f32 vals + bool active + f32
    loss + bool ok."""
    return p * 4 + p + 4 + 1


# ---------------------------------------------------------------------------
# jitted buffer programs (shape-polymorphic via jit retracing)
# ---------------------------------------------------------------------------


def _append_impl(hv, ha, hl, hok, rows, acts, loss, ok, idx):
    hv = jax.lax.dynamic_update_slice(hv, rows, (idx, 0))
    ha = jax.lax.dynamic_update_slice(ha, acts, (idx, 0))
    hl = jax.lax.dynamic_update_slice(hl, loss, (idx,))
    hok = jax.lax.dynamic_update_slice(hok, ok, (idx,))
    return hv, ha, hl, hok


def _grow_impl(hv, ha, hl, hok, new_cap):
    # Pad values match _padded_history exactly: 0 vals, False active,
    # +inf loss, False ok.
    pad = new_cap - hv.shape[0]
    return (jnp.pad(hv, ((0, pad), (0, 0))),
            jnp.pad(ha, ((0, pad), (0, 0))),
            jnp.pad(hl, ((0, pad),), constant_values=np.inf),
            jnp.pad(hok, ((0, pad),)))


def _slice_impl(hv, ha, hl, hok, cap):
    return hv[:cap], ha[:cap], hl[:cap], hok[:cap]


def _overlay_impl(hv, ha, hl, hok, pv, pa, lie, idx):
    m = pv.shape[0]
    hv = jax.lax.dynamic_update_slice(hv, pv, (idx, 0))
    ha = jax.lax.dynamic_update_slice(ha, pa, (idx, 0))
    hl = jax.lax.dynamic_update_slice(
        hl, jnp.full((m,), lie, jnp.float32), (idx,))
    hok = jax.lax.dynamic_update_slice(
        hok, jnp.ones((m,), jnp.bool_), (idx,))
    return hv, ha, hl, hok


# -- batched (fleet) programs: same semantics, one leading cohort axis ------


def _append_b_impl(hv, ha, hl, hok, rows, acts, loss, ok, lane, idx):
    """Per-lane delta append into the stacked ``[B, cap, ...]`` buffers."""
    hv = jax.lax.dynamic_update_slice(hv, rows[None], (lane, idx, 0))
    ha = jax.lax.dynamic_update_slice(ha, acts[None], (lane, idx, 0))
    hl = jax.lax.dynamic_update_slice(hl, loss[None], (lane, idx))
    hok = jax.lax.dynamic_update_slice(hok, ok[None], (lane, idx))
    return hv, ha, hl, hok


def _grow_b_impl(hv, ha, hl, hok, new_cap):
    pad = new_cap - hv.shape[1]
    return (jnp.pad(hv, ((0, 0), (0, pad), (0, 0))),
            jnp.pad(ha, ((0, 0), (0, pad), (0, 0))),
            jnp.pad(hl, ((0, 0), (0, pad)), constant_values=np.inf),
            jnp.pad(hok, ((0, 0), (0, pad))))


def _slice_b_impl(hv, ha, hl, hok, cap):
    return hv[:, :cap], ha[:, :cap], hl[:, :cap], hok[:, :cap]


def _clear_b_impl(hv, ha, hl, hok, lane):
    """Reset one lane to the pad values (device-side, zero upload)."""
    cap, p = hv.shape[1], hv.shape[2]
    hv = jax.lax.dynamic_update_slice(
        hv, jnp.zeros((1, cap, p), hv.dtype), (lane, 0, 0))
    ha = jax.lax.dynamic_update_slice(
        ha, jnp.zeros((1, cap, p), jnp.bool_), (lane, 0, 0))
    hl = jax.lax.dynamic_update_slice(
        hl, jnp.full((1, cap), np.inf, hl.dtype), (lane, 0))
    hok = jax.lax.dynamic_update_slice(
        hok, jnp.zeros((1, cap), jnp.bool_), (lane, 0))
    return hv, ha, hl, hok


def _overlay_b_impl(hv, ha, hl, hok, pvz, paz, liez, start, mcnt):
    """Per-lane fantasy overlay with VARIABLE row counts.

    ``dynamic_update_slice`` cannot place a different number of rows per
    lane, so the overlay is a gather/where program instead: position
    ``j`` of lane ``b`` takes fantasy row ``j - start[b]`` when that
    index is in ``[0, mcnt[b])`` and the canonical row otherwise.
    ``pvz/paz/liez`` are ``[B, Mmax, ...]`` host-flattened slot rows
    (multi-slot lies flattened to one per-row lie vector, preserving the
    solo path's slot layout exactly)."""
    cap = hv.shape[1]
    j = jnp.arange(cap)[None, :] - start[:, None]          # [B, cap]
    inr = (j >= 0) & (j < mcnt[:, None])
    jc = jnp.clip(j, 0, pvz.shape[1] - 1)
    hv = jnp.where(inr[:, :, None],
                   jnp.take_along_axis(pvz, jc[:, :, None], axis=1), hv)
    ha = jnp.where(inr[:, :, None],
                   jnp.take_along_axis(paz, jc[:, :, None], axis=1), ha)
    hl = jnp.where(inr, jnp.take_along_axis(liez, jc, axis=1), hl)
    hok = jnp.where(inr, True, hok)
    return hv, ha, hl, hok


_FNS: dict = {}
_FNS_LOCK = threading.Lock()


def _donate_ok() -> bool:
    # Donation on the CPU backend is never honored and warns per program;
    # on TPU/GPU it lets XLA alias the append in place.
    try:
        return jax.default_backend() != "cpu"
    except Exception:  # pragma: no cover - backend init failure
        return False


def _fn(name: str):
    fn = _FNS.get(name)
    if fn is not None:
        return fn
    with _FNS_LOCK:
        fn = _FNS.get(name)
        if fn is None:
            donate = (0, 1, 2, 3) if _donate_ok() else ()
            if donate and name in ("append", "append_b", "clear_b"):
                _registry().counter("device.donated_programs").inc()
            if name == "append":
                # Exact-shape in-place aliasing; a donating program.
                fn = jax.jit(_append_impl, donate_argnums=donate)
            elif name == "grow":
                # Shapes differ old→new so donation could never alias —
                # plain pad-copy (device-side only, zero upload bytes).
                fn = jax.jit(_grow_impl, static_argnums=(4,))
            elif name == "slice":
                fn = jax.jit(_slice_impl, static_argnums=(4,))
            elif name == "overlay":
                # canonical buffers must SURVIVE — no donation
                fn = jax.jit(_overlay_impl)
            # batched (fleet) twins of the four programs above
            elif name == "append_b":
                fn = jax.jit(_append_b_impl, donate_argnums=donate)
            elif name == "grow_b":
                fn = jax.jit(_grow_b_impl, static_argnums=(4,))
            elif name == "slice_b":
                fn = jax.jit(_slice_b_impl, static_argnums=(4,))
            elif name == "clear_b":
                fn = jax.jit(_clear_b_impl, donate_argnums=donate)
            else:  # overlay_b: derived copy, canonical lanes survive
                fn = jax.jit(_overlay_b_impl)
            _FNS[name] = fn
    return fn


# ---------------------------------------------------------------------------
# resident store
# ---------------------------------------------------------------------------


class _Resident:
    """Canonical device buffers for one (trials, space, placement)."""

    __slots__ = ("cs", "cap", "n", "tids", "bufs")

    def __init__(self, cs, cap, n, tids, bufs):
        self.cs = cs        # strong ref: pins id(cs) while this entry lives
        self.cap = cap      # canonical capacity (monotone within an entry)
        self.n = n          # real rows resident
        self.tids = tids    # i64[n] — coherence fingerprint of those rows
        self.bufs = bufs    # (hv, ha, hl, hok) device arrays [cap, ...]


# trials → {(id(cs), shard_key): _Resident}.  Weak on the trials object so
# a finished experiment's device buffers free with it; _Resident holds cs
# strongly so the id(cs) key cannot be recycled while the entry lives.
_STORE: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_LOCK = threading.Lock()

# trials → wipe generation.  ``forget`` bumps it; external batched stores
# (fleet cohorts are NOT keyed by trials identity, so the WeakKeyDictionary
# pop cannot reach them) compare generations to catch tid reuse after
# ``delete_all`` — reinserted tids restart at 0 and can prefix-match a
# stale fingerprint that the tids check alone would wrongly accept.
_GENS: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def generation(trials) -> int:
    """Monotone wipe counter for ``trials`` (bumped by :func:`forget`).
    Feed it to :func:`device_history_batched` via ``gens`` so cohort
    lanes invalidate on tenant/experiment deletion."""
    try:
        return _GENS.get(trials, 0)
    except TypeError:
        return 0

# LRU order over every live resident entry: (weakref(trials), inner key) →
# None, hottest last.  Only consulted when a cap is set; dead referents
# fall out for free as their _STORE entries vanish.
_LRU: "OrderedDict" = OrderedDict()

# Every live BatchedResident (fleet lane stack), weakly held — consulted
# only by obs.device's HBM accounting, never on a hot path.
_BATCHED: "weakref.WeakSet" = weakref.WeakSet()


def _lru_touch(trials, key):
    """Mark (trials, key) most-recently-used and evict past the cap.
    Caller holds _LOCK."""
    try:
        ref = weakref.ref(trials)
    except TypeError:
        return
    _LRU[(ref, key)] = None
    _LRU.move_to_end((ref, key))
    cap = resident_cap()
    if not cap:
        return
    evicted = 0
    while len(_LRU) > cap:
        (ref, k), _ = _LRU.popitem(last=False)
        tr = ref()
        if tr is None:
            continue                      # referent died; nothing resident
        try:
            states = _STORE.get(tr)
        except TypeError:                 # pragma: no cover - exotic trials
            continue
        if states is not None and states.pop(k, None) is not None:
            evicted += 1
    if evicted:
        _registry().counter("history.evicted").inc(evicted)


def _lru_drop(trials):
    """Forget every LRU slot for ``trials``.  Caller holds _LOCK."""
    try:
        ref = weakref.ref(trials)
    except TypeError:
        return
    for k in [k for k in _LRU if k[0] == ref or k[0]() is None]:
        _LRU.pop(k, None)


def _states(trials):
    """Per-``trials`` resident-state dict, created under ``_LOCK``:
    two suggest threads racing the first touch must agree on ONE dict,
    or the loser's uploads land in a store nobody reads again."""
    try:
        with _LOCK:
            d = _STORE.get(trials)
            if d is None:
                d = {}
                _STORE[trials] = d
            return d
    except TypeError:       # exotic trials without weakref support
        return None


def _pad_full(h, cap, p):
    n = h["vals"].shape[0]
    vals = np.zeros((cap, p), np.float32)
    active = np.zeros((cap, p), bool)
    loss = np.full((cap,), np.inf, np.float32)
    ok = np.zeros((cap,), bool)
    vals[:n] = h["vals"]
    active[:n] = h["active"]
    loss[:n] = h["loss"]
    ok[:n] = h["ok"]
    return vals, active, loss, ok


def _put(arrs, sharding):
    if sharding is None:
        return tuple(jax.device_put(a) for a in arrs)
    return tuple(jax.device_put(a, sharding) for a in arrs)


def _validate(st, cs, h, p):
    """Coherence: the resident rows must be a tids-prefix of the current
    history (the exact check Trials.history() itself revalidates with)."""
    return (st is not None and st.cs is cs
            and st.bufs[0].shape[1] == p
            and st.n <= h["tids"].shape[0]
            and np.array_equal(st.tids, h["tids"][: st.n]))


class HistoryOrderError(RuntimeError):
    """The trials log REORDERED rows the resident ring already holds.

    The append path's contract is that completed trials are append-only
    in tid order; a silent full rebuild on reorder would mask the bug
    that scrambled the log (and burn a full re-upload every step while
    doing so).  Raised only on a *true* reorder — every resident tid
    still present, relative order changed — which no legitimate store
    operation (shrink, warm-start injection, a late async completion
    inserting a lower tid mid-history) produces; those keep the counted
    silent-rebuild fallback.
    """


def _check_tid_order(st, cs, h, p, reg):
    """Distinguish reorders from legitimate rebuild causes; raise on the
    former (``history.order_violations`` counter), return on the latter."""
    if st is None or st.cs is not cs or st.bufs[0].shape[1] != p \
            or st.n == 0:
        return
    pos = {int(t): i for i, t in
           enumerate(np.asarray(h["tids"]).tolist())}
    idxs = [pos.get(int(t)) for t in np.asarray(st.tids).tolist()]
    if any(ix is None for ix in idxs):
        return      # resident rows vanished/replaced: legitimate rebuild
    if all(b > a for a, b in zip(idxs, idxs[1:])):
        return      # still a subsequence (mid-insert): legitimate rebuild
    reg.counter("history.order_violations").inc()
    # Typed record alongside the counter: order violations are exactly
    # the corruption postmortems are opened for, so the event must be in
    # the ring when a flight bundle snapshots it.
    EVENTS.emit("history_order_violation", name="resident_ring",
                n_resident=int(st.n), positions=idxs[:8])
    raise HistoryOrderError(
        f"resident history rows appended out of tid order: the trials "
        f"log still contains all {st.n} resident tids but permuted them "
        f"(first rows now at log positions {idxs[:8]}...). The device "
        f"ring is append-only in tid order; a store that reorders "
        f"completed trials is corrupting the optimization history.")


def device_history(trials, cs, h, n_cap, fantasies=None, sharding=None,
                   shard_key=None):
    """Return ``(hv, ha, hl, hok)`` device arrays bit-identical to
    ``tpe._padded_history`` of ``h`` (+ optional constant-liar fantasy
    rows) at capacity ``n_cap``, uploading only the delta since the last
    call.

    ``fantasies`` is ``(pv f32[M,P], pa bool[M,P], lie f32)`` — overlaid
    into rows ``[n, n+M)`` of a DERIVED copy (exactly where the legacy
    host-side concat put them) without dirtying the canonical buffers.
    A LIST of such tuples is a multi-slot overlay: one slot per pending
    batch (the depth-D pipeline keeps D batches in flight, each with its
    own lie value), laid out contiguously from row ``n``.  Slots are
    clipped to the capacity slack — ``dynamic_update_slice`` would
    otherwise silently clamp the start index and overwrite REAL rows —
    and every clipped fantasy row increments ``history.fantasy_clipped``
    (``suggest_dispatch`` sizes the bucket to include fantasy rows, so a
    nonzero count means a caller bypassed that sizing).
    ``sharding``/``shard_key`` pin mesh placement for the sharded suggest
    paths (replicated history); distinct placements keep distinct
    canonical buffers.
    """
    n, p = h["vals"].shape
    reg = _registry()
    states = _states(trials)
    key = (id(cs), shard_key)
    with _LOCK:
        st = states.get(key) if states is not None else None
        if not _validate(st, cs, h, p):
            _check_tid_order(st, cs, h, p, reg)
            # Prefix mismatch (or first touch): ONE full re-upload at the
            # requested capacity — correctness fallback, never wrong rows.
            cap = max(n_cap, st.cap if st is not None else 0)
            bufs = _put(_pad_full(h, cap, p), sharding)
            st = _Resident(cs, cap, n, h["tids"], bufs)
            if states is not None:
                states[key] = st
            reg.counter("history.rebuilds").inc()
            reg.counter("history.upload_bytes").inc(cap * _row_bytes(p))
        else:
            if max(n_cap, n) > st.cap:
                # Rollover missed by the pregrow trigger (e.g. a batched
                # call's slack jumped a bucket): device pad-copy now.
                st.bufs = _fn("grow")(*st.bufs, max(n_cap, n))
                st.cap = max(n_cap, n)
            k = n - st.n
            if k > 0:
                rows = np.ascontiguousarray(h["vals"][st.n:n])
                acts = np.ascontiguousarray(h["active"][st.n:n])
                loss = np.ascontiguousarray(h["loss"][st.n:n])
                oks = np.ascontiguousarray(h["ok"][st.n:n])
                if sharding is not None:
                    rows, acts, loss, oks = _put((rows, acts, loss, oks),
                                                 sharding)
                st.bufs = _fn("append")(*st.bufs, rows, acts, loss, oks,
                                        np.int32(st.n))
                st.n = n
                st.tids = h["tids"]
                reg.counter("history.upload_bytes").inc(k * _row_bytes(p))
            reg.counter("history.append_hits").inc()
        if states is not None:
            _lru_touch(trials, key)
        out = st.bufs
    if st.cap > n_cap:
        # Canonical outgrew the request (pregrow band / post-batch single
        # call): derive the exact-capacity view device-side.
        out = _fn("slice")(*out, n_cap)
    if fantasies is not None:
        slots = fantasies if isinstance(fantasies, list) else [fantasies]
        idx = n
        for pv, pa, lie in slots:
            if not len(pv):
                continue
            room = n_cap - idx
            if room <= 0:
                reg.counter("history.fantasy_clipped").inc(len(pv))
                continue
            if len(pv) > room:
                reg.counter("history.fantasy_clipped").inc(len(pv) - room)
                pv, pa = pv[:room], pa[:room]
            if sharding is not None:
                pv, pa = _put((pv, pa), sharding)
            out = _fn("overlay")(*out, pv, pa, np.float32(lie), np.int32(idx))
            reg.counter("history.upload_bytes").inc(len(pv) * (p * 4 + p))
            idx += len(pv)
    return out


def pregrow(trials, cs, n_cap, shard_key=None):
    """Roll the canonical buffers to ``n_cap`` ahead of the bucket flip.

    Piggybacks on ``suggest_dispatch``'s ``_prewarm_async`` boundary
    trigger (``n_rows >= 0.75·cap``): the pad-copy runs while the current
    bucket still has headroom, so the first call on the next bucket pays
    neither a compile (prewarmed) nor the copy.  Pure device work — no
    host→device bytes.  No-op when the store is cold or already big.
    """
    states = _states(trials)
    if states is None:
        return
    with _LOCK:
        st = states.get((id(cs), shard_key))
        if st is None or st.cap >= n_cap:
            return
        st.bufs = _fn("grow")(*st.bufs, n_cap)
        st.cap = n_cap


def forget(trials):
    """Drop all resident buffers for ``trials`` (frees device memory).

    Called by stores that know their history is going away wholesale
    (``Trials.delete_all``, pool shutdown, the netstore/service
    ``delete_all`` verb); ordinary mutation needs no call — the
    tids-prefix check catches it.
    """
    with _LOCK:
        _lru_drop(trials)
        try:
            _STORE.pop(trials, None)
            _GENS[trials] = _GENS.get(trials, 0) + 1
        except TypeError:
            pass


# ---------------------------------------------------------------------------
# batched (fleet) resident store
# ---------------------------------------------------------------------------


class BatchedResident:
    """Stacked canonical device buffers for a cohort of experiments.

    The fleet twin of :class:`_Resident`: one set of ``[B, cap, ...]``
    buffers, one lane per experiment, owned by its
    :class:`~hyperopt_tpu.fleet.CohortScheduler` cohort (lifetime is the
    scheduler's problem, so no weak keying here).  Per-lane cursors and
    tids fingerprints drive the same delta-append / coherence-fallback
    contract as the solo store.
    """

    __slots__ = ("b", "cap", "p", "n", "tids", "gens", "filled", "bufs",
                 "__weakref__")

    def __init__(self, b: int, cap: int, p: int):
        self.b = b
        self.cap = cap
        self.p = p
        self.n = [0] * b            # real rows resident per lane
        self.tids = [None] * b      # per-lane coherence fingerprint
        self.gens = [0] * b         # per-lane wipe generation (see _GENS)
        self.filled = [False] * b   # lane ever held real rows?
        self.bufs = _put((np.zeros((b, cap, p), np.float32),
                          np.zeros((b, cap, p), bool),
                          np.full((b, cap), np.inf, np.float32),
                          np.zeros((b, cap), bool)), None)
        # Telemetry-only weak registration: lets obs.device report live
        # lane-stack HBM without any ownership or lifetime coupling.
        with _LOCK:
            _BATCHED.add(self)


def _lane_coherent(st: BatchedResident, i: int, h, gen: int) -> bool:
    return (st.tids[i] is not None
            and st.gens[i] == gen
            and st.n[i] <= h["tids"].shape[0]
            and np.array_equal(st.tids[i], h["tids"][: st.n[i]]))


def device_history_batched(store, lanes, n_cap, fantasies=None, gens=None):
    """Batched history feed for one cohort: returns ``(store, bufs)``
    with ``bufs = (hv[B,n_cap,P], ha, hl[B,n_cap], hok)`` where lane
    ``i`` is bit-identical to ``tpe._padded_history(lanes[i], n_cap)``
    (+ that lane's constant-liar overlay).

    ``lanes`` is a length-B list of ``Trials.history()`` dicts, ``None``
    marking a padding lane (empty history) and :data:`KEEP` marking an
    occupied lane whose experiment sits out this dispatch (buffers left
    untouched, output unused).  ``store`` is the
    :class:`BatchedResident` returned by the previous call for this
    cohort, or ``None`` on first touch; lane count / param-width changes
    rebuild it wholesale, capacity growth is a device pad-copy, and a
    coherent lane uploads only its delta rows.  ``fantasies`` is an
    optional length-B list of per-lane overlay specs in
    :func:`device_history` form (a ``(pv, pa, lie)`` tuple or a list of
    slot tuples, or ``None``); the overlay lands in a DERIVED copy via a
    variable-count gather program, leaving the canonical lanes clean.
    ``gens`` is an optional length-B list of :func:`generation` values —
    a lane whose generation moved (its trials was wiped via ``forget`` /
    ``delete_all``) is re-uploaded wholesale even if reused tids happen
    to prefix-match the stale fingerprint.
    """
    b = len(lanes)
    if gens is None:
        gens = [0] * b
    real = [h for h in lanes if isinstance(h, dict)]
    if not real:
        raise ValueError("device_history_batched: all lanes are padding")
    p = real[0]["vals"].shape[1]
    reg = _registry()
    if (store is None or store.b != b or store.p != p
            or store.cap > n_cap):
        # Shape migration (new cohort tier / param width / capacity
        # shrink): start clean.  Capacity only ever shrinks when the
        # cohort key changed, which re-keys the store anyway.
        store = BatchedResident(b, n_cap, p)
    elif store.cap < n_cap:
        store.bufs = _fn("grow_b")(*store.bufs, n_cap)
        store.cap = n_cap
    cap = store.cap
    for i, h in enumerate(lanes):
        if h is KEEP:
            # Live experiment sitting out this dispatch: resident rows
            # and metadata stay put; its output lane is unused.
            continue
        if h is None:
            if store.filled[i]:
                store.bufs = _fn("clear_b")(*store.bufs, np.int32(i))
                store.n[i], store.tids[i] = 0, None
                store.filled[i] = False
            store.gens[i] = gens[i]
            continue
        n = h["vals"].shape[0]
        if _lane_coherent(store, i, h, gens[i]):
            k = n - store.n[i]
            if k > 0:
                store.bufs = _fn("append_b")(
                    *store.bufs,
                    np.ascontiguousarray(h["vals"][store.n[i]:n]),
                    np.ascontiguousarray(h["active"][store.n[i]:n]),
                    np.ascontiguousarray(h["loss"][store.n[i]:n]),
                    np.ascontiguousarray(h["ok"][store.n[i]:n]),
                    np.int32(i), np.int32(store.n[i]))
                reg.counter("history.upload_bytes").inc(k * _row_bytes(p))
            store.n[i], store.tids[i] = n, h["tids"]
            reg.counter("history.append_hits").inc()
        else:
            # First touch, prefix mismatch, or wipe-generation change:
            # one full-lane re-upload (padded to cap, so it also clears
            # any stale slack rows).
            store.bufs = _fn("append_b")(
                *store.bufs, *_pad_full(h, cap, p), np.int32(i), np.int32(0))
            store.n[i], store.tids[i] = n, h["tids"]
            store.filled[i] = True
            reg.counter("history.rebuilds").inc()
            reg.counter("history.upload_bytes").inc(cap * _row_bytes(p))
        store.gens[i] = gens[i]
        store.filled[i] = store.filled[i] or n > 0
    out = store.bufs
    if cap > n_cap:
        out = _fn("slice_b")(*out, n_cap)
    if fantasies is not None and any(f is not None for f in fantasies):
        out = _overlay_batched(out, lanes, fantasies, n_cap, p, reg)
    return store, out


def _overlay_batched(bufs, lanes, fantasies, n_cap, p, reg):
    """Flatten per-lane fantasy slots into padded ``[B, Mmax, ...]``
    arrays and apply the gather overlay — slot layout (contiguous from
    each lane's ``n``, per-slot lie values, capacity clipping + the
    ``history.fantasy_clipped`` counter) identical to the solo path."""
    b = len(lanes)
    rows_v, rows_a, rows_l, start, mcnt = [], [], [], [], []
    clipped = upload = 0
    for i in range(b):
        f = fantasies[i] if i < len(fantasies) else None
        n = lanes[i]["vals"].shape[0] if isinstance(lanes[i], dict) else 0
        slots = [] if f is None else (f if isinstance(f, list) else [f])
        pv_l, pa_l, lie_l = [], [], []
        for pv, pa, lie in slots:
            if len(pv):
                pv_l.append(np.asarray(pv, np.float32))
                pa_l.append(np.asarray(pa, bool))
                lie_l.append(np.full(len(pv), lie, np.float32))
        total = sum(len(v) for v in pv_l)
        room = max(n_cap - n, 0)
        m = min(total, room)
        if total > m:
            clipped += total - m
        rows_v.append(np.concatenate(pv_l)[:m] if total
                      else np.zeros((0, p), np.float32))
        rows_a.append(np.concatenate(pa_l)[:m] if total
                      else np.zeros((0, p), bool))
        rows_l.append(np.concatenate(lie_l)[:m] if total
                      else np.zeros((0,), np.float32))
        start.append(n)
        mcnt.append(m)
        upload += m * (p * 4 + p + 4)
    mmax = max(max(mcnt), 1)
    pvz = np.zeros((b, mmax, p), np.float32)
    paz = np.zeros((b, mmax, p), bool)
    liez = np.zeros((b, mmax), np.float32)
    for i in range(b):
        m = mcnt[i]
        if m:
            pvz[i, :m] = rows_v[i]
            paz[i, :m] = rows_a[i]
            liez[i, :m] = rows_l[i]
    if clipped:
        reg.counter("history.fantasy_clipped").inc(clipped)
    if not any(mcnt):
        return bufs
    reg.counter("history.upload_bytes").inc(upload)
    return _fn("overlay_b")(*bufs, pvz, paz, liez,
                            np.asarray(start, np.int32),
                            np.asarray(mcnt, np.int32))


def pregrow_batched(store, n_cap):
    """Roll a cohort's stacked buffers to ``n_cap`` ahead of the bucket
    flip (the batch-dim twin of :func:`pregrow`): pure device pad-copy,
    zero host→device bytes.  No-op when cold or already big."""
    if store is None or store.cap >= n_cap:
        return store
    store.bufs = _fn("grow_b")(*store.bufs, n_cap)
    store.cap = n_cap
    return store
