"""Device-resident history feed: O(P) per-trial host→device transfer.

``tpe.suggest_dispatch`` used to rebuild the full padded history on host
(``_padded_history`` — fresh ``n_cap×P`` numpy allocs) and re-upload all
of it every call: O(n_cap·P) bytes across the axon tunnel per step for a
delta of one row.  This module keeps the padded ``(hv, ha, hl, hok)``
buffers RESIDENT on device, per ``(trials, space, mesh-placement)``, with
an append cursor:

* **Append** — only the newly completed ``[k, P]`` rows (+ losses/flags)
  cross host→device, through a jitted ``dynamic_update_slice`` program
  whose history operands are donated (in-place XLA aliasing) on
  accelerator backends.
* **Coherence** — the same tids-prefix check ``Trials.history()`` uses:
  the store remembers the tids of the rows it holds, and any mismatch
  (deletions, warm-start injection, multi-process stores rewriting the
  log) falls back to ONE full re-upload.  Never wrong answers; the
  fallback is counted, not silent.
* **Bucket rollover** — a single on-device pad-copy to the next
  power-of-two capacity, pre-triggered from ``suggest_dispatch``'s
  ``_prewarm_async`` boundary check so the switchover call doesn't pay
  it; zero host→device bytes.
* **In-flight fantasies** — ``_with_inflight_fantasies``'s host-side
  concat would dirty the buffers every overlapped step, so constant-liar
  rows are instead OVERLAID device-side into the slack rows past
  ``n_real`` (a non-donating program: the canonical buffers survive
  untouched for the next append).

Gate: ``HYPEROPT_TPU_RESIDENT_HISTORY`` (default on; ``=0`` restores the
legacy host-padded feed).  The buffer CONTENT is bit-identical to
``_padded_history`` either way — tests/test_history.py pins seeded
proposal parity — so the toggle is a transfer-path choice, not a math
choice.

Instrumentation (``obs.metrics``): ``history.upload_bytes`` (every
host→device byte this module moves), ``history.append_hits`` (calls
served by the delta path), ``history.rebuilds`` (full re-uploads).  The
steady-state per-trial upload contract — O(P) bytes, not O(n_cap·P) —
is asserted from these counters in the tier-1 suite.
"""

from __future__ import annotations

import os
import threading
import weakref

import jax
import jax.numpy as jnp
import numpy as np

from .obs.metrics import registry as _registry

__all__ = ["enabled", "device_history", "pregrow", "forget"]


def enabled() -> bool:
    """Resident-history gate (``HYPEROPT_TPU_RESIDENT_HISTORY``, default on)."""
    return os.environ.get("HYPEROPT_TPU_RESIDENT_HISTORY", "1").lower() \
        not in ("0", "off", "false")


def _row_bytes(p: int) -> int:
    """Host→device bytes per history row: f32 vals + bool active + f32
    loss + bool ok."""
    return p * 4 + p + 4 + 1


# ---------------------------------------------------------------------------
# jitted buffer programs (shape-polymorphic via jit retracing)
# ---------------------------------------------------------------------------


def _append_impl(hv, ha, hl, hok, rows, acts, loss, ok, idx):
    hv = jax.lax.dynamic_update_slice(hv, rows, (idx, 0))
    ha = jax.lax.dynamic_update_slice(ha, acts, (idx, 0))
    hl = jax.lax.dynamic_update_slice(hl, loss, (idx,))
    hok = jax.lax.dynamic_update_slice(hok, ok, (idx,))
    return hv, ha, hl, hok


def _grow_impl(hv, ha, hl, hok, new_cap):
    # Pad values match _padded_history exactly: 0 vals, False active,
    # +inf loss, False ok.
    pad = new_cap - hv.shape[0]
    return (jnp.pad(hv, ((0, pad), (0, 0))),
            jnp.pad(ha, ((0, pad), (0, 0))),
            jnp.pad(hl, ((0, pad),), constant_values=np.inf),
            jnp.pad(hok, ((0, pad),)))


def _slice_impl(hv, ha, hl, hok, cap):
    return hv[:cap], ha[:cap], hl[:cap], hok[:cap]


def _overlay_impl(hv, ha, hl, hok, pv, pa, lie, idx):
    m = pv.shape[0]
    hv = jax.lax.dynamic_update_slice(hv, pv, (idx, 0))
    ha = jax.lax.dynamic_update_slice(ha, pa, (idx, 0))
    hl = jax.lax.dynamic_update_slice(
        hl, jnp.full((m,), lie, jnp.float32), (idx,))
    hok = jax.lax.dynamic_update_slice(
        hok, jnp.ones((m,), jnp.bool_), (idx,))
    return hv, ha, hl, hok


_FNS: dict = {}
_FNS_LOCK = threading.Lock()


def _donate_ok() -> bool:
    # Donation on the CPU backend is never honored and warns per program;
    # on TPU/GPU it lets XLA alias the append in place.
    try:
        return jax.default_backend() != "cpu"
    except Exception:  # pragma: no cover - backend init failure
        return False


def _fn(name: str):
    fn = _FNS.get(name)
    if fn is not None:
        return fn
    with _FNS_LOCK:
        fn = _FNS.get(name)
        if fn is None:
            donate = (0, 1, 2, 3) if _donate_ok() else ()
            if name == "append":
                # Exact-shape in-place aliasing; the only donating program.
                fn = jax.jit(_append_impl, donate_argnums=donate)
            elif name == "grow":
                # Shapes differ old→new so donation could never alias —
                # plain pad-copy (device-side only, zero upload bytes).
                fn = jax.jit(_grow_impl, static_argnums=(4,))
            elif name == "slice":
                fn = jax.jit(_slice_impl, static_argnums=(4,))
            else:  # overlay: canonical buffers must SURVIVE — no donation
                fn = jax.jit(_overlay_impl)
            _FNS[name] = fn
    return fn


# ---------------------------------------------------------------------------
# resident store
# ---------------------------------------------------------------------------


class _Resident:
    """Canonical device buffers for one (trials, space, placement)."""

    __slots__ = ("cs", "cap", "n", "tids", "bufs")

    def __init__(self, cs, cap, n, tids, bufs):
        self.cs = cs        # strong ref: pins id(cs) while this entry lives
        self.cap = cap      # canonical capacity (monotone within an entry)
        self.n = n          # real rows resident
        self.tids = tids    # i64[n] — coherence fingerprint of those rows
        self.bufs = bufs    # (hv, ha, hl, hok) device arrays [cap, ...]


# trials → {(id(cs), shard_key): _Resident}.  Weak on the trials object so
# a finished experiment's device buffers free with it; _Resident holds cs
# strongly so the id(cs) key cannot be recycled while the entry lives.
_STORE: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_LOCK = threading.Lock()


def _states(trials):
    try:
        d = _STORE.get(trials)
        if d is None:
            d = {}
            _STORE[trials] = d
        return d
    except TypeError:       # exotic trials without weakref support
        return None


def _pad_full(h, cap, p):
    n = h["vals"].shape[0]
    vals = np.zeros((cap, p), np.float32)
    active = np.zeros((cap, p), bool)
    loss = np.full((cap,), np.inf, np.float32)
    ok = np.zeros((cap,), bool)
    vals[:n] = h["vals"]
    active[:n] = h["active"]
    loss[:n] = h["loss"]
    ok[:n] = h["ok"]
    return vals, active, loss, ok


def _put(arrs, sharding):
    if sharding is None:
        return tuple(jax.device_put(a) for a in arrs)
    return tuple(jax.device_put(a, sharding) for a in arrs)


def _validate(st, cs, h, p):
    """Coherence: the resident rows must be a tids-prefix of the current
    history (the exact check Trials.history() itself revalidates with)."""
    return (st is not None and st.cs is cs
            and st.bufs[0].shape[1] == p
            and st.n <= h["tids"].shape[0]
            and np.array_equal(st.tids, h["tids"][: st.n]))


def device_history(trials, cs, h, n_cap, fantasies=None, sharding=None,
                   shard_key=None):
    """Return ``(hv, ha, hl, hok)`` device arrays bit-identical to
    ``tpe._padded_history`` of ``h`` (+ optional constant-liar fantasy
    rows) at capacity ``n_cap``, uploading only the delta since the last
    call.

    ``fantasies`` is ``(pv f32[M,P], pa bool[M,P], lie f32)`` — overlaid
    into rows ``[n, n+M)`` of a DERIVED copy (exactly where the legacy
    host-side concat put them) without dirtying the canonical buffers.
    A LIST of such tuples is a multi-slot overlay: one slot per pending
    batch (the depth-D pipeline keeps D batches in flight, each with its
    own lie value), laid out contiguously from row ``n``.  Slots are
    clipped to the capacity slack — ``dynamic_update_slice`` would
    otherwise silently clamp the start index and overwrite REAL rows —
    and every clipped fantasy row increments ``history.fantasy_clipped``
    (``suggest_dispatch`` sizes the bucket to include fantasy rows, so a
    nonzero count means a caller bypassed that sizing).
    ``sharding``/``shard_key`` pin mesh placement for the sharded suggest
    paths (replicated history); distinct placements keep distinct
    canonical buffers.
    """
    n, p = h["vals"].shape
    reg = _registry()
    states = _states(trials)
    key = (id(cs), shard_key)
    with _LOCK:
        st = states.get(key) if states is not None else None
        if not _validate(st, cs, h, p):
            # Prefix mismatch (or first touch): ONE full re-upload at the
            # requested capacity — correctness fallback, never wrong rows.
            cap = max(n_cap, st.cap if st is not None else 0)
            bufs = _put(_pad_full(h, cap, p), sharding)
            st = _Resident(cs, cap, n, h["tids"], bufs)
            if states is not None:
                states[key] = st
            reg.counter("history.rebuilds").inc()
            reg.counter("history.upload_bytes").inc(cap * _row_bytes(p))
        else:
            if max(n_cap, n) > st.cap:
                # Rollover missed by the pregrow trigger (e.g. a batched
                # call's slack jumped a bucket): device pad-copy now.
                st.bufs = _fn("grow")(*st.bufs, max(n_cap, n))
                st.cap = max(n_cap, n)
            k = n - st.n
            if k > 0:
                rows = np.ascontiguousarray(h["vals"][st.n:n])
                acts = np.ascontiguousarray(h["active"][st.n:n])
                loss = np.ascontiguousarray(h["loss"][st.n:n])
                oks = np.ascontiguousarray(h["ok"][st.n:n])
                if sharding is not None:
                    rows, acts, loss, oks = _put((rows, acts, loss, oks),
                                                 sharding)
                st.bufs = _fn("append")(*st.bufs, rows, acts, loss, oks,
                                        np.int32(st.n))
                st.n = n
                st.tids = h["tids"]
                reg.counter("history.upload_bytes").inc(k * _row_bytes(p))
            reg.counter("history.append_hits").inc()
        out = st.bufs
    if st.cap > n_cap:
        # Canonical outgrew the request (pregrow band / post-batch single
        # call): derive the exact-capacity view device-side.
        out = _fn("slice")(*out, n_cap)
    if fantasies is not None:
        slots = fantasies if isinstance(fantasies, list) else [fantasies]
        idx = n
        for pv, pa, lie in slots:
            if not len(pv):
                continue
            room = n_cap - idx
            if room <= 0:
                reg.counter("history.fantasy_clipped").inc(len(pv))
                continue
            if len(pv) > room:
                reg.counter("history.fantasy_clipped").inc(len(pv) - room)
                pv, pa = pv[:room], pa[:room]
            if sharding is not None:
                pv, pa = _put((pv, pa), sharding)
            out = _fn("overlay")(*out, pv, pa, np.float32(lie), np.int32(idx))
            reg.counter("history.upload_bytes").inc(len(pv) * (p * 4 + p))
            idx += len(pv)
    return out


def pregrow(trials, cs, n_cap, shard_key=None):
    """Roll the canonical buffers to ``n_cap`` ahead of the bucket flip.

    Piggybacks on ``suggest_dispatch``'s ``_prewarm_async`` boundary
    trigger (``n_rows >= 0.75·cap``): the pad-copy runs while the current
    bucket still has headroom, so the first call on the next bucket pays
    neither a compile (prewarmed) nor the copy.  Pure device work — no
    host→device bytes.  No-op when the store is cold or already big.
    """
    states = _states(trials)
    if states is None:
        return
    with _LOCK:
        st = states.get((id(cs), shard_key))
        if st is None or st.cap >= n_cap:
            return
        st.bufs = _fn("grow")(*st.bufs, n_cap)
        st.cap = n_cap


def forget(trials):
    """Drop all resident buffers for ``trials`` (frees device memory).

    Called by stores that know their history is going away wholesale
    (``Trials.delete_all``, pool shutdown); ordinary mutation needs no
    call — the tids-prefix check catches it.
    """
    with _LOCK:
        try:
            _STORE.pop(trials, None)
        except TypeError:
            pass
