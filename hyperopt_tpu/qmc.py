"""Quasi-Monte-Carlo suggest: scrambled-Sobol / Halton low-discrepancy search.

Beyond-reference addition (upstream hyperopt has only pseudo-random
``rand.suggest`` — SURVEY.md §2 rand.py): a low-discrepancy sequence covers
the search space far more evenly at small budgets, which matters exactly
where the reference's defaults live — the ``n_startup_jobs=20`` warm-start
trials that seed TPE's first posterior.  Use standalone::

    fmin(fn, space, algo=hyperopt_tpu.qmc.suggest, ...)

or as TPE's startup phase (string alias or the module itself)::

    fmin(fn, space, algo=partial(tpe.suggest, startup="qmc"), ...)

Design: startup-scale work (tens of points, P columns) is host-side numpy —
one inverse-CDF transform per distribution family over the unit hypercube,
then the compiled space's activity mask.  No device round-trip; the jitted
path stays reserved for the EI sweeps where the FLOPs are.

Successive calls CONTINUE the sequence: one engine is cached per (trials
object, engine name, dimension) — scrambled with the FIRST call's seed,
fast-forwarded past any pre-existing trials (resume), then advanced
naturally — so 20 trials enqueued one-at-a-time cover the hypercube
exactly like 20 enqueued at once.  Later calls' seeds are deliberately
ignored: re-scrambling mid-experiment would destroy the joint
low-discrepancy property.  A resumed experiment (fresh Trials handle)
starts a new scramble at the right sequence position.
"""

from __future__ import annotations

import threading

import numpy as np
from scipy import special
from scipy.stats import qmc as _qmc

from . import base
from .space import (
    CATEGORICAL,
    LOGNORMAL,
    LOGUNIFORM,
    NORMAL,
    QLOGNORMAL,
    QLOGUNIFORM,
    QNORMAL,
    QUNIFORM,
    RANDINT,
    UNIFORMINT,
    UNIFORM,
)

_LOG_KINDS = (LOGUNIFORM, QLOGUNIFORM, LOGNORMAL, QLOGNORMAL)


def _transform_column(spec, u):
    """Inverse-CDF map of uniform[0,1) draws ``u`` onto one parameter."""
    kind = spec.kind
    if kind == CATEGORICAL or (kind == RANDINT and spec.probs is not None):
        probs = np.asarray(spec.probs, dtype=np.float64)
        edges = np.cumsum(probs)
        edges[-1] = 1.0                      # guard fp round-down
        v = np.searchsorted(edges, u, side="right").astype(np.float64)
        if kind == RANDINT and spec.low:
            v += spec.low
        return v
    if kind in (UNIFORM, LOGUNIFORM, QUNIFORM, QLOGUNIFORM):
        z = spec.low + u * (spec.high - spec.low)
    elif kind == UNIFORMINT:
        return np.floor(spec.low + u * (spec.high - spec.low + 1)).clip(
            spec.low, spec.high)
    elif kind == RANDINT:
        return np.floor(spec.low + u * (spec.high - spec.low)).clip(
            spec.low, spec.high - 1)
    else:   # normal family: mu + sigma * Phi^-1(u)
        z = spec.mu + spec.sigma * special.ndtri(np.clip(u, 1e-12, 1 - 1e-12))
    if kind in _LOG_KINDS:
        z = np.exp(z)
    if spec.q:
        z = np.round(z / spec.q) * spec.q
        if kind in (QUNIFORM, QLOGUNIFORM):
            lo = np.exp(spec.low) if kind == QLOGUNIFORM else spec.low
            hi = np.exp(spec.high) if kind == QLOGUNIFORM else spec.high
            z = np.clip(z, np.round(lo / spec.q) * spec.q,
                        np.round(hi / spec.q) * spec.q)
    return z


# One engine per (trials object, engine name, dim), held weakly so it dies
# with the experiment.  The scramble seed must stay FIXED while the
# sequence position advances — re-scrambling per fmin iteration (each call
# gets a fresh `seed` from the rstate stream) would destroy the joint
# low-discrepancy property the module exists for.
_engines = None
_engines_lock = threading.RLock()   # re-entered by suggest_batch around the draw


def _engine_for(trials, name, dim, seed):
    # Locked: two threads suggesting against the same Trials must not race
    # setdefault/engine creation and hand out duplicate or restarted Sobol
    # points.  Cheap — one lookup per suggest call.
    global _engines
    with _engines_lock:
        if _engines is None:
            import weakref

            _engines = weakref.WeakKeyDictionary()
        per_trials = _engines.setdefault(trials, {})
        key = (name, dim)
        eng = per_trials.get(key)
        if eng is None:
            cls = {"sobol": _qmc.Sobol, "halton": _qmc.Halton}[name]
            eng = cls(d=dim, scramble=True, seed=int(seed) % (2 ** 32))
            # Resume case (pre-existing trials, e.g. exp_key/pickle resume):
            # skip the points the experiment already consumed.  The
            # re-scramble only affects joint uniformity across the resume
            # boundary.
            if len(trials):
                eng.fast_forward(len(trials))
            per_trials[key] = eng
        return eng


def suggest_batch(new_ids, domain, trials, seed, engine="sobol"):
    """Raw (vals[n, P], active[n, P]) low-discrepancy samples."""
    cs = domain.cs
    n = len(new_ids)
    if n == 0 or cs.n_params == 0:
        return (np.zeros((n, cs.n_params), np.float32),
                np.ones((n, cs.n_params), bool))
    # The draw advances the engine's sequence position non-atomically, so
    # it needs the same lock as lookup/creation — otherwise two threads can
    # receive identical points from the shared engine.
    with _engines_lock:
        eng = _engine_for(trials, engine, cs.n_params, seed)
        u = eng.random(n)                                # [n, P] in [0, 1)
    vals = np.zeros((n, cs.n_params), np.float32)
    for j, spec in enumerate(cs.params):
        vals[:, j] = _transform_column(spec, u[:, j])
    return vals, cs.active_mask_host(vals)


def suggest(new_ids, domain, trials, seed, engine="sobol"):
    """QMC suggest (plugin contract: ``suggest(new_ids, domain, trials,
    seed)``).  ``engine`` is ``"sobol"`` (default) or ``"halton"``."""
    vals, active = suggest_batch(new_ids, domain, trials, seed, engine=engine)
    return base.docs_from_samples(domain.cs, new_ids, vals, active,
                                  exp_key=getattr(trials, "exp_key", None))


def suggest_halton(new_ids, domain, trials, seed):
    return suggest(new_ids, domain, trials, seed, engine="halton")


#: registry hook (hyperopt_tpu.backends.contract resolves through this)
BACKENDS = {"qmc": suggest, "sobol": suggest, "halton": suggest_halton}
