"""`fmin` — the optimization loop and public API.

Reference: ``hyperopt/fmin.py`` (SURVEY.md §2 L5 — ``FMinIter`` ~L60-300,
``fmin()`` ~L300-550, ``space_eval`` ~L560, ``generate_trials_to_calculate``
~L580; mount was empty, anchors from upstream hyperopt).

The plugin boundaries the north star requires are preserved exactly:

* ``algo=`` — any callable ``suggest(new_ids, domain, trials, seed) -> docs``;
  bind hyperparameters with ``functools.partial(tpe.suggest, gamma=...)``.
* ``trials=`` — any :class:`~hyperopt_tpu.base.Trials` subclass; asynchronous
  subclasses only get docs enqueued and are polled until the queue drains.
"""

from __future__ import annotations

import logging
import numbers
import os
import pickle
import time
from functools import partial  # re-exported for reference parity

import numpy as np

from . import base
from .base import (
    Ctrl,
    Domain,
    JOB_STATE_DONE,
    JOB_STATE_ERROR,
    JOB_STATE_NEW,
    JOB_STATE_RUNNING,
    Trials,
    coarse_utcnow,
)
from .exceptions import AllTrialsFailed, is_transient
from .obs import context as _context
from .obs import flight as _flight
from .obs import metrics as _metrics
from .obs.events import EVENTS
from .space import compile_space
from .utils.progress import default_callback, no_progress_callback

logger = logging.getLogger(__name__)


def space_eval(space, hp_assignment: dict):
    """Substitute a ``{label: value}`` assignment into a search space.

    Reference: ``hyperopt/fmin.py::space_eval``.  Accepts the dicts produced
    by ``fmin(return_argmin=True)`` / ``trials.argmin`` (choice values are
    branch indices) and returns the concrete nested configuration.
    """
    return compile_space(space).eval_point(hp_assignment)


def fmin_pass_expr_memo_ctrl(f):
    """Decorator marking an objective as wanting ``(expr, memo, ctrl)``
    instead of a realized config (reference:
    ``hyperopt/fmin.py::fmin_pass_expr_memo_ctrl``); ``Domain`` reads the
    attribute when ``fmin(..., pass_expr_memo_ctrl=None)``."""
    f.fmin_pass_expr_memo_ctrl = True
    return f


def generate_trials_to_calculate(points, exp_key=None):
    """Seed a ``Trials`` with predetermined points to evaluate first.

    Reference: ``hyperopt/fmin.py::generate_trials_to_calculate``.
    ``points`` is a list of ``{label: value}`` dicts.
    """
    trials = Trials(exp_key=exp_key)
    docs = []
    for tid, pt in enumerate(points):
        doc = base.new_trial_doc(tid, exp_key=exp_key)
        doc["misc"]["idxs"] = {k: [tid] for k in pt}
        doc["misc"]["vals"] = {k: [v] for k, v in pt.items()}
        docs.append(doc)
    trials.insert_trial_docs(docs)
    trials.refresh()
    return trials


class FMinIter:
    """The scheduler loop (reference: ``hyperopt/fmin.py::FMinIter``).

    Iterating yields after each batch of completed trials; ``exhaust()`` runs
    to ``max_evals``.  Synchronous trials are evaluated in-process by
    ``serial_evaluate``; asynchronous trials are enqueued and polled.
    """

    catch_eval_exceptions = False
    pickle_protocol = -1

    def __init__(self, algo, domain, trials, rstate=None,
                 early_stop_fn=None, trials_save_file="",
                 asynchronous=None, max_queue_len=1,
                 poll_interval_secs=0.1, max_evals=None,
                 timeout=None, loss_threshold=None,
                 show_progressbar=True, verbose=False, trace_dir=None,
                 overlap_suggest=False, overlap_depth=None, evaluators=None,
                 max_trial_retries=None):
        from .obs import NullTracer, Tracer
        trace_dir = trace_dir or os.environ.get("HYPEROPT_TPU_TRACE_DIR")
        self.tracer = (Tracer(trace_dir, device_trace=True) if trace_dir
                       else NullTracer())
        self.algo = algo
        self.domain = domain
        self.trials = trials
        if rstate is None:
            rstate = np.random.default_rng()
        self.rstate = rstate
        self.early_stop_fn = early_stop_fn
        self.early_stop_args: list = []
        self.trials_save_file = trials_save_file
        if asynchronous is None:
            self.asynchronous = bool(getattr(trials, "asynchronous", False))
        else:
            self.asynchronous = asynchronous
        self.max_queue_len = max_queue_len
        self.poll_interval_secs = poll_interval_secs
        self.max_evals = max_evals
        self.timeout = timeout
        self.loss_threshold = loss_threshold
        self.start_time = time.time()
        self.show_progressbar = show_progressbar
        self.verbose = verbose
        # Per-trial transient-failure budget: a trial whose evaluation
        # dies with a *transient* error (exceptions.is_transient — injected
        # faults, netstore outages, user-raised TransientEvaluationError)
        # is re-run on the SAME point up to this many times, with
        # fail_count bookkeeping on the doc, before it settles as a
        # permanent failure.  0 (default) = today's fail-fast behavior.
        if max_trial_retries is None:
            env_retries = os.environ.get(
                "HYPEROPT_TPU_MAX_TRIAL_RETRIES", "")
            try:
                max_trial_retries = int(env_retries) if env_retries else 0
            except ValueError:
                logger.warning("ignoring non-integer "
                               "HYPEROPT_TPU_MAX_TRIAL_RETRIES=%r",
                               env_retries)
                max_trial_retries = 0
        self.max_trial_retries = max(0, int(max_trial_retries))
        # serial_evaluate's monotone scan cursor: _dynamic_trials is
        # append-only and settled states never revert to NEW, so every
        # batch resumes the NEW-trial scan where the last one stopped
        # (O(N) total bookkeeping over a run instead of O(N²)).
        self._serial_cursor = 0
        # PP-analog overlap (SURVEY.md §2 parallelism table), generalized
        # to a depth-D pipeline (hyperopt_tpu/pipeline.py): up to D suggest
        # dispatch handles in flight feed `evaluators` concurrent workers
        # through a completion queue.  overlap_suggest=True is the depth-1
        # single-evaluator alias, which reproduces the historical overlap
        # stream bit-for-bit; HYPEROPT_TPU_PIPELINE_DEPTH overrides the
        # default depth process-wide.  Needs a dispatch-capable algo
        # (tpe.suggest / suggest_quantile) and a synchronous backend; the
        # in-flight posterior is up to D batches stale — the standard
        # async-optimizer tradeoff, fantasy-compensated via Trials.inflight.
        if overlap_depth is None:
            env_depth = os.environ.get("HYPEROPT_TPU_PIPELINE_DEPTH", "")
            if env_depth:
                try:
                    overlap_depth = int(env_depth)
                except ValueError:
                    logger.warning("ignoring non-integer "
                                   "HYPEROPT_TPU_PIPELINE_DEPTH=%r", env_depth)
        evaluators = 1 if evaluators is None else max(1, int(evaluators))
        if overlap_depth is None:
            depth = 1 if overlap_suggest else 0
        else:
            depth = max(0, int(overlap_depth))
        if depth == 0 and evaluators > 1:
            depth = 1  # concurrent evaluation needs the pipelined loop
        self.overlap_depth = depth
        self.evaluators = evaluators
        self._pipeline = None
        if depth > 0 and not self.asynchronous:
            fn, kw = algo, {}
            if isinstance(algo, partial) and not algo.args:
                fn = algo.func
                kw = dict(algo.keywords or {})
            d = getattr(fn, "dispatch", None)
            m = getattr(fn, "materialize", None)
            if d is not None and m is not None:
                from .pipeline import PipelinedExecutor
                self._pipeline = PipelinedExecutor(
                    self, depth=depth, evaluators=evaluators,
                    dispatch=lambda ids, dom, tr, seed: d(
                        ids, dom, tr, seed, **kw),
                    materialize=m,
                    handle_ready=getattr(fn, "handle_ready", None),
                    start_transfer=getattr(fn, "start_transfer", None))
        self.overlap_suggest = self._pipeline is not None

    # -- evaluation ---------------------------------------------------------

    def serial_evaluate(self, N=-1):
        _reg = _metrics.registry()
        dyn = self.trials._dynamic_trials
        # Monotone cursor: everything before it is settled (DONE/ERROR) —
        # NEW trials only ever appear by append, so each batch scans the
        # tail instead of re-walking the full log (10k-trial runs used to
        # pay an O(N²) rescan here).  fmin.scan_skipped accumulates the
        # avoided doc visits; the cursor stalls (never reverses) on
        # transient RUNNING docs from async/pool backends.
        cur = min(self._serial_cursor, len(dyn))
        _reg.counter("fmin.scan_skipped").inc(cur)
        advance = True
        for i in range(cur, len(dyn)):
            trial = dyn[i]
            if trial["state"] != JOB_STATE_NEW:
                if advance and trial["state"] in (JOB_STATE_DONE,
                                                  JOB_STATE_ERROR):
                    self._serial_cursor = i + 1
                else:
                    advance = False
                continue
            trial["state"] = JOB_STATE_RUNNING
            trial["book_time"] = coarse_utcnow()
            EVENTS.emit("trial_start", trial=trial["tid"])
            ctrl = Ctrl(self.trials, current_trial=trial)
            try:
                spec = base.spec_from_misc(trial["misc"])
                # Events emitted inside the objective (faults, compiles,
                # user instrumentation) attach to this trial via the
                # ambient context; free when tracing is disarmed.
                with _context.bind_doc(trial):
                    while True:
                        try:
                            result = self.domain.evaluate(spec, ctrl)
                            break
                        except Exception as e:
                            fail_count = trial["misc"].get("fail_count", 0)
                            if not (is_transient(e)
                                    and fail_count < self.max_trial_retries):
                                raise
                            # Transient: charge the budget and re-run the
                            # SAME point instead of losing it to a
                            # permanent FAIL.
                            trial["misc"]["fail_count"] = fail_count + 1
                            _reg.counter("fmin.trials.retried").inc()
                            EVENTS.emit("trial_retry", trial=trial["tid"],
                                        attempt=fail_count + 1,
                                        error=type(e).__name__)
            except Exception as e:
                logger.error("job exception: %s", e)
                trial["state"] = JOB_STATE_ERROR
                trial["misc"]["error"] = (type(e).__name__, str(e))
                trial["refresh_time"] = coarse_utcnow()
                EVENTS.emit("trial_end", trial=trial["tid"], state="error",
                            error=type(e).__name__)
                _reg.counter("fmin.trials.error").inc()
                if not self.catch_eval_exceptions:
                    self.trials.refresh()
                    raise
            else:
                trial["state"] = JOB_STATE_DONE
                trial["result"] = result
                trial["refresh_time"] = coarse_utcnow()
                EVENTS.emit("trial_end", trial=trial["tid"], state="done",
                            loss=result.get("loss"))
                _reg.counter("fmin.trials.done").inc()
            if advance:
                self._serial_cursor = i + 1
            N -= 1
            if N == 0:
                break
        self.trials.refresh()

    def block_until_done(self):
        if self.asynchronous:
            unfinished = (JOB_STATE_NEW, JOB_STATE_RUNNING)
            cancelled = False
            while self.trials.count_by_state_unsynced(unfinished) > 0:
                if not cancelled and self.timeout is not None and \
                        time.time() - self.start_time >= self.timeout:
                    # Global fmin timeout: don't wait out stragglers — stop
                    # them (reference: SparkTrials cancellation on timeout).
                    self._cancel_inflight("fmin timeout")
                    cancelled = True
                if cancelled and not callable(
                        getattr(self.trials, "cancel_inflight", None)):
                    # The backend can't cancel (file/net stores): trials
                    # left NEW/RUNNING may never finish — a dead worker
                    # fleet would park us here forever.  Return with
                    # best-so-far; the store keeps the stragglers.
                    logger.warning(
                        "fmin timeout with %d unfinished trial(s) left "
                        "in the store",
                        self.trials.count_by_state_unsynced(unfinished))
                    break
                time.sleep(self.poll_interval_secs)
                self.trials.refresh()
        else:
            self.serial_evaluate()

    # -- loop ---------------------------------------------------------------

    def _stopped(self, n_done):
        if self.max_evals is not None and n_done >= self.max_evals:
            return True
        if self.timeout is not None and \
                time.time() - self.start_time >= self.timeout:
            return True
        if self.loss_threshold is not None:
            try:
                if self.trials.best_trial["result"]["loss"] <= \
                        self.loss_threshold:
                    return True
            except AllTrialsFailed:
                pass
        return False

    def run_one_batch(self):
        """Enqueue up to ``max_queue_len`` new trials and evaluate/poll once.

        Returns True if the experiment should stop (algo exhausted or early
        stop fired).  This is the plain (non-pipelined) loop body; when a
        pipeline is configured, ``_loop`` delegates to
        :class:`~hyperopt_tpu.pipeline.PipelinedExecutor` instead.
        """
        trials = self.trials
        stopped = False

        qlen = trials.count_by_state_unsynced((JOB_STATE_NEW,
                                               JOB_STATE_RUNNING))
        remaining = (self.max_evals - self.n_enqueued()
                     if self.max_evals is not None else self.max_queue_len)
        n_to_enqueue = min(self.max_queue_len - qlen, remaining)
        if n_to_enqueue > 0:
            with self.tracer.span("suggest"):
                seed = int(self.rstate.integers(2 ** 31 - 1))
                new_ids = trials.new_trial_ids(n_to_enqueue)
                trials.refresh()
                new_trials = self.algo(new_ids, self.domain, trials, seed)
                EVENTS.emit("suggest",
                            n=0 if new_trials is None else len(new_trials))
            if new_trials is None or len(new_trials) == 0:
                stopped = True
            else:
                if _context.armed():
                    # Stamp the run's trace context into each doc so any
                    # process that later claims it (netstore server, file
                    # or net workers) attaches its spans to this trial.
                    for doc in new_trials:
                        _context.stamp_misc(doc["misc"], tid=doc["tid"],
                                            trace_id=self.tracer.trace_id)
                if EVENTS.enabled:
                    for doc in new_trials:
                        EVENTS.emit("trial_queued", trial=doc["tid"])
                with self.tracer.span("store"):
                    trials.insert_trial_docs(new_trials)
                    trials.refresh()

        if self.asynchronous:
            with self.tracer.span("poll"):
                time.sleep(self.poll_interval_secs)
                trials.refresh()
        else:
            with self.tracer.span("evaluate"):
                self.serial_evaluate()

        with self.tracer.span("save"):
            self._save_trials()

        if self.early_stop_fn is not None:
            with self.tracer.span("early_stop"):
                stop, kwargs = self.early_stop_fn(self.trials,
                                                  *self.early_stop_args)
            self.early_stop_args = kwargs
            if stop:
                logger.info("early stop triggered")
                self._cancel_inflight("early stop")
                stopped = True
        _metrics.registry().counter("fmin.batches").inc()
        return stopped

    def _cancel_inflight(self, reason):
        """Stop in-flight work on backends that support cancellation
        (reference: SparkTrials cancels its job group on timeout/early stop,
        SURVEY.md §3.5)."""
        cancel = getattr(self.trials, "cancel_inflight", None)
        if callable(cancel):
            n = cancel(reason)
            if n:
                logger.info("cancelled %d in-flight trial(s): %s", n, reason)

    def n_done(self):
        return self.trials.count_by_state_unsynced(
            (JOB_STATE_DONE, JOB_STATE_ERROR))

    def n_enqueued(self):
        return self.trials.count_by_state_unsynced(
            (JOB_STATE_NEW, JOB_STATE_RUNNING, JOB_STATE_DONE,
             JOB_STATE_ERROR))

    def _save_trials(self):
        if not self.trials_save_file:
            return
        if self.trials_save_file.endswith(".json"):
            # Portable checkpoint: plain-JSON trial docs (the same encoding
            # FileTrials stores on disk), loadable without unpickling
            # arbitrary code.  Attachments and Trials-subclass state are
            # NOT captured — use the pickle form (any other extension) or a
            # durable FileTrials for those.
            import json

            def _default(o):
                # User result dicts routinely carry np.float32/np.int64 (loss
                # is coerced, extra keys are not); persist them as plain
                # scalars rather than crashing the checkpoint mid-run.
                if isinstance(o, np.generic):
                    return o.item()
                if isinstance(o, np.ndarray):
                    return o.tolist()
                raise TypeError(
                    f"trial doc contains non-JSON-serializable {type(o).__name__}; "
                    "use a pickle trials_save_file (non-.json extension) for "
                    "arbitrary result payloads")

            tmp = f"{self.trials_save_file}.tmp.{os.getpid()}"
            try:
                with open(tmp, "w") as f:
                    json.dump({"exp_key": self.trials.exp_key,
                               "docs": list(self.trials)}, f, default=_default)
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
            os.replace(tmp, self.trials_save_file)
            EVENTS.emit("store_flush", name="trials_save_file")
            return
        with open(self.trials_save_file, "wb") as f:
            pickle.dump(self.trials, f, protocol=self.pickle_protocol)
        EVENTS.emit("store_flush", name="trials_save_file")

    def run(self, N, block_until_done=True):
        """Reference-compat: enqueue+evaluate ~N more trials."""
        target = self.n_done() + N
        saved_max = self.max_evals
        self.max_evals = target if saved_max is None else min(saved_max, target)
        try:
            self._loop()
        finally:
            self.max_evals = saved_max
        if block_until_done:
            self.block_until_done()

    def _loop(self):
        progress_ctx = default_callback if self.show_progressbar \
            else no_progress_callback
        with progress_ctx(initial=self.n_done(), total=self.max_evals) as prog:
            if self._pipeline is not None:
                status = self._pipeline.run(prog)
                if status != "fallback":
                    return self
                # The executor hit its consecutive-slot-failure cap,
                # drained cleanly, and asked us to finish the run on the
                # plain synchronous loop (pipeline.py::_FALLBACK_AFTER).
                logger.warning("pipeline fell back to the synchronous loop")
            while not self._stopped(self.n_done()):
                before = self.n_done()
                stopped = self.run_one_batch()
                after = self.n_done()
                prog.update(after - before)
                try:
                    prog.postfix(self.trials.best_trial["result"]["loss"])
                except AllTrialsFailed:
                    pass
                if stopped:
                    break
                if after == before and not self.asynchronous:
                    break  # no forward progress possible
        return self

    def __iter__(self):
        """Step-wise iteration (reference: FMinIter is its own iterator):
        yields the number of completed trials after each batch."""
        while not self._stopped(self.n_done()):
            before = self.n_done()
            stopped = self.run_one_batch()
            yield self.n_done()
            if stopped or (self.n_done() == before and not self.asynchronous):
                break

    def exhaust(self):
        """Run until ``max_evals`` complete (or a stop condition fires)."""
        # Arm the flight recorder when a dump dir is configured
        # (HYPEROPT_TPU_FLIGHT_DIR) — a no-op otherwise, so every run
        # gets black-box capture for free once the env knob is set.
        _flight.install()
        self.tracer.start_device_trace()
        t0 = time.perf_counter()
        try:
            self._loop()
            self.block_until_done()
        except BaseException as e:
            # Freeze the black box before the exception unwinds the
            # driver; on_crash ignores operator interrupts.
            _flight.on_crash("fmin", e)
            raise
        finally:
            wall = time.perf_counter() - t0
            if wall > 0:
                _metrics.registry().gauge("fmin.trials_per_sec").set(
                    self.n_done() / wall)
            self.tracer.set_wall(wall)
            self.tracer.stop_device_trace()
            self.tracer.dump()
        return self


def fmin(fn, space, algo=None, max_evals=None,
         timeout=None, loss_threshold=None,
         trials=None, rstate=None,
         allow_trials_fmin=True, pass_expr_memo_ctrl=None,
         catch_eval_exceptions=False,
         verbose=True, return_argmin=True,
         points_to_evaluate=None, max_queue_len=1,
         show_progressbar=True, early_stop_fn=None,
         trials_save_file="", trace_dir=None, overlap_suggest=False,
         overlap_depth=None, evaluators=None, max_trial_retries=None,
         mode=None, sync_stride=None):
    """Minimize ``fn`` over ``space`` using ``algo``.

    Reference-parity signature: ``hyperopt/fmin.py::fmin`` (SURVEY.md §2 L5).

    Parameters mirror the reference: ``fn`` objective (returns float loss or a
    result dict with ``loss``/``status``), ``space`` an ``hp.*`` structure,
    ``algo`` a suggest callable (default TPE), ``max_evals``, wall-clock
    ``timeout`` (seconds), ``loss_threshold``, ``trials`` (plugin boundary),
    ``rstate`` (``np.random.Generator``), ``points_to_evaluate`` (list of
    ``{label: value}`` dicts run first), ``trials_save_file`` (pickle
    checkpoint, auto-resume), ``early_stop_fn(trials, *args)->(stop, args)``,
    ``return_argmin`` (return best point dict vs None).

    TPU-first addition: the pipelined loop (``hyperopt_tpu/pipeline.py``).
    ``overlap_depth=D`` keeps up to D suggest dispatches in flight on
    device — each started with ``copy_to_host_async`` so materialization
    never fetch-syncs — while ``evaluators=E`` worker threads run the
    objective concurrently, recording results through a completion queue
    as they land.  ``overlap_suggest=True`` is the ``overlap_depth=1,
    evaluators=1`` alias and reproduces the historical overlap stream
    bit-for-bit; ``HYPEROPT_TPU_PIPELINE_DEPTH`` overrides the default
    depth process-wide.  The in-flight posterior is up to D batches stale
    (constant-liar fantasies for pending trials compensate — Snoek et al.
    2012).  Requires a dispatch-capable algo (``tpe.suggest`` /
    ``tpe.suggest_quantile``, optionally ``functools.partial``-bound);
    silently degrades to the ordinary loop otherwise.

    Robustness addition: ``max_trial_retries=N`` re-runs a trial on the
    same point up to N times when its evaluation dies with a *transient*
    error (``hyperopt_tpu.exceptions.is_transient`` — injected faults,
    ``NetstoreUnavailable``, user-raised ``TransientEvaluationError``)
    before it settles as a permanent failure; each retry increments
    ``fail_count`` in the trial's ``misc``.  Default 0 (fail fast);
    ``HYPEROPT_TPU_MAX_TRIAL_RETRIES`` sets the process-wide default.

    Whole-loop-on-device addition: ``mode='device'`` runs the entire
    suggest→evaluate→record loop on the accelerator for JAX-traceable
    objectives (``hyperopt_tpu/device.py`` module doc for the objective
    contract: a flat ``{label: f32 scalar}`` dict under jit).  Trials land
    in ``trials`` in bulk every ``sync_stride`` evaluations (``None`` = one
    fetch for the whole run); ``early_stop_fn``, ``timeout`` and
    ``loss_threshold`` are checked on the landed slab between strides.  At
    ``sync_stride=1`` the run is seeded-bit-parity with the hosted loop
    (same ``rstate`` draw cadence, same seeded kernel entries).  Only
    TPE-family ``algo`` values compose (``tpe.suggest`` /
    ``suggest_quantile``, optionally ``partial``-bound); host-loop-only
    options (``points_to_evaluate``, ``pass_expr_memo_ctrl``, pipelining,
    retries, ``trials_save_file``) raise.  Device runs stay observable
    through the in-carry telemetry slab (``HYPEROPT_TPU_DEVICE_TELEMETRY``,
    default on): per-segment best-so-far / EI levels / anomaly counts are
    backfilled into events, metrics, health, costs and flight bundles at
    every sync boundary without perturbing sampled trials — see
    ``obs/devtel.py`` and docs/OBSERVABILITY.md "Device mode".  See
    docs/API.md "fmin modes".
    """
    if mode not in (None, "host", "device"):
        raise ValueError(f"mode must be None, 'host' or 'device', "
                         f"got {mode!r}")
    if sync_stride is not None and mode != "device":
        raise ValueError("sync_stride only applies to mode='device'")
    if algo is None:
        algo = "tpe"
    if isinstance(algo, str):
        # String names resolve through the backend registry (TPU-first
        # addition; the reference requires the callable form, which of
        # course still works).  register_backend-registered heads are
        # addressable here by name, same as the builtins.
        from .backends import contract as _backends
        algo = _backends.resolve(algo)

    if rstate is None:
        env_seed = os.environ.get("HYPEROPT_FMIN_SEED", "")
        if env_seed:
            rstate = np.random.default_rng(int(env_seed))
        else:
            rstate = np.random.default_rng()
    elif isinstance(rstate, (int, np.integer)):
        rstate = np.random.default_rng(int(rstate))

    validate_timeout(timeout)
    validate_loss_threshold(loss_threshold)

    if trials_save_file and os.path.exists(trials_save_file) and trials is None:
        if trials_save_file.endswith(".json"):
            import json

            with open(trials_save_file) as f:
                payload = json.load(f)
            trials = base.trials_from_docs(payload["docs"],
                                           exp_key=payload.get("exp_key"))
        else:
            with open(trials_save_file, "rb") as f:
                trials = pickle.load(f)

    if trials is None:
        if points_to_evaluate is None:
            trials = Trials()
        else:
            if not isinstance(points_to_evaluate, list):
                raise ValueError("points_to_evaluate must be a list of dicts")
            trials = generate_trials_to_calculate(points_to_evaluate)

    if mode == "device":
        unsupported = [name for name, v in (
            ("points_to_evaluate", points_to_evaluate),
            ("pass_expr_memo_ctrl", pass_expr_memo_ctrl),
            ("catch_eval_exceptions", catch_eval_exceptions or None),
            ("overlap_suggest", overlap_suggest or None),
            ("overlap_depth", overlap_depth),
            ("evaluators", evaluators),
            ("max_trial_retries", max_trial_retries),
            ("trials_save_file", trials_save_file or None),
        ) if v is not None]
        if unsupported:
            raise ValueError(
                "mode='device' runs the whole loop on the accelerator; "
                "host-loop option(s) do not apply: "
                + ", ".join(unsupported))
        if max_evals is None:
            raise ValueError("mode='device' requires max_evals (the "
                             "compiled loop needs a trial budget)")
        if getattr(trials, "asynchronous", False):
            raise ValueError("mode='device' evaluates on device; "
                             "asynchronous Trials backends do not apply")
        algo_kw = _device_algo_kwargs(algo)
        from .device import fmin_trials as _device_fmin_trials

        _device_fmin_trials(
            fn, space, max_evals=max_evals, trials=trials, rstate=rstate,
            sync_stride=sync_stride, early_stop_fn=early_stop_fn,
            timeout=timeout, loss_threshold=loss_threshold,
            show_progressbar=show_progressbar and verbose, **algo_kw)
        if return_argmin:
            if len(trials.trials) == 0:
                raise AllTrialsFailed(
                    "There are no evaluation tasks, cannot return argmin "
                    "of task losses.")
            return trials.argmin
        if len(trials) > 0:
            return trials.best_trial["result"]["loss"]
        return None

    if allow_trials_fmin and hasattr(trials, "fmin") and \
            type(trials).fmin is not Trials.fmin:
        # durable/async backends may implement their own fmin; delegate.
        return trials.fmin(
            fn, space, algo=algo, max_evals=max_evals, timeout=timeout,
            loss_threshold=loss_threshold, rstate=rstate,
            pass_expr_memo_ctrl=pass_expr_memo_ctrl,
            verbose=verbose, catch_eval_exceptions=catch_eval_exceptions,
            return_argmin=return_argmin, show_progressbar=show_progressbar,
            early_stop_fn=early_stop_fn, trials_save_file=trials_save_file,
            max_trial_retries=max_trial_retries, trace_dir=trace_dir)

    domain = Domain(fn, space, pass_expr_memo_ctrl=pass_expr_memo_ctrl)

    rval = FMinIter(algo, domain, trials, rstate=rstate,
                    early_stop_fn=early_stop_fn,
                    trials_save_file=trials_save_file,
                    max_queue_len=max_queue_len,
                    max_evals=max_evals, timeout=timeout,
                    loss_threshold=loss_threshold,
                    show_progressbar=show_progressbar and verbose,
                    verbose=verbose, trace_dir=trace_dir,
                    overlap_suggest=overlap_suggest,
                    overlap_depth=overlap_depth, evaluators=evaluators,
                    max_trial_retries=max_trial_retries)
    rval.catch_eval_exceptions = catch_eval_exceptions
    rval.exhaust()
    rval._save_trials()

    if return_argmin:
        if len(trials.trials) == 0:
            raise AllTrialsFailed(
                f"There are no evaluation tasks, cannot return argmin of task losses.")
        return trials.argmin
    if len(trials) > 0:
        return trials.best_trial["result"]["loss"]
    return None


#: algo keywords the device loop bakes into its compiled program — the
#: TPE tuning surface, minus anything host-loop-only.
_DEVICE_ALGO_KEYS = frozenset((
    "gamma", "prior_weight", "n_startup_jobs", "n_EI_candidates",
    "linear_forgetting", "split", "multivariate", "cat_prior"))


def _device_algo_kwargs(algo):
    """Map a TPE-family ``algo`` callable onto device-loop kwargs.

    The device loop does not call ``algo`` (its suggest step is compiled
    into the scan body), so the callable is only a carrier for tuning
    kwargs: ``functools.partial(tpe.suggest, gamma=...)`` unwraps to
    ``{'gamma': ...}``.  Anything that is not ``tpe.suggest`` /
    ``suggest_quantile`` — or that binds a host-only option like
    ``startup='qmc'`` — raises, because silently running a different
    algorithm than the one the caller named would be worse than failing.
    """
    from . import tpe as _tpe

    kw = {}
    fn_ = algo
    while isinstance(fn_, partial):
        if fn_.args:
            raise ValueError("mode='device': partial-bound positional "
                             "algo args are not supported")
        for k, v in (fn_.keywords or {}).items():
            kw.setdefault(k, v)
        fn_ = fn_.func
    if fn_ is _tpe.suggest_quantile:
        kw.setdefault("split", "quantile")
    elif fn_ is not _tpe.suggest:
        name = getattr(fn_, "__name__", repr(fn_))
        raise ValueError(
            f"mode='device' supports the TPE family only (tpe.suggest / "
            f"tpe.suggest_quantile, optionally functools.partial-bound); "
            f"got {name}. Use algo='tpe' or run mode=None.")
    kw.pop("verbose", None)
    startup = kw.pop("startup", None)
    if startup not in (None, "rand"):
        raise ValueError(
            f"mode='device': startup={startup!r} is host-only; the "
            "compiled loop warm-starts with the pseudo-random sampler")
    bad = sorted(set(kw) - _DEVICE_ALGO_KEYS)
    if bad:
        raise ValueError(
            "mode='device' cannot honor algo keyword(s) "
            f"{bad}; supported: {sorted(_DEVICE_ALGO_KEYS)}")
    return kw


def validate_timeout(timeout):
    if timeout is not None and (not isinstance(timeout, numbers.Real)
                                or timeout <= 0):
        raise Exception(f"The timeout argument should be None or a positive "
                        f"value. Given value: {timeout}")


def validate_loss_threshold(loss_threshold):
    if loss_threshold is not None and not isinstance(loss_threshold,
                                                     numbers.Real):
        raise Exception(f"The loss_threshold argument should be None or a "
                        f"numeric value. Given value: {loss_threshold}")
