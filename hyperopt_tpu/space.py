"""Search-space specification and its XLA compiler.

This replaces the reference's ``pyll`` stochastic expression graph + interpreter
(``hyperopt/pyll/base.py::rec_eval``, ``hyperopt/pyll/stochastic.py``,
``hyperopt/vectorize.py::VectorizeHelper`` — anchors per SURVEY.md §2; the
reference mount was empty, symbols cited from upstream hyperopt).

Design (TPU-first, NOT a translation):

* The reference *interprets* a graph of ``Apply`` nodes per call, and represents
  N vectorized samples of a conditional space as ragged ``idxs``/``vals`` lists.
  Ragged host-side interpretation is hostile to XLA, so here a space is
  **compiled once** into a pure, shape-static sampler:

      ``sample(key, n) -> (vals: f32[n, P], active: bool[n, P])``

  Every one of the P scalar hyperparameters gets a dense column; parameters
  sitting under an unchosen ``hp.choice`` branch are still drawn (negligible
  wasted FLOPs) but masked out in ``active``.  Dense vals + boolean mask is the
  MXU/VPU-friendly encoding of the reference's ragged idxs/vals.

* Conditional structure is static: each parameter carries the full chain of
  ``(choice_param_id, branch_index)`` conditions under which it is live, so
  ``active`` is a handful of fused equality/AND ops.

* Sampling is batched by *family*, not per-parameter: one ``uniform`` draw for
  every uniform-family column, one ``normal`` draw for every normal-family
  column and one Gumbel-argmax for every categorical column, followed by
  vectorized affine/exp/round transforms.  A 100-dim space costs 3 RNG calls,
  not 100.

Distribution semantics mirror ``hyperopt/pyll/stochastic.py`` (SURVEY.md §2):
uniform, loguniform, quniform, qloguniform, normal, lognormal, qnormal,
qlognormal, randint, uniformint, categorical (choice / pchoice).
Quantized variants compute ``round(x / q) * q`` like the reference.
"""

from __future__ import annotations


import math
import operator as _operator
from collections import OrderedDict
from dataclasses import dataclass
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .exceptions import DuplicateLabel, InvalidAnnotatedParameter

# ---------------------------------------------------------------------------
# DSL expression nodes (what hp.* constructors build, what users nest in
# dicts / lists / tuples)
# ---------------------------------------------------------------------------

# Distribution kind tags.
UNIFORM = "uniform"
LOGUNIFORM = "loguniform"
QUNIFORM = "quniform"
QLOGUNIFORM = "qloguniform"
NORMAL = "normal"
LOGNORMAL = "lognormal"
QNORMAL = "qnormal"
QLOGNORMAL = "qlognormal"
RANDINT = "randint"
UNIFORMINT = "uniformint"
CATEGORICAL = "categorical"

# Families used by the batched sampler / TPE posterior builder.
_UNIFORM_FAMILY = (UNIFORM, LOGUNIFORM, QUNIFORM, QLOGUNIFORM, UNIFORMINT)
_NORMAL_FAMILY = (NORMAL, LOGNORMAL, QNORMAL, QLOGNORMAL)
_INT_KINDS = (RANDINT, UNIFORMINT, CATEGORICAL)
_LOG_KINDS = (LOGUNIFORM, QLOGUNIFORM, LOGNORMAL, QLOGNORMAL)
_Q_KINDS = (QUNIFORM, QLOGUNIFORM, QNORMAL, QLOGNORMAL)


# Widest hp.randint range representable exactly in the f32 vals matrix.
_MAX_RANDINT_RANGE = 2 ** 24


def prng_impl() -> str:
    """PRNG lowering for every key this package creates.

    ``HYPEROPT_TPU_PRNG``: ``threefry2x32`` (default — JAX's default
    counter-based generator, identical streams on every backend) or
    ``rbg`` (XLA RngBitGenerator: the TPU's hardware generator for the
    bit draws, threefry only for ``split``/``fold_in``).  Motivation:
    the round-5 on-chip profile attributes ~3 ms of the ~11.6 ms true
    step compute to threefry bit generation alone
    (``profile_step_tpu_20260801_0836.json`` ``rng_bits``) — ALU work
    the hardware generator does nearly for free.  Different impls are
    different RNG STREAMS (seeded runs re-baseline), same
    distributions (the KS/χ² suite passes under either).
    """
    import os

    env = os.environ.get("HYPEROPT_TPU_PRNG", "threefry2x32")
    return env if env in ("threefry2x32", "rbg", "unsafe_rbg") \
        else "threefry2x32"


def prng_key(seed):
    """``jax.random.key`` under the :func:`prng_impl` lowering — the one
    key-construction entry every suggest/sample path uses (traceable:
    ``seed`` may be a traced uint32, as in the seeded-jit entries)."""
    return jax.random.key(seed, impl=prng_impl())
# Above this many options a randint is sampled by integer draw instead of
# materialized per-option logits (dense logits are what TPE's categorical
# posterior consumes; wide randints use the quantized-continuous posterior).
_DENSE_CAT_MAX = 1024


class Expr:
    """Base class for search-space expressions built by ``hp.*`` / ``scope``.

    Supports the reference's pyll arithmetic composition (``hyperopt/pyll/
    base.py`` operator overloads on ``Apply`` nodes, SURVEY.md §2):
    ``hp.uniform("x", 0, 1) * 10 + 1`` builds a deterministic expression
    tree over the stochastic leaves.
    """

    __slots__ = ()

    # -- pyll-parity operator overloads (each builds an Apply node) ---------

    def __add__(self, other):
        return Apply("add", (self, other))

    def __radd__(self, other):
        return Apply("add", (other, self))

    def __sub__(self, other):
        return Apply("sub", (self, other))

    def __rsub__(self, other):
        return Apply("sub", (other, self))

    def __mul__(self, other):
        return Apply("mul", (self, other))

    def __rmul__(self, other):
        return Apply("mul", (other, self))

    def __truediv__(self, other):
        return Apply("truediv", (self, other))

    def __rtruediv__(self, other):
        return Apply("truediv", (other, self))

    def __floordiv__(self, other):
        return Apply("floordiv", (self, other))

    def __rfloordiv__(self, other):
        return Apply("floordiv", (other, self))

    def __mod__(self, other):
        return Apply("mod", (self, other))

    def __pow__(self, other):
        return Apply("pow", (self, other))

    def __rpow__(self, other):
        return Apply("pow", (other, self))

    def __neg__(self):
        return Apply("neg", (self,))

    def __abs__(self):
        return Apply("abs", (self,))

    def __getitem__(self, item):
        return Apply("getitem", (self, item))

    def __iter__(self):
        # Without this, Python's legacy iteration protocol would fall back
        # to __getitem__(0), __getitem__(1), ... — each returning a fresh
        # Apply node — so list(expr)/unpacking/np coercion would hang
        # building an infinite sequence instead of failing fast.
        raise TypeError(
            f"{type(self).__name__} expressions are not iterable")

    # Make numpy defer to the operator overloads above instead of trying to
    # coerce/iterate the expression into an array.
    __array_ufunc__ = None


class Apply(Expr):
    """A deterministic operation over sub-expressions (pyll ``Apply`` analog).

    Reference: ``hyperopt/pyll/base.py`` builtin ops via ``@scope.define``
    (``getitem``, ``switch``, arithmetic, ``len`` — ~L900+) and the
    ubiquitous ``scope.int(hp.quniform(...))`` idiom.

    TPU-first placement: expressions are **decode-time host transforms**.
    The stochastic leaves stay dense device columns (sampled and modeled by
    TPE exactly as before — the reference likewise stores raw
    ``hyperopt_param`` draws in ``misc.vals`` and applies expressions during
    ``rec_eval`` config reconstruction, SURVEY.md §3.3), so expression
    nodes cost nothing on the suggest hot path.
    """

    __slots__ = ("op", "args")

    def __init__(self, op: str, args: tuple):
        if op not in _SCOPE_IMPLS:
            raise InvalidAnnotatedParameter(
                f"unknown scope op {op!r}; register it with "
                f"hyperopt_tpu.scope.define")
        self.op = op
        self.args = tuple(args)

    def __repr__(self):
        return f"scope.{self.op}({', '.join(map(repr, self.args))})"


# Host-side implementations of scope ops (callable at decode time).
# Extended by @scope.define (hyperopt_tpu/scope.py).
_SCOPE_IMPLS = {
    "add": _operator.add,
    "sub": _operator.sub,
    "mul": _operator.mul,
    "truediv": _operator.truediv,
    "div": _operator.truediv,
    "floordiv": _operator.floordiv,
    "mod": _operator.mod,
    "pow": _operator.pow,
    "neg": _operator.neg,
    "abs": abs,
    "int": int,
    "float": float,
    "round": round,
    "log": math.log,
    "log2": math.log2,
    "log10": math.log10,
    "exp": math.exp,
    "sqrt": math.sqrt,
    "ceil": math.ceil,
    "floor": math.floor,
    "min": min,
    "max": max,
    "len": len,
    "getitem": _operator.getitem,
    "pos_args": lambda *a: tuple(a),
    # "switch" is structural (lazy branch selection) — handled by the
    # compiler/decoder directly, never called as a plain function.
    "switch": None,
}


def define_op(name: str, fn) -> None:
    """Register a host-side implementation for a scope op (the extension
    point behind ``@scope.define``, reference: ``pyll.scope.define``)."""
    if name in _SCOPE_IMPLS:
        raise ValueError(f"scope op {name!r} already defined")
    _SCOPE_IMPLS[name] = fn


class Param(Expr):
    """A single scalar hyperparameter with a named prior distribution.

    Mirrors the reference's ``scope.hyperopt_param(label, dist(...))`` wrapper
    (``hyperopt/pyll_utils.py`` — SURVEY.md §2): the label travels with the node.
    """

    __slots__ = ("label", "kind", "low", "high", "mu", "sigma", "q", "probs")

    def __init__(self, label, kind, low=None, high=None, mu=None, sigma=None,
                 q=None, probs=None):
        if not isinstance(label, str):
            raise TypeError(f"hyperparameter label must be a str, got {label!r}")
        self.label = label
        self.kind = kind
        self.low = low
        self.high = high
        self.mu = mu
        self.sigma = sigma
        self.q = q
        self.probs = probs

    def __repr__(self):
        return f"Param({self.label!r}, {self.kind})"


class Choice(Expr):
    """``hp.choice`` / ``hp.pchoice``: a categorical index selecting one of
    several sub-spaces.  The index itself is a :class:`Param` of kind
    ``categorical``; the options may contain further nested expressions.
    """

    __slots__ = ("label", "options", "probs")

    def __init__(self, label, options, probs=None):
        if not isinstance(label, str):
            raise TypeError(f"hyperparameter label must be a str, got {label!r}")
        options = list(options)
        if len(options) == 0:
            raise ValueError(f"hp.choice({label!r}): needs at least one option")
        if probs is not None:
            probs = [float(p) for p in probs]
            if len(probs) != len(options):
                raise ValueError(
                    f"hp.pchoice({label!r}): {len(probs)} probabilities for "
                    f"{len(options)} options")
            if any(p < 0 for p in probs):
                raise ValueError(
                    f"hp.pchoice({label!r}): negative probability")
            total = sum(probs)
            if not np.isclose(total, 1.0, atol=1e-3):
                raise ValueError(
                    f"hp.pchoice({label!r}): probabilities sum to {total}, not 1")
            probs = [p / total for p in probs]
        self.label = label
        self.options = options
        self.probs = probs

    def __repr__(self):
        return f"Choice({self.label!r}, {len(self.options)} options)"


# ---------------------------------------------------------------------------
# Compiled representation
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class ParamSpec:
    """Flat compile-time record for one scalar hyperparameter column."""

    pid: int
    label: str
    kind: str
    # Distribution parameters (None where not applicable).
    low: Optional[float] = None
    high: Optional[float] = None
    mu: Optional[float] = None
    sigma: Optional[float] = None
    q: Optional[float] = None
    # Categorical: prior probabilities (uniform for randint / plain choice).
    probs: Optional[tuple] = None
    n_options: int = 0
    # Conjunction of (choice pid, branch index) conditions under which this
    # parameter is live.  Empty tuple = unconditional.
    conditions: tuple = ()

    @property
    def is_int(self) -> bool:
        return self.kind in _INT_KINDS

    @property
    def is_log(self) -> bool:
        return self.kind in _LOG_KINDS

    @property
    def is_categorical_like(self) -> bool:
        return self.kind in (RANDINT, CATEGORICAL)


def _point_value(point: dict, label: str):
    """Scalar value of ``label`` in a point dict; unwraps length-1 sequences
    (trials ``vals`` style); KeyError if absent or empty."""
    v = point[label]
    if isinstance(v, (list, tuple, np.ndarray)):
        if len(v) == 0:
            raise KeyError(label)
        v = v[0]
    return v


# Template node tags (host-side nested-structure reconstruction).
_T_LITERAL = 0
_T_PARAM = 1
_T_CHOICE = 2
_T_DICT = 3
_T_LIST = 4
_T_TUPLE = 5
_T_APPLY = 6   # (tag, op_name, (arg_templates...))
_T_SWITCH = 7  # (tag, idx_template, (branch_templates...)) — general index


class CompiledSpace:
    """A search space compiled to a batched XLA sampler + host decoder.

    Public surface:

    * ``sample(key, n)`` -> ``(vals f32[n, P], active bool[n, P])`` (jitted)
    * ``decode_row(vals_row, active_row)`` -> the nested config the user's
      objective receives (reference: ``Domain.memo_from_config`` +
      ``pyll.rec_eval`` substitution, SURVEY.md §3.3)
    * ``eval_point(point_dict)`` -> same, from a ``{label: value}`` dict
      (reference: ``hyperopt/fmin.py::space_eval``)
    * ``params`` — ordered list of :class:`ParamSpec`
    """

    def __init__(self, space):
        self._labels_seen = {}
        self.params: list[ParamSpec] = []
        self._mutable_specs = []  # build buffer
        self.template = self._build(space, conditions=())
        self.params = self._mutable_specs
        del self._mutable_specs
        self.n_params = len(self.params)
        self.by_label = {p.label: p for p in self.params}
        self._sampler_cache = {}
        self._build_groups()

    # -- compile-time walk --------------------------------------------------

    def _add_param(self, node: Param, conditions) -> int:
        if node.label in self._labels_seen:
            raise DuplicateLabel(
                f"label {node.label!r} used more than once in the search space")
        pid = len(self._mutable_specs)
        self._labels_seen[node.label] = pid
        kw = dict(pid=pid, label=node.label, kind=node.kind,
                  conditions=tuple(conditions))
        if node.kind == CATEGORICAL:
            probs = node.probs
            n = len(probs)
            kw.update(probs=tuple(float(p) for p in probs), n_options=n)
        elif node.kind == RANDINT:
            low = int(node.low)
            high = int(node.high)
            n = high - low
            if n <= 0:
                raise ValueError(
                    f"hp.randint({node.label!r}): empty range [{low}, {high})")
            if n > _MAX_RANDINT_RANGE or (
                    max(abs(low), abs(high)) > _MAX_RANDINT_RANGE):
                # Values are stored in an f32 SoA matrix on device; integers
                # above 2**24 would silently lose precision — both for wide
                # ranges AND for narrow ranges placed far from zero
                # (randint(1e9, 1e9+10): every value collides in f32).
                # Ranges this wide are seed-search idioms where model-based
                # suggest carries no information anyway — reject loudly
                # rather than corrupt.
                raise ValueError(
                    f"hp.randint({node.label!r}): range [{low}, {high}) "
                    f"needs integers beyond {_MAX_RANDINT_RANGE} (f32-exact "
                    f"integer limit); shrink/rescale the range (e.g. search "
                    f"an offset or exponent instead)")
            probs = tuple([1.0 / n] * n) if n <= _DENSE_CAT_MAX else None
            kw.update(low=float(low), high=float(high), probs=probs,
                      n_options=n)
        else:
            if node.kind in _UNIFORM_FAMILY:
                low, high = float(node.low), float(node.high)
                if not low < high:
                    raise ValueError(
                        f"hp.{node.kind}({node.label!r}): low {low} >= high {high}")
                # For log kinds the bounds are in log space (reference DSL:
                # loguniform(label, low, high) draws exp(uniform(low, high))).
                kw.update(low=low, high=high)
            else:
                kw.update(mu=float(node.mu), sigma=float(node.sigma))
            if node.kind in _Q_KINDS or node.kind == UNIFORMINT:
                q = 1.0 if node.kind == UNIFORMINT else float(node.q)
                if q <= 0:
                    raise ValueError(f"hp.{node.kind}({node.label!r}): q must be > 0")
                kw.update(q=q)
                self._check_exact_lattice(node, kw, q)
        self._mutable_specs.append(ParamSpec(**kw))
        return pid

    @staticmethod
    def _check_exact_lattice(node: Param, kw: dict, q: float) -> None:
        """Integer-exactness guard for every quantized kind.

        Sampled values are lattice points ``k*q`` held in the f32 ``vals``
        matrix; once ``|k|`` exceeds 2**24 adjacent lattice points collide
        and decode silently returns corrupted integers — e.g.
        ``hp.quniform("x", 0, 1e9, 1)`` above ~1.6e7.  The ``hp.randint``
        path already rejected such ranges; this extends the same guard to
        quniform/qloguniform/qnormal/qlognormal/uniformint (corruption here
        is silent, so a compile-time raise is strictly better).  Bounded
        kinds get a hard reject; the unbounded normal family rejects on a
        2-sigma core envelope, with the residual tail made SAFE rather
        than illegal — sample_traced clips q-lattice normal draws to the
        +/-2**24*q exactly-representable edge.
        """
        limit = float(_MAX_RANDINT_RANGE)
        if node.kind in (QUNIFORM, UNIFORMINT):
            bad = max(abs(kw["low"]), abs(kw["high"])) / q > limit
            reach = "the bound furthest from zero"
        elif node.kind == QNORMAL:
            # Unbounded support: reject only when the 2-sigma CORE of the
            # distribution corrupts (most draws would collide); rarer tail
            # draws SATURATE at the +/-2**24*q lattice edge instead of
            # corrupting (sample_traced clips them) — e.g. the reference
            # test space qlognormal(0, 2, 1) stays legal, its beyond-limit
            # mass being ~4e-17.
            bad = (abs(kw["mu"]) + 2.0 * kw["sigma"]) / q > limit
            reach = "|mu| + 2*sigma"
        elif node.kind == QLOGUNIFORM:
            bad = kw["high"] > math.log(limit) + math.log(q)
            reach = "exp(high)"
        elif node.kind == QLOGNORMAL:
            bad = kw["mu"] + 2.0 * kw["sigma"] > math.log(limit) + math.log(q)
            reach = "exp(mu + 2*sigma)"
        else:
            return
        if bad:
            raise ValueError(
                f"hp.{node.kind}({node.label!r}): lattice indices up to "
                f"{reach} / q exceed {_MAX_RANDINT_RANGE}, the f32-exact "
                f"integer limit of the on-device values matrix; values this "
                f"far from zero would silently collide on the q={q} lattice. "
                f"Shrink the range, increase q, or rescale the parameter "
                f"(e.g. search an exponent instead)")

    def _build(self, node, conditions):
        """Walk the nested structure, returning a template tree."""
        if isinstance(node, Choice):
            probs = node.probs or [1.0 / len(node.options)] * len(node.options)
            idx_param = Param(node.label, CATEGORICAL, probs=probs)
            pid = self._add_param(idx_param, conditions)
            branches = []
            for b, opt in enumerate(node.options):
                branches.append(
                    self._build(opt, conditions + ((pid, b),)))
            return (_T_CHOICE, pid, tuple(branches))
        if isinstance(node, Apply):
            if node.op == "switch":
                return self._build_switch(node, conditions)
            return (_T_APPLY, node.op,
                    tuple(self._build(a, conditions) for a in node.args))
        if isinstance(node, Param):
            pid = self._add_param(node, conditions)
            return (_T_PARAM, pid)
        if isinstance(node, dict):
            items = tuple(
                (k, self._build(v, conditions)) for k, v in node.items())
            return (_T_DICT, items)
        if isinstance(node, list):
            return (_T_LIST, tuple(self._build(v, conditions) for v in node))
        if isinstance(node, tuple):
            return (_T_TUPLE, tuple(self._build(v, conditions) for v in node))
        if isinstance(node, Expr):
            raise InvalidAnnotatedParameter(f"unknown expression node {node!r}")
        # Plain literal (int, float, str, None, np scalar, ...).
        return (_T_LITERAL, node)

    def _build_switch(self, node: Apply, conditions):
        """``scope.switch(idx, *options)`` (reference: pyll builtin behind
        every conditional).  When the index is a bare 0-based integer-family
        ``Param``, branches compile with proper activity conditions —
        identical to ``hp.choice``; a general index expression falls back to
        unconditioned branches (all live — a safe superset for the
        suggest-side activity masks) selected at decode time."""
        if len(node.args) < 2:
            raise InvalidAnnotatedParameter(
                "scope.switch needs an index and at least one option")
        idx, *options = node.args
        if isinstance(idx, Param) and (
                idx.kind == CATEGORICAL
                or (idx.kind in (RANDINT, UNIFORMINT) and int(idx.low) == 0)):
            pid = self._add_param(idx, conditions)
            n_opt = self._mutable_specs[pid].n_options or (
                int(idx.high) + (1 if idx.kind == UNIFORMINT else 0))
            if n_opt != len(options):
                raise InvalidAnnotatedParameter(
                    f"scope.switch({idx.label!r}): index has {n_opt} values "
                    f"but {len(options)} options were given")
            branches = tuple(
                self._build(opt, conditions + ((pid, b),))
                for b, opt in enumerate(options))
            return (_T_CHOICE, pid, branches)
        idx_t = self._build(idx, conditions)
        branches = tuple(self._build(opt, conditions) for opt in options)
        return (_T_SWITCH, idx_t, branches)

    # -- sampler compilation ------------------------------------------------

    def _build_groups(self):
        """Partition params into batched sampling groups; precompute constants."""
        uf, nf, cat, wide = [], [], [], []
        for p in self.params:
            if p.kind == CATEGORICAL or (p.kind == RANDINT and
                                         p.probs is not None):
                cat.append(p)
            elif p.kind == RANDINT:
                wide.append(p)  # integer draw, no per-option logits
            elif p.kind in _UNIFORM_FAMILY:
                uf.append(p)
            else:
                nf.append(p)
        self._uf, self._nf, self._cat, self._wide = uf, nf, cat, wide

        def f32(xs):
            return np.asarray(xs, dtype=np.float32)

        # Uniform family: draw u~U[0,1), x = a + (b-a)u in "fit space"
        # (log space for loguniform/qloguniform), then exp / round / clip.
        self._uf_a = f32([p.low if p.kind != UNIFORMINT else p.low - 0.5
                          for p in uf])
        self._uf_b = f32([p.high if p.kind != UNIFORMINT else p.high + 0.5
                          for p in uf])
        self._uf_log = np.asarray([p.is_log for p in uf], dtype=bool)
        self._uf_q = f32([p.q if p.q else 0.0 for p in uf])
        # uniformint draws quniform(q=1) over [low-0.5, high+0.5] like the
        # reference (hyperopt/pyll_utils.py::hp_uniformint), then clips.
        self._uf_clip_lo = f32([p.low if p.kind == UNIFORMINT else -np.inf
                                for p in uf])
        self._uf_clip_hi = f32([p.high if p.kind == UNIFORMINT else np.inf
                                for p in uf])

        self._nf_mu = f32([p.mu for p in nf])
        self._nf_sigma = f32([p.sigma for p in nf])
        self._nf_log = np.asarray([p.is_log for p in nf], dtype=bool)
        self._nf_q = f32([p.q if p.q else 0.0 for p in nf])
        # Quantized normal-family tails saturate at the last f32-exact
        # lattice point (+/-2**24*q) instead of silently colliding — the
        # compile-time guard rejects only distributions whose 2-sigma core
        # crosses this edge (see _check_exact_lattice).
        self._nf_clip = f32([_MAX_RANDINT_RANGE * p.q if p.q else np.inf
                             for p in nf])

        kmax = max([p.n_options for p in cat], default=1)
        self.cat_kmax = kmax
        logits = np.full((len(cat), kmax), -np.inf, dtype=np.float32)
        for i, p in enumerate(cat):
            logits[i, : p.n_options] = np.log(np.asarray(p.probs))
        self._cat_logits = logits
        self._cat_offset = f32([p.low if p.kind == RANDINT else 0.0 for p in cat])

        self._wide_low = np.asarray([int(p.low) for p in wide], dtype=np.int32)
        self._wide_high = np.asarray([int(p.high) for p in wide], dtype=np.int32)

        # Column permutation: concat(uf, nf, cat, wide) order -> pid order.
        order = ([p.pid for p in uf] + [p.pid for p in nf]
                 + [p.pid for p in cat] + [p.pid for p in wide])
        self._inv_perm = np.argsort(np.asarray(order, dtype=np.int64)) \
            if order else np.zeros(0, dtype=np.int64)

        # Conditions, flattened for the mask computation.
        self._cond_by_pid = [p.conditions for p in self.params]

    def sample_traced(self, key, n: int):
        """Draw ``n`` configurations; traceable inside jit (n static).

        Returns ``(vals f32[n, P], active bool[n, P])``.
        """
        cols = []
        k_u, k_n, k_c, k_w = jax.random.split(key, 4)
        if self._uf:
            u = jax.random.uniform(k_u, (n, len(self._uf)), dtype=jnp.float32)
            x = self._uf_a + (self._uf_b - self._uf_a) * u
            x = jnp.where(self._uf_log, jnp.exp(x), x)
            x = jnp.where(self._uf_q > 0,
                          jnp.round(x / jnp.where(self._uf_q > 0, self._uf_q, 1.0))
                          * self._uf_q, x)
            x = jnp.clip(x, self._uf_clip_lo, self._uf_clip_hi)
            cols.append(x)
        if self._nf:
            z = jax.random.normal(k_n, (n, len(self._nf)), dtype=jnp.float32)
            x = self._nf_mu + self._nf_sigma * z
            x = jnp.where(self._nf_log, jnp.exp(x), x)
            x = jnp.where(self._nf_q > 0,
                          jnp.round(x / jnp.where(self._nf_q > 0, self._nf_q, 1.0))
                          * self._nf_q, x)
            x = jnp.clip(x, -self._nf_clip, self._nf_clip)
            cols.append(x)
        if self._cat:
            g = jax.random.gumbel(
                k_c, (n, len(self._cat), self.cat_kmax), dtype=jnp.float32)
            idx = jnp.argmax(self._cat_logits[None, :, :] + g, axis=-1)
            cols.append(self._cat_offset + idx.astype(jnp.float32))
        if self._wide:
            w = jax.random.randint(
                k_w, (n, len(self._wide)), self._wide_low, self._wide_high)
            cols.append(w.astype(jnp.float32))
        if cols:
            vals = jnp.concatenate(cols, axis=1)[:, self._inv_perm]
        else:
            vals = jnp.zeros((n, 0), dtype=jnp.float32)
        active = self.active_mask(vals)
        return vals, active

    def active_mask(self, vals):
        """bool[n, P] liveness mask from the categorical columns of ``vals``."""
        n = vals.shape[0]
        masks = []
        for pid, conds in enumerate(self._cond_by_pid):
            if not conds:
                masks.append(jnp.ones((n,), dtype=bool))
            else:
                m = jnp.ones((n,), dtype=bool)
                for cpid, branch in conds:
                    m = m & (vals[:, cpid] == branch)
                masks.append(m)
        if not masks:
            return jnp.zeros((n, 0), dtype=bool)
        return jnp.stack(masks, axis=1)

    def active_mask_host(self, vals: np.ndarray) -> np.ndarray:
        """Host-numpy twin of :meth:`active_mask`.

        The mask is a pure function of the values row (conjunctions of
        ``vals[:, cpid] == branch`` over exactly-representable integer
        codes), so a suggest step only needs to fetch ONE device array —
        the values — and can rebuild the mask here for free.  Through a
        high-RTT attachment (the axon tunnel's ~70-90 ms per-fetch sync)
        that halves the per-suggest cost; on local attachment it saves a
        device op and a transfer.
        """
        vals = np.asarray(vals)
        n = vals.shape[0]
        out = np.ones((n, self.n_params), dtype=bool)
        for pid, conds in enumerate(self._cond_by_pid):
            for cpid, branch in conds:
                out[:, pid] &= vals[:, cpid] == branch
        return out

    # Volatile attribute names dropped from pickles: jitted callables and the
    # suggest-kernel caches other modules attach (tpe.get_kernel,
    # parallel.sharded — the latter holds Mesh/Device objects, which cannot
    # pickle).  With compile_space memoized, one shared CompiledSpace
    # accumulates them, and Domain pickling (FileTrials.save_domain,
    # trials_save_file) must not drag them along.
    # Register every externally-attached kernel cache here (tpe.get_kernel,
    # anneal, parallel.sharded).
    _VOLATILE_ATTRS = ("_sampler_cache", "_tpe_kernels", "_anneal_kernel",
                       "_sharded_tpe_kernels", "_dispatch_kernels",
                       "_multi_start_fns", "_device_fmin_cache",
                       "_gp_kernels", "_es_kernels")

    def __getstate__(self):
        state = self.__dict__.copy()
        for k in self._VOLATILE_ATTRS:
            state.pop(k, None)
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._sampler_cache = {}

    def _jitted_sampler(self, n: int):
        fn = self._sampler_cache.get(n)
        if fn is None:
            ensure_persistent_compilation_cache()
            fn = jax.jit(lambda key: self.sample_traced(key, n))
            self._sampler_cache[n] = fn
        return fn

    def sample(self, key, n: int):
        """Jitted entry point: draw n configurations."""
        return self._jitted_sampler(int(n))(key)

    # -- host-side decoding -------------------------------------------------

    def _param_value(self, spec: ParamSpec, raw) -> Any:
        if spec.kind == CATEGORICAL:
            return int(raw)
        if spec.kind in (RANDINT, UNIFORMINT):
            return int(raw)
        if spec.q:
            # Re-snap to the q-lattice in f64 on the host: the device
            # value is the f32 ROUNDING of a lattice point, which for
            # large-magnitude non-power-of-two lattices (quniform(0, 1e9,
            # 100) passes the collision guard since 1e7 < 2**24) decodes
            # off-lattice (999999904.0).  The guard ensures distinct
            # lattice points stay distinct in f32, so round(raw/q)
            # recovers the exact intended k and k·q in f64 is exact
            # (round-5 advisor finding #3).
            return float(np.round(float(raw) / spec.q) * spec.q)
        return float(raw)

    def _walk(self, getter):
        """Reconstruct the nested user config; ``getter(pid)`` supplies the
        raw value of each parameter reached along the active path."""

        def rec(t):
            tag = t[0]
            if tag == _T_LITERAL:
                return t[1]
            if tag == _T_PARAM:
                spec = self.params[t[1]]
                return self._param_value(spec, getter(t[1]))
            if tag == _T_CHOICE:
                idx = int(getter(t[1]))
                return rec(t[2][idx])
            if tag == _T_DICT:
                return {k: rec(v) for k, v in t[1]}
            if tag == _T_LIST:
                return [rec(v) for v in t[1]]
            if tag == _T_TUPLE:
                return tuple(rec(v) for v in t[1])
            if tag == _T_APPLY:
                return _SCOPE_IMPLS[t[1]](*(rec(a) for a in t[2]))
            if tag == _T_SWITCH:
                idx = int(rec(t[1]))
                if not 0 <= idx < len(t[2]):
                    raise IndexError(
                        f"scope.switch index {idx} out of range for "
                        f"{len(t[2])} options")
                return rec(t[2][idx])
            raise AssertionError(tag)

        return rec(self.template)

    def decode_row(self, vals_row, active_row=None):
        """Reconstruct the nested user config from one sample row."""
        vals_row = np.asarray(vals_row)
        return self._walk(lambda pid: vals_row[pid])

    def eval_point(self, point: dict):
        """``space_eval``: substitute a ``{label: value}`` assignment.

        Accepts values only for parameters on the active path (like the
        reference's ``space_eval``); inactive labels may be present or absent.
        Values may be scalars or length-1 sequences (trials ``vals`` style).
        """
        return self._walk(lambda pid: _point_value(point,
                                                   self.params[pid].label))

    # -- misc ---------------------------------------------------------------

    def active_path_pids(self, point: dict):
        """pids of parameters live under assignment ``point`` (host-side)."""
        out = []

        def ok(spec):
            for cpid, branch in spec.conditions:
                try:
                    v = _point_value(point, self.params[cpid].label)
                except KeyError:
                    return False
                if int(v) != branch:
                    return False
            return True

        for spec in self.params:
            if ok(spec):
                out.append(spec.pid)
        return out

    def __repr__(self):
        return (f"CompiledSpace(P={self.n_params}, "
                f"uf={len(self._uf)}, nf={len(self._nf)}, cat={len(self._cat)})")


_persistent_cache_checked = False


def ensure_persistent_compilation_cache() -> None:
    """Point JAX's persistent compilation cache at a default directory.

    Called lazily right before the first jit in this process (sampler or
    suggest-kernel build), when the backend is initialized anyway.  The TPE
    bucket ladder costs seconds-to-minutes of XLA compiles per fresh
    process — on the tunneled TPU each program is a 20-40 s compile — so
    every later process (repeat experiments, workers, benchmarks) skips
    compiles it has seen.

    Default-on for the TPU backend only: CPU AOT cache loads in this XLA
    version log a multi-KB pseudo-feature mismatch error per entry (the
    compile-side feature list embeds tuning flags like ``+prefer-no-gather``
    that host redetection lacks), which would spam every user process.
    ``HYPEROPT_TPU_COMPILE_CACHE=<dir>`` forces it on for any backend,
    ``=0`` disables, and an existing user configuration is respected.
    """
    global _persistent_cache_checked
    if _persistent_cache_checked:
        return
    _persistent_cache_checked = True
    import os

    val = os.environ.get("HYPEROPT_TPU_COMPILE_CACHE", "")
    if val == "0":
        return
    try:
        if jax.config.jax_compilation_cache_dir:   # user already set one
            return
        if not val and jax.default_backend() != "tpu":
            return
        path = val or os.path.join(os.path.expanduser("~"),
                                   ".cache", "hyperopt_tpu", "xla")
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # The bucket ladder is many mid-sized programs; persisting from
        # 0.1 s (default 1 s) shaved another ~25% off a fresh process's
        # warm start (measured 4.2 s → 3.2 s for a 150-eval CPU run).
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)
    except Exception:   # cache plumbing must never break compilation
        pass


class _Uncacheable(Exception):
    """Space contains a literal the structural cache cannot key safely."""


# Literal leaf types whose hash/eq equality implies interchangeability.
_VALUE_TYPES = (str, int, float, bool, bytes, type(None), np.generic)


def _freeze(obj):
    """Hashable structural fingerprint of a space (for the compile cache).

    Equal fingerprints ⇒ the spaces compile to behaviorally identical
    ``CompiledSpace`` objects (same columns, same template, value-equal
    literals).  Raises :class:`_Uncacheable` on literals outside the
    value-type whitelist (e.g. arrays, callables) — those spaces are simply
    compiled fresh each time.
    """
    if isinstance(obj, Choice):
        return ("C", obj.label,
                None if obj.probs is None else tuple(obj.probs),
                tuple(_freeze(o) for o in obj.options))
    if isinstance(obj, Param):
        return ("P", obj.label, obj.kind, obj.low, obj.high, obj.mu,
                obj.sigma, obj.q,
                None if obj.probs is None else tuple(obj.probs))
    if isinstance(obj, Apply):
        return ("A", obj.op, tuple(_freeze(a) for a in obj.args))
    if isinstance(obj, dict):
        # Insertion order preserved: it determines column (pid) order.
        # Keys get the same type discrimination as value leaves (True vs 1
        # vs 1.0 hash equal but must not share a compilation).
        return ("D", tuple(((type(k).__name__, k), _freeze(v))
                           for k, v in obj.items()))
    if isinstance(obj, list):
        return ("L", tuple(_freeze(v) for v in obj))
    if isinstance(obj, tuple):
        return ("T", tuple(_freeze(v) for v in obj))
    if isinstance(obj, _VALUE_TYPES):
        # Type name disambiguates 1 / True / 1.0 (equal hashes).
        return ("V", type(obj).__name__, obj)
    raise _Uncacheable(type(obj).__name__)


_compile_cache: "OrderedDict[tuple, CompiledSpace]" = OrderedDict()
_COMPILE_CACHE_MAX = 64


def compile_space(space) -> CompiledSpace:
    """Compile a nested ``hp.*`` structure into a :class:`CompiledSpace`.

    Memoized on the space's structural fingerprint: repeated ``fmin`` calls
    (or Domain/bench/sharded constructions) over an equal space share ONE
    ``CompiledSpace`` — and with it every jitted sampler and TPE kernel
    already compiled for it.  Without this, each ``fmin`` call re-jits the
    whole bucket ladder: a profiled 150-eval CPU run spent 21 of 26.5 s in
    recompiles of programs an earlier identical run had already built.
    """
    if isinstance(space, CompiledSpace):
        return space
    try:
        key = _freeze(space)
    except (_Uncacheable, TypeError):
        return CompiledSpace(space)
    cs = _compile_cache.get(key)
    if cs is None:
        cs = CompiledSpace(space)
        _compile_cache[key] = cs
        if len(_compile_cache) > _COMPILE_CACHE_MAX:
            _compile_cache.popitem(last=False)
    else:
        _compile_cache.move_to_end(key)
    return cs


def expr_to_config(space):
    """Per-label distribution + activation-condition metadata.

    Reference: ``hyperopt/pyll_utils.py::expr_to_config`` — walks the pyll
    graph extracting, for every hyperparameter label, its distribution and
    the conditions under which it participates.  The compiled representation
    already carries exactly this, so this is a (re-)exported view::

        {label: {"dist": kind, "args": {...}, "conditions": (
                    (gating_label, branch_index), ...)}}
    """
    cs = compile_space(space)
    out = {}
    for p in cs.params:
        args = {k: getattr(p, k) for k in ("low", "high", "mu", "sigma", "q")
                if getattr(p, k) is not None}
        if p.kind == CATEGORICAL:
            args["upper"] = p.n_options
            if p.probs is not None:
                args["p"] = p.probs
        out[p.label] = {
            "dist": p.kind,
            "args": args,
            "conditions": tuple((cs.params[cpid].label, branch)
                                for cpid, branch in p.conditions),
        }
    return out
