"""Exception types.

Reference parity: ``hyperopt/exceptions.py`` (AllTrialsFailed, InvalidTrial,
DuplicateLabel; mount was empty — anchors per SURVEY.md §2).
"""


class HyperoptTpuError(Exception):
    """Base class for all framework errors."""


class AllTrialsFailed(HyperoptTpuError):
    """Raised when ``fmin`` finishes without a single successful trial."""


class InvalidTrial(HyperoptTpuError):
    """A trial document failed schema validation."""


class InvalidResultStatus(HyperoptTpuError):
    """Objective returned a result dict with an unknown ``status``."""


class InvalidLoss(HyperoptTpuError):
    """Objective returned a non-finite / non-float loss with status ok."""


class DuplicateLabel(HyperoptTpuError):
    """The same hyperparameter label was used twice in one search space."""


class InvalidAnnotatedParameter(HyperoptTpuError):
    """A search-space leaf is not a recognized hyperparameter expression."""


class InjectedFault(HyperoptTpuError):
    """A seeded fault fired at a named fault point (``hyperopt_tpu.faults``).

    Always deliberate — raised only when a fault schedule is armed, never
    by production code paths.  Carries the fault-point name so retry logic
    and chaos tests can attribute the failure.
    """

    def __init__(self, point, call_no=None):
        self.point = point
        self.call_no = call_no
        suffix = f" (call #{call_no})" if call_no is not None else ""
        super().__init__(f"injected fault at {point!r}{suffix}")


class TransientEvaluationError(HyperoptTpuError):
    """An objective failure the caller believes is worth retrying.

    Raise this (or a subclass) from an objective to ask the trial loop to
    re-run the same point, subject to the ``max_trial_retries`` budget.
    """


class QuotaExceeded(HyperoptTpuError):
    """A tenant exceeded one of its service quotas (max concurrent claims
    or trials/s admission rate) and the server refused the verb.

    Deliberately NOT transient: a caller looping on quota rejections is
    over its budget by construction — backing off blindly would mask
    starvation.  Callers that can wait should sleep past the refill
    window and retry explicitly.
    """


class Backpressure(HyperoptTpuError):
    """The service is shedding load and asks the caller to come back later.

    Unlike :class:`QuotaExceeded` (a per-tenant budget the caller is over
    by construction), backpressure is a *fleet* condition: the autoscaler
    tightened admission because capacity cannot grow fast enough.  The
    server names its own price — ``retry_after_s`` — and well-behaved
    clients (``_Rpc`` / ``RouterTrials``) sleep a jittered fraction of it
    and retry WITHOUT burning their transport retry budget: the bytes
    made it there and back, the server just said "not yet".
    """

    def __init__(self, message, retry_after_s=1.0):
        self.retry_after_s = float(retry_after_s)
        super().__init__(message)


class ShardFenced(HyperoptTpuError):
    """The shard (or one store on it) is fenced for a topology change.

    A typed retriable *redirect*, not a failure: the verb reached a
    server that is mid-cutover (rebalance, promotion, or a per-store
    migration) and deliberately refused it so the moving state stays
    quiesced.  A routed client (``_RoutedRpc``) reacts by forcing a
    shard-map refresh and retrying against the new owner; a direct
    client sees it surface after the transport retry budget because a
    fence does not lift by itself — the *map* changes instead.
    """


class NetstoreUnavailable(HyperoptTpuError):
    """Netstore transport failure that survived the whole retry budget.

    Distinct from server-*reported* errors (which stay ``RuntimeError``:
    the server was reachable and answered with a fault of its own).  This
    one means the bytes never made it there and back.
    """

    def __init__(self, message, attempts=None):
        self.attempts = attempts
        super().__init__(message)


#: Exception classes the trial loop treats as retryable without charging
#: the trial a permanent failure.  Deliberately narrow: an arbitrary
#: objective bug must NOT burn retry budget looping on itself.
TRANSIENT_ERRORS = (InjectedFault, TransientEvaluationError,
                    NetstoreUnavailable)


def is_transient(exc):
    """True when ``exc`` is an error the retry budget should absorb."""
    return isinstance(exc, TRANSIENT_ERRORS)


#: The same classification by exception *type name* — for recovery paths
#: where only the marshalled name survives (a forked evaluation child
#: reports ``(type_name, message)`` over its pipe, not the object).
TRANSIENT_ERROR_NAMES = frozenset(c.__name__ for c in TRANSIENT_ERRORS)
