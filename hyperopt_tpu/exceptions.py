"""Exception types.

Reference parity: ``hyperopt/exceptions.py`` (AllTrialsFailed, InvalidTrial,
DuplicateLabel; mount was empty — anchors per SURVEY.md §2).
"""


class HyperoptTpuError(Exception):
    """Base class for all framework errors."""


class AllTrialsFailed(HyperoptTpuError):
    """Raised when ``fmin`` finishes without a single successful trial."""


class InvalidTrial(HyperoptTpuError):
    """A trial document failed schema validation."""


class InvalidResultStatus(HyperoptTpuError):
    """Objective returned a result dict with an unknown ``status``."""


class InvalidLoss(HyperoptTpuError):
    """Objective returned a non-finite / non-float loss with status ok."""


class DuplicateLabel(HyperoptTpuError):
    """The same hyperparameter label was used twice in one search space."""


class InvalidAnnotatedParameter(HyperoptTpuError):
    """A search-space leaf is not a recognized hyperparameter expression."""
