"""Columnar binary wire codec — one frame format from RPC to snapshot.

Every hot verb of the service/netstore stack historically moved trial
*documents* as JSON.  The native unit of this system is the columnar
slab (``vals f32[n,P]``, ``loss f32[n]``, ``tids i64[n]``) that
``history.py`` keeps resident on device, and the dominant wire cost of
a trial document is its numeric leaves.  This module packs any
JSON-shaped payload into a versioned little-endian binary frame:

    offset 0   magic        b"HTW1"              (4 bytes)
    offset 4   version      u16 LE               (currently 1)
    offset 6   reserved     u16 LE               (0)
    offset 8   header_len   u32 LE
    offset 12  header       UTF-8 JSON skeleton  (header_len bytes)
    ...        segments     raw ndarray bytes, concatenated in order

The header is the original payload with its bulk numeric content
*hoisted out* into the segments:

* Lists of dicts (trial docs, WAL records) are grouped by structure
  signature — the ordered tuple of (leaf path, leaf kind) produced by a
  depth-first walk.  Per group, float leaves become one ``<f8`` segment
  column and int leaves one ``<i8`` segment column; strings, bools,
  ``None`` and empty containers stay as JSON columns in the header.
  First-seen path order is preserved, so decoded dicts have the exact
  key insertion order of the originals.
* Decoding materializes plain Python values bit-identical to what
  ``json.loads(json.dumps(payload))`` would yield — f64 segments
  round-trip NaN/±Inf and every float bit pattern exactly (Python's
  JSON emits NaN/Infinity tokens and repr round-trips f64, so the two
  encodings agree bit-for-bit; the property test in ``test_wire.py``
  pins this).

Because decode is lossless over JSON values, WAL replay byte-identity
(``state_bytes()``) holds across wire formats by construction.

Negotiation: requests carry ``Content-Type: application/x-hyperopt-frame``
and servers sniff the magic bytes (robust through the shard router,
which forwards opaque bodies); replies are framed iff the request was.
``HYPEROPT_TPU_WIRE=json|binary|auto`` (default ``auto``) selects the
client mode — ``auto`` falls back to JSON per peer on the first framed
request a peer rejects, counting ``wire.json_fallbacks``.
"""

from __future__ import annotations

import json
import os
import struct
from typing import Any, Dict, List, Tuple

import numpy as np

__all__ = [
    "MAGIC", "VERSION", "CONTENT_TYPE", "FRAMED_VERBS", "CODEC_FIXTURES",
    "WireError", "mode", "is_frame", "encode", "decode",
]

MAGIC = b"HTW1"
VERSION = 1
CONTENT_TYPE = "application/x-hyperopt-frame"
_HDR = struct.Struct("<4sHHI")

# Verbs whose request/reply bodies ride the binary frame when the wire
# mode allows it.  The WP008 analyzer rule reconciles this catalog
# against CODEC_FIXTURES below: every framed verb must round-trip
# through the shared fixtures in BOTH directions.
_FRAMED_VERBS = frozenset({
    "insert_docs",       # bulk doc upload (client -> server)
    "docs",              # full history fetch (server -> client)
    "fetch_since",       # delta history fetch (server -> client)
    "wal_ship",          # primary -> replica WAL record batches
    "snapshot_install",  # primary -> replica full-state install
})
FRAMED_VERBS = _FRAMED_VERBS


class WireError(ValueError):
    """Malformed or unsupported binary frame."""


def mode() -> str:
    """Wire mode from ``HYPEROPT_TPU_WIRE``: json | binary | auto."""
    m = os.environ.get("HYPEROPT_TPU_WIRE", "auto").strip().lower()
    return m if m in ("json", "binary", "auto") else "auto"


def is_frame(raw: bytes) -> bool:
    return isinstance(raw, (bytes, bytearray)) and raw[:4] == MAGIC


# -- columnar packing ---------------------------------------------------------
#
# Header skeleton markers (reserved keys, escaped via __lit__ when a user
# dict happens to contain one):
#   {"__seg__": i}                     scalar column hoisted to segment i
#   {"__recs__": [...], "__n__": n}    columnarized list-of-dicts
#   {"__lit__": {...}}                 verbatim dict that contained a marker

_MARKERS = ("__seg__", "__recs__", "__lit__")

# Leaf kinds: "f" -> <f8 segment, "i" -> <i8 segment, "o" -> JSON column.
_I64_MIN, _I64_MAX = -(2 ** 63), 2 ** 63 - 1


def _flatten(doc: dict, out: List[Tuple[tuple, str, Any]]) -> None:
    """Depth-first leaf walk.  Path elements: str = dict key, int = list
    index (JSON dict keys are always str, so this is unambiguous).
    Raises TypeError on non-JSON values — the caller then falls back to
    leaving that list uncolumnarized."""
    def walk(x, path):
        if isinstance(x, dict):
            if x:
                for k, v in x.items():
                    if not isinstance(k, str):
                        raise TypeError("non-str dict key")
                    walk(v, path + (k,))
            else:
                out.append((path, "o", {}))
        elif isinstance(x, list):
            if x:
                for i, v in enumerate(x):
                    walk(v, path + (i,))
            else:
                out.append((path, "o", []))
        elif type(x) is bool or x is None or isinstance(x, str):
            out.append((path, "o", x))
        elif isinstance(x, float):
            out.append((path, "f", x))
        elif isinstance(x, int):
            if _I64_MIN <= x <= _I64_MAX:
                out.append((path, "i", x))
            else:
                out.append((path, "o", x))
        else:
            raise TypeError(f"non-JSON leaf {type(x).__name__}")
    walk(doc, ())


def _set_path(root: dict, path: tuple, val: Any) -> None:
    """Materialize ``val`` at ``path``; containers are created on demand
    (next element str -> dict, int -> list).  List indices arrive in
    increasing order per parent, so list growth is append-only."""
    cur = root
    for i, el in enumerate(path[:-1]):
        nxt_el = path[i + 1]
        fresh = {} if isinstance(nxt_el, str) else []
        if isinstance(el, str):
            if el not in cur:
                cur[el] = fresh
            cur = cur[el]
        else:
            if el == len(cur):
                cur.append(fresh)
            cur = cur[el]
    last = path[-1]
    if isinstance(last, str):
        cur[last] = val
    else:
        if last == len(cur):
            cur.append(val)
        else:
            cur[last] = val


def _pack_records(recs: List[dict], segs: List[np.ndarray]):
    """Columnarize a list of dicts, grouped by structure signature."""
    flat = []
    for r in recs:
        leaves: List[Tuple[tuple, str, Any]] = []
        _flatten(r, leaves)
        flat.append(leaves)
    groups: Dict[tuple, dict] = {}
    for j, leaves in enumerate(flat):
        sig = tuple((path, kind) for path, kind, _ in leaves)
        g = groups.get(sig)
        if g is None:
            g = groups[sig] = {"rows": [], "cols": [[] for _ in sig]}
        g["rows"].append(j)
        cols = g["cols"]
        for c, (_, _, val) in enumerate(leaves):
            cols[c].append(val)
    out_groups = []
    for sig, g in groups.items():
        enc_cols = []
        for (path, kind), col in zip(sig, g["cols"]):
            const = _const_of(col, kind)
            if const is not None:
                enc_cols.append({"__const__": const[0]})
            elif kind == "f":
                segs.append(np.asarray(col, dtype="<f8"))
                enc_cols.append({"__seg__": len(segs) - 1})
            elif kind == "i":
                segs.append(np.asarray(col, dtype="<i8"))
                enc_cols.append({"__seg__": len(segs) - 1})
            else:
                enc_cols.append(col)
        rows = g["rows"]
        if rows == list(range(rows[0], rows[0] + len(rows))):
            rows = {"__range__": [rows[0], len(rows)]}
        out_groups.append({
            "sig": [[list(path), kind] for path, kind in sig],
            "rows": rows,
            "cols": enc_cols,
        })
    return {"__recs__": out_groups, "__n__": len(recs)}


def _const_of(col: list, kind: str):
    """(value,) when every entry of the column is the same value (float
    equality is by f64 bit pattern so NaN columns collapse too); else
    None.  The constant lands in the JSON header — exact for floats
    because Python's json repr round-trips every f64."""
    first = col[0]
    if kind == "f":
        b0 = struct.pack("<d", first)
        same = all(struct.pack("<d", v) == b0 for v in col)
    else:
        t0 = type(first)
        same = all(type(v) is t0 and v == first for v in col)
    return (first,) if same else None


def _pack(x: Any, segs: List[np.ndarray]) -> Any:
    if isinstance(x, dict):
        if any(m in x for m in _MARKERS):
            return {"__lit__": {k: _pack(v, segs) for k, v in x.items()}}
        return {k: _pack(v, segs) for k, v in x.items()}
    if isinstance(x, list):
        if len(x) >= 2 and all(type(e) is dict for e in x):
            try:
                return _pack_records(x, segs)
            except TypeError:
                pass  # non-JSON leaves: leave as a plain JSON list
        return [_pack(v, segs) for v in x]
    return x


def _unpack_records(node: dict, segs: List[np.ndarray]) -> List[dict]:
    n = node["__n__"]
    out: List[Any] = [None] * n
    for g in node["__recs__"]:
        sig = [(tuple(path), kind) for path, kind in g["sig"]]
        rows = g["rows"]
        if isinstance(rows, dict):
            start, cnt = rows["__range__"]
            rows = list(range(start, start + cnt))
        cols = []
        for (path, kind), col in zip(sig, g["cols"]):
            if isinstance(col, dict) and "__const__" in col:
                v = col["__const__"]
                if kind == "f":
                    cols.append([float(v)] * len(rows))
                elif kind == "i":
                    cols.append([int(v)] * len(rows))
                else:
                    # fresh container per row: empty-dict/list leaves must
                    # not alias across decoded docs
                    cols.append([v.copy() if isinstance(v, (dict, list))
                                 else v for _ in rows])
            elif kind == "f":
                cols.append([float(v) for v in segs[col["__seg__"]]])
            elif kind == "i":
                cols.append([int(v) for v in segs[col["__seg__"]]])
            else:
                cols.append(col)
        for idx, j in enumerate(rows):
            doc: dict = {}
            for (path, kind), col in zip(sig, cols):
                if path:
                    _set_path(doc, path, col[idx])
                # path == () only for the empty dict leaf: doc stays {}
            out[j] = doc
    return out


def _unpack(x: Any, segs: List[np.ndarray]) -> Any:
    if isinstance(x, dict):
        if "__lit__" in x:
            return {k: _unpack(v, segs) for k, v in x["__lit__"].items()}
        if "__recs__" in x:
            return _unpack_records(x, segs)
        if "__seg__" in x:
            return segs[x["__seg__"]].tolist()
        return {k: _unpack(v, segs) for k, v in x.items()}
    if isinstance(x, list):
        return [_unpack(v, segs) for v in x]
    return x


# -- frame assembly -----------------------------------------------------------

_DTYPES = {"<f8": np.dtype("<f8"), "<i8": np.dtype("<i8")}


def encode(payload: Any) -> bytes:
    """Pack a JSON-shaped payload into one binary frame."""
    segs: List[np.ndarray] = []
    body = _pack(payload, segs)
    header = {
        "body": body,
        "segs": [[arr.dtype.str, int(arr.size)] for arr in segs],
    }
    hraw = json.dumps(header, separators=(",", ":")).encode()
    parts = [_HDR.pack(MAGIC, VERSION, 0, len(hraw)), hraw]
    parts.extend(arr.tobytes() for arr in segs)
    return b"".join(parts)


def decode(raw: bytes) -> Any:
    """Reverse of :func:`encode`; raises :class:`WireError` on damage."""
    if len(raw) < _HDR.size:
        raise WireError("frame shorter than fixed header")
    magic, ver, _, hlen = _HDR.unpack_from(raw, 0)
    if magic != MAGIC:
        raise WireError("bad magic")
    if ver != VERSION:
        raise WireError(f"unsupported frame version {ver}")
    off = _HDR.size
    if len(raw) < off + hlen:
        raise WireError("truncated header")
    try:
        header = json.loads(raw[off:off + hlen].decode())
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise WireError(f"corrupt header: {e}") from None
    off += hlen
    segs: List[np.ndarray] = []
    for dtype_str, size in header.get("segs", []):
        dt = _DTYPES.get(dtype_str)
        if dt is None:
            raise WireError(f"unsupported segment dtype {dtype_str!r}")
        nbytes = int(size) * dt.itemsize
        if len(raw) < off + nbytes:
            raise WireError("truncated segment")
        segs.append(np.frombuffer(raw, dtype=dt, count=int(size),
                                  offset=off))
        off += nbytes
    return _unpack(header["body"], segs)


# -- shared codec fixtures ----------------------------------------------------
#
# One canonical request/reply body per framed verb.  These are the
# ground truth the WP008 analyzer rule reconciles against FRAMED_VERBS,
# and test_wire.py round-trips every entry through encode/decode in
# both directions (client encode -> server decode and back).

_DOC = {
    "tid": 7, "exp_key": "default", "state": 2, "owner": None,
    "spec": None,
    "result": {"loss": 0.125, "status": "ok"},
    "misc": {"tid": 7, "cmd": ["domain_attachment", "FMinIter_Domain"],
             "idxs": {"x": [7]}, "vals": {"x": [0.5]}},
    "book_time": 1700000000.0, "refresh_time": 1700000001.0,
}

CODEC_FIXTURES = {
    "insert_docs": {
        "req": {"verb": "insert_docs", "exp_key": "default",
                "docs": [_DOC, dict(_DOC, tid=8)]},
        "reply": {"tids": [7, 8]},
    },
    "docs": {
        "req": {"verb": "docs", "exp_key": "default"},
        "reply": {"docs": [_DOC, dict(_DOC, tid=8)]},
    },
    "fetch_since": {
        "req": {"verb": "fetch_since", "exp_key": "default",
                "cursor": [0, 12]},
        "reply": {"docs": [_DOC], "cursor": [0, 14], "full": False},
    },
    "wal_ship": {
        "req": {"verb": "wal_ship", "from_seq": 3,
                "records": [{"seq": 4, "t": 1700000000.0, "tenant": "t0",
                             "verb": "insert_docs",
                             "req": {"docs": [_DOC]}}]},
        "reply": {"applied": 1, "seq": 4},
    },
    "snapshot_install": {
        "req": {"verb": "snapshot_install", "seq": 9,
                "snapshot": {"seq": 9, "stores": [
                    {"tenant": "t0", "exp_key": "default",
                     "state": {"docs": [_DOC], "claims": {},
                               "allocated": [7]}}]}},
        "reply": {"ok": True, "seq": 9},
    },
}
