"""Public search-space DSL — drop-in surface of the reference's ``hyperopt.hp``.

Reference: ``hyperopt/hp.py`` + ``hyperopt/pyll_utils.py`` (SURVEY.md §2 L1;
mount was empty, anchors from upstream hyperopt).  Every constructor returns a
:class:`~hyperopt_tpu.space.Expr` node; nested dicts/lists/tuples of nodes form
a space, compiled once by :func:`hyperopt_tpu.space.compile_space`.

Sampling semantics (matching ``hyperopt/pyll/stochastic.py``):

* ``uniform(label, low, high)`` — U[low, high]
* ``loguniform(label, low, high)`` — exp(U[low, high]) (bounds in log space)
* ``quniform`` / ``qloguniform`` — ``round(x / q) * q``
* ``normal(label, mu, sigma)`` / ``lognormal`` / ``qnormal`` / ``qlognormal``
* ``randint(label, upper)`` or ``randint(label, low, upper)`` — integer in
  [low, upper)
* ``uniformint(label, low, high)`` — integer in [low, high], inclusive
* ``choice(label, options)`` — one of the option sub-spaces
* ``pchoice(label, [(p, option), ...])`` — weighted choice
"""

from __future__ import annotations

from .space import (
    Choice,
    Expr,
    LOGNORMAL,
    LOGUNIFORM,
    NORMAL,
    Param,
    QLOGNORMAL,
    QLOGUNIFORM,
    QNORMAL,
    QUNIFORM,
    RANDINT,
    UNIFORM,
    UNIFORMINT,
)

__all__ = [
    "uniform", "loguniform", "quniform", "qloguniform",
    "normal", "lognormal", "qnormal", "qlognormal",
    "randint", "uniformint", "choice", "pchoice",
]


def uniform(label, low, high) -> Expr:
    """Uniform float in [low, high]."""
    return Param(label, UNIFORM, low=low, high=high)


def loguniform(label, low, high) -> Expr:
    """exp(U[low, high]) — i.e. log of the value is uniform; bounds in log space."""
    return Param(label, LOGUNIFORM, low=low, high=high)


def quniform(label, low, high, q) -> Expr:
    """round(U[low, high] / q) * q."""
    return Param(label, QUNIFORM, low=low, high=high, q=q)


def qloguniform(label, low, high, q) -> Expr:
    """round(exp(U[low, high]) / q) * q."""
    return Param(label, QLOGUNIFORM, low=low, high=high, q=q)


def normal(label, mu, sigma) -> Expr:
    """Normal(mu, sigma), unbounded."""
    return Param(label, NORMAL, mu=mu, sigma=sigma)


def lognormal(label, mu, sigma) -> Expr:
    """exp(Normal(mu, sigma)) — positive, log is normal."""
    return Param(label, LOGNORMAL, mu=mu, sigma=sigma)


def qnormal(label, mu, sigma, q) -> Expr:
    """round(Normal(mu, sigma) / q) * q."""
    return Param(label, QNORMAL, mu=mu, sigma=sigma, q=q)


def qlognormal(label, mu, sigma, q) -> Expr:
    """round(exp(Normal(mu, sigma)) / q) * q."""
    return Param(label, QLOGNORMAL, mu=mu, sigma=sigma, q=q)


def randint(label, *args) -> Expr:
    """``randint(label, upper)`` → int in [0, upper);
    ``randint(label, low, upper)`` → int in [low, upper)."""
    if len(args) == 1:
        low, high = 0, args[0]
    elif len(args) == 2:
        low, high = args
    else:
        raise TypeError("randint takes (label, upper) or (label, low, upper)")
    return Param(label, RANDINT, low=low, high=high)


def uniformint(label, low, high, q=1.0) -> Expr:
    """Integer uniform on [low, high], inclusive (reference: quniform q=1 → int)."""
    if float(q) != 1.0:
        raise ValueError("q must be 1.0 for uniformint (reference behavior)")
    return Param(label, UNIFORMINT, low=low, high=high)


def choice(label, options) -> Expr:
    """Select one of ``options`` (each may be any nested sub-space)."""
    return Choice(label, options)


def pchoice(label, p_options) -> Expr:
    """Weighted choice: ``p_options = [(prob, option), ...]``."""
    probs = [p for p, _ in p_options]
    options = [o for _, o in p_options]
    return Choice(label, options, probs=probs)
