"""The ``scope`` expression namespace — pyll-parity deterministic ops.

Reference: ``hyperopt/pyll/base.py::SymbolTable`` / ``scope`` (~L50) and its
builtin ops (``@scope.define``: ``switch``, ``getitem``, arithmetic, ``len``,
~L900+; SURVEY.md §2) — the composition layer behind idioms like::

    scope.int(hp.quniform("n_layers", 1, 64, 1))
    scope.switch(hp.randint("act", 3), "relu", "tanh", "gelu")
    hp.uniform("frac", 0, 1) * scope.len(some_list)

TPU-first placement (NOT a graph interpreter): expressions are deterministic
**decode-time host transforms** layered over the compiled dense sampler —
the reference likewise stores raw ``hyperopt_param`` draws in ``misc.vals``
and applies expressions only during ``rec_eval`` config reconstruction
(SURVEY.md §3.3), so this costs nothing on the device suggest path and the
TPE posterior is unchanged.

Extension point (reference: ``@scope.define``)::

    from hyperopt_tpu import scope

    @scope.define
    def megabytes(x):
        return x * 1024 * 1024

    space = {"cache": scope.megabytes(hp.quniform("mb", 1, 512, 1))}
"""

from __future__ import annotations

from .space import Apply, _SCOPE_IMPLS, define_op


class _OpBuilder:
    """Callable that builds an :class:`~hyperopt_tpu.space.Apply` node."""

    __slots__ = ("name",)

    def __init__(self, name: str):
        self.name = name

    def __call__(self, *args):
        return Apply(self.name, args)

    def __repr__(self):
        return f"scope.{self.name}"


class _Scope:
    """Attribute access builds expression nodes: ``scope.int(x)`` →
    ``Apply("int", (x,))``.  ``@scope.define`` registers new ops."""

    def __getattr__(self, name):
        if name == "define":
            return self._define
        if name in _SCOPE_IMPLS:
            return _OpBuilder(name)
        raise AttributeError(
            f"scope has no op {name!r}; register it with @scope.define")

    @staticmethod
    def _define(fn):
        """Decorator: register ``fn`` as a scope op and return its builder.

        The decorated name then works both as ``scope.<name>(...)`` and as
        the returned callable — matching the reference's ``@scope.define``.
        """
        define_op(fn.__name__, fn)
        return _OpBuilder(fn.__name__)


scope = _Scope()
