"""RD — name-registry coherence between source and catalogs.

Five registries, each checked in both directions:

* env vars     ``HYPEROPT_TPU_*`` string literals read in source vs the
               docs/API.md catalog.
               RD001 read-but-undocumented · RD002 documented-but-unread
* fault points first args of ``maybe_fail()`` calls and the
               ``FAULT_POINTS`` frozenset in faults.py vs docs/API.md.
               RD003 injected-point-not-in-FAULT_POINTS ·
               RD004 FAULT_POINTS-entry-not-in-docs
* service verbs ``self._rpc("X")`` client literals and ``*_VERBS``
               frozensets vs the ``verb == "X"`` dispatcher arms.
               RD005 referenced-but-no-dispatch-arm ·
               RD008 dispatch-arm-never-referenced
* obs metrics  ``.counter/.gauge/.histogram("name")`` emission literals
               (f-strings become ``prefix*`` wildcards) vs the dotted
               names back-ticked in API.md's Observability sections
               (``<placeholder>`` segments become ``*``).
               RD006 emitted-but-uncataloged · RD007 cataloged-but-unemitted
* SLO names    ``SloSpec("name", ...)`` declarations in source vs the
               concrete ``slo.<name>.{firing,burn_fast,burn_slow,value}``
               gauge tokens back-ticked in docs/API.md.  The suffix
               restriction keeps the ``slo.alerts.fired`` counters from
               reading as a declared SLO called "alerts".
               RD009 declared-but-uncataloged · RD010 cataloged-but-undeclared

All extraction is AST / text based — nothing is imported, so a metric
emitted behind an env guard or a lazily-registered fault point is still
seen.  Docstring prose is excluded from the env-var scan (a mention is
not a read).  Doc tokens only count as *metric* catalog entries when
their first dotted segment matches some emitted metric's first segment;
this keeps module paths and config keys out of RD007 at the cost of
missing a catalog section whose whole subsystem was deleted (which
RD002/RD004 would catch via its env vars / fault points anyway).
"""

from __future__ import annotations

import ast
import re

from .core import Finding, dotted_name, joined_str_prefix, str_const

RULES = ("RD001", "RD002", "RD003", "RD004",
         "RD005", "RD006", "RD007", "RD008", "RD009", "RD010")

_ENV_RE = re.compile(r"HYPEROPT_TPU_[A-Z0-9_]+")
_DOC_TOKEN_RE = re.compile(r"`([a-z][a-z0-9_]*(?:\.[a-z0-9_.<>*-]+)+)`")
_EMITTERS = {"counter", "gauge", "histogram"}
_NONMETRIC_SUFFIXES = (".py", ".md", ".json", ".jsonl", ".txt", ".log")
_SLO_SUFFIXES = ("firing", "burn_fast", "burn_slow", "value")


def _doc_line(text: str, token: str) -> int:
    for i, line in enumerate(text.splitlines(), 1):
        if token in line:
            return i
    return 1


def _docstring_ids(tree: ast.Module):
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.Module, ast.ClassDef, ast.FunctionDef,
                             ast.AsyncFunctionDef)):
            body = node.body
            if body and isinstance(body[0], ast.Expr) and \
                    isinstance(body[0].value, ast.Constant) and \
                    isinstance(body[0].value.value, str):
                out.add(id(body[0].value))
    return out


def _wild_match(a: str, b: str) -> bool:
    """Match two names where either may carry ``*`` wildcards."""
    if "*" not in a and "*" not in b:
        return a == b
    pa, pb = a.split("*", 1)[0], b.split("*", 1)[0]
    return pa.startswith(pb) or pb.startswith(pa)


def _literal_set(node) -> set:
    """String elements of a set/frozenset/tuple/list literal expression."""
    out = set()
    if isinstance(node, ast.Call) and node.args:
        name = dotted_name(node.func)
        if name and name.split(".")[-1] in ("frozenset", "set", "tuple"):
            node = node.args[0]
    if isinstance(node, (ast.Set, ast.Tuple, ast.List)):
        for el in node.elts:
            s = str_const(el)
            if s:
                out.add(s)
    return out


class _Extract:
    """One pass over every module: all four registries' source side."""

    def __init__(self, project):
        self.env: dict = {}            # name -> (file, line)
        self.fault_sites: dict = {}    # point -> (file, line)
        self.fault_points: set = set()
        self.fault_file = "hyperopt_tpu/faults.py"
        self.client_verbs: dict = {}   # verb -> (file, line)
        self.dispatch_verbs: dict = {} # verb -> (file, line)
        self.metrics: dict = {}        # name/pattern -> (file, line)
        self.slo_specs: dict = {}      # SLO name -> (file, line)
        for module in project.package_modules():
            self._scan(module)

    def _scan(self, module):
        rel = module.rel
        doc_ids = _docstring_ids(module.tree)
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Constant) and \
                    isinstance(node.value, str) and id(node) not in doc_ids:
                for name in _ENV_RE.findall(node.value):
                    self.env.setdefault(name, (rel, node.lineno))
            if not isinstance(node, ast.Call):
                continue
            fname = dotted_name(node.func) or ""
            tail = fname.split(".")[-1]
            if tail == "maybe_fail" and node.args:
                point = str_const(node.args[0])
                if point:
                    self.fault_sites.setdefault(point, (rel, node.lineno))
            elif tail == "_rpc" and node.args:
                verb = str_const(node.args[0])
                if verb:
                    self.client_verbs.setdefault(verb, (rel, node.lineno))
            elif tail == "SloSpec":
                name = str_const(node.args[0]) if node.args else None
                if name is None:
                    for kw in node.keywords:
                        if kw.arg == "name":
                            name = str_const(kw.value)
                if name:
                    self.slo_specs.setdefault(name, (rel, node.lineno))
            elif isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _EMITTERS and node.args:
                # matches both reg.counter("x") and registry().counter("x")
                # (dotted_name cannot resolve a Call base)
                name = str_const(node.args[0]) or \
                    joined_str_prefix(node.args[0])
                if name:
                    self.metrics.setdefault(name, (rel, node.lineno))
        # FAULT_POINTS / *_VERBS literal sets (module or class scope)
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Assign):
                continue
            for tgt in node.targets:
                tname = tgt.id if isinstance(tgt, ast.Name) else None
                if not tname:
                    continue
                if tname == "FAULT_POINTS":
                    self.fault_points |= _literal_set(node.value)
                    self.fault_file = rel
                elif tname.endswith("_VERBS"):
                    for v in _literal_set(node.value):
                        self.client_verbs.setdefault(v, (rel, node.lineno))
        # dispatcher arms: verb == "X" comparisons anywhere in the module
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Compare) and \
                    isinstance(node.left, ast.Name) and \
                    node.left.id == "verb" and \
                    all(isinstance(op, ast.Eq) for op in node.ops):
                for comp in node.comparators:
                    v = str_const(comp)
                    if v:
                        self.dispatch_verbs.setdefault(
                            v, (rel, node.lineno))


def check(project) -> list:
    findings: list = []
    ext = _Extract(project)
    api = project.file_text("docs/API.md")
    api_env = set(_ENV_RE.findall(api))

    # RD001 / RD002 — env vars
    for name, (rel, line) in sorted(ext.env.items()):
        if name not in api_env:
            findings.append(Finding(
                "RD001", rel, line, name,
                f"env var {name} is read in source but missing from the "
                "docs/API.md catalog"))
    for name in sorted(api_env - set(ext.env)):
        findings.append(Finding(
            "RD002", "docs/API.md", _doc_line(api, name), name,
            f"env var {name} is documented in docs/API.md but never read "
            "in source"))

    # RD003 / RD004 — fault points
    for point, (rel, line) in sorted(ext.fault_sites.items()):
        if ext.fault_points and point not in ext.fault_points:
            findings.append(Finding(
                "RD003", rel, line, point,
                f"maybe_fail point '{point}' is not in faults.FAULT_POINTS"))
    for point in sorted(ext.fault_points):
        if f"`{point}`" not in api:
            findings.append(Finding(
                "RD004", "docs/API.md", 1, point,
                f"fault point '{point}' is in FAULT_POINTS but not "
                "documented in docs/API.md"))

    # RD005 / RD008 — service verbs
    for verb, (rel, line) in sorted(ext.client_verbs.items()):
        if ext.dispatch_verbs and verb not in ext.dispatch_verbs:
            findings.append(Finding(
                "RD005", rel, line, verb,
                f"verb '{verb}' is sent/cataloged by clients but has no "
                "dispatcher arm"))
    for verb, (rel, line) in sorted(ext.dispatch_verbs.items()):
        if ext.client_verbs and verb not in ext.client_verbs:
            findings.append(Finding(
                "RD008", rel, line, verb,
                f"dispatcher handles verb '{verb}' that no client or "
                "*_VERBS catalog references"))

    # RD006 / RD007 — obs metrics vs the Observability doc sections
    obs_text, keep = [], False
    for line in api.splitlines():
        if line.startswith("#"):
            keep = "observability" in line.lower()
        if keep:
            obs_text.append(line)
    obs_text = "\n".join(obs_text)
    first_segs = {m.split(".")[0].rstrip("*") for m in ext.metrics}
    catalog = set()
    for tok in _DOC_TOKEN_RE.findall(obs_text):
        if tok.endswith(_NONMETRIC_SUFFIXES) or tok in ext.fault_points:
            continue
        pat = re.sub(r"<[^>]*>", "*", tok)
        if pat.split(".")[0].split("*")[0] in first_segs:
            catalog.add(pat)
    for name, (rel, line) in sorted(ext.metrics.items()):
        if catalog and not any(_wild_match(name, p) for p in catalog):
            findings.append(Finding(
                "RD006", rel, line, name,
                f"metric '{name}' is emitted but not cataloged in "
                "docs/API.md's Observability section"))
    for pat in sorted(catalog):
        if not any(_wild_match(name, pat) for name in ext.metrics):
            findings.append(Finding(
                "RD007", "docs/API.md", _doc_line(api, pat.split("*")[0]),
                pat,
                f"metric '{pat}' is cataloged in docs/API.md but never "
                "emitted"))

    # RD009 / RD010 — declared SLO names vs the cataloged slo.* gauges.
    # Only tokens shaped ``slo.<name>.<suffix>`` with a per-spec gauge
    # suffix and a concrete (wildcard-free) middle segment count as a
    # cataloged SLO name — ``slo.alerts.fired`` (a counter) and
    # ``slo.<name>.firing`` (the placeholder form) do not.
    slo_doc: dict = {}
    for tok in _DOC_TOKEN_RE.findall(api):
        parts = tok.split(".")
        if len(parts) == 3 and parts[0] == "slo" and \
                parts[2] in _SLO_SUFFIXES and \
                "<" not in parts[1] and "*" not in parts[1]:
            slo_doc.setdefault(parts[1], tok)
    for name, (rel, line) in sorted(ext.slo_specs.items()):
        if slo_doc and name not in slo_doc:
            findings.append(Finding(
                "RD009", rel, line, name,
                f"SLO '{name}' is declared in source (SloSpec) but none "
                f"of its slo.{name}.* gauges are cataloged in docs/API.md"))
    for name, tok in sorted(slo_doc.items()):
        if name not in ext.slo_specs:
            findings.append(Finding(
                "RD010", "docs/API.md", _doc_line(api, tok), name,
                f"SLO '{name}' is cataloged in docs/API.md ({tok}) but "
                "no SloSpec declares it in source"))
    return findings
