"""Invariant analyzer suite — static checks gating tier-1.

Eight AST-based checkers over the package (see each module's docstring
for the rule catalog):

* :mod:`.jit_purity`          JP001–JP007 — trace-time purity of jit/vmap
  paths, host callbacks and Python RNG in lax control-flow bodies
* :mod:`.lock_order`          LK001–LK003 — lock discipline in threaded layers
* :mod:`.registry_drift`      RD001–RD010 — env/fault/verb/metric/SLO catalogs
* :mod:`.artifacts`           AH001       — benchmark artifact schema guards
* :mod:`.wire_protocol`       WP001–WP006 — client/dispatcher/WAL coherence
* :mod:`.replay_determinism`  RT001–RT004 — no nondeterminism on WAL replay
* :mod:`.exception_safety`    ES001–ES003 — release/surface/start discipline
* :mod:`.fault_coverage`      FP001       — every wire/WAL edge has a hook

Run as ``python -m hyperopt_tpu.analysis [--json] [--baseline FILE]``;
the tier-1 gate (``tests/test_analysis_gate.py``) runs the same
:func:`run_repo` against the checked-in ``baseline.json``.

This package imports **stdlib only** and never imports the modules it
analyzes (pure ``ast`` over source text) — it runs on a machine without
JAX and is immune to import-time side effects.
"""

from __future__ import annotations

import os
import time

from . import (artifacts, exception_safety, fault_coverage, jit_purity,
               lock_order, registry_drift, replay_determinism,
               wire_protocol)
from .core import Baseline, Finding, Project

__all__ = ["CHECKERS", "Baseline", "Finding", "Project",
           "run_project", "run_repo", "default_baseline_path"]

#: name -> (checker module, rule-id tuple), in report order.
CHECKERS = {
    "jit-purity": (jit_purity, jit_purity.RULES),
    "lock-order": (lock_order, lock_order.RULES),
    "registry-drift": (registry_drift, registry_drift.RULES),
    "artifact-honesty": (artifacts, artifacts.RULES),
    "wire-protocol": (wire_protocol, wire_protocol.RULES),
    "replay-determinism": (replay_determinism, replay_determinism.RULES),
    "exception-safety": (exception_safety, exception_safety.RULES),
    "fault-coverage": (fault_coverage, fault_coverage.RULES),
}


def default_baseline_path(root: str) -> str:
    return os.path.join(root, "hyperopt_tpu", "analysis", "baseline.json")


def run_project(project, checkers=None, timings=None) -> list:
    """Run the named checkers (default: all) over a built project.

    ``timings``, if given, is a dict filled with per-checker wall time
    in seconds (the ``--json`` report surfaces it so the tier-1 budget
    has per-checker attribution when it creeps).
    """
    findings = []
    for name, (mod, _rules) in CHECKERS.items():
        if checkers and name not in checkers:
            continue
        t0 = time.perf_counter()
        findings.extend(mod.check(project))
        if timings is not None:
            timings[name] = round(
                timings.get(name, 0.0) + time.perf_counter() - t0, 4)
    findings.sort(key=lambda f: (f.file, f.line, f.rule, f.symbol))
    return findings


def run_repo(root: str, checkers=None, timings=None) -> list:
    """Parse the repo at ``root`` and run the checkers over it."""
    return run_project(Project.from_dir(root), checkers=checkers,
                       timings=timings)
