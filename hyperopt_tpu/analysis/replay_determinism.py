"""RT — replay determinism: nothing reachable from WAL replay may
depend on the machine it replays on.

The byte-identity scrub (DESIGN.md §9) compares ``state_bytes()``
between a primary and a replica that each rebuilt their store by
re-executing WAL records.  That comparison is only meaningful if the
replay path is a pure function of the log: the one sanctioned clock is
the pinned ``now_override`` (each record replays at its logged ``t``),
and entropy, wall clocks, environment reads, or unordered-set
iteration anywhere on the path turns an honest divergence detector
into a flake.  These checkers BFS the static call graph from the
replay entry points and flag nondeterminism taint.

Entry points (structural, no imports): functions that assign
``_replaying = True`` (the recovery and wal-ship apply paths), call
``maybe_fail("wal.replay", ...)``, are named ``_apply_record``, or are
the serialization surface itself (``state_dict`` / ``state_bytes`` /
``state_payload`` — what the scrub hashes).

RT001  Wall-clock read (``time.time``, ``datetime.now``/``utcnow``,
       ``coarse_utcnow``) in a replay-reachable function.  Functions
       that reference ``now_override`` are the pinned-clock pattern
       itself and are exempt.
RT002  Entropy (``random.*``, ``os.urandom``, ``uuid.*``,
       ``secrets.*``) in a replay-reachable function — two replays of
       one log diverge by construction.
RT003  Environment read (``os.environ`` / ``os.getenv``) in a
       replay-reachable function — replay outcome depends on deploy
       env, not the log.
RT004  Iteration over a ``set`` (or ``list(set)``/``tuple(set)``) in a
       replay-reachable function without ``sorted()`` — serialized
       output inherits hash order.

Call-graph resolution (over-approximate by design, documented in
DESIGN.md §8): plain names resolve same-module; ``self.M``/
``super().M`` resolve to any method named ``M`` in the same module,
else in ``hyperopt_tpu/service/``; ``super().M`` additionally takes
candidates across the store substrate (``hyperopt_tpu/parallel/``)
because that is the one edge where the override chain crosses modules
(ServiceServer extends netstore's StoreServer — the dispatch arms
replay re-executes live there); ``self.attr.M`` and store-alias
(``ft``) receivers resolve by method name within the service package
only — the store replay mutates is ``service/store.MemTrials``, not
the file/net client stores that happen to share method names.  A
leading
``if self._replaying ...: return`` guard marks everything below it as
live-only and prunes the walk.
"""

from __future__ import annotations

import ast

from .core import Finding, call_func_name, qualified_functions, str_const

RULES = ("RT001", "RT002", "RT003", "RT004")

_SERVICE_PREFIX = "hyperopt_tpu/service/"
#: Where replay-reachable methods may live: the service fleet plus the
#: store substrate it subclasses (ServiceServer extends netstore's
#: StoreServer; the dispatch arms replay re-executes are defined there).
_REPLAY_PREFIXES = ("hyperopt_tpu/service/", "hyperopt_tpu/parallel/")

_WALL_CLOCKS = frozenset({"time.time", "datetime.now", "datetime.utcnow",
                          "coarse_utcnow"})
_ENTROPY_ROOTS = frozenset({"random", "uuid", "secrets"})
_ROOT_NAMES = frozenset({"state_dict", "state_bytes", "state_payload",
                         "_apply_record"})


def _replay_stmts(body):
    """Statements of a body that are on the replay path: a leading
    ``if self._replaying or ...: return`` guard routes replay into its
    own branch, so everything after it is live-only."""
    out = []
    for stmt in body:
        if isinstance(stmt, ast.If) and _positive_replaying(stmt.test) \
                and stmt.body and isinstance(stmt.body[-1], ast.Return):
            out.extend(stmt.body)
            break
        out.append(stmt)
    return out


def _positive_replaying(test) -> bool:
    """Does the test read ``_replaying`` outside a ``not``?"""
    negated = set()
    for node in ast.walk(test):
        if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.Not):
            for sub in ast.walk(node.operand):
                negated.add(id(sub))
    for node in ast.walk(test):
        if isinstance(node, ast.Attribute) and node.attr == "_replaying" \
                and id(node) not in negated:
            return True
    return False


class _Graph:
    def __init__(self, project):
        self.project = project
        self.funcs: dict[tuple, ast.AST] = {}        # (rel, qual) -> node
        self.by_module: dict[str, dict] = {}          # rel -> {name: qual}
        self.service_methods: dict[str, list] = {}    # name -> [(rel, qual)]
        self.substrate_methods: dict[str, list] = {}  # super() chain only
        self.roots: set[tuple] = set()
        # Classes the service package names as bases: the only classes
        # whose methods a service-side ``super().M`` can land on.
        base_names: set = set()
        for module in project.package_modules():
            if not module.rel.startswith(_SERVICE_PREFIX):
                continue
            for node in ast.walk(module.tree):
                if isinstance(node, ast.ClassDef):
                    for b in node.bases:
                        bn = b.id if isinstance(b, ast.Name) else (
                            b.attr if isinstance(b, ast.Attribute)
                            else None)
                        if bn:
                            base_names.add(bn)
        for module in project.package_modules():
            rel = module.rel
            names = {}
            for qual, func, cls in qualified_functions(module.tree):
                key = (rel, qual)
                self.funcs[key] = func
                name = qual.rsplit(".", 1)[-1]
                names.setdefault(name, []).append(qual)
                if rel.startswith(_REPLAY_PREFIXES) and cls in base_names:
                    self.substrate_methods.setdefault(name, []) \
                        .append(key)
                if rel.startswith(_SERVICE_PREFIX):
                    self.service_methods.setdefault(name, []).append(key)
                if self._is_root(rel, name, func):
                    self.roots.add(key)
            self.by_module[rel] = names

    @staticmethod
    def _is_root(rel, name, func) -> bool:
        # Serialization-surface roots only anchor in the service package
        # (other subsystems reuse these method names); the structural
        # markers (_replaying, wal.replay hooks) anchor anywhere.
        if name in _ROOT_NAMES and rel.startswith(_SERVICE_PREFIX):
            return True
        for node in ast.walk(func):
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                # Only *entering* replay marks a root: ``__init__``'s
                # ``self._replaying = False`` initializer and the
                # ``finally`` reset are live-side bookkeeping.
                value = node.value if isinstance(node, ast.Assign) else None
                if not (isinstance(value, ast.Constant)
                        and value.value is True):
                    continue
                for t in targets:
                    tn = t.attr if isinstance(t, ast.Attribute) else (
                        t.id if isinstance(t, ast.Name) else None)
                    if tn == "_replaying":
                        return True
            elif isinstance(node, ast.Call):
                name_ = call_func_name(node) or ""
                if name_.rsplit(".", 1)[-1] == "maybe_fail" and node.args:
                    point = str_const(node.args[0]) or ""
                    if point.startswith("wal.replay"):
                        return True
        return False

    def edges(self, key) -> set:
        rel, _qual = key
        func = self.funcs[key]
        out: set[tuple] = set()
        store_aliases = {"ft"}
        for node in self._replay_walk(func):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call):
                tail = (call_func_name(node.value) or "").rsplit(".", 1)[-1]
                if tail.endswith("_store"):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            store_aliases.add(t.id)
        for node in self._replay_walk(func):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Name):
                for qual in self.by_module[rel].get(f.id, ()):
                    out.add((rel, qual))
            elif isinstance(f, ast.Attribute):
                m = f.attr
                recv = f.value
                is_selfish = (
                    (isinstance(recv, ast.Name)
                     and (recv.id in ("self", "cls")
                          or recv.id in store_aliases))
                    or (isinstance(recv, ast.Call)
                        and (call_func_name(recv) or "") == "super")
                    or (isinstance(recv, ast.Attribute)
                        and isinstance(recv.value, ast.Name)
                        and recv.value.id == "self"))
                if not is_selfish:
                    continue
                local = [(rel, q) for q in self.by_module[rel].get(m, ())]
                direct_self = isinstance(recv, ast.Name) \
                    and recv.id in ("self", "cls")
                if isinstance(recv, ast.Call):
                    # super().M: the override chain crosses modules
                    # (ServiceServer -> StoreServer), so take both the
                    # same-module and the substrate-wide candidates.
                    out.update(local)
                    out.update(self.service_methods.get(m, []))
                    out.update(self.substrate_methods.get(m, []))
                elif local and direct_self:
                    out.update(local)
                else:
                    cross = self.service_methods.get(m, [])
                    out.update(cross if cross else local)
        return out

    def _replay_walk(self, func):
        for stmt in _replay_stmts(func.body):
            yield from ast.walk(stmt)

    def reachable(self) -> set:
        seen = set(self.roots)
        frontier = list(self.roots)
        while frontier:
            key = frontier.pop()
            for nxt in self.edges(key):
                if nxt not in seen:
                    seen.add(nxt)
                    frontier.append(nxt)
        return seen


def _references_now_override(func) -> bool:
    for node in ast.walk(func):
        if isinstance(node, ast.Attribute) and node.attr == "now_override":
            return True
        if isinstance(node, ast.Name) and node.id == "now_override":
            return True
    return False


def _set_names(func, cls_sets) -> set:
    """Local names bound to set values, plus class-level set attrs."""
    names = set(cls_sets)
    for node in ast.walk(func):
        if isinstance(node, ast.Assign):
            v = node.value
            is_set = isinstance(v, ast.Set) or (
                isinstance(v, ast.Call)
                and (call_func_name(v) or "") in ("set", "frozenset"))
            if is_set:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        names.add(t.id)
    return names


def _class_set_attrs(project) -> dict:
    """{rel: {class: set(attrs assigned set()/frozenset())}}"""
    out: dict = {}
    for module in project.package_modules():
        rel = module.rel
        per = {}
        for node in module.tree.body:
            if not isinstance(node, ast.ClassDef):
                continue
            attrs = set()
            for sub in ast.walk(node):
                if isinstance(sub, ast.Assign) \
                        and isinstance(sub.value, ast.Call) \
                        and (call_func_name(sub.value) or "") in (
                            "set", "frozenset"):
                    for t in sub.targets:
                        if isinstance(t, ast.Attribute) \
                                and isinstance(t.value, ast.Name) \
                                and t.value.id == "self":
                            attrs.add(t.attr)
            if attrs:
                per[node.name] = attrs
        if per:
            out[rel] = per
    return out


def check(project) -> list:
    graph = _Graph(project)
    if not graph.roots:
        return []
    findings: list = []
    set_attrs_by_mod = _class_set_attrs(project)
    seen_keys = set()

    for rel, qual in sorted(graph.reachable()):
        func = graph.funcs[(rel, qual)]
        pinned = _references_now_override(func)
        cls = qual.split(".")[0] if "." in qual else None
        cls_sets = set_attrs_by_mod.get(rel, {}).get(cls, set())
        local_sets = _set_names(func, set())

        for node in _replay_stmts(func.body):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    name = call_func_name(sub) or ""
                    tail = name.rsplit(".", 1)[-1]
                    dotted2 = ".".join(name.split(".")[-2:])
                    if not pinned and (dotted2 in _WALL_CLOCKS
                                       or tail == "coarse_utcnow"):
                        _emit(findings, seen_keys, "RT001", rel, sub.lineno,
                              qual, f"wall-clock read {name}() on the WAL "
                              f"replay path — replays at different times "
                              f"diverge; use the pinned now_override clock")
                    root = name.split(".")[0]
                    if root in _ENTROPY_ROOTS or dotted2 == "os.urandom":
                        _emit(findings, seen_keys, "RT002", rel, sub.lineno,
                              qual, f"entropy source {name}() on the WAL "
                              f"replay path — two replays of one log "
                              f"diverge by construction")
                    if dotted2 in ("os.getenv", "environ.get"):
                        _emit(findings, seen_keys, "RT003", rel, sub.lineno,
                              qual, f"environment read {name}() on the WAL "
                              f"replay path — replay depends on deploy "
                              f"env, not the log")
                    if tail in ("list", "tuple") and sub.args:
                        a = sub.args[0]
                        if _is_set_expr(a, local_sets, cls_sets):
                            _emit(findings, seen_keys, "RT004", rel,
                                  sub.lineno, qual,
                                  "materializing a set in hash order on "
                                  "the replay path — wrap in sorted()")
                elif isinstance(sub, ast.Subscript) \
                        and isinstance(sub.value, ast.Attribute) \
                        and isinstance(sub.value.value, ast.Name) \
                        and sub.value.value.id == "os" \
                        and sub.value.attr == "environ" \
                        and isinstance(sub.ctx, ast.Load):
                    _emit(findings, seen_keys, "RT003", rel, sub.lineno,
                          qual, "os.environ[...] read on the WAL replay "
                          "path — replay depends on deploy env, not the "
                          "log")
                elif isinstance(sub, (ast.For, ast.comprehension)):
                    it = sub.iter
                    if _is_set_expr(it, local_sets, cls_sets):
                        line = getattr(sub, "lineno", getattr(
                            it, "lineno", func.lineno))
                        _emit(findings, seen_keys, "RT004", rel, line, qual,
                              "iterating a set in hash order on the "
                              "replay path — wrap in sorted()")
    findings.sort(key=lambda f: (f.file, f.line, f.rule))
    return findings


def _is_set_expr(node, local_sets, cls_sets) -> bool:
    if isinstance(node, ast.Name):
        return node.id in local_sets
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr in cls_sets
    if isinstance(node, ast.Set):
        return True
    if isinstance(node, ast.Call) and (call_func_name(node) or "") in (
            "set", "frozenset"):
        return True
    return False


def _emit(findings, seen, rule, rel, line, qual, msg):
    key = (rule, rel, qual, line)
    if key in seen:
        return
    seen.add(key)
    findings.append(Finding(rule, rel, line, qual, msg))
