"""JP — trace-time purity of jit/vmap/pallas kernel paths.

Walks every function reachable from a ``jax.jit`` / ``jax.vmap`` /
``pl.pallas_call`` entry point (wrapping calls, decorators, including
``partial(jit, ...)`` forms and lambdas) through the *same-module* call
graph, and flags the host-sync / recompile hazard classes that ROADMAP
items 4–5 exist to kill:

JP001  ``.item()`` on a value inside a traced function — a device→host
       sync per call.
JP002  ``float()`` / ``int()`` / ``bool()`` on a non-constant inside a
       traced function — concretizes a tracer (ConcretizationTypeError
       at best, a silent host round-trip when the value is already
       concrete by accident).
JP003  ``np.*`` / ``numpy.*`` call on non-constant arguments inside a
       traced function — numpy computes on host, forcing materialization.
JP004  Python ``if`` / ``while`` on a traced parameter — either a
       tracer-boolean error or, with scalar leaks, a recompile per
       distinct value.  Structure tests (``x is None``,
       ``isinstance(x, ...)``) and parameters marked static
       (``static_argnums`` / ``static_argnames``) are exempt: those
       branch on trace-time structure, which is the supported idiom.
JP005  Use-after-donation: an argument passed in a donated position of a
       ``jax.jit(..., donate_argnums=...)`` callable is read again after
       the call — donated buffers are invalidated by XLA aliasing (the
       ``history.py`` delta-append rings are the in-repo donors).
JP006  Host callback (``pure_callback`` / ``io_callback`` /
       ``jax.debug.callback`` / ``host_callback``) inside a traced
       function — a host round trip per invocation, which in a scan body
       means one per *carried step* and defeats the whole-loop-on-device
       contract (``device_fmin`` / ``fmin(mode="device")``).
JP007  Python-side RNG inside a traced function — ``np.random.*``,
       stdlib ``random.*``, or a ``.integers()`` Generator draw.  Host
       randomness is frozen at trace time (same value every execution)
       and invisible to JAX's key discipline; thread a ``prng_key``
       through the carry instead.

Entry points include control-flow combinator bodies: the function
handed to ``lax.scan`` (arg 0), ``lax.fori_loop`` (arg 2),
``lax.while_loop`` (args 0 and 1), ``lax.cond`` (args 1 and 2) and
``lax.map`` (arg 0) is traced even when the call site itself is not
jitted, so those bodies get the full JP sweep — this is what keeps the
``fmin(mode="device")`` carry loop honest.

Purely lexical + same-module reachability: cross-module calls are out of
scope (each module's own traced entry points cover its kernels).
"""

from __future__ import annotations

import ast

from .core import Finding, dotted_name, qualified_functions

RULES = ("JP001", "JP002", "JP003", "JP004", "JP005", "JP006", "JP007")

_TRACERS = {"jit", "vmap", "pmap", "pallas_call", "shard_map"}
_CASTS = {"float", "int", "bool"}

# Control-flow combinators whose function arguments are traced bodies:
# name of the callable's last component -> positional indices to resolve.
_CTRL_FLOW = {"scan": (0,), "fori_loop": (2,), "while_loop": (0, 1),
              "cond": (1, 2)}


def _ctrl_flow_positions(name: str | None):
    """Traced-body arg positions for lax control-flow calls, else None.
    ``map`` requires a ``lax`` qualifier so the Python builtin never
    resolves; the other names are distinctive enough bare."""
    if not name:
        return None
    parts = name.split(".")
    if parts[-1] == "map":
        return (0,) if "lax" in parts[:-1] else None
    return _CTRL_FLOW.get(parts[-1])


def _is_trace_wrapper(name: str | None) -> bool:
    """True for ``jit``, ``jax.jit``, ``jax.experimental.x.pallas_call``…"""
    if not name:
        return False
    return name.split(".")[-1] in _TRACERS


def _partial_trace_call(call: ast.Call):
    """``partial(jax.jit, ...)`` / ``functools.partial(jit, ...)`` →
    the inner jit Call-alike (kwargs carry static args), else None."""
    name = dotted_name(call.func)
    if not name or name.split(".")[-1] != "partial":
        return False
    return bool(call.args) and _is_trace_wrapper(dotted_name(call.args[0]))


def _const_tuple(node):
    """Literal int-tuple/int value, else None (unresolvable)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for el in node.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, int):
                out.append(el.value)
            else:
                return None
        return tuple(out)
    return None


def _static_names(call: ast.Call, fn: ast.FunctionDef | None):
    """Parameter names marked static in a jit() call wrapping ``fn``."""
    static = set()
    params = [a.arg for a in fn.args.args] if fn is not None else []
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            if isinstance(kw.value, ast.Constant):
                static.add(str(kw.value.value))
            elif isinstance(kw.value, (ast.Tuple, ast.List)):
                for el in kw.value.elts:
                    if isinstance(el, ast.Constant):
                        static.add(str(el.value))
        elif kw.arg == "static_argnums":
            nums = _const_tuple(kw.value)
            for i in nums or ():
                if 0 <= i < len(params):
                    static.add(params[i])
    return static


class _ModuleIndex:
    """Per-module symbol tables the walker resolves against."""

    def __init__(self, module):
        self.module = module
        self.funcs: dict = {}      # name -> FunctionDef (top level)
        self.methods: dict = {}    # (class, name) -> FunctionDef
        self.np_aliases: set = set()
        self.rng_aliases: set = set()   # stdlib random / numpy.random
        for qual, node, cls in qualified_functions(module.tree):
            if cls is None:
                self.funcs[node.name] = node
            else:
                self.methods[(cls, node.name)] = node
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "numpy":
                        self.np_aliases.add(a.asname or "numpy")
                    elif a.name in ("random", "numpy.random"):
                        self.rng_aliases.add(
                            a.asname or a.name.split(".")[-1])
            elif isinstance(node, ast.ImportFrom):
                if node.module == "numpy":
                    for a in node.names:
                        if a.name == "random":
                            self.rng_aliases.add(a.asname or "random")


def _entry_points(index: _ModuleIndex):
    """(func_node, class_name, static_param_names) for every function the
    module hands to a trace wrapper, plus decorated ones."""
    entries = []

    def resolve(node, cls, scopes=()):
        if isinstance(node, ast.Name):
            for scope in reversed(scopes):   # nested defs shadow globals
                if node.id in scope:
                    return (scope[node.id], cls)
            fn = index.funcs.get(node.id)
            return (fn, None) if fn is not None else None
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and node.value.id == "self" \
                and cls is not None:
            fn = index.methods.get((cls, node.attr))
            return (fn, cls) if fn is not None else None
        return None

    # decorators: @jit / @jax.jit / @partial(jit, static_argnames=...)
    for qual, node, cls in qualified_functions(index.module.tree):
        for dec in node.decorator_list:
            if _is_trace_wrapper(dotted_name(dec)):
                entries.append((node, cls, set()))
            elif isinstance(dec, ast.Call) and (
                    _is_trace_wrapper(dotted_name(dec.func))
                    or _partial_trace_call(dec)):
                entries.append((node, cls, _static_names(dec, node)))

    # wrapping calls: jit(f), jax.jit(jax.vmap(f), static_argnums=...),
    # pl.pallas_call(kernel, ...) — resolve Name / self.attr / lambda.
    class _Wraps(ast.NodeVisitor):
        def __init__(self):
            self.cls = None
            self.scopes = []    # local def tables, innermost last

        def visit_ClassDef(self, node):
            prev, self.cls = self.cls, node.name
            self.generic_visit(node)
            self.cls = prev

        def visit_FunctionDef(self, node):
            # scan/cond bodies are usually CLOSURES of a builder — make
            # the builder's nested defs resolvable while inside it.
            local = {n.name: n for n in ast.walk(node)
                     if isinstance(n, ast.FunctionDef) and n is not node}
            self.scopes.append(local)
            self.generic_visit(node)
            self.scopes.pop()

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Call(self, node):
            if _is_trace_wrapper(dotted_name(node.func)) and node.args:
                target, outer_static = node.args[0], _static_names(node, None)
                # unwrap nesting: jit(vmap(f))
                while isinstance(target, ast.Call) and \
                        _is_trace_wrapper(dotted_name(target.func)) \
                        and target.args:
                    target = target.args[0]
                if isinstance(target, ast.Lambda):
                    entries.append((target, self.cls, set()))
                else:
                    got = resolve(target, self.cls, self.scopes)
                    if got is not None:
                        fn, cls = got
                        entries.append(
                            (fn, cls, _static_names(node, fn)))
            # lax control flow: the body args are traced even when the
            # call site itself isn't jitted (scan bodies ARE the device
            # loop in fmin(mode="device")).
            positions = _ctrl_flow_positions(dotted_name(node.func))
            for pos in positions or ():
                if pos >= len(node.args):
                    continue
                target = node.args[pos]
                while isinstance(target, ast.Call) and \
                        _is_trace_wrapper(dotted_name(target.func)) \
                        and target.args:
                    target = target.args[0]
                if isinstance(target, ast.Lambda):
                    entries.append((target, self.cls, set()))
                else:
                    got = resolve(target, self.cls, self.scopes)
                    if got is not None:
                        entries.append((got[0], got[1], set()))
            self.generic_visit(node)

    _Wraps().visit(index.module.tree)
    return entries


def _reachable(index: _ModuleIndex, entries):
    """BFS over same-module calls: Name() → top-level func, self.m() →
    method of the entry's class.  Returns {id(node): (node, cls, static)}."""
    seen: dict = {}
    work = list(entries)
    while work:
        fn, cls, static = work.pop()
        if id(fn) in seen:
            continue
        seen[id(fn)] = (fn, cls, static)
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            callee = None
            if isinstance(node.func, ast.Name):
                callee = (index.funcs.get(node.func.id), None)
            elif isinstance(node.func, ast.Attribute) and \
                    isinstance(node.func.value, ast.Name) and \
                    node.func.value.id == "self" and cls is not None:
                callee = (index.methods.get((cls, node.func.attr)), cls)
            if callee and callee[0] is not None and id(callee[0]) not in seen:
                work.append((callee[0], callee[1], set()))
    return seen


def _fn_name(fn, cls):
    name = getattr(fn, "name", "<lambda>")
    return f"{cls}.{name}" if cls else name


def _traced_params(fn, static):
    args = fn.args
    names = [a.arg for a in args.args + args.kwonlyargs
             + getattr(args, "posonlyargs", [])]
    if args.vararg:
        names.append(args.vararg.arg)
    return {n for n in names if n not in static and n != "self"}


def _is_env_read(node) -> bool:
    """``os.environ.get(...)`` / ``os.getenv(...)`` — a host string at
    trace time, never a tracer; casting it is config parsing, not a sync."""
    if not isinstance(node, ast.Call):
        return False
    name = dotted_name(node.func) or ""
    return "environ" in name or name.endswith("getenv")


def _structure_test_names(test):
    """Names that only appear in `x is None` / `isinstance(x, ...)` /
    `hasattr/getattr/len(...)`-free structure positions — exempt."""
    exempt = set()
    for node in ast.walk(test):
        if isinstance(node, ast.Compare):
            ops_none = all(isinstance(op, (ast.Is, ast.IsNot))
                           for op in node.ops) and all(
                isinstance(c, ast.Constant) and c.value is None
                for c in node.comparators)
            if ops_none and isinstance(node.left, ast.Name):
                exempt.add(node.left.id)
        elif isinstance(node, ast.Call):
            fname = dotted_name(node.func)
            if fname in ("isinstance", "hasattr", "callable", "len"):
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Name):
                        exempt.add(sub.id)
    return exempt


def _is_host_callback(name: str | None) -> bool:
    if not name:
        return False
    last = name.split(".")[-1]
    if last in ("pure_callback", "io_callback"):
        return True
    if "host_callback" in name:
        return True
    return last == "callback" and "debug" in name


def _is_python_rng(name: str | None, node: ast.Call, index) -> bool:
    if isinstance(node.func, ast.Attribute) and \
            node.func.attr == "integers":
        return True     # np.random.Generator.integers draw
    if not name:
        return False
    parts = name.split(".")
    if parts[0] in index.np_aliases and len(parts) > 2 \
            and parts[1] == "random":
        return True     # np.random.normal(...) etc.
    return len(parts) > 1 and parts[0] in index.rng_aliases


def _check_body(findings, rel, fn, cls, static, index):
    sym = _fn_name(fn, cls)
    traced = _traced_params(fn, static)
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                name = dotted_name(node.func)
                if isinstance(node.func, ast.Attribute) and \
                        node.func.attr == "item" and not node.args:
                    findings.append(Finding(
                        "JP001", rel, node.lineno, sym,
                        ".item() in a traced function forces a "
                        "device->host sync"))
                elif _is_host_callback(name):
                    findings.append(Finding(
                        "JP006", rel, node.lineno, sym,
                        f"host callback {name}() inside a traced function "
                        "— one host round trip per call (per carried step "
                        "in a scan body)"))
                elif _is_python_rng(name, node, index):
                    findings.append(Finding(
                        "JP007", rel, node.lineno, sym,
                        "Python-side RNG inside a traced function — the "
                        "draw freezes at trace time; thread a jax PRNG "
                        "key through the carry instead"))
                elif name in _CASTS and node.args and not isinstance(
                        node.args[0], ast.Constant) and \
                        not _is_env_read(node.args[0]):
                    findings.append(Finding(
                        "JP002", rel, node.lineno, sym,
                        f"{name}() on a non-constant in a traced function "
                        "concretizes a tracer"))
                elif name and name.split(".")[0] in index.np_aliases \
                        and node.args and any(
                            not isinstance(a, ast.Constant)
                            for a in node.args):
                    findings.append(Finding(
                        "JP003", rel, node.lineno, sym,
                        f"host numpy call {name}() on non-constant args "
                        "inside a traced function"))
            elif isinstance(node, (ast.If, ast.While)):
                exempt = _structure_test_names(node.test)
                hits = sorted(
                    {n.id for n in ast.walk(node.test)
                     if isinstance(n, ast.Name)} & traced - exempt)
                if hits:
                    findings.append(Finding(
                        "JP004", rel, node.lineno, sym,
                        f"Python branch on traced parameter(s) "
                        f"{', '.join(hits)} (tracer boolean / recompile "
                        "per value; mark static or use lax.cond/jnp.where)"))


def _donated_calls(index: _ModuleIndex):
    """name -> donated positions, for ``g = jax.jit(f, donate_argnums=...)``
    bindings at module or function scope (literal argnums only)."""
    table: dict = {}
    for node in ast.walk(index.module.tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            call = node.value
            if not _is_trace_wrapper(dotted_name(call.func)):
                continue
            donate = None
            for kw in call.keywords:
                if kw.arg == "donate_argnums":
                    donate = _const_tuple(kw.value)
            if donate:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        table[tgt.id] = donate
    return table


def _check_donation(findings, rel, index: _ModuleIndex):
    donated = _donated_calls(index)
    if not donated:
        return
    for qual, fn, cls in qualified_functions(index.module.tree):
        stmts = list(fn.body)
        # statement-ordered scan: record donated arg names at call sites,
        # flag any later Load of those names (before reassignment).
        dead: dict = {}    # var name -> donation call line
        for stmt in stmts:
            # reads first (a = f(a) reads then rebinds)
            for node in ast.walk(stmt):
                if isinstance(node, ast.Call):
                    name = dotted_name(node.func)
                    if name in donated:
                        for pos in donated[name]:
                            if pos < len(node.args) and isinstance(
                                    node.args[pos], ast.Name):
                                dead.setdefault(node.args[pos].id,
                                                node.lineno)
            for node in ast.walk(stmt):
                if isinstance(node, ast.Name) and \
                        isinstance(node.ctx, ast.Load) and node.id in dead \
                        and node.lineno > dead[node.id]:
                    findings.append(Finding(
                        "JP005", rel, node.lineno, qual,
                        f"'{node.id}' read after being donated to a "
                        f"donate_argnums jit at line {dead[node.id]} "
                        "(donated buffers are invalidated)"))
                    del dead[node.id]
                    break
            # rebinding clears the hazard
            targets = []
            if isinstance(stmt, ast.Assign):
                targets = stmt.targets
            elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
                targets = [stmt.target]
            for tgt in targets:
                for node in ast.walk(tgt):
                    if isinstance(node, ast.Name):
                        dead.pop(node.id, None)


def check(project) -> list:
    findings: list = []
    for module in project.package_modules():
        index = _ModuleIndex(module)
        entries = _entry_points(index)
        if entries:
            for fn, cls, static in _reachable(index, entries).values():
                _check_body(findings, module.rel, fn, cls, static, index)
        _check_donation(findings, module.rel, index)
    return findings
