"""ES — exception safety in the threaded layers: failures must either
release, surface, or not start under a lock.

Fault injection (faults.py) can prove the *handled* failure paths; it
cannot see the failure modes where the error never surfaces — a lock
left held after an exception, a daemon thread swallowing its own death,
a thread spun up while its creator still holds the lock the new thread
will immediately want.  These are the bugs with multi-hour debugging
tails because the process looks healthy.

ES001  Manual ``lock.acquire()`` with no try/finally ``release()`` —
       any exception between the two leaves the lock held forever.
       ``with lock:`` is the idiom; a bare acquire is only tolerated as
       the statement immediately before (or inside) a ``try`` whose
       ``finally`` releases the same lock.
ES002  A broad ``except``/``except Exception`` inside a thread-entry
       function (or anything it calls, same module) that neither
       re-raises nor surfaces (logging/print/metrics) — the daemon dies
       or degrades silently and fault injection never sees it.
ES003  A thread started while holding a lock — directly
       (``Thread(...).start()``) or by constructing a class whose
       ``__init__`` starts one.  The new thread's first lock
       acquisition races its creator's critical section; if the creator
       ever blocks on the child, it deadlocks.

Thread-entry functions are found structurally: any function referenced
as ``target=`` in a ``threading.Thread(...)`` call, the function
containing that call when the target is a nested def, and their
same-module transitive callees.  Surfacing calls are attribute calls
named ``exception``/``error``/``warning``/``critical``/``info``/
``debug``/``log``, bare ``print``, or metric emissions (``.inc``/
``.set``/``.observe``).
"""

from __future__ import annotations

import ast

from .core import (Finding, call_func_name, dotted_name,
                   qualified_functions)

RULES = ("ES001", "ES002", "ES003")

_LOCK_CTORS = frozenset({"Lock", "RLock", "Condition", "Semaphore",
                         "BoundedSemaphore"})
_SURFACE_TAILS = frozenset({"exception", "error", "warning", "warn",
                            "critical", "info", "debug", "log", "print",
                            "inc", "set", "observe"})


def _lockish_name(expr) -> str | None:
    """A name that denotes a lock: ``self._lock``-style attributes or
    bare names containing 'lock'/'cv'/'cond'."""
    if isinstance(expr, ast.Attribute):
        if "lock" in expr.attr.lower() or expr.attr.lower() in (
                "cv", "cond"):
            return "." + expr.attr
        return None
    if isinstance(expr, ast.Name):
        low = expr.id.lower()
        if "lock" in low or low in ("cv", "cond"):
            return expr.id
        return None
    return None


def _lock_attrs(tree) -> set:
    """self attributes assigned a threading lock anywhere in the module
    (plus module-level lock names) — extends the name heuristic so
    ``self._gate = threading.Lock()`` counts even without 'lock' in the
    name."""
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            ctor = (call_func_name(node.value) or "").rsplit(".", 1)[-1]
            if ctor not in _LOCK_CTORS:
                continue
            for t in node.targets:
                if isinstance(t, ast.Attribute):
                    out.add("." + t.attr)
                elif isinstance(t, ast.Name):
                    out.add(t.id)
    return out


def _release_targets(stmts) -> set:
    out = set()
    for stmt in stmts:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "release":
                name = _lockish_name(node.func.value)
                if name is None and isinstance(node.func.value,
                                               ast.Attribute):
                    name = "." + node.func.value.attr
                elif name is None and isinstance(node.func.value, ast.Name):
                    name = node.func.value.id
                if name:
                    out.add(name)
    return out


def _acquire_name(stmt, known_locks) -> tuple | None:
    """(lock_name, line) if the statement's top-level expression is an
    ``acquire()`` call on a lock."""
    expr = None
    if isinstance(stmt, ast.Expr):
        expr = stmt.value
    elif isinstance(stmt, ast.Assign):
        expr = stmt.value
    if not (isinstance(expr, ast.Call)
            and isinstance(expr.func, ast.Attribute)
            and expr.func.attr == "acquire"):
        return None
    recv = expr.func.value
    name = _lockish_name(recv)
    if name is None:
        if isinstance(recv, ast.Attribute):
            name = "." + recv.attr
        elif isinstance(recv, ast.Name):
            name = recv.id
        if name not in known_locks:
            return None
    return name, expr.lineno


def _check_acquires(func, rel, qual, known_locks, findings):
    def scan(body):
        for i, stmt in enumerate(body):
            got = _acquire_name(stmt, known_locks)
            if got is not None:
                name, line = got
                ok = False
                nxt = body[i + 1] if i + 1 < len(body) else None
                if isinstance(nxt, ast.Try) \
                        and name in _release_targets(nxt.finalbody):
                    ok = True
                if not ok:
                    findings.append(Finding(
                        "ES001", rel, line, qual,
                        f"manual {name}.acquire() with no try/finally "
                        f"release — an exception leaves the lock held; "
                        f"use 'with'"))
            if isinstance(stmt, ast.Try):
                released = _release_targets(stmt.finalbody)
                # acquires inside try-with-finally-release are fine
                for j, sub in enumerate(stmt.body):
                    got = _acquire_name(sub, known_locks)
                    if got is not None and got[0] not in released:
                        findings.append(Finding(
                            "ES001", rel, got[1], qual,
                            f"manual {got[0]}.acquire() with no "
                            f"try/finally release — an exception leaves "
                            f"the lock held; use 'with'"))
                for sub in stmt.body:
                    for blk in _sub_blocks(sub):
                        scan(blk)
                for h in stmt.handlers:
                    scan(h.body)
                scan(stmt.orelse)
                scan(stmt.finalbody)
                continue
            for blk in _sub_blocks(stmt):
                scan(blk)

    scan(func.body)


def _sub_blocks(stmt):
    for attr in ("body", "orelse", "finalbody"):
        blk = getattr(stmt, attr, None)
        if blk and not isinstance(stmt, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
            yield blk
    for h in getattr(stmt, "handlers", ()):
        yield h.body


def _is_broad_handler(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = []
    if isinstance(t, ast.Tuple):
        names = [dotted_name(e) or "" for e in t.elts]
    else:
        names = [dotted_name(t) or ""]
    return any(n.rsplit(".", 1)[-1] in ("Exception", "BaseException")
               for n in names)


def _handler_surfaces(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            tail = node.func.attr if isinstance(node.func, ast.Attribute) \
                else ((call_func_name(node) or "").rsplit(".", 1)[-1])
            if tail in _SURFACE_TAILS:
                return True
        # ``except Exception as e: queue.put((.., e))`` marshals the
        # exception onward — the failure is someone else's to surface.
        if handler.name and isinstance(node, ast.Name) \
                and node.id == handler.name \
                and isinstance(node.ctx, ast.Load):
            return True
    return False


def _thread_entry_functions(tree) -> set:
    """Names of functions that run on (or start) daemon threads: every
    ``target=`` reference, plus the containing function when the target
    is a nested def (the handler scan covers the whole lexical scope)."""
    entries = set()
    funcs = list(qualified_functions(tree))
    for qual, func, _cls in funcs:
        for node in ast.walk(func):
            if not (isinstance(node, ast.Call)
                    and (call_func_name(node) or "").rsplit(
                        ".", 1)[-1] == "Thread"):
                continue
            for kw in node.keywords:
                if kw.arg != "target":
                    continue
                tname = (dotted_name(kw.value) or "").rsplit(".", 1)[-1]
                if not tname:
                    continue
                nested = {d.name for d in ast.walk(func)
                          if isinstance(d, (ast.FunctionDef,
                                            ast.AsyncFunctionDef))
                          and d is not func}
                if tname in nested:
                    entries.add(qual)       # scan the enclosing scope
                else:
                    entries.add(tname)
    # close over same-module calls from entry functions
    by_name: dict[str, list] = {}
    for qual, func, _cls in funcs:
        by_name.setdefault(qual.rsplit(".", 1)[-1], []).append(qual)
        by_name.setdefault(qual, []).append(qual)
    calls: dict[str, set] = {}
    for qual, func, _cls in funcs:
        out = set()
        for node in ast.walk(func):
            if isinstance(node, ast.Call):
                tail = (call_func_name(node) or "").rsplit(".", 1)[-1]
                if tail:
                    out.update(by_name.get(tail, ()))
        calls[qual] = out
    changed = True
    while changed:
        changed = False
        for qual, out in calls.items():
            short = qual.rsplit(".", 1)[-1]
            if qual in entries or short in entries:
                fresh = out - entries
                if fresh:
                    entries.update(fresh)
                    changed = True
    return entries


def _thread_starting_classes(project) -> set:
    """Class names whose ``__init__`` starts a thread."""
    out = set()
    for module in project.package_modules():
        rel = module.rel
        for node in module.tree.body:
            if not isinstance(node, ast.ClassDef):
                continue
            for sub in node.body:
                if isinstance(sub, ast.FunctionDef) \
                        and sub.name == "__init__" \
                        and _starts_thread(sub):
                    out.add(node.name)
    return out


def _starts_thread(func) -> bool:
    thread_names = set()
    for node in ast.walk(func):
        if isinstance(node, ast.Assign) \
                and isinstance(node.value, ast.Call) \
                and (call_func_name(node.value) or "").rsplit(
                    ".", 1)[-1] == "Thread":
            for t in node.targets:
                if isinstance(t, ast.Name):
                    thread_names.add(t.id)
                elif isinstance(t, ast.Attribute):
                    thread_names.add("." + t.attr)
    for node in ast.walk(func):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "start"):
            continue
        recv = node.func.value
        if isinstance(recv, ast.Call) and (call_func_name(recv) or "") \
                .rsplit(".", 1)[-1] == "Thread":
            return True
        if isinstance(recv, ast.Name) and recv.id in thread_names:
            return True
        if isinstance(recv, ast.Attribute) \
                and "." + recv.attr in thread_names:
            return True
    return False


def _check_starts_under_lock(func, rel, qual, known_locks,
                             thread_classes, findings):
    thread_locals = set()
    for node in ast.walk(func):
        if isinstance(node, ast.Assign) \
                and isinstance(node.value, ast.Call):
            tail = (call_func_name(node.value) or "").rsplit(".", 1)[-1]
            if tail == "Thread":
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        thread_locals.add(t.id)

    def scan(body, held):
        for stmt in body:
            if isinstance(stmt, ast.With):
                locks = []
                for item in stmt.items:
                    name = _lockish_name(item.context_expr)
                    if name is None:
                        ce = item.context_expr
                        if isinstance(ce, ast.Attribute) \
                                and "." + ce.attr in known_locks:
                            name = "." + ce.attr
                        elif isinstance(ce, ast.Name) \
                                and ce.id in known_locks:
                            name = ce.id
                    if name:
                        locks.append(name)
                scan(stmt.body, held + locks)
                continue
            if held:
                for node in ast.walk(stmt):
                    if not isinstance(node, ast.Call):
                        continue
                    if isinstance(node.func, ast.Attribute) \
                            and node.func.attr == "start":
                        recv = node.func.value
                        started = (
                            (isinstance(recv, ast.Call)
                             and (call_func_name(recv) or "").rsplit(
                                 ".", 1)[-1] == "Thread")
                            or (isinstance(recv, ast.Name)
                                and recv.id in thread_locals))
                        if started:
                            findings.append(Finding(
                                "ES003", rel, node.lineno, qual,
                                f"thread started while holding "
                                f"{held[-1]} — the child's first lock "
                                f"acquisition races this critical "
                                f"section"))
                    else:
                        ctor = call_func_name(node) or ""
                        if ctor.rsplit(".", 1)[-1] in thread_classes:
                            findings.append(Finding(
                                "ES003", rel, node.lineno, qual,
                                f"{ctor}() starts a thread in __init__ "
                                f"while {held[-1]} is held — construct "
                                f"outside the lock, publish under it"))
            for blk in _sub_blocks(stmt):
                scan(blk, held)

    scan(func.body, [])


def check(project) -> list:
    findings: list = []
    thread_classes = _thread_starting_classes(project)
    for module in project.package_modules():
        rel = module.rel
        tree = module.tree
        known_locks = _lock_attrs(tree)
        entries = _thread_entry_functions(tree)
        for qual, func, _cls in qualified_functions(tree):
            _check_acquires(func, rel, qual, known_locks, findings)
            _check_starts_under_lock(func, rel, qual, known_locks,
                                     thread_classes, findings)
            short = qual.rsplit(".", 1)[-1]
            if qual in entries or short in entries:
                for node in ast.walk(func):
                    if isinstance(node, ast.ExceptHandler) \
                            and _is_broad_handler(node) \
                            and not _handler_surfaces(node):
                        findings.append(Finding(
                            "ES002", rel, node.lineno, qual,
                            "broad except swallows silently inside a "
                            "thread-entry path — the daemon degrades "
                            "with no trace; log, count, or re-raise"))
    findings.sort(key=lambda f: (f.file, f.line, f.rule))
    return findings
