"""FP — fault-point coverage: chaos can reach every wire and WAL edge.

The failover proofs (DESIGN.md §9) are only as strong as the fault
schedule's reach: an RPC or durable-append code path with no
``faults.maybe_fail`` hook is a path the chaos harness can never
exercise, so its failure handling is permanently untested.  RD003/
RD004 already reconcile hook *names* against the ``FAULT_POINTS``
catalog; this rule closes the other direction — the *sites* that must
carry a hook at all.

FP001  A function that performs wire I/O (calls ``urlopen`` or checks
       out the pooled transport via ``_rpc_pool``) or the durable WAL
       append (an ``append`` method in a module naming the
       ``wal.jsonl`` log) contains no ``maybe_fail(...)`` hook — fault
       injection cannot reach this network/durability edge.  The pool's
       own internals are exempt: the *call sites* carry the hooks, so
       one hook guards every transport however many sockets it cycles.
"""

from __future__ import annotations

import ast

from .core import Finding, call_func_name, qualified_functions, str_const

RULES = ("FP001",)

_WAL_LOG = "wal.jsonl"


def _module_names_wal(tree) -> bool:
    for node in ast.walk(tree):
        s = str_const(node)
        if s is not None and _WAL_LOG in s:
            return True
    return False


def check(project) -> list:
    findings: list = []
    for module in project.package_modules():
        rel = module.rel
        is_wal_module = _module_names_wal(module.tree)
        for qual, func, _cls in qualified_functions(module.tree):
            does_io_line = 0
            kind = None
            has_hook = False
            for node in ast.walk(func):
                if not isinstance(node, ast.Call):
                    continue
                tail = (call_func_name(node) or "").rsplit(".", 1)[-1]
                if tail == "urlopen" and not does_io_line:
                    does_io_line, kind = node.lineno, "wire I/O (urlopen)"
                elif tail == "_rpc_pool" and not does_io_line:
                    does_io_line, kind = node.lineno, \
                        "wire I/O (pooled transport)"
                elif tail == "maybe_fail" and node.args \
                        and str_const(node.args[0]):
                    has_hook = True
            if is_wal_module and qual.rsplit(".", 1)[-1] == "append" \
                    and not does_io_line:
                does_io_line, kind = func.lineno, "the durable WAL append"
            if does_io_line and not has_hook:
                findings.append(Finding(
                    "FP001", rel, does_io_line, qual,
                    f"{kind} with no maybe_fail hook — fault injection "
                    f"cannot reach this edge; add a cataloged fault "
                    f"point"))
    return findings
