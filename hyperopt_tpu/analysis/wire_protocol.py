"""WP — wire-protocol coherence: clients, dispatchers, and the WAL agree.

The service fleet's exactly-once story (DESIGN.md §9) rests on three
cross-process contracts no single module can see whole: every verb a
client emits has a dispatcher arm (and vice versa), every field a
dispatcher *requires* is supplied at every call site, and the verbs
that mutate durable store state are exactly the verbs that are WAL-
logged and idempotency-keyed.  These checkers reconcile all three from
source text alone.

WP001  A client RPC call site emits a verb no dispatcher arm handles —
       the request can only ever come back ``unknown verb``.
WP002  A dispatcher arm handles a verb nothing emits and no ``*_VERBS``
       catalog names — dead protocol surface (or a client was lost).
WP003  A client call site omits a field the dispatcher arm reads with
       ``req["field"]`` (a hard KeyError on the server).  Sites that
       splat ``**kw`` and the fields ``_Rpc.__call__`` injects
       (``verb``/``exp_key``/``idem``/``ctx``) are exempt.
WP004  A verb that mutates durable store state is neither in a
       ``*_MUTATING_VERBS`` catalog (the client auto-attaches an
       idempotency key — the attach itself is verified structurally)
       nor declared retry-convergent in a ``*_IDEMPOTENT_VERBS``
       catalog: a retried request can execute twice.
WP005  A ``*_WAL_VERBS`` catalog disagrees with the set of dispatcher
       arms that actually mutate durable store state — either a WAL-
       logged verb whose replay re-executes a read, or a mutation that
       survives no crash.  "Durable" is computed, not assumed: the
       attributes ``state_dict`` serializes.
WP006  Catalog hygiene: a verb in both ``*_MUTATING_VERBS`` and
       ``*_IDEMPOTENT_VERBS`` (contradiction), or declared idempotent
       without being a mutating verb at all (stale declaration).
WP007  A verb declared in a ``*_READONLY_VERBS`` catalog (the server
       serves these on the lock-free read path, off the write lock and
       ahead of any fsync queue) mutates durable store state, appears
       in a mutating/WAL/idempotent catalog, or names no dispatcher
       arm at all — any of which lets a "read" race the writers the
       dispatch lock exists to serialize.
WP008  Binary-frame coverage: every verb in a ``*FRAMED_VERBS``
       catalog (its request/reply bodies ride the columnar binary
       frame) must have a dispatcher arm AND a ``CODEC_FIXTURES``
       entry carrying BOTH directions (``req`` and ``reply`` bodies —
       the shared fixtures ``test_wire.py`` round-trips through client
       encode ↔ server decode), and every fixture key must still be a
       framed verb.  Keeps the WP001–WP006 ground truth honest when a
       verb's bytes stop being JSON.

Conventions honored (all structural, none import-time): client call
sites are calls whose callee name ends in ``rpc`` (``self._rpc``,
``old_rpc``, ``self._fleet_rpc(url)(...)``) or is ``_router``, with a
string-literal verb as first argument; dispatcher arms are
``verb == "x"`` comparisons inside functions whose name contains
``dispatch`` or is ``do_POST``; the store variable in a dispatcher is
``ft`` or any name assigned from a ``*_store(...)`` call, followed
through helper calls that pass it on (bounded depth).
"""

from __future__ import annotations

import ast
import re

from .core import Finding, call_func_name, qualified_functions, str_const

RULES = ("WP001", "WP002", "WP003", "WP004", "WP005", "WP006", "WP007",
         "WP008")

#: Fields _Rpc.__call__ injects into every request on the client side
#: (``wait_s`` rides along only on long-poll reserve, popped by the
#: dispatcher before the verb arm ever sees the request).
_IMPLICIT_FIELDS = frozenset({"verb", "exp_key", "idem", "ctx", "wait_s"})

#: Container methods that mutate their receiver in place.
_MUTATORS = frozenset({
    "append", "add", "update", "pop", "popitem", "clear", "setdefault",
    "extend", "insert", "remove", "discard", "move_to_end",
})

_FOLLOW_DEPTH = 3

#: What a verb looks like — filters URL/token literals handed to
#: ``_Rpc(...)`` constructors out of the client-site extraction.
_VERB_RE = re.compile(r"^[a-z][a-z0-9_]*$")


def _literal_strs(node):
    """String elements of a set/list/tuple literal, unwrapping a
    ``frozenset({...})`` / ``set([...])`` call."""
    if isinstance(node, ast.Call) and call_func_name(node) in (
            "frozenset", "set") and node.args:
        node = node.args[0]
    if isinstance(node, (ast.Set, ast.List, ast.Tuple)):
        out = []
        for el in node.elts:
            s = str_const(el)
            if s is None:
                return None
            out.append(s)
        return out
    return None


def _callee_tail(call: ast.Call) -> str | None:
    """Trailing name of the callee, looking through one call layer so
    ``self._fleet_rpc(url)("promote")`` resolves to ``_fleet_rpc``."""
    func = call.func
    if isinstance(func, ast.Call):
        inner = call_func_name(func)
        return inner.rsplit(".", 1)[-1] if inner else None
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


class _ClientSite:
    __slots__ = ("verb", "rel", "line", "symbol", "kwargs", "has_star")

    def __init__(self, verb, rel, line, symbol, kwargs, has_star):
        self.verb, self.rel, self.line = verb, rel, line
        self.symbol, self.kwargs, self.has_star = symbol, kwargs, has_star


class _Arm:
    __slots__ = ("verb", "rel", "line", "symbol", "body")

    def __init__(self, verb, rel, line, symbol, body):
        self.verb, self.rel, self.line = verb, rel, line
        self.symbol, self.body = symbol, body


def _arm_verbs(test) -> list:
    """Verbs of a ``verb == "x"`` (or ``verb in ("x", "y")``) test."""
    if not (isinstance(test, ast.Compare)
            and isinstance(test.left, ast.Name)
            and test.left.id == "verb"
            and len(test.ops) == 1):
        return []
    if isinstance(test.ops[0], ast.Eq):
        s = str_const(test.comparators[0])
        return [s] if s is not None else []
    return []


class _Extract:
    """One pass over the project: client sites, dispatcher arms,
    catalogs, and the idempotency-attach proof."""

    def __init__(self, project):
        self.client_sites: list[_ClientSite] = []
        self.arms: dict[str, list[_Arm]] = {}
        # catalogs: suffix-keyed {name: (rel, line, set(verbs))}
        self.mutating: dict[str, tuple] = {}
        self.idempotent: dict[str, tuple] = {}
        self.wal: dict[str, tuple] = {}
        self.readonly: dict[str, tuple] = {}
        self.framed: dict[str, tuple] = {}
        # CODEC_FIXTURES: verb -> (rel, line, has_req, has_reply)
        self.codec_fixtures: dict[str, tuple] = {}
        self.other_catalog_verbs: set[str] = set()
        self.idem_attach_proven = False
        self.funcs: dict[tuple, ast.AST] = {}     # (rel, name) -> node
        self.methods: dict[str, list] = {}        # name -> [(rel, node)]
        self.project = project
        for module in project.package_modules():
            rel = module.rel
            self._scan_module(rel, module.tree)

    def _scan_module(self, rel, tree):
        top = set()
        for qualname, func, _cls in qualified_functions(tree):
            name = qualname.rsplit(".", 1)[-1]
            top.add(id(func))
            self.funcs[(rel, qualname)] = func
            self.methods.setdefault(name, []).append((rel, func))
            self._scan_function(rel, qualname, func)
        # Dispatchers hidden from qualified_functions — a ``do_POST``
        # on a handler class built inside a factory method (the router)
        # still holds arms; client sites inside it were already picked
        # up by the enclosing method's walk, so extract arms only.
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and id(node) not in top \
                    and ("dispatch" in node.name
                         or node.name == "do_POST"):
                self._scan_arms(rel, node.name, node)
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target = node.targets[0]
                tname = target.id if isinstance(target, ast.Name) else (
                    target.attr if isinstance(target, ast.Attribute)
                    else None)
                if not tname or not tname.endswith("_VERBS"):
                    continue
                verbs = _literal_strs(node.value)
                if verbs is None:
                    continue
                entry = (rel, node.lineno, frozenset(verbs))
                if tname.endswith("_MUTATING_VERBS"):
                    self.mutating[tname] = entry
                elif tname.endswith("_IDEMPOTENT_VERBS"):
                    self.idempotent[tname] = entry
                elif tname.endswith("_WAL_VERBS"):
                    self.wal[tname] = entry
                elif tname.endswith("_READONLY_VERBS"):
                    self.readonly[tname] = entry
                elif tname.endswith("FRAMED_VERBS"):
                    self.framed[tname] = entry
                else:
                    self.other_catalog_verbs.update(verbs)
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.targets[0].id == "CODEC_FIXTURES" \
                    and isinstance(node.value, ast.Dict):
                for k, v in zip(node.value.keys, node.value.values):
                    verb = str_const(k)
                    if verb is None:
                        continue
                    dirs = set()
                    if isinstance(v, ast.Dict):
                        dirs = {str_const(dk) for dk in v.keys}
                    self.codec_fixtures[verb] = (
                        rel, node.lineno, "req" in dirs, "reply" in dirs)

    def _scan_arms(self, rel, qualname, func):
        for node in ast.walk(func):
            if isinstance(node, ast.If):
                for verb in _arm_verbs(node.test):
                    self.arms.setdefault(verb, []).append(_Arm(
                        verb, rel, node.lineno, qualname, node.body))

    def _scan_function(self, rel, qualname, func):
        in_dispatch = "dispatch" in func.name or func.name == "do_POST"
        tests_mutating = False
        stores_idem = False
        for node in ast.walk(func):
            if isinstance(node, ast.Call):
                tail = _callee_tail(node)
                if tail and (tail.lower().endswith("rpc")
                             or tail == "_router") and node.args:
                    verb = str_const(node.args[0])
                    if verb is not None and _VERB_RE.match(verb):
                        kwargs = {kw.arg for kw in node.keywords
                                  if kw.arg is not None}
                        star = any(kw.arg is None for kw in node.keywords)
                        self.client_sites.append(_ClientSite(
                            verb, rel, node.lineno, qualname, kwargs, star))
            elif isinstance(node, ast.If) and in_dispatch:
                for verb in _arm_verbs(node.test):
                    self.arms.setdefault(verb, []).append(_Arm(
                        verb, rel, node.lineno, qualname, node.body))
            elif isinstance(node, ast.Compare):
                for comp in node.comparators:
                    name = comp.id if isinstance(comp, ast.Name) else (
                        comp.attr if isinstance(comp, ast.Attribute)
                        else None)
                    if name and name.endswith("_MUTATING_VERBS"):
                        tests_mutating = True
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Subscript) \
                            and str_const(t.slice) == "idem":
                        stores_idem = True
        if tests_mutating and stores_idem:
            self.idem_attach_proven = True

    # -- durable-state analysis ----------------------------------------------

    def durable_classes(self):
        """{(rel, class): frozenset(durable attrs)} for every class whose
        ``state_dict`` defines what durability means."""
        out = {}
        for module in self.project.package_modules():
            rel = module.rel
            for node in module.tree.body:
                if not isinstance(node, ast.ClassDef):
                    continue
                for sub in node.body:
                    if isinstance(sub, ast.FunctionDef) \
                            and sub.name == "state_dict":
                        attrs = self._self_attr_loads(sub)
                        if attrs:
                            out[(rel, node.name)] = frozenset(attrs)
        return out

    @staticmethod
    def _self_attr_loads(func):
        called, withctx = set(), set()
        for node in ast.walk(func):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and isinstance(node.func.value, ast.Name) \
                    and node.func.value.id == "self":
                called.add(node.func.attr)
            elif isinstance(node, ast.withitem):
                for sub in ast.walk(node.context_expr):
                    if isinstance(sub, ast.Attribute) \
                            and isinstance(sub.value, ast.Name) \
                            and sub.value.id == "self":
                        withctx.add(sub.attr)
        attrs = set()
        for node in ast.walk(func):
            if isinstance(node, ast.Attribute) \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id == "self" \
                    and node.attr not in called and node.attr not in withctx:
                attrs.add(node.attr)
        return attrs

    def mutating_methods(self, durable):
        """Names of store methods that mutate a durable attribute,
        closed over same-class method calls."""
        by_class = {}
        for module in self.project.package_modules():
            rel = module.rel
            for node in module.tree.body:
                if isinstance(node, ast.ClassDef) \
                        and (rel, node.name) in durable:
                    by_class[(rel, node.name)] = node
        mutating: set[str] = set()
        calls: dict[str, set] = {}
        for key, cls in by_class.items():
            attrs = durable[key]
            for sub in cls.body:
                if not isinstance(sub, ast.FunctionDef):
                    continue
                if _mutates_attrs(sub, attrs, receiver="self"):
                    mutating.add(sub.name)
                callees = set()
                for node in ast.walk(sub):
                    if isinstance(node, ast.Call) \
                            and isinstance(node.func, ast.Attribute) \
                            and isinstance(node.func.value, ast.Name) \
                            and node.func.value.id == "self":
                        callees.add(node.func.attr)
                calls.setdefault(sub.name, set()).update(callees)
        changed = True
        while changed:
            changed = False
            for name, callees in calls.items():
                if name not in mutating and callees & mutating:
                    mutating.add(name)
                    changed = True
        return mutating

    # -- dispatcher arm analysis ---------------------------------------------

    def _store_aliases(self, func, extra=()):
        aliases = set(extra)
        for node in ast.walk(func):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call):
                tail = _callee_tail(node.value)
                if tail and tail.endswith("_store"):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            aliases.add(t.id)
        aliases.add("ft")
        return aliases

    def arm_required_fields(self, arm: _Arm) -> set:
        """``req["field"]`` reads in the arm body, following helper
        calls that receive ``req`` (bounded depth)."""
        fields: set[str] = set()
        self._walk_req(arm.body, arm.rel, fields, _FOLLOW_DEPTH, set())
        return fields

    def _walk_req(self, body, rel, fields, depth, seen):
        for stmt in body:
            for node in ast.walk(stmt):
                if isinstance(node, ast.Subscript) \
                        and isinstance(node.value, ast.Name) \
                        and node.value.id == "req" \
                        and isinstance(node.ctx, ast.Load):
                    s = str_const(node.slice)
                    if s is not None:
                        fields.add(s)
                elif isinstance(node, ast.Call) and depth > 0:
                    passes_req = any(
                        isinstance(a, ast.Name) and a.id == "req"
                        for a in node.args)
                    if not passes_req:
                        continue
                    tail = _callee_tail(node)
                    for trel, tfunc in self.methods.get(tail, ()):
                        key = (trel, tfunc.name)
                        if key in seen:
                            continue
                        seen.add(key)
                        self._walk_req(tfunc.body, trel, fields,
                                       depth - 1, seen)

    def arm_mutates(self, arm: _Arm, durable_attrs, mut_methods) -> bool:
        return self._walk_mut(arm.body, {"ft"}, durable_attrs,
                              mut_methods, _FOLLOW_DEPTH, set())

    def _walk_mut(self, body, aliases, attrs, mut_methods, depth, seen):
        for stmt in body:
            for node in ast.walk(stmt):
                if isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and isinstance(node.func.value, ast.Name) \
                        and node.func.value.id in aliases \
                        and node.func.attr in mut_methods:
                    return True
                if _mutates_attrs_node(node, attrs, aliases):
                    return True
            # follow helpers handed a store alias (e.g. _suggest_verb(ft,…))
            for node in ast.walk(stmt):
                if not isinstance(node, ast.Call) or depth <= 0:
                    continue
                positions = [i for i, a in enumerate(node.args)
                             if isinstance(a, ast.Name) and a.id in aliases]
                if not positions:
                    continue
                tail = _callee_tail(node)
                for trel, tfunc in self.methods.get(tail, ()):
                    key = (trel, tfunc.name)
                    if key in seen:
                        continue
                    seen.add(key)
                    params = [a.arg for a in tfunc.args.args]
                    if params and params[0] == "self":
                        params = params[1:]
                    sub_alias = {params[i] for i in positions
                                 if i < len(params)}
                    if sub_alias and self._walk_mut(
                            tfunc.body, sub_alias | self._store_aliases(
                                tfunc), attrs, mut_methods, depth - 1, seen):
                        return True
        return False


def _mutates_attrs_node(node, attrs, receivers) -> bool:
    """Store/delete/mutator-call on ``<recv>.<attr>`` for a durable attr."""
    def _hits(expr):
        return (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id in receivers and expr.attr in attrs)

    if isinstance(node, (ast.Assign, ast.AugAssign)):
        targets = node.targets if isinstance(node, ast.Assign) \
            else [node.target]
        for t in targets:
            if _hits(t):
                return True
            if isinstance(t, ast.Subscript) and _hits(t.value):
                return True
    elif isinstance(node, ast.Delete):
        for t in node.targets:
            if isinstance(t, ast.Subscript) and _hits(t.value):
                return True
    elif isinstance(node, ast.Call) \
            and isinstance(node.func, ast.Attribute) \
            and node.func.attr in _MUTATORS and _hits(node.func.value):
        return True
    return False


def _mutates_attrs(func, attrs, receiver) -> bool:
    """Does ``func`` mutate one of ``attrs`` on ``receiver`` — directly
    or through a local aliasing a receiver-derived container?"""
    if func.name == "__init__":
        return False
    aliases = {receiver}
    for node in ast.walk(func):
        root_hits = any(
            isinstance(sub, ast.Attribute)
            and isinstance(sub.value, ast.Name)
            and sub.value.id == receiver and sub.attr in attrs
            for sub in ast.walk(node))
        if isinstance(node, ast.Assign) and root_hits:
            for t in node.targets:
                if isinstance(t, ast.Name):
                    aliases.add(t.id)
        elif isinstance(node, (ast.For, ast.comprehension)) and root_hits:
            t = node.target
            if isinstance(t, ast.Name):
                aliases.add(t.id)
    for node in ast.walk(func):
        if _mutates_attrs_node(node, attrs, {receiver}):
            return True
        # subscript-store / mutator call on an alias of durable state
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                if isinstance(t, ast.Subscript) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id in aliases - {receiver}:
                    return True
    return False


def check(project) -> list:
    ext = _Extract(project)
    findings: list = []

    catalog_verbs = set(ext.other_catalog_verbs)
    for table in (ext.mutating, ext.idempotent, ext.wal, ext.readonly,
                  ext.framed):
        for _rel, _line, verbs in table.values():
            catalog_verbs.update(verbs)

    # WP001 / WP002: both-direction site-level reconciliation
    if ext.arms:
        for site in ext.client_sites:
            if site.verb not in ext.arms:
                findings.append(Finding(
                    "WP001", site.rel, site.line, site.symbol,
                    f"client emits verb '{site.verb}' but no dispatcher "
                    f"arm handles it"))
    if ext.client_sites or catalog_verbs:
        emitted = {s.verb for s in ext.client_sites}
        for verb, arms in sorted(ext.arms.items()):
            if verb not in emitted and verb not in catalog_verbs:
                arm = arms[0]
                findings.append(Finding(
                    "WP002", arm.rel, arm.line, arm.symbol,
                    f"dispatcher handles verb '{verb}' but no client "
                    f"call site or *_VERBS catalog references it"))

    # WP003: required-field drift per verb
    required: dict[str, set] = {}
    for verb, arms in ext.arms.items():
        fields = set()
        for arm in arms:
            fields |= ext.arm_required_fields(arm)
        required[verb] = fields - _IMPLICIT_FIELDS
    for site in ext.client_sites:
        if site.has_star:
            continue
        missing = sorted(required.get(site.verb, set()) - site.kwargs)
        if missing:
            findings.append(Finding(
                "WP003", site.rel, site.line, site.symbol,
                f"verb '{site.verb}' call site omits required field(s) "
                f"{missing} the dispatcher reads with req[...]"))

    # Durable-state ground truth for WP004/WP005
    durable = ext.durable_classes()
    durable_attrs = set()
    for attrs in durable.values():
        durable_attrs |= attrs
    mut_methods = ext.mutating_methods(durable) if durable else set()
    server_mutating = set()
    if durable:
        for verb, arms in ext.arms.items():
            if any(ext.arm_mutates(arm, durable_attrs, mut_methods)
                   for arm in arms):
                server_mutating.add(verb)

    mutating_verbs = set()
    for _rel, _line, verbs in ext.mutating.values():
        mutating_verbs |= verbs
    idempotent_verbs = set()
    for _rel, _line, verbs in ext.idempotent.values():
        idempotent_verbs |= verbs
    wal_verbs = set()
    for _rel, _line, verbs in ext.wal.values():
        wal_verbs |= verbs

    # WP004: every mutating verb carries an idem key or is declared
    # retry-convergent.  The client attach is proven, not assumed.
    mutating_universe = wal_verbs | server_mutating
    if ext.mutating and mutating_universe:
        if not ext.idem_attach_proven:
            for name, (rel, line, _verbs) in sorted(ext.mutating.items()):
                findings.append(Finding(
                    "WP004", rel, line, name,
                    f"catalog {name} exists but no client code tests "
                    f"membership and stores kw['idem'] — the idempotency "
                    f"attach is unproven"))
        for verb in sorted(mutating_universe):
            if verb in mutating_verbs or verb in idempotent_verbs:
                continue
            if verb in ext.arms:
                arm = min(ext.arms[verb], key=lambda a: (a.rel, a.line))
                rel, line, sym = arm.rel, arm.line, f"{arm.symbol}:{verb}"
            else:
                name = sorted(ext.wal)[0]
                rel, line, _v = ext.wal[name]
                sym = f"{name}:{verb}"
            findings.append(Finding(
                "WP004", rel, line, sym,
                f"mutating verb '{verb}' reaches the wire with no "
                f"idempotency key: not in *_MUTATING_VERBS (client "
                f"auto-attach) and not declared retry-convergent in "
                f"*_IDEMPOTENT_VERBS"))

    # WP005: *_WAL_VERBS == the arms that actually mutate durable state
    if durable and ext.wal:
        for name, (rel, line, verbs) in sorted(ext.wal.items()):
            for verb in sorted(verbs):
                if verb in ext.arms and verb not in server_mutating:
                    findings.append(Finding(
                        "WP005", rel, line, f"{name}:{verb}",
                        f"'{verb}' is WAL-logged but its dispatcher arm "
                        f"never mutates durable store state — replay "
                        f"re-executes a read"))
        for verb in sorted(server_mutating - wal_verbs):
            arm = min(ext.arms[verb], key=lambda a: (a.rel, a.line))
            findings.append(Finding(
                "WP005", arm.rel, arm.line, f"{arm.symbol}:{verb}",
                f"verb '{verb}' mutates durable store state but is in no "
                f"*_WAL_VERBS catalog — the mutation survives no crash"))

    # WP006: catalog hygiene for the idempotency declarations
    if ext.idempotent:
        for name, (rel, line, verbs) in sorted(ext.idempotent.items()):
            for verb in sorted(verbs & mutating_verbs):
                findings.append(Finding(
                    "WP006", rel, line, f"{name}:{verb}",
                    f"'{verb}' is declared both retry-convergent "
                    f"({name}) and idempotency-keyed (*_MUTATING_VERBS) "
                    f"— pick one"))
            if mutating_universe:
                for verb in sorted(verbs - mutating_universe):
                    findings.append(Finding(
                        "WP006", rel, line, f"{name}:{verb}",
                        f"'{verb}' is declared retry-convergent in {name} "
                        f"but is not a mutating verb — stale declaration"))

    # WP007: the lock-free read path serves exactly verbs that touch no
    # durable state and answer to no other catalog's contract.
    if ext.readonly:
        conflicting = wal_verbs | mutating_verbs | idempotent_verbs
        for name, (rel, line, verbs) in sorted(ext.readonly.items()):
            for verb in sorted(verbs):
                if verb in server_mutating:
                    findings.append(Finding(
                        "WP007", rel, line, f"{name}:{verb}",
                        f"'{verb}' is declared read-only ({name}) but its "
                        f"dispatcher arm mutates durable store state — "
                        f"served off the write lock it races every writer"))
                elif verb in conflicting:
                    findings.append(Finding(
                        "WP007", rel, line, f"{name}:{verb}",
                        f"'{verb}' is declared read-only ({name}) and also "
                        f"mutating/WAL-logged/retry-convergent in another "
                        f"catalog — the declarations contradict"))
                elif verb not in ext.arms:
                    findings.append(Finding(
                        "WP007", rel, line, f"{name}:{verb}",
                        f"read-only verb '{verb}' has no dispatcher arm — "
                        f"stale catalog entry"))

    # WP008: binary-framed verbs round-trip through the shared codec
    # fixtures in both directions, and the fixture set never goes stale.
    if ext.framed:
        framed_all = set()
        for name, (rel, line, verbs) in sorted(ext.framed.items()):
            framed_all |= verbs
            for verb in sorted(verbs):
                if verb not in ext.arms:
                    findings.append(Finding(
                        "WP008", rel, line, f"{name}:{verb}",
                        f"framed verb '{verb}' has no dispatcher arm — a "
                        f"frame-encoded request has nowhere to decode"))
                fx = ext.codec_fixtures.get(verb)
                if fx is None:
                    findings.append(Finding(
                        "WP008", rel, line, f"{name}:{verb}",
                        f"framed verb '{verb}' has no CODEC_FIXTURES "
                        f"entry — nothing pins its encode↔decode "
                        f"round-trip"))
                elif not (fx[2] and fx[3]):
                    missing = [d for d, got in (("req", fx[2]),
                                                ("reply", fx[3])) if not got]
                    findings.append(Finding(
                        "WP008", fx[0], fx[1], f"CODEC_FIXTURES:{verb}",
                        f"fixture for framed verb '{verb}' lacks "
                        f"{missing} — both directions (client encode ↔ "
                        f"server decode) must round-trip"))
        for verb, (rel, line, _rq, _rp) in sorted(
                ext.codec_fixtures.items()):
            if verb not in framed_all:
                findings.append(Finding(
                    "WP008", rel, line, f"CODEC_FIXTURES:{verb}",
                    f"fixture '{verb}' names a verb no *FRAMED_VERBS "
                    f"catalog declares — stale fixture"))
    return findings
