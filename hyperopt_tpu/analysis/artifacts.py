"""AH — artifact honesty: every JSON-writing benchmark has a schema guard.

AH001  A ``benchmarks/*.py`` module that serializes JSON (``json.dump``
       / ``json.dumps``) has no named schema guard in
       ``tests/test_artifacts_contract.py`` — its artifact shape can
       drift silently and downstream consumers (the A/B drivers, the
       show CLI) find out at read time.  A guard counts when the
       contract test mentions the benchmark's stem anywhere (test name,
       artifact filename, or grandfather list with a justification).
"""

from __future__ import annotations

import ast

from .core import Finding, dotted_name

RULES = ("AH001",)

_CONTRACT = "tests/test_artifacts_contract.py"


def _writes_json(tree: ast.Module) -> int:
    """Line of the first json.dump/json.dumps call, else 0."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            name = dotted_name(node.func) or ""
            if name.split(".")[-1] in ("dump", "dumps") and \
                    name.split(".")[0] == "json":
                return node.lineno
    return 0


def check(project) -> list:
    findings: list = []
    contract = project.file_text(_CONTRACT)
    for rel, module in sorted(project.modules.items()):
        if not rel.startswith("benchmarks/") or not rel.endswith(".py"):
            continue
        line = _writes_json(module.tree)
        if not line:
            continue
        stem = rel.rsplit("/", 1)[-1][:-3]
        if stem not in contract:
            findings.append(Finding(
                "AH001", rel, line, stem,
                f"benchmark writes a JSON artifact but {_CONTRACT} has "
                f"no schema guard mentioning '{stem}'"))
    return findings
