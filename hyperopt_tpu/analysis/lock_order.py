"""LK — lock discipline across the threaded layers.

Scoped to modules that import ``threading`` (pipeline, service, obs,
fleet, parallel, plus the tpe/history prewarm paths).  Three rules:

LK001  Lock-order cycle: the ``with lock:`` nesting graph (lexical
       nesting plus same-module/ same-class transitive acquires through
       calls) contains a cycle — two threads taking the locks in
       opposite orders can deadlock.
LK002  Unlocked write to module-level shared mutable state (dicts /
       lists / sets / WeakKeyDictionary assigned at module scope) from
       a function that holds no lock at the write site.  The PR 2
       unlocked-defaultdict bug class.
LK003  Check-then-act race: a container is membership-tested /
       ``.get()``-probed and then subscript-written in the same
       function with neither site under a lock, or a function composes
       two same-class methods that each take the same lock (sharing an
       argument, the first result feeding a branch) without holding
       that lock across the pair — the PR 6 lost-update class and the
       netstore reply-cache / kernel-cache shape.

Convention honored: a function whose docstring contains "caller holds"
is exempt from LK002/LK003 — the lock obligation is documented at the
call sites, which the checker covers when analyzing them.
"""

from __future__ import annotations

import ast

from .core import Finding, dotted_name, qualified_functions

RULES = ("LK001", "LK002", "LK003")

_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}
_CONTAINER_CTORS = {"dict", "list", "set", "defaultdict", "OrderedDict",
                    "WeakKeyDictionary", "WeakValueDictionary", "deque",
                    "Counter"}
_MUTATORS = {"append", "update", "setdefault", "pop", "popitem", "clear",
             "add", "extend", "insert", "remove", "discard", "appendleft"}


def _imports_threading(tree: ast.Module) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            if any(a.name.split(".")[0] == "threading" for a in node.names):
                return True
        elif isinstance(node, ast.ImportFrom):
            if (node.module or "").split(".")[0] == "threading":
                return True
    return False


def _is_lock_ctor(call) -> bool:
    if not isinstance(call, ast.Call):
        return False
    name = dotted_name(call.func)
    return bool(name) and name.split(".")[-1] in _LOCK_CTORS


def _is_container_ctor(node) -> bool:
    if isinstance(node, (ast.Dict, ast.List, ast.Set, ast.ListComp,
                         ast.DictComp, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        name = dotted_name(node.func)
        return bool(name) and name.split(".")[-1] in _CONTAINER_CTORS
    return False


class _ModuleLocks:
    """Lock and shared-container tables for one module."""

    def __init__(self, module):
        self.module = module
        self.module_locks: set = set()          # bare names
        self.instance_locks: dict = {}          # class -> {attr}
        self.shared: set = set()                # module-level container names
        for node in module.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name):
                name = node.targets[0].id
                if _is_lock_ctor(node.value):
                    self.module_locks.add(name)
                elif _is_container_ctor(node.value):
                    self.shared.add(name)
            elif isinstance(node, ast.AnnAssign) and \
                    isinstance(node.target, ast.Name) and node.value is not None:
                if _is_lock_ctor(node.value):
                    self.module_locks.add(node.target.id)
                elif _is_container_ctor(node.value):
                    self.shared.add(node.target.id)
        for cls in module.tree.body:
            if not isinstance(cls, ast.ClassDef):
                continue
            attrs = set()
            for node in ast.walk(cls):
                if isinstance(node, ast.Assign) and _is_lock_ctor(node.value):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Attribute) and \
                                isinstance(tgt.value, ast.Name) and \
                                tgt.value.id == "self":
                            attrs.add(tgt.attr)
            if attrs:
                self.instance_locks[cls.name] = attrs

    def lock_id(self, expr, cls):
        """Canonical lock node id for a with-item expr, else None."""
        if isinstance(expr, ast.Name) and expr.id in self.module_locks:
            return expr.id
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name):
            base, attr = expr.value.id, expr.attr
            if base == "self" and cls and \
                    attr in self.instance_locks.get(cls, ()):
                return f"{cls}.{attr}"
            # obj._lock on a known lock-bearing class attr: match by attr
            for cname, attrs in self.instance_locks.items():
                if attr in attrs:
                    return f"{cname}.{attr}"
        return None


def _caller_holds(fn) -> bool:
    doc = ast.get_docstring(fn) or ""
    return "caller holds" in doc.lower()


def _direct_acquires(fn, locks: _ModuleLocks, cls):
    out = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.With):
            for item in node.items:
                lid = locks.lock_id(item.context_expr, cls)
                if lid:
                    out.add(lid)
    return out


def _transitive_acquires(locks: _ModuleLocks):
    """Fixpoint of acquire sets through same-module / same-class calls."""
    funcs = {}
    for qual, node, cls in qualified_functions(locks.module.tree):
        funcs[qual] = (node, cls)
    acq = {q: _direct_acquires(n, locks, c) for q, (n, c) in funcs.items()}
    callees = {}
    for qual, (node, cls) in funcs.items():
        outs = set()
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            if isinstance(sub.func, ast.Name) and sub.func.id in funcs:
                outs.add(sub.func.id)
            elif isinstance(sub.func, ast.Attribute) and \
                    isinstance(sub.func.value, ast.Name) and \
                    sub.func.value.id == "self" and cls and \
                    f"{cls}.{sub.func.attr}" in funcs:
                outs.add(f"{cls}.{sub.func.attr}")
        callees[qual] = outs
    changed = True
    while changed:
        changed = False
        for qual, outs in callees.items():
            merged = set(acq[qual])
            for o in outs:
                merged |= acq[o]
            if merged != acq[qual]:
                acq[qual] = merged
                changed = True
    return funcs, acq, callees


def _order_edges(locks: _ModuleLocks, funcs, acq):
    """(held, acquired, line) edges from nesting + calls under a lock."""
    edges = []

    def scan(body, held, cls, qual):
        for node in body:
            if isinstance(node, ast.With):
                ids = [locks.lock_id(i.context_expr, cls)
                       for i in node.items]
                ids = [i for i in ids if i]
                for h in held:
                    for lid in ids:
                        if h != lid:
                            edges.append((h, lid, node.lineno))
                scan(node.body, held + ids, cls, qual)
                continue
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call) and held:
                    callee = None
                    if isinstance(sub.func, ast.Name) and \
                            sub.func.id in funcs:
                        callee = sub.func.id
                    elif isinstance(sub.func, ast.Attribute) and \
                            isinstance(sub.func.value, ast.Name) and \
                            sub.func.value.id == "self" and cls and \
                            f"{cls}.{sub.func.attr}" in funcs:
                        callee = f"{cls}.{sub.func.attr}"
                    if callee:
                        for lid in acq.get(callee, ()):
                            for h in held:
                                if h != lid:
                                    edges.append((h, lid, sub.lineno))
            for attr in ("body", "orelse", "finalbody"):
                inner = getattr(node, attr, None)
                if inner:
                    scan(inner, held, cls, qual)
            for handler in getattr(node, "handlers", []):
                scan(handler.body, held, cls, qual)

    for qual, (node, cls) in funcs.items():
        scan(node.body, [], cls, qual)
    return edges


def _find_cycles(edges):
    graph: dict = {}
    for a, b, _line in edges:
        graph.setdefault(a, set()).add(b)
    cycles, seen = [], set()
    for start in sorted(graph):
        stack = [(start, [start])]
        while stack:
            cur, path = stack.pop()
            for nxt in sorted(graph.get(cur, ())):
                if nxt == start and len(path) > 1:
                    key = frozenset(path)
                    if key not in seen:
                        seen.add(key)
                        cycles.append(path + [start])
                elif nxt not in path:
                    stack.append((nxt, path + [nxt]))
    return cycles


class _BodyScan:
    """Lexical scan of one function: lock-held state per site."""

    def __init__(self, locks, cls):
        self.locks = locks
        self.cls = cls
        self.shared_writes = []     # (name, line, held?)
        self.tests = {}             # container expr -> held?
        self.stores = {}            # container expr -> (line, held?)
        self.locked_method_calls = []   # (method, lockid, args, test?, line)

    def scan(self, body, held, under_test=False):
        for node in body:
            if isinstance(node, ast.With):
                ids = [self.locks.lock_id(i.context_expr, self.cls)
                       for i in node.items]
                self.scan(node.body, held + [i for i in ids if i])
                self._expr_walk(node, held, skip_body=True)
                continue
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue    # nested def: own thread-entry analysis
            self._expr_walk(node, held)
            if isinstance(node, (ast.If, ast.While)):
                self._record_tests(node.test, held)
            for attr in ("body", "orelse", "finalbody"):
                inner = getattr(node, attr, None)
                if inner:
                    self.scan(inner, held)
            for handler in getattr(node, "handlers", []):
                self.scan(handler.body, held)

    def _record_tests(self, test, held):
        for node in ast.walk(test):
            expr = None
            if isinstance(node, ast.Compare) and any(
                    isinstance(op, (ast.In, ast.NotIn)) for op in node.ops):
                expr = dotted_name(node.comparators[0])
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "get":
                expr = dotted_name(node.func.value)
            if expr:
                self.tests[expr] = self.tests.get(expr, False) or bool(held)

    def _expr_walk(self, stmt, held, skip_body=False):
        nodes = []
        if skip_body:
            for item in getattr(stmt, "items", []):
                nodes.extend(ast.walk(item))
        else:
            if isinstance(stmt, (ast.If, ast.While)):
                nodes = list(ast.walk(stmt.test))
            elif isinstance(stmt, ast.Assign):
                nodes = list(ast.walk(stmt))
            elif isinstance(stmt, (ast.Expr, ast.Return, ast.AugAssign,
                                   ast.AnnAssign, ast.Delete, ast.Raise,
                                   ast.Assert)):
                nodes = list(ast.walk(stmt))
            else:
                return
        for node in nodes:
            # stores: D[k] = v / del D[k]
            if isinstance(node, ast.Subscript) and \
                    isinstance(node.ctx, (ast.Store, ast.Del)):
                expr = dotted_name(node.value)
                if expr:
                    base = expr.split(".")[0]
                    if expr in self.locks.shared or \
                            base in self.locks.shared:
                        self.shared_writes.append(
                            (expr, node.lineno, bool(held)))
                    prev = self.stores.get(expr)
                    if prev is None or (prev[1] and not held):
                        self.stores[expr] = (node.lineno, bool(held))
            # mutator calls on shared module containers: D.append(...)
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _MUTATORS:
                expr = dotted_name(node.func.value)
                if expr and expr in self.locks.shared:
                    self.shared_writes.append((expr, node.lineno, bool(held)))
            # `x = D.get(k)` probes outside an If test
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "get":
                expr = dotted_name(node.func.value)
                if expr:
                    self.tests[expr] = \
                        self.tests.get(expr, False) or bool(held)
            # same-class locked-method calls (for the compose rule)
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    isinstance(node.func.value, ast.Name) and \
                    node.func.value.id == "self" and not held:
                self.locked_method_calls.append(
                    (node.func.attr,
                     tuple(ast.dump(a) for a in node.args), node.lineno))


def _check_module(findings, locks: _ModuleLocks):
    rel = locks.module.rel
    funcs, acq, _callees = _transitive_acquires(locks)

    # LK001 — cycles
    edges = _order_edges(locks, funcs, acq)
    for cycle in _find_cycles(edges):
        findings.append(Finding(
            "LK001", rel, min(l for a, b, l in edges
                              if a in cycle and b in cycle),
            "<module>",
            "lock-order cycle: " + " -> ".join(cycle)))

    for qual, (fn, cls) in funcs.items():
        if _caller_holds(fn):
            continue
        scan = _BodyScan(locks, cls)
        scan.scan(fn.body, [])

        # LK002 — unlocked writes to module-level shared containers
        reported = set()
        for name, line, held in scan.shared_writes:
            if not held and name not in reported:
                reported.add(name)
                findings.append(Finding(
                    "LK002", rel, line, qual,
                    f"write to module-level shared container '{name}' "
                    "without holding a lock"))

        # LK003a — lexical check-then-act on one container.  Bare local
        # names are function-private (no race); only module-level shared
        # containers and dotted state (self.X / obj.X) qualify.
        for expr, tested_held in scan.tests.items():
            stored = scan.stores.get(expr)
            if stored and not tested_held and not stored[1]:
                base = expr.split(".")[0]
                if "." not in expr and expr not in locks.shared:
                    continue
                if base == "self" and cls and \
                        not locks.instance_locks.get(cls):
                    continue    # class has no lock: single-threaded by design
                findings.append(Finding(
                    "LK003", rel, stored[0], qual,
                    f"check-then-act on '{expr}': membership/get probe and "
                    "subscript write with no lock held across the pair"))

        # LK003b — non-atomic compose of two locked same-class methods
        if cls and locks.instance_locks.get(cls):
            calls = [(m, args, line) for m, args, line
                     in scan.locked_method_calls
                     if f"{cls}.{m}" in acq and acq[f"{cls}.{m}"]]
            for i, (m1, a1, l1) in enumerate(calls):
                for m2, a2, l2 in calls[i + 1:]:
                    if m1 == m2 or not (set(a1) & set(a2)):
                        continue
                    common = acq[f"{cls}.{m1}"] & acq[f"{cls}.{m2}"]
                    if common:
                        findings.append(Finding(
                            "LK003", rel, l2, qual,
                            f"calls {m1}()/{m2}() each take "
                            f"{sorted(common)[0]} but '{qual}' composes "
                            "them without holding it — the pair is not "
                            "atomic"))
                        break
                else:
                    continue
                break

    return findings


def check(project) -> list:
    findings: list = []
    for module in project.package_modules():
        if not _imports_threading(module.tree):
            continue
        _check_module(findings, _ModuleLocks(module))
    return findings
