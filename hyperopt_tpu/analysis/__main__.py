"""CLI: ``python -m hyperopt_tpu.analysis [options]``.

Exit codes: 0 — no findings outside the baseline and no stale entries;
1 — new findings or stale baseline entries; 2 — malformed baseline.

``--json`` prints the full machine-readable report (the input of
``hyperopt-tpu-show lint``), including per-checker wall time;
``--write-baseline`` snapshots the current findings into the baseline
file with TODO notes to be annotated; ``--diff BASE`` narrows the
*report* to files changed vs a git ref (the analysis itself still
parses the whole repo — the cross-module reconciliations are only
meaningful over the full project — so full-run semantics are
preserved: a finding in a changed file fires identically to a full
run); ``--sarif OUT`` additionally writes the report as SARIF 2.1.0
for CI diff annotation.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

from . import CHECKERS, default_baseline_path, run_repo
from .core import Baseline

_SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                 "master/Schemata/sarif-schema-2.1.0.json")


def changed_files(root: str, base: str) -> set:
    """Repo-relative paths changed vs ``base`` (committed + worktree)."""
    out = subprocess.run(
        ["git", "-C", root, "diff", "--name-only", base, "--"],
        capture_output=True, text=True, check=True).stdout
    return {line.strip() for line in out.splitlines() if line.strip()}


def build_report(root, baseline_path, checkers=None, diff_files=None,
                 with_timings=False) -> dict:
    timings: dict = {} if with_timings else None
    findings = run_repo(root, checkers=checkers, timings=timings)
    baseline = Baseline.load(baseline_path)
    if checkers:
        # Partial run: entries owned by checkers that didn't run can't be
        # judged stale — keep only the selected checkers' rules in scope.
        active = set()
        for name in checkers:
            active |= set(CHECKERS[name][1])
        baseline = Baseline(entries=[e for e in baseline.entries
                                     if e.get("rule") in active],
                            path=baseline.path)
    if diff_files is not None:
        # Diff-scoped report: the full project was analyzed (above), so
        # every finding in a changed file is exactly what a full run
        # would produce; findings and baseline staleness for unchanged
        # files are out of scope for this report.
        findings = [f for f in findings if f.file in diff_files]
        baseline = Baseline(entries=[e for e in baseline.entries
                                     if e.get("file") in diff_files],
                            path=baseline.path)
    errors = baseline.validate()
    new, baselined, stale = baseline.match(findings)
    counts: dict = {}
    for f in findings:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    report = {
        "root": os.path.abspath(root),
        "baseline": baseline_path,
        "baseline_errors": errors,
        "counts": dict(sorted(counts.items())),
        "new": [f.to_dict() for f in new],
        "baselined": [f.to_dict() for f in baselined],
        "stale": [{"rule": e.get("rule"), "file": e.get("file"),
                   "symbol": e.get("symbol"), "note": e.get("note")}
                  for e in stale],
    }
    if timings is not None:
        report["timings_s"] = dict(sorted(timings.items()))
    if diff_files is not None:
        report["diff_files"] = sorted(diff_files)
    return report


def sarif_from_report(report: dict) -> dict:
    """SARIF 2.1.0 document from a report dict: new findings at level
    ``error`` (they fail the gate), baselined at ``note``."""
    results = []
    rule_ids = set()
    for f, level in ([(x, "error") for x in report["new"]]
                     + [(x, "note") for x in report["baselined"]]):
        rule_ids.add(f["rule"])
        results.append({
            "ruleId": f["rule"],
            "level": level,
            "message": {"text": f"[{f['symbol']}] {f['message']}"},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f["file"]},
                    "region": {"startLine": max(1, int(f["line"]))},
                },
            }],
        })
    return {
        "$schema": _SARIF_SCHEMA,
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "hyperopt-tpu-analysis",
                "informationUri":
                    "docs/API.md#invariant-analyzers",
                "rules": [{"id": rid} for rid in sorted(rule_ids)],
            }},
            "results": results,
        }],
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m hyperopt_tpu.analysis",
        description="Run the invariant analyzer suite over the repo.")
    ap.add_argument("--root", default=".",
                    help="repo root to analyze (default: cwd)")
    ap.add_argument("--baseline", default=None,
                    help="baseline file (default: "
                         "hyperopt_tpu/analysis/baseline.json under root)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the machine-readable report")
    ap.add_argument("--checker", action="append", choices=sorted(CHECKERS),
                    help="run only this checker (repeatable)")
    ap.add_argument("--diff", metavar="BASE", default=None,
                    help="narrow the report to files changed vs this git "
                         "ref (full project still analyzed)")
    ap.add_argument("--sarif", metavar="OUT", default=None,
                    help="also write the report as SARIF 2.1.0 to OUT")
    ap.add_argument("--write-baseline", action="store_true",
                    help="snapshot current findings into the baseline")
    args = ap.parse_args(argv)

    root = os.path.abspath(args.root)
    baseline_path = args.baseline or default_baseline_path(root)

    if args.write_baseline:
        findings = run_repo(root, checkers=args.checker)
        old = Baseline.load(baseline_path)
        notes = {(e["rule"], e["file"], e["symbol"]): e["note"]
                 for e in old.entries if e.get("note")}
        doc = Baseline.render(findings, notes=notes)
        with open(baseline_path, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=2, sort_keys=False)
            f.write("\n")
        print(f"wrote {len(doc['entries'])} entries to {baseline_path}")
        return 0

    diff_files = None
    if args.diff is not None:
        try:
            diff_files = changed_files(root, args.diff)
        except (OSError, subprocess.CalledProcessError) as e:
            print(f"--diff {args.diff}: git diff failed: {e}",
                  file=sys.stderr)
            return 2

    report = build_report(root, baseline_path, checkers=args.checker,
                          diff_files=diff_files,
                          with_timings=args.as_json)
    if args.sarif:
        with open(args.sarif, "w", encoding="utf-8") as f:
            json.dump(sarif_from_report(report), f, indent=2)
            f.write("\n")
    if args.as_json:
        json.dump(report, sys.stdout, indent=2)
        print()
    else:
        for key in ("new", "baselined"):
            for f in report[key]:
                tag = " (baselined)" if key == "baselined" else ""
                print(f"{f['file']}:{f['line']}: {f['rule']} "
                      f"[{f['symbol']}] {f['message']}{tag}")
        for e in report["stale"]:
            print(f"stale baseline entry: {e['rule']} {e['file']} "
                  f"[{e['symbol']}] — finding no longer fires; delete it")
        for err in report["baseline_errors"]:
            print(f"baseline error: {err}")
        total = sum(report["counts"].values())
        print(f"{total} finding(s): {len(report['new'])} new, "
              f"{len(report['baselined'])} baselined, "
              f"{len(report['stale'])} stale baseline entr(ies); "
              f"counts {report['counts']}")
    if report["baseline_errors"]:
        return 2
    if report["new"] or report["stale"]:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
