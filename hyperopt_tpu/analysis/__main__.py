"""CLI: ``python -m hyperopt_tpu.analysis [options]``.

Exit codes: 0 — no findings outside the baseline and no stale entries;
1 — new findings or stale baseline entries; 2 — malformed baseline.

``--json`` prints the full machine-readable report (the input of
``hyperopt-tpu-show lint``); ``--write-baseline`` snapshots the current
findings into the baseline file with TODO notes to be annotated.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from . import CHECKERS, default_baseline_path, run_repo
from .core import Baseline


def build_report(root, baseline_path, checkers=None) -> dict:
    findings = run_repo(root, checkers=checkers)
    baseline = Baseline.load(baseline_path)
    if checkers:
        # Partial run: entries owned by checkers that didn't run can't be
        # judged stale — keep only the selected checkers' rules in scope.
        active = set()
        for name in checkers:
            active |= set(CHECKERS[name][1])
        baseline = Baseline(entries=[e for e in baseline.entries
                                     if e.get("rule") in active],
                            path=baseline.path)
    errors = baseline.validate()
    new, baselined, stale = baseline.match(findings)
    counts: dict = {}
    for f in findings:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    return {
        "root": os.path.abspath(root),
        "baseline": baseline_path,
        "baseline_errors": errors,
        "counts": dict(sorted(counts.items())),
        "new": [f.to_dict() for f in new],
        "baselined": [f.to_dict() for f in baselined],
        "stale": [{"rule": e.get("rule"), "file": e.get("file"),
                   "symbol": e.get("symbol"), "note": e.get("note")}
                  for e in stale],
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m hyperopt_tpu.analysis",
        description="Run the invariant analyzer suite over the repo.")
    ap.add_argument("--root", default=".",
                    help="repo root to analyze (default: cwd)")
    ap.add_argument("--baseline", default=None,
                    help="baseline file (default: "
                         "hyperopt_tpu/analysis/baseline.json under root)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the machine-readable report")
    ap.add_argument("--checker", action="append", choices=sorted(CHECKERS),
                    help="run only this checker (repeatable)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="snapshot current findings into the baseline")
    args = ap.parse_args(argv)

    root = os.path.abspath(args.root)
    baseline_path = args.baseline or default_baseline_path(root)

    if args.write_baseline:
        findings = run_repo(root, checkers=args.checker)
        old = Baseline.load(baseline_path)
        notes = {(e["rule"], e["file"], e["symbol"]): e["note"]
                 for e in old.entries if e.get("note")}
        doc = Baseline.render(findings, notes=notes)
        with open(baseline_path, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=2, sort_keys=False)
            f.write("\n")
        print(f"wrote {len(doc['entries'])} entries to {baseline_path}")
        return 0

    report = build_report(root, baseline_path, checkers=args.checker)
    if args.as_json:
        json.dump(report, sys.stdout, indent=2)
        print()
    else:
        for key in ("new", "baselined"):
            for f in report[key]:
                tag = " (baselined)" if key == "baselined" else ""
                print(f"{f['file']}:{f['line']}: {f['rule']} "
                      f"[{f['symbol']}] {f['message']}{tag}")
        for e in report["stale"]:
            print(f"stale baseline entry: {e['rule']} {e['file']} "
                  f"[{e['symbol']}] — finding no longer fires; delete it")
        for err in report["baseline_errors"]:
            print(f"baseline error: {err}")
        total = sum(report["counts"].values())
        print(f"{total} finding(s): {len(report['new'])} new, "
              f"{len(report['baselined'])} baselined, "
              f"{len(report['stale'])} stale baseline entr(ies); "
              f"counts {report['counts']}")
    if report["baseline_errors"]:
        return 2
    if report["new"] or report["stale"]:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
