"""Import-free static-analysis core: projects, findings, baselines.

Everything in ``hyperopt_tpu.analysis`` works on **source text and
``ast`` trees only** — the analyzed modules are never imported, so the
suite runs on a machine without JAX installed and cannot be skewed by
import-time side effects (``faults.configure_from_env`` at import,
backend probes, cache warmups).  ``tests/test_analysis.py`` pins this:
no module in this package may import anything outside the stdlib.

The unit of work is a :class:`Project`: a set of parsed Python modules
(keyed by repo-relative posix path) plus raw text files the checkers
cross-reference (docs/API.md, the artifacts contract test).  Build one
from a repo checkout with :func:`Project.from_dir` or from in-memory
sources with :func:`Project.from_sources` (how the fixture tests feed
one known violation per rule).

Findings are matched against a checked-in, annotated baseline on
``(rule, file, symbol)`` — line numbers drift with every edit, the
enclosing symbol does not.  The contract is burn-down, not suppression:
a baseline entry whose finding disappeared is *stale* and fails the
gate just like a new finding, so fixes must delete their entry.
"""

from __future__ import annotations

import ast
import json
import os
from dataclasses import dataclass, field

__all__ = [
    "Finding",
    "Module",
    "Project",
    "Baseline",
    "dotted_name",
    "call_func_name",
    "qualified_functions",
]


@dataclass(frozen=True)
class Finding:
    """One rule violation, anchored to a file / line / enclosing symbol."""

    rule: str          # e.g. "JP001"
    file: str          # repo-relative posix path
    line: int          # 1-based, best effort
    symbol: str        # enclosing function/class qualname, or "<module>"
    message: str       # human-readable statement of the violation

    def key(self):
        return (self.rule, self.file, self.symbol)

    def to_dict(self) -> dict:
        return {"rule": self.rule, "file": self.file, "line": self.line,
                "symbol": self.symbol, "message": self.message}

    def render(self) -> str:
        return (f"{self.file}:{self.line}: {self.rule} [{self.symbol}] "
                f"{self.message}")


@dataclass
class Module:
    """One parsed source module."""

    rel: str           # repo-relative posix path
    text: str
    tree: ast.Module = field(repr=False)

    @classmethod
    def parse(cls, rel: str, text: str) -> "Module":
        return cls(rel=rel, text=text, tree=ast.parse(text, filename=rel))


class Project:
    """Parsed modules + reference text files for one analysis run."""

    #: Analyzed package prefix (checkers scope rules to it).
    package = "hyperopt_tpu/"

    def __init__(self, modules, files=None, root=None):
        self.modules: dict = {m.rel: m for m in modules}
        self.files: dict = dict(files or {})   # rel -> raw text (non-py)
        self.root = root

    # -- construction --------------------------------------------------------

    @classmethod
    def from_dir(cls, root: str) -> "Project":
        """Parse the package, benchmarks, the artifacts-contract test and
        docs/API.md from a repo checkout.  ``hyperopt_tpu/analysis/`` is
        excluded from its own jurisdiction — the tool's fixture strings
        and rule tables would otherwise feed the registry scans."""
        root = os.path.abspath(root)
        modules, files = [], {}
        for sub in ("hyperopt_tpu", "benchmarks"):
            base = os.path.join(root, sub)
            if not os.path.isdir(base):
                continue
            for dirpath, dirnames, filenames in os.walk(base):
                dirnames[:] = sorted(
                    d for d in dirnames
                    if d != "__pycache__"
                    and not (sub == "hyperopt_tpu" and d == "analysis"))
                for fn in sorted(filenames):
                    if not fn.endswith(".py"):
                        continue
                    path = os.path.join(dirpath, fn)
                    rel = os.path.relpath(path, root).replace(os.sep, "/")
                    with open(path, encoding="utf-8") as f:
                        text = f.read()
                    try:
                        modules.append(Module.parse(rel, text))
                    except SyntaxError:
                        # A syntactically broken module is someone else's
                        # build failure; skip rather than crash the gate.
                        continue
        for rel in ("docs/API.md", "tests/test_artifacts_contract.py"):
            path = os.path.join(root, rel)
            if os.path.exists(path):
                with open(path, encoding="utf-8") as f:
                    files[rel] = f.read()
        return cls(modules, files=files, root=root)

    @classmethod
    def from_sources(cls, sources: dict, files=None) -> "Project":
        """Build from ``{rel_path: source_text}`` (fixture tests)."""
        return cls([Module.parse(rel, text)
                    for rel, text in sorted(sources.items())],
                   files=files)

    # -- access --------------------------------------------------------------

    def package_modules(self):
        """Modules under the analyzed package, sorted by path."""
        return [m for rel, m in sorted(self.modules.items())
                if rel.startswith(self.package)]

    def module(self, rel: str):
        return self.modules.get(rel)

    def file_text(self, rel: str) -> str:
        return self.files.get(rel, "")


class Baseline:
    """Annotated findings the gate tolerates (burn-down list).

    JSON form::

        {"version": 1,
         "entries": [{"rule": "...", "file": "...", "symbol": "...",
                      "note": "why this is baselined, not fixed"}]}

    Every entry MUST carry a non-empty ``note`` — an unannotated
    suppression is itself an error (`validate`).
    """

    def __init__(self, entries=None, path=None):
        self.entries = list(entries or [])
        self.path = path

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not os.path.exists(path):
            return cls(entries=[], path=path)
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        return cls(entries=doc.get("entries", []), path=path)

    def validate(self):
        """Return a list of error strings for malformed entries."""
        errs = []
        for i, e in enumerate(self.entries):
            missing = [k for k in ("rule", "file", "symbol") if not e.get(k)]
            if missing:
                errs.append(f"baseline entry {i}: missing {missing}")
            if not str(e.get("note", "")).strip():
                errs.append(
                    f"baseline entry {i} ({e.get('rule')} {e.get('file')} "
                    f"{e.get('symbol')}): empty 'note' — annotate why this "
                    "finding is tolerated")
        return errs

    def keys(self):
        return {(e["rule"], e["file"], e["symbol"]) for e in self.entries
                if e.get("rule") and e.get("file") and e.get("symbol")}

    def match(self, findings):
        """Split ``findings`` → (new, baselined) and compute stale entries.

        Returns ``(new_findings, baselined_findings, stale_entries)``.
        """
        keys = self.keys()
        hit = set()
        new, old = [], []
        for f in findings:
            if f.key() in keys:
                hit.add(f.key())
                old.append(f)
            else:
                new.append(f)
        stale = [e for e in self.entries
                 if (e.get("rule"), e.get("file"), e.get("symbol"))
                 not in hit]
        return new, old, stale

    @staticmethod
    def render(findings, notes=None) -> dict:
        """Serialize ``findings`` into baseline-document form (used by
        ``--write-baseline``); ``notes`` maps keys to annotations."""
        notes = notes or {}
        entries, seen = [], set()
        for f in sorted(findings, key=lambda f: (f.rule, f.file, f.symbol)):
            if f.key() in seen:
                continue
            seen.add(f.key())
            entries.append({
                "rule": f.rule, "file": f.file, "symbol": f.symbol,
                "note": notes.get(f.key(), "TODO: annotate or fix"),
            })
        return {"version": 1, "entries": entries}


# ---------------------------------------------------------------------------
# shared AST helpers
# ---------------------------------------------------------------------------


def dotted_name(node) -> str | None:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_func_name(call: ast.Call) -> str | None:
    return dotted_name(call.func)


def qualified_functions(tree: ast.Module):
    """Yield ``(qualname, func_node, class_name_or_None)`` for every
    function: top-level defs and class methods (one nesting level of
    classes; nested defs stay inside their parent's body walk)."""
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node.name, node, None
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield f"{node.name}.{sub.name}", sub, node.name


def str_const(node) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def joined_str_prefix(node) -> str | None:
    """Literal prefix of an f-string up to its first placeholder, with a
    trailing ``*`` wildcard (``f"faults.injected.{p}"`` → ``faults.injected.*``)."""
    if not isinstance(node, ast.JoinedStr):
        return None
    prefix = []
    for part in node.values:
        if isinstance(part, ast.Constant) and isinstance(part.value, str):
            prefix.append(part.value)
        else:
            break
    return "".join(prefix) + "*"
