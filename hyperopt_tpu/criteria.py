"""Standalone Bayesian-optimization acquisition criteria.

Reference: ``hyperopt/criteria.py`` (~80 LoC, SURVEY.md §2): Gaussian EI /
logEI / UCB formulas — historical utilities largely unused by the TPE path,
kept for API parity.  Here they are jax.numpy implementations (jit/vmap
friendly, usable on device) with the same signatures.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax.scipy.special import log_ndtr
from jax.scipy.stats import norm


def EI_empirical(samples, thresh):
    """Expected improvement over ``thresh`` from empirical samples:
    ``mean(max(samples - thresh, 0))`` (reference: criteria.py::EI_empirical).
    """
    samples = jnp.asarray(samples)
    return jnp.maximum(samples - thresh, 0.0).mean()


def EI_gaussian(mean, var, thresh):
    """Analytic expected improvement of N(mean, var) over ``thresh``
    (reference: criteria.py::EI_gaussian)."""
    sigma = jnp.sqrt(var)
    score = (mean - thresh) / sigma
    return sigma * (score * norm.cdf(score) + norm.pdf(score))


def logEI_gaussian(mean, var, thresh):
    """log(EI_gaussian), numerically stable deep into the negative-score
    tail (reference: criteria.py::logEI_gaussian — which switches to an
    asymptotic form; here the stable path uses log-space arithmetic)."""
    sigma = jnp.sqrt(var)
    score = (mean - thresh) / sigma
    # EI = sigma * (score * Phi(score) + phi(score)).  For very negative
    # score, Phi(score)*score + phi(score) -> phi(score) * (1 - |score|...)
    # — compute both terms in log space and combine.
    log_phi = norm.logpdf(score)
    log_Phi = log_ndtr(score)
    # score * Phi + phi == phi + score * Phi; sign(score) decides the path.
    pos = jnp.log1p(jnp.exp(log_Phi + jnp.log(jnp.maximum(score, 1e-38))
                            - log_phi)) + log_phi
    # moderately negative score: phi - |score| * Phi > 0; log1p form.
    neg = log_phi + jnp.log1p(
        -jnp.exp(jnp.minimum(log_Phi
                             + jnp.log(jnp.maximum(-score, 1e-38))
                             - log_phi, -1e-7)))
    # deep tail (score << 0): Mills-ratio asymptotics,
    # EI ~ sigma * phi(s) / s^2 * (1 - 3/s^2).
    s2 = jnp.maximum(score * score, 1e-38)
    deep = log_phi - jnp.log(s2) + jnp.log1p(-jnp.minimum(3.0 / s2, 0.5))
    out = jnp.where(score >= 0, pos, jnp.where(score > -6.0, neg, deep))
    return jnp.log(sigma) + out


def UCB(mean, var, zscore):
    """Upper confidence bound: ``mean + zscore * sqrt(var)``
    (reference: criteria.py::UCB)."""
    return mean + jnp.sqrt(var) * zscore
