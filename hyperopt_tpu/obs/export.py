"""Wire-correct OpenMetrics text exposition of the metrics registry.

``render_openmetrics()`` turns a registry snapshot — or the full
``GET /metrics`` payload including the PR 6 fleet-merged view — into
the OpenMetrics 1.0 text format, so any standard Prometheus-compatible
scraper can point at the existing token-gated ``GET /metrics`` endpoint
(the netstore handler content-negotiates on the ``Accept`` header and
serves this instead of JSON).

Encoding rules:

* dotted registry names sanitize to Prometheus names
  (``netstore.verb.suggest.s`` → ``hyperopt_tpu_netstore_verb_suggest_s``);
* counters gain the mandated ``_total`` suffix; gauges export verbatim;
* the registry allows one dotted name to live in several typed tables
  at once (``tpe._obs_ms``: counter + histogram; ``pipeline.occupancy``:
  gauge + histogram) — OpenMetrics families cannot, so the histogram
  keeps the bare family name and a colliding counter exports as
  ``<name>_cumulative`` / a colliding gauge as ``<name>_current``
  (renames are computed over local and fleet views together so both
  scopes land in one family);
* histograms export as native histogram families —
  ``<name>_bucket{le="..."}`` with **cumulative** counts (registry
  states are per-bucket; the cumulative sum happens here), a ``+Inf``
  bucket, ``_count`` and ``_sum``;
* every sample carries a ``scope`` label: ``scope="local"`` for this
  process's registry, ``scope="fleet"`` for the exactly-merged
  fleet view (one family, two labeled series — the fleet-merged
  per-verb latency distributions are real histogram series a scraper
  can quantile over);
* the exposition ends with the mandatory ``# EOF`` line.

``parse_openmetrics()`` is the strict round-trip parser the test suite
uses: it enforces name grammar, TYPE-before-sample ordering,
type-appropriate suffixes, bucket monotonicity, ``+Inf``/``_count``
agreement, and the ``# EOF`` terminator — close to what a conformant
scraper would reject.
"""

from __future__ import annotations

import math
import re

__all__ = ["CONTENT_TYPE", "render_openmetrics", "parse_openmetrics",
           "sanitize_name", "wants_openmetrics", "histogram_groups",
           "histogram_quantile"]

#: Content type a negotiated ``GET /metrics`` reply carries.
CONTENT_TYPE = "application/openmetrics-text; version=1.0.0; charset=utf-8"

#: Accept-header substrings that select the text exposition over JSON.
ACCEPT_TOKENS = ("openmetrics-text", "text/plain")

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_SANITIZE_RE = re.compile(r"[^a-zA-Z0-9_:]")
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+(\S+)(?:\s+(\S+))?$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')

_SUFFIXES = {"counter": ("_total",),
             "gauge": ("",),
             "histogram": ("_bucket", "_count", "_sum")}


def wants_openmetrics(accept: str) -> bool:
    """Content negotiation: does this ``Accept`` header pick the text
    exposition over the default JSON payload?"""
    accept = (accept or "").lower()
    return any(tok in accept for tok in ACCEPT_TOKENS)


def sanitize_name(name: str, prefix: str = "hyperopt_tpu") -> str:
    out = _SANITIZE_RE.sub("_", name)
    if prefix:
        out = f"{prefix}_{out}"
    if not _NAME_RE.match(out):
        out = "_" + out
    return out


def _fmt(v) -> str:
    v = float(v)
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def _esc(v: str) -> str:
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _labels(d: dict) -> str:
    if not d:
        return ""
    inner = ",".join(f'{k}="{_esc(v)}"' for k, v in sorted(d.items()))
    return "{" + inner + "}"


class _Family:
    def __init__(self, name, ftype):
        self.name, self.ftype = name, ftype
        self.lines = []

    def sample(self, suffix, labels, value):
        self.lines.append(
            f"{self.name}{suffix}{_labels(labels)} {_fmt(value)}")


def _scalar_renames(payload, prefix):
    """Sanitized names claimed by more than one typed table anywhere in
    the payload (local snapshot or fleet-merged view) — the registry's
    shared-name idiom (``_obs_ms`` counter+histogram,
    ``pipeline.occupancy`` gauge+histogram).  The histogram keeps the
    bare family name; returns the (counter, gauge) name sets that must
    rename at export."""
    snaps = [payload]
    merged = (payload.get("fleet") or {}).get("merged")
    if merged:
        snaps.append(merged)
    hists, counters, gauges = set(), set(), set()
    for snap in snaps:
        for name, h in (snap.get("histograms") or {}).items():
            if h.get("state"):
                hists.add(sanitize_name(name, prefix))
        for name in (snap.get("counters") or {}):
            counters.add(sanitize_name(name, prefix))
        for name in (snap.get("gauges") or {}):
            gauges.add(sanitize_name(name, prefix))
    return counters & (hists | gauges), gauges & (hists | counters)


def _scoped(families, snap, scope, prefix,
            renames=(frozenset(), frozenset())):
    """Fold one snapshot-shaped dict into the family table."""
    counter_renames, gauge_renames = renames
    for name, v in sorted(snap.get("counters", {}).items()):
        sname = sanitize_name(name, prefix)
        if sname in counter_renames:
            sname += "_cumulative"
        fam = _family(families, sname, "counter")
        fam.sample("_total", {"scope": scope}, v)
    for name, v in sorted(snap.get("gauges", {}).items()):
        sname = sanitize_name(name, prefix)
        if sname in gauge_renames:
            sname += "_current"
        fam = _family(families, sname, "gauge")
        fam.sample("", {"scope": scope}, v)
    for name, h in sorted(snap.get("histograms", {}).items()):
        st = h.get("state")
        if not st:
            continue
        fam = _family(families, sanitize_name(name, prefix), "histogram")
        cum = 0
        for i, c in enumerate(st["counts"]):
            cum += c
            le = (st["bounds"][i] if i < len(st["bounds"])
                  else float("inf"))
            fam.sample("_bucket", {"scope": scope, "le": _fmt(le)}, cum)
        fam.sample("_count", {"scope": scope}, st["count"])
        fam.sample("_sum", {"scope": scope}, st["sum"])
    kc = snap.get("kernel_cache")
    if kc:
        for key in ("requests", "misses"):
            fam = _family(families,
                          sanitize_name(f"kernel_cache.{key}", prefix),
                          "counter")
            fam.sample("_total", {"scope": scope}, kc.get(key, 0))


def _family(families, name, ftype):
    fam = families.get(name)
    if fam is None:
        fam = families[name] = _Family(name, ftype)
    elif fam.ftype != ftype:
        raise ValueError(f"family {name}: {fam.ftype} vs {ftype}")
    return fam


def render_openmetrics(payload: dict, prefix: str = "hyperopt_tpu") -> str:
    """Encode a ``metrics_payload()`` dict (or bare ``snapshot()``) as
    OpenMetrics text.  The local registry exports as ``scope="local"``;
    when a ``fleet.merged`` view is present it exports as
    ``scope="fleet"`` samples of the same families."""
    families: dict = {}
    renames = _scalar_renames(payload, prefix)
    _scoped(families, payload, "local", prefix, renames)
    merged = (payload.get("fleet") or {}).get("merged")
    if merged:
        _scoped(families, merged, "fleet", prefix, renames)
    out = []
    for name in sorted(families):
        fam = families[name]
        out.append(f"# TYPE {name} {fam.ftype}")
        out.extend(fam.lines)
    out.append("# EOF")
    return "\n".join(out) + "\n"


# -- strict parser (round-trip validation) ----------------------------------

def _parse_value(tok: str) -> float:
    if tok == "+Inf":
        return float("inf")
    if tok == "-Inf":
        return float("-inf")
    return float(tok)


def parse_openmetrics(text: str) -> dict:
    """Strictly parse an OpenMetrics exposition.

    Returns ``{family: {"type": t, "samples": [(suffix, labels, value)]}}``
    and raises ``ValueError`` on any grammar or semantic violation:
    missing ``# EOF``, samples before their TYPE, wrong suffix for the
    declared type, non-monotone histogram buckets, a ``+Inf`` bucket
    that disagrees with ``_count``, or duplicate sample keys.
    """
    lines = text.splitlines()
    if not lines or lines[-1] != "# EOF":
        raise ValueError("exposition must end with '# EOF'")
    families: dict = {}
    seen_samples = set()
    for ln, line in enumerate(lines[:-1], 1):
        if not line:
            raise ValueError(f"line {ln}: blank line inside exposition")
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) < 3 or parts[1] not in ("TYPE", "HELP", "UNIT"):
                raise ValueError(f"line {ln}: bad metadata line {line!r}")
            if parts[1] != "TYPE":
                continue
            name, ftype = parts[2], (parts[3] if len(parts) > 3 else "")
            if not _NAME_RE.match(name):
                raise ValueError(f"line {ln}: bad family name {name!r}")
            if ftype not in _SUFFIXES:
                raise ValueError(f"line {ln}: unknown type {ftype!r}")
            if name in families:
                raise ValueError(f"line {ln}: duplicate TYPE for {name}")
            families[name] = {"type": ftype, "samples": []}
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            raise ValueError(f"line {ln}: unparsable sample {line!r}")
        sname, rawlabels, rawval = m.group(1), m.group(2) or "", m.group(3)
        labels = dict(_LABEL_RE.findall(rawlabels[1:-1])) if rawlabels \
            else {}
        fam_name, suffix = None, None
        for name, fam in families.items():
            for suf in _SUFFIXES[fam["type"]]:
                if sname == name + suf and (
                        fam_name is None or len(name) > len(fam_name)):
                    fam_name, suffix = name, suf
        if fam_name is None:
            raise ValueError(
                f"line {ln}: sample {sname!r} has no preceding TYPE "
                "(or wrong suffix for its family type)")
        key = (sname, tuple(sorted(labels.items())))
        if key in seen_samples:
            raise ValueError(f"line {ln}: duplicate sample {key}")
        seen_samples.add(key)
        families[fam_name]["samples"].append(
            (suffix, labels, _parse_value(rawval)))
    _validate_histograms(families)
    return families


def histogram_groups(fam: dict) -> dict:
    """Group a parsed histogram family's samples by non-``le`` labels:
    ``{labelset: {"buckets": [(le, cum)], "count": n, "sum": s}}``."""
    groups: dict = {}
    for suffix, labels, value in fam["samples"]:
        gkey = tuple(sorted((k, v) for k, v in labels.items()
                            if k != "le"))
        g = groups.setdefault(gkey, {"buckets": [], "count": None,
                                     "sum": None})
        if suffix == "_bucket":
            if "le" not in labels:
                raise ValueError("bucket sample missing le")
            g["buckets"].append((_parse_value(labels["le"]), value))
        elif suffix == "_count":
            g["count"] = value
        elif suffix == "_sum":
            g["sum"] = value
    return groups


def _validate_histograms(families: dict) -> None:
    for name, fam in families.items():
        if fam["type"] != "histogram":
            continue
        groups = histogram_groups(fam)
        for gkey, g in groups.items():
            if not g["buckets"]:
                raise ValueError(f"{name}{dict(gkey)}: no buckets")
            if g["count"] is None or g["sum"] is None:
                raise ValueError(f"{name}{dict(gkey)}: missing _count/_sum")
            les = [le for le, _ in g["buckets"]]
            if les != sorted(les) or len(set(les)) != len(les):
                raise ValueError(f"{name}{dict(gkey)}: le not ascending")
            counts = [c for _, c in g["buckets"]]
            if any(b < a for a, b in zip(counts, counts[1:])):
                raise ValueError(f"{name}{dict(gkey)}: buckets not "
                                 "cumulative")
            if not math.isinf(les[-1]):
                raise ValueError(f"{name}{dict(gkey)}: missing +Inf bucket")
            if counts[-1] != g["count"]:
                raise ValueError(
                    f"{name}{dict(gkey)}: +Inf bucket {counts[-1]} != "
                    f"_count {g['count']}")


def histogram_quantile(fam_group, q: float):
    """Quantile from parsed cumulative buckets — what a scraper's
    ``histogram_quantile()`` would compute (bucket-upper-bound rule,
    matching ``metrics._quantile_locked``)."""
    buckets = sorted(fam_group["buckets"])
    total = fam_group["count"]
    if not total:
        return None
    target = q * total
    for le, cum in buckets:
        if cum >= target and cum > 0:
            return le
    return buckets[-1][0]
