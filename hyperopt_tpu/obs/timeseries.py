"""Bounded in-process time-series store over the metrics registry.

``GET /metrics`` and the fleet merge (PR 6) expose *instantaneous*
cumulative state; nothing in the stack retains it, so windowed
questions — "what was p95 suggest latency over the last minute", "is
the WAL fsync lag trending up" — were unanswerable without an external
scraper.  ``TimeSeriesStore`` closes that gap in-process:

* ``scrape()`` snapshots the registry (``snapshot(states=True)``) and
  appends one sample per counter/gauge/histogram to a bounded ring.
* Each series keeps a **raw** ring (every scrape) plus downsampled
  tiers at 1 s / 10 s / 60 s resolution.  All ring capacities are
  powers of two; a tier holds the *last* sample of each aligned period,
  which is exact for cumulative series (counters, cumulative histogram
  states) and last-write-wins for gauges.  Retention therefore grows
  geometrically per tier while memory stays O(sum of caps).
* Histogram samples store the full cumulative bucket-count state, so a
  *windowed* histogram is the elementwise difference of two cumulative
  states — exact windowed quantiles with no per-observation cost.
* Stores are mergeable across processes: ``export_series()`` /
  ``ingest()`` move raw samples between processes with timestamps
  normalized by the PR 6 skew estimate (``clock.skew_s`` convention:
  ``t_server ≈ t_client - skew_s``), and ``merged_window_state()``
  folds per-source windows with ``metrics.merge_histogram_states``.

Overhead: zero unless ``scrape()`` is called — nothing hooks the
metric hot paths.  A scrape is O(series) dict walks; measured numbers
live in the ``obs`` bench phase and DESIGN.md §6.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque

from . import metrics as _metrics

__all__ = ["TimeSeriesStore", "TIERS", "RAW_CAP"]

#: (period_s, capacity) downsampling tiers — capacities are powers of
#: two; retention per tier = period × capacity.
TIERS = ((1.0, 512), (10.0, 256), (60.0, 128))

#: Raw ring capacity (one slot per scrape), power of two.
RAW_CAP = 1024

# Rough per-sample cost estimates for nbytes() — tuple + floats for a
# scalar sample, plus the interned counts tuple for histogram samples.
_SCALAR_SAMPLE_B = 120
_HIST_BASE_B = 160


class _Series:
    __slots__ = ("kind", "bounds", "raw", "tiers")

    def __init__(self, kind, raw_cap, tiers, bounds=None):
        self.kind = kind                 # "counter" | "gauge" | "hist"
        self.bounds = bounds             # shared bucket bounds (hist only)
        self.raw = deque(maxlen=raw_cap)         # (t, v)
        self.tiers = tuple(deque(maxlen=cap) for _, cap in tiers)
        # tier entries: (bucket_index, t, v)


def _hist_value(state):
    """Compact cumulative-histogram sample from a registry state dict."""
    return (tuple(state["counts"]), int(state["count"]),
            float(state["sum"]), state.get("min"), state.get("max"))


def _hist_state(bounds, value):
    counts, count, total, mn, mx = value
    return {"bounds": list(bounds), "counts": list(counts),
            "count": count, "sum": total, "min": mn, "max": mx}


def _diff_hist(bounds, end, start):
    """Windowed (non-cumulative-over-time) state: ``end - start``."""
    if start is None:
        return _hist_state(bounds, end)
    c_end, n_end, s_end = end[0], end[1], end[2]
    c_sta, n_sta, s_sta = start[0], start[1], start[2]
    counts = [max(0, a - b) for a, b in zip(c_end, c_sta)]
    # min/max are not differentiable over a window; report the
    # cumulative end extrema (documented approximation).
    return {"bounds": list(bounds), "counts": counts,
            "count": max(0, n_end - n_sta), "sum": s_end - s_sta,
            "min": end[3], "max": end[4]}


class TimeSeriesStore:
    """Bounded multi-tier sample store; see module docstring.

    ``reg`` pins the store to one :class:`~.metrics.MetricsRegistry`
    (tests use isolated registries); default is the process-global one,
    resolved at scrape time.
    """

    def __init__(self, reg=None, raw_cap: int = RAW_CAP, tiers=TIERS):
        self._reg = reg
        self._raw_cap = int(raw_cap)
        self._tiers = tuple((float(p), int(c)) for p, c in tiers)
        self._series: dict = {}
        self._lock = threading.Lock()
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self.n_scrapes = 0

    # -- write side ----------------------------------------------------------

    def registry(self):
        return self._reg if self._reg is not None else _metrics.registry()

    def scrape(self, now: float | None = None) -> float:
        """Sample every registry series once; returns the scrape
        duration in seconds.  ``now`` overrides the sample timestamp so
        tests can drive synthetic clocks."""
        t0 = time.perf_counter()
        now = time.time() if now is None else float(now)
        reg = self.registry()
        snap = reg.snapshot(states=True)
        with self._lock:
            for name, v in snap.get("counters", {}).items():
                self._append(name, "counter", now, float(v))
            for name, v in snap.get("gauges", {}).items():
                self._append(name, "gauge", now, float(v))
            for name, h in snap.get("histograms", {}).items():
                st = h.get("state")
                if st and st.get("count"):
                    self._append_hist(name, now, st)
            self.n_scrapes += 1
        dur = time.perf_counter() - t0
        # Self-telemetry rides on the scraped registry (next scrape
        # picks it up) — emitted AFTER our lock is released so the only
        # lock edge is store-lock -> nothing.
        reg.gauge("obs.timeseries.series").set(self.n_series())
        reg.gauge("obs.timeseries.samples").set(self.n_samples())
        reg.gauge("obs.timeseries.bytes").set(self.nbytes())
        reg.histogram("obs.timeseries.scrape_s").observe(dur)
        return dur

    def _get_series(self, name, kind, bounds=None):
        """Get-or-create one series; caller holds ``self._lock``."""
        s = self._series.get(name)
        if s is None:
            s = self._series[name] = _Series(kind, self._raw_cap,
                                             self._tiers, bounds=bounds)
        return s

    def _append(self, name, kind, t, v):
        s = self._get_series(name, kind)
        self._push(s, t, v)

    def _append_hist(self, name, t, state):
        s = self._get_series(name, "hist", bounds=tuple(state["bounds"]))
        if s.bounds is None:
            s.bounds = tuple(state["bounds"])
        self._push(s, t, _hist_value(state))

    def _push(self, s, t, v):
        s.raw.append((t, v))
        for (period, _cap), ring in zip(self._tiers, s.tiers):
            bucket = int(t // period)
            if ring and ring[-1][0] == bucket:
                ring[-1] = (bucket, t, v)   # last sample of period wins
            else:
                ring.append((bucket, t, v))

    # -- read side -----------------------------------------------------------

    def n_series(self) -> int:
        with self._lock:
            return len(self._series)

    def n_samples(self) -> int:
        with self._lock:
            return sum(len(s.raw) + sum(len(r) for r in s.tiers)
                       for s in self._series.values())

    def nbytes(self) -> int:
        """Order-of-magnitude memory estimate (tracked as
        ``obs.timeseries.bytes``)."""
        total = 0
        with self._lock:
            for s in self._series.values():
                n = len(s.raw) + sum(len(r) for r in s.tiers)
                if s.kind == "hist":
                    per = _HIST_BASE_B + 8 * (len(s.bounds or ()) + 1)
                else:
                    per = _SCALAR_SAMPLE_B
                total += n * per
        return total

    def series_names(self) -> list:
        with self._lock:
            return sorted(self._series)

    def _pick_samples(self, s, start):
        """Finest ring whose retention covers ``start`` (best effort:
        the ring reaching furthest back otherwise), as [(t, v), ...]."""
        if s.raw and s.raw[0][0] <= start:
            return list(s.raw)
        best = list(s.raw)
        for ring in s.tiers:
            if ring:
                cand = [(t, v) for _, t, v in ring]
                if cand[0][0] <= start:
                    return cand
                if not best or cand[0][0] < best[0][0]:
                    best = cand
        return best

    def samples(self, name, window_s=None, now=None):
        """Scalar samples ``[(t, value), ...]`` within the window (all
        retained samples when ``window_s`` is None)."""
        now = time.time() if now is None else float(now)
        start = -float("inf") if window_s is None else now - float(window_s)
        with self._lock:
            s = self._series.get(name)
            if s is None:
                return []
            out = self._pick_samples(s, start)
        return [(t, v) for t, v in out if start <= t <= now]

    def _sample_at(self, s, t):
        """Latest retained sample with timestamp <= t, or None."""
        best = None
        for t_i, v in s.raw:
            if t_i <= t:
                best = (t_i, v)
        if best is not None:
            return best
        for ring in reversed(s.tiers):      # coarse rings reach further back
            for _, t_i, v in ring:
                if t_i <= t:
                    best = (t_i, v)
            if best is not None:
                return best
        return None

    def delta(self, name, window_s, now=None):
        """Counter increase over the window (None when < 2 samples
        bracket it).  The baseline is the last sample at/before the
        window start, falling back to the earliest in-window sample."""
        now = time.time() if now is None else float(now)
        start = now - float(window_s)
        with self._lock:
            s = self._series.get(name)
            if s is None:
                return None
            end = self._sample_at(s, now)
            base = self._sample_at(s, start)
            if base is None:
                inw = [(t, v) for t, v in self._pick_samples(s, start)
                       if start <= t <= now]
                base = inw[0] if inw else None
        if end is None or base is None or end[0] <= base[0]:
            return None
        return max(0.0, end[1] - base[1])

    def rate(self, name, window_s, now=None):
        d = self.delta(name, window_s, now=now)
        return None if d is None else d / float(window_s)

    def window_state(self, name, window_s, now=None):
        """Windowed histogram state (end-cumulative minus
        start-cumulative), or None if no sample covers the window end."""
        now = time.time() if now is None else float(now)
        start = now - float(window_s)
        with self._lock:
            s = self._series.get(name)
            if s is None or s.kind != "hist":
                return None
            end = self._sample_at(s, now)
            if end is None:
                return None
            base = self._sample_at(s, start)
            bounds = s.bounds
        return _diff_hist(bounds, end[1], None if base is None else base[1])

    def window_quantile(self, name, q, window_s, now=None):
        st = self.window_state(name, window_s, now=now)
        if not st or not st["count"]:
            return None
        return _metrics._state_quantile(st, q)

    def window_frac_above(self, name, threshold, window_s, now=None):
        """Fraction of window observations whose bucket upper bound
        exceeds ``threshold`` — the conservative (bucket-resolution)
        tail fraction SLO burn rates are computed from."""
        st = self.window_state(name, window_s, now=now)
        if not st or not st["count"]:
            return None
        bad = 0
        for i, c in enumerate(st["counts"]):
            upper = (st["bounds"][i] if i < len(st["bounds"])
                     else float("inf"))
            if upper > threshold:
                bad += c
        return bad / st["count"]

    # -- cross-process merge -------------------------------------------------

    def export_series(self) -> dict:
        """JSON-able raw-tier dump for cross-process merging."""
        out = {}
        with self._lock:
            for name, s in self._series.items():
                if s.kind == "hist":
                    raw = [[t, list(v[0]), v[1], v[2]] for t, v in s.raw]
                    out[name] = {"kind": "hist", "bounds": list(s.bounds),
                                 "raw": raw}
                else:
                    out[name] = {"kind": s.kind,
                                 "raw": [[t, v] for t, v in s.raw]}
        return out

    def ingest(self, src: str, series: dict, skew_s: float = 0.0) -> None:
        """Fold another process's ``export_series()`` dump in under
        ``<src>:<name>`` keys, timestamps skew-normalized onto this
        process's clock (``t_local = t_remote - skew_s``, matching the
        ``clock.skew_s`` gauge convention from the heartbeat RTT
        estimate)."""
        skew = float(skew_s or 0.0)
        with self._lock:
            for name, ser in series.items():
                key = f"{src}:{name}"
                if ser.get("kind") == "hist":
                    bounds = tuple(ser["bounds"])
                    s = self._get_series(key, "hist", bounds=bounds)
                    for t, counts, count, total in ser["raw"]:
                        self._push(s, t - skew,
                                   (tuple(counts), int(count),
                                    float(total), None, None))
                else:
                    s = self._get_series(key, ser.get("kind", "gauge"))
                    for t, v in ser["raw"]:
                        self._push(s, t - skew, float(v))

    def merged_window_state(self, names, window_s, now=None):
        """One windowed histogram state across several series (e.g. the
        same verb's latency ingested from N processes)."""
        states = [self.window_state(n, window_s, now=now) for n in names]
        return _metrics.merge_histogram_states(states)

    # -- background scraper --------------------------------------------------

    def start(self, interval_s: float = 2.0):
        """Daemon scrape loop; idempotent."""
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(interval_s):
                try:
                    self.scrape()
                except Exception:       # pragma: no cover - keep scraping
                    logging.getLogger(__name__).exception(
                        "timeseries scrape pass failed; continuing")

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="obs-timeseries-scraper")
        self._thread.start()

    def stop(self):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)
