"""Observability subsystem: structured events, metrics, loop tracing.

SURVEY.md §5.1 names tracing/profiling as a required auxiliary subsystem;
until round 6 it lived as ad-hoc counters in ``utils/tracing.py`` plus
bespoke instrumentation re-rolled inside each bench script.  This package
makes telemetry first-class, in three layers:

* :mod:`~hyperopt_tpu.obs.events` — a process-global **structured event
  log**: a bounded ring buffer of typed events (``trial_start/end``,
  ``suggest``, ``compile``, ``store_claim/write/flush``,
  ``worker_up/down``, ``transfer_borrow/drop``) carrying trial ids,
  monotonic + wall timestamps and nested span ids, dumpable as JSONL and
  exportable as Chrome ``trace_event`` JSON so host spans load in
  Perfetto alongside ``jax.profiler`` device traces.
* :mod:`~hyperopt_tpu.obs.metrics` — a process-global **metrics
  registry** (counters / gauges / histograms behind one lock,
  near-zero-cost when disabled) fed by the loop, both suggest
  algorithms, the device-resident loop and all four parallel backends;
  also home to the TPE kernel-cache compile-shape counters
  (``kernel_cache_event`` / ``kernel_cache_stats``).
* :mod:`~hyperopt_tpu.obs.trace` — the per-``fmin`` :class:`Tracer`
  (span aggregation + ``jax.profiler`` device traces) which arms the
  event log for the run and writes ``loop_trace.json`` /
  ``loop_events.jsonl`` / ``chrome_trace.json`` into its ``trace_dir``.
* :mod:`~hyperopt_tpu.obs.context` — **cross-process trace context**
  (``trace_id``/``span``/``tid``), stamped by the driver into netstore
  RPC bodies and trial ``misc``, adopted by the server and workers so
  every process's events attach to the originating trial; armed by the
  Tracer alongside the event log, one-boolean-check free when disarmed.

Surfacing: ``hyperopt-tpu-show trace <dir>`` renders a per-phase summary
table from a trace directory; ``hyperopt-tpu-show trace --merge <dirs…>``
clock-normalizes several processes' ``loop_events.jsonl`` into one
Perfetto trace with per-trial flow arrows; ``hyperopt-tpu-show live
<url>`` polls a netstore's fleet metrics into a terminal dashboard; the
netstore server exposes local + per-worker + merged fleet metrics via a
token-gated ``GET /metrics``.

Everything here is host-side bookkeeping — nothing in this package ever
touches the traced/compiled XLA programs.
"""

from __future__ import annotations

from . import bundle  # noqa: F401
from . import context  # noqa: F401
from . import costs  # noqa: F401
from . import flight  # noqa: F401
from .events import EVENTS, EventLog, events_to_chrome  # noqa: F401
from .metrics import (  # noqa: F401
    LabelLru,
    MetricsRegistry,
    kernel_cache_event,
    kernel_cache_stats,
    merge_histogram_states,
    merge_snapshots,
    metrics_enabled,
    registry,
    summarize_state,
)
from .trace import NullTracer, Tracer  # noqa: F401
