"""Observability subsystem: structured events, metrics, loop tracing.

SURVEY.md §5.1 names tracing/profiling as a required auxiliary subsystem;
until round 6 it lived as ad-hoc counters in ``utils/tracing.py`` plus
bespoke instrumentation re-rolled inside each bench script.  This package
makes telemetry first-class, in three layers:

* :mod:`~hyperopt_tpu.obs.events` — a process-global **structured event
  log**: a bounded ring buffer of typed events (``trial_start/end``,
  ``suggest``, ``compile``, ``store_claim/write/flush``,
  ``worker_up/down``, ``transfer_borrow/drop``) carrying trial ids,
  monotonic + wall timestamps and nested span ids, dumpable as JSONL and
  exportable as Chrome ``trace_event`` JSON so host spans load in
  Perfetto alongside ``jax.profiler`` device traces.
* :mod:`~hyperopt_tpu.obs.metrics` — a process-global **metrics
  registry** (counters / gauges / histograms behind one lock,
  near-zero-cost when disabled) fed by the loop, both suggest
  algorithms, the device-resident loop and all four parallel backends;
  also home to the TPE kernel-cache compile-shape counters
  (``kernel_cache_event`` / ``kernel_cache_stats``).
* :mod:`~hyperopt_tpu.obs.trace` — the per-``fmin`` :class:`Tracer`
  (span aggregation + ``jax.profiler`` device traces) which arms the
  event log for the run and writes ``loop_trace.json`` /
  ``loop_events.jsonl`` / ``chrome_trace.json`` into its ``trace_dir``.

Surfacing: ``hyperopt-tpu-show trace <dir>`` renders a per-phase summary
table from a trace directory; the netstore server exposes the registry
via a token-gated ``GET /metrics``.

Everything here is host-side bookkeeping — nothing in this package ever
touches the traced/compiled XLA programs.
"""

from __future__ import annotations

from .events import EVENTS, EventLog  # noqa: F401
from .metrics import (  # noqa: F401
    MetricsRegistry,
    kernel_cache_event,
    kernel_cache_stats,
    metrics_enabled,
    registry,
)
from .trace import NullTracer, Tracer  # noqa: F401
