"""Process-global metrics registry: counters, gauges, histograms.

One :class:`MetricsRegistry` (``registry()``) serves the whole process.
All mutation happens under a single registry lock — contention is
negligible at loop rates (tens of updates per trial vs one device
dispatch) and a single lock keeps ``snapshot()`` trivially consistent.

Cost model: metrics are **on by default** (``HYPEROPT_TPU_METRICS=0``
disables) because each update is two dict/float ops under an uncontended
lock.  When disabled, every ``inc``/``set``/``observe`` returns after a
single attribute check — the disabled path is the budget the
``trials_per_sec`` bench holds to <1% (DESIGN.md §6).

Well-known loop-feed series (fed by ``tpe.suggest_dispatch`` and
``hyperopt_tpu.history``): ``history.upload_bytes`` /
``history.append_hits`` / ``history.rebuilds`` — the resident-history
transfer contract (steady-state O(P) bytes/trial, asserted in
tests/test_history.py) — and ``suggest.upload_ms`` /
``suggest.dispatch_ms`` / ``suggest.fetch_sync_ms``, the host-loop
phase breakdown ``bench.py``'s trials_sec phase snapshots into its
``loop_breakdown`` artifact field.  Each ``suggest.*_ms`` name is fed
**twice** per sample: the counter accumulates total milliseconds (the
legacy breakdown contract) and a same-named millisecond-bucketed
histogram (50µs .. ~26s, ×2/bucket) records the distribution so the
pipeline bench can report p50/p95 stall times via ``summary()``.

Pipeline-executor series (fed by ``hyperopt_tpu.pipeline``):
``pipeline.occupancy`` (gauge+histogram, in-flight suggest handles
after each dispatch), ``pipeline.eval_backlog`` (gauge, trials
submitted to the evaluator and not yet recorded),
``pipeline.stall.suggest_bound`` (counter, times the executor wanted
to feed the evaluator but the head handle was still computing) with
``pipeline.stall.suggest_bound_ms`` (counter+histogram, time blocked
materializing a not-yet-ready head), ``pipeline.stall.eval_bound``
(counter, times every slot was ready but the evaluator was still
busy), ``history.fantasy_clipped`` (counter, fantasy rows dropped at
the overlay capacity edge — nonzero means a dispatch under-sized its
bucket), and ``fmin.scan_skipped`` (counter, dynamic-trial docs the
``serial_evaluate`` monotone cursor avoided re-scanning).

Also home to the TPE kernel-cache compile-shape counters
(:func:`kernel_cache_event` / :func:`kernel_cache_stats`), relocated
from ``utils/tracing.py``.  These stay **always-on** regardless of the
enable flag — they are the compile-shape accounting contract consumed by
``benchmarks/atpe_profile.py`` — and each miss additionally emits a
``compile`` event into the structured event log.
"""

from __future__ import annotations

import bisect
import os
import threading
from typing import Optional

from . import events as _events

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "LabelLru",
    "MetricsRegistry",
    "registry",
    "metrics_enabled",
    "kernel_cache_event",
    "kernel_cache_stats",
    "merge_histogram_states",
    "summarize_state",
    "merge_snapshots",
]

# Log-spaced latency bucket upper bounds (seconds): 100µs .. ~52s, ×2 per
# bucket, plus a catch-all.  Covers netstore RPCs through full fmin runs.
DEFAULT_BUCKETS = tuple(1e-4 * (2.0 ** i) for i in range(20))


def _enabled_from_env() -> bool:
    return os.environ.get("HYPEROPT_TPU_METRICS", "1") not in ("0", "off", "false")


class Counter:
    """Monotonic float counter."""

    __slots__ = ("name", "_reg", "_value")

    def __init__(self, name: str, reg: "MetricsRegistry"):
        self.name = name
        self._reg = reg
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if not self._reg._enabled:
            return
        with self._reg._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._reg._lock:
            return self._value


class Gauge:
    """Last-write-wins instantaneous value."""

    __slots__ = ("name", "_reg", "_value")

    def __init__(self, name: str, reg: "MetricsRegistry"):
        self.name = name
        self._reg = reg
        self._value = 0.0

    def set(self, v: float) -> None:
        if not self._reg._enabled:
            return
        with self._reg._lock:
            self._value = float(v)

    @property
    def value(self) -> float:
        with self._reg._lock:
            return self._value


class Histogram:
    """Fixed-bucket histogram with count/sum/min/max.

    Buckets are upper bounds in the observed unit (default: log-spaced
    seconds for latencies).  Quantiles in ``summary()`` are bucket-upper-
    bound approximations — good enough for "p99 netstore reserve is 8ms",
    not for SLO math.
    """

    __slots__ = ("name", "_reg", "bounds", "_counts", "_count", "_sum", "_min", "_max")

    def __init__(self, name: str, reg: "MetricsRegistry", buckets=None):
        self.name = name
        self._reg = reg
        self.bounds = tuple(sorted(buckets)) if buckets else DEFAULT_BUCKETS
        self._counts = [0] * (len(self.bounds) + 1)  # +1 overflow bucket
        self._count = 0
        self._sum = 0.0
        self._min = None
        self._max = None

    def observe(self, v: float) -> None:
        if not self._reg._enabled:
            return
        with self._reg._lock:
            self._counts[bisect.bisect_left(self.bounds, v)] += 1
            self._count += 1
            self._sum += v
            if self._min is None or v < self._min:
                self._min = v
            if self._max is None or v > self._max:
                self._max = v

    def _quantile_locked(self, q: float):
        if self._count == 0:
            return None
        target = q * self._count
        seen = 0
        for i, c in enumerate(self._counts):
            seen += c
            if seen >= target:
                return self.bounds[i] if i < len(self.bounds) else self._max
        return self._max

    def summary(self) -> dict:
        with self._reg._lock:
            if self._count == 0:
                return {"count": 0}
            return {
                "count": self._count,
                "sum": self._sum,
                "mean": self._sum / self._count,
                "min": self._min,
                "max": self._max,
                "p50": self._quantile_locked(0.50),
                "p90": self._quantile_locked(0.90),
                "p95": self._quantile_locked(0.95),
                "p99": self._quantile_locked(0.99),
            }

    def state(self) -> dict:
        """Mergeable wire form: the full bucket vector plus the scalars.

        Two states with identical ``bounds`` merge losslessly by summing
        counts (:func:`merge_histogram_states`) — this is what workers
        piggyback on heartbeats and what the server aggregates into the
        fleet view.  JSON-serializable by construction.
        """
        with self._reg._lock:
            return {
                "bounds": list(self.bounds),
                "counts": list(self._counts),
                "count": self._count,
                "sum": self._sum,
                "min": self._min,
                "max": self._max,
            }


class MetricsRegistry:
    """Lock-protected name → metric table with one-call snapshot."""

    def __init__(self, enabled: Optional[bool] = None):
        self._lock = threading.Lock()
        self._enabled = _enabled_from_env() if enabled is None else bool(enabled)
        self._counters: dict = {}
        self._gauges: dict = {}
        self._histograms: dict = {}
        # Kernel-cache compile-shape accounting (always-on; see module doc).
        self._kernel_cache: dict = {"requests": 0, "misses": 0, "by_key": {}}

    # -- arming ----------------------------------------------------------
    @property
    def enabled(self) -> bool:
        return self._enabled

    def set_enabled(self, flag: bool) -> None:
        self._enabled = bool(flag)

    # -- get-or-create ---------------------------------------------------
    def counter(self, name: str) -> Counter:
        with self._lock:
            m = self._counters.get(name)
            if m is None:
                m = self._counters[name] = Counter(name, self)
            return m

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            m = self._gauges.get(name)
            if m is None:
                m = self._gauges[name] = Gauge(name, self)
            return m

    def histogram(self, name: str, buckets=None) -> Histogram:
        with self._lock:
            m = self._histograms.get(name)
            if m is None:
                m = self._histograms[name] = Histogram(name, self, buckets)
            return m

    # -- removal (label-cardinality control) -----------------------------
    def remove(self, name: str) -> int:
        """Drop a series by exact name from all three tables.  Returns
        how many metrics were removed (0..3).  Handles to a removed
        metric keep working but mutate an orphan no snapshot sees —
        the price of get-or-create handles staying lock-free."""
        with self._lock:
            n = 0
            for table in (self._counters, self._gauges, self._histograms):
                if table.pop(name, None) is not None:
                    n += 1
            return n

    def remove_prefix(self, prefix: str) -> int:
        """Drop every series whose name starts with ``prefix`` (evicting
        one tenant's whole per-verb family at once).  Returns the count."""
        with self._lock:
            n = 0
            for table in (self._counters, self._gauges, self._histograms):
                dead = [k for k in table if k.startswith(prefix)]
                for k in dead:
                    del table[k]
                n += len(dead)
            return n

    # -- kernel cache (always-on) ---------------------------------------
    def kernel_cache_event(self, key, hit: bool) -> None:
        ks = repr(key)
        with self._lock:
            kc = self._kernel_cache
            kc["requests"] += 1
            per = kc["by_key"].setdefault(ks, {"requests": 0, "misses": 0})
            per["requests"] += 1
            if not hit:
                kc["misses"] += 1
                per["misses"] += 1
        if not hit:
            _events.EVENTS.emit("compile", name="tpe_kernel", key=ks)

    def kernel_cache_stats(self, reset: bool = False) -> dict:
        with self._lock:
            kc = self._kernel_cache
            out = {
                "requests": kc["requests"],
                "misses": kc["misses"],
                "by_key": {k: dict(v) for k, v in kc["by_key"].items()},
            }
            if reset:
                kc["requests"] = 0
                kc["misses"] = 0
                kc["by_key"] = {}
        return out

    # -- readout ---------------------------------------------------------
    def snapshot(self, reset: bool = False, states: bool = False) -> dict:
        """One consistent read of everything, for /metrics and benches.

        ``states=True`` additionally embeds each histogram's mergeable
        :meth:`Histogram.state` under a ``"state"`` key — the wire form
        workers piggyback on heartbeats so the server can merge exact
        bucket counts instead of unmergeable quantile summaries.
        """
        with self._lock:
            out = {
                "enabled": self._enabled,
                "counters": {n: c._value for n, c in sorted(self._counters.items())},
                "gauges": {n: g._value for n, g in sorted(self._gauges.items())},
                "kernel_cache": {
                    "requests": self._kernel_cache["requests"],
                    "misses": self._kernel_cache["misses"],
                    "by_key": {
                        k: dict(v) for k, v in self._kernel_cache["by_key"].items()
                    },
                },
            }
        # Histogram.summary takes the same lock; collect outside the hold.
        if states:
            out["histograms"] = {
                n: {**h.summary(), "state": h.state()}
                for n, h in sorted(self._histograms.items())
            }
        else:
            out["histograms"] = {
                n: h.summary() for n, h in sorted(self._histograms.items())
            }
        if reset:
            self.reset()
        return out

    def reset(self) -> None:
        """Zero all metrics (kernel cache included). Mainly for tests/benches."""
        with self._lock:
            for c in self._counters.values():
                c._value = 0.0
            for g in self._gauges.values():
                g._value = 0.0
            for h in self._histograms.values():
                h._counts = [0] * (len(h.bounds) + 1)
                h._count = 0
                h._sum = 0.0
                h._min = None
                h._max = None
            self._kernel_cache = {"requests": 0, "misses": 0, "by_key": {}}


# ---------------------------------------------------------------------------
# cross-process aggregation (fleet /metrics)
# ---------------------------------------------------------------------------


def merge_histogram_states(states) -> Optional[dict]:
    """Merge :meth:`Histogram.state` dicts by summing bucket counts.

    The merge is **associative and commutative** (integer bucket sums,
    float sum accumulation, min/max of extrema — tests pin associativity
    in tests/test_obs_fleet.py), so the server can fold worker snapshots
    in any arrival order.  All inputs must share identical ``bounds``;
    mismatched bucket layouts raise ``ValueError`` rather than silently
    mis-binning.  Falsy entries are skipped; merging nothing returns None.
    """
    states = [s for s in states if s]
    if not states:
        return None
    bounds = list(states[0]["bounds"])
    counts = [0] * (len(bounds) + 1)
    count = 0
    total = 0.0
    mn = None
    mx = None
    for s in states:
        if list(s["bounds"]) != bounds:
            raise ValueError(
                f"cannot merge histograms with different bucket bounds "
                f"({len(s['bounds'])} vs {len(bounds)} buckets)")
        for i, c in enumerate(s["counts"]):
            counts[i] += c
        count += s["count"]
        total += s["sum"]
        if s["min"] is not None and (mn is None or s["min"] < mn):
            mn = s["min"]
        if s["max"] is not None and (mx is None or s["max"] > mx):
            mx = s["max"]
    return {"bounds": bounds, "counts": counts, "count": count,
            "sum": total, "min": mn, "max": mx}


def _state_quantile(state: dict, q: float):
    # Same bucket-upper-bound approximation as Histogram._quantile_locked.
    count = state["count"]
    if count == 0:
        return None
    target = q * count
    seen = 0
    bounds = state["bounds"]
    for i, c in enumerate(state["counts"]):
        seen += c
        if seen >= target:
            return bounds[i] if i < len(bounds) else state["max"]
    return state["max"]


def summarize_state(state: dict) -> dict:
    """:meth:`Histogram.summary`-schema dict computed from a state
    (merged or single); same bucket-upper-bound quantile approximation,
    so a quantile of a merged state is bounded below by the largest
    member's same-quantile bucket lower bound and above by its upper
    bound — the invariant the quantile-bounds test pins."""
    if not state or state["count"] == 0:
        return {"count": 0}
    return {
        "count": state["count"],
        "sum": state["sum"],
        "mean": state["sum"] / state["count"],
        "min": state["min"],
        "max": state["max"],
        "p50": _state_quantile(state, 0.50),
        "p90": _state_quantile(state, 0.90),
        "p95": _state_quantile(state, 0.95),
        "p99": _state_quantile(state, 0.99),
    }


def merge_snapshots(snaps) -> dict:
    """Fold registry snapshots from several processes into one fleet view.

    Counters and gauges **sum** across members (fleet trials/s is the sum
    of worker rates; occupancy and backlog likewise aggregate by sum —
    last-write gauges that don't sum meaningfully, like clock skew, are
    read from the per-worker labels instead).  Histograms merge exactly
    when members carry ``"state"`` (``snapshot(states=True)``); entries
    without state are skipped — summaries alone are not mergeable.
    """
    counters: dict = {}
    gauges: dict = {}
    hstates: dict = {}
    for snap in snaps:
        if not snap:
            continue
        for k, v in (snap.get("counters") or {}).items():
            counters[k] = counters.get(k, 0.0) + v
        for k, v in (snap.get("gauges") or {}).items():
            gauges[k] = gauges.get(k, 0.0) + v
        for k, h in (snap.get("histograms") or {}).items():
            st = h.get("state") if isinstance(h, dict) else None
            if st:
                hstates.setdefault(k, []).append(st)
    histograms = {}
    for k in sorted(hstates):
        merged = merge_histogram_states(hstates[k])
        entry = summarize_state(merged)
        entry["state"] = merged
        histograms[k] = entry
    return {
        "counters": dict(sorted(counters.items())),
        "gauges": dict(sorted(gauges.items())),
        "histograms": histograms,
    }


class LabelLru:
    """Bounded set of live metric labels with LRU eviction.

    Dynamic-label series (``health.verdict.<store>``, per-tenant verb
    counters) grow without bound under experiment churn.  Each emitting
    site keeps one ``LabelLru``; :meth:`touch` marks a label live and
    returns the labels evicted to stay under ``cap``.  The caller
    removes the evicted labels' series (``remove`` / ``remove_prefix``)
    — this class tracks recency only, so it stays usable for both
    exact-name gauges and per-tenant name prefixes.  Each eviction
    bumps ``obs.series_evicted``.

    ``cap`` falls back to ``HYPEROPT_TPU_SERIES_LABEL_CAP`` (default
    256), mirroring the ``HYPEROPT_TPU_RESIDENT_HISTORY_CAP`` pattern.
    """

    DEFAULT_CAP = 256

    def __init__(self, cap: Optional[int] = None,
                 reg: Optional[MetricsRegistry] = None):
        if cap is None:
            raw = os.environ.get("HYPEROPT_TPU_SERIES_LABEL_CAP", "")
            try:
                cap = int(raw) if raw else self.DEFAULT_CAP
            except ValueError:
                cap = self.DEFAULT_CAP
        self.cap = max(1, int(cap))
        self._reg = reg
        self._lock = threading.Lock()
        self._labels: dict = {}   # label -> None, insertion-ordered

    def touch(self, label: str) -> list:
        """Mark ``label`` most-recently-used; return evicted labels."""
        with self._lock:
            self._labels.pop(label, None)
            self._labels[label] = None
            evicted = []
            while len(self._labels) > self.cap:
                evicted.append(next(iter(self._labels)))
                del self._labels[evicted[-1]]
        if evicted:
            reg = self._reg if self._reg is not None else _REGISTRY
            reg.counter("obs.series_evicted").inc(len(evicted))
        return evicted

    def __len__(self) -> int:
        with self._lock:
            return len(self._labels)


_REGISTRY = MetricsRegistry()


def registry() -> MetricsRegistry:
    """The process-global registry."""
    return _REGISTRY


def metrics_enabled() -> bool:
    return _REGISTRY.enabled


def kernel_cache_event(key, hit: bool) -> None:
    """Record one ``tpe.get_kernel`` lookup. ``key``: the cache-key tuple.

    A miss means a fresh ``_TpeKernel`` was constructed — a new XLA
    program will be traced and compiled — so ``misses`` is the
    per-process compile-shape count (``benchmarks/atpe_profile.py``).
    """
    _REGISTRY.kernel_cache_event(key, hit)


def kernel_cache_stats(reset: bool = False) -> dict:
    """Snapshot (and optionally reset) the kernel-cache counters.

    Returns ``{"requests": int, "misses": int, "by_key": {repr(key):
    {"requests": int, "misses": int}}}`` — the same schema the counters
    had in ``utils/tracing.py``; ``benchmarks/atpe_profile.py`` and the
    ATPE tiering tests consume it unchanged.
    """
    return _REGISTRY.kernel_cache_stats(reset=reset)
