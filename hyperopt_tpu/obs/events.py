"""Bounded-buffer structured event log with Chrome trace_event export.

One process-global :class:`EventLog` (``EVENTS``) collects typed events
from the loop, the suggest algorithms and the parallel backends.  The
log is **disabled by default** — ``emit()``/``span()`` reduce to a single
attribute check — and is armed either explicitly or by constructing a
:class:`~hyperopt_tpu.obs.trace.Tracer` with a ``trace_dir``.

Event vocabulary (advisory, not enforced — see EVENT_TYPES):

* ``trial_start`` / ``trial_end`` — one pair per trial, carrying the tid
* ``suggest`` — one per suggest call (point event; the wall time lives
  in the enclosing ``span_begin/span_end`` pair emitted by the Tracer)
* ``compile`` — a kernel-cache miss (TPE kernel or device-loop run
  cache); each one is a fresh XLA compilation
* ``store_claim`` / ``store_write`` / ``store_flush`` — trial-store
  claim/result/persistence traffic
* ``worker_up`` / ``worker_down`` — parallel worker lifecycle
* ``transfer_borrow`` / ``transfer_drop`` — ATPE cross-run transfer
  decisions
* ``span_begin`` / ``span_end`` — nested named spans (suggest, evaluate,
  store, save, ...) with per-thread parent links

Each record carries ``t_mono`` (``time.perf_counter()``) and ``t_wall``
(epoch seconds, derived from a single wall/mono anchor pair so the two
clocks never disagree about ordering), the emitting thread, and the
enclosing span id.  Storage is a ``collections.deque(maxlen=capacity)``
ring buffer (``HYPEROPT_TPU_TRACE_BUFFER``, default 65536): a run that
out-lives the buffer keeps the most recent events instead of growing
without bound.

``to_chrome_trace()`` converts span pairs into ``"ph": "X"`` complete
events and everything else into ``"ph": "i"`` instants, microsecond
timestamps, which Perfetto / chrome://tracing load directly; because
``ts`` is epoch-anchored the host spans line up with ``jax.profiler``
device traces captured in the same run.
"""

from __future__ import annotations

import itertools
import json
import os
import socket
import threading
import time
from collections import deque
from contextlib import contextmanager

from . import context as _context

__all__ = ["EVENTS", "EventLog", "EVENT_TYPES", "DEFAULT_CAPACITY",
           "events_to_chrome"]

DEFAULT_CAPACITY = 65536

#: Advisory vocabulary for ``type`` — emit() accepts any string so new
#: subsystems can add events without touching this module, but everything
#: the core emits is listed here (tests pin the core set against it).
EVENT_TYPES = frozenset(
    {
        "trial_start",
        "trial_end",
        "suggest",
        "compile",
        "store_claim",
        "store_write",
        "store_flush",
        "store_requeue",
        "worker_up",
        "worker_down",
        "transfer_borrow",
        "transfer_drop",
        "span_begin",
        "span_end",
        "pipeline_dispatch",
        "pipeline_materialize",
        "pipeline_cancel",
        "pipeline_fallback",
        "fault_injected",
        "trial_retry",
        "trial_queued",
        "store_heartbeat",
        "rpc",
        "slo_alert",
        "flight_dump",
        "history_order_violation",
    }
)


def _capacity_from_env() -> int:
    raw = os.environ.get("HYPEROPT_TPU_TRACE_BUFFER", "")
    try:
        cap = int(raw) if raw else DEFAULT_CAPACITY
    except ValueError:
        cap = DEFAULT_CAPACITY
    return max(1, cap)


class EventLog:
    """Thread-safe bounded ring buffer of typed telemetry events."""

    def __init__(self, capacity: int | None = None):
        if capacity is None:
            capacity = _capacity_from_env()
        self.capacity = max(1, int(capacity))
        self._buf: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._span_ids = itertools.count(1)
        self._tls = threading.local()
        self._enabled = False
        self.n_emitted = 0  # total ever emitted (buffer may have dropped some)
        self.n_dropped = 0  # events the full ring displaced (overflow tally)
        # One wall/mono anchor pair: t_wall is always derived from t_mono so
        # the two clocks can never disagree about event ordering.
        self._wall0 = time.time()
        self._mono0 = time.perf_counter()
        # Process identity + clock anchor, exported as the first line of
        # dump_jsonl() so the cross-process merger (show.py merge_traces)
        # can clock-normalize and label each lane.  ``skew_s`` is this
        # process's estimated wall-clock offset *relative to the netstore
        # server* (set from heartbeat replies); the merger subtracts it.
        self._meta = {
            "pid": os.getpid(),
            "host": socket.gethostname(),
            "wall0": self._wall0,
            "mono0": self._mono0,
            "skew_s": 0.0,
        }

    # -- process metadata ------------------------------------------------
    def set_meta(self, **kw) -> None:
        """Attach/override header fields (worker_id, role, trace_id, skew_s)."""
        with self._lock:
            self._meta.update(kw)

    def meta(self) -> dict:
        with self._lock:
            return dict(self._meta)

    # -- arming ----------------------------------------------------------
    @property
    def enabled(self) -> bool:
        return self._enabled

    def enable(self) -> None:
        self._enabled = True

    def disable(self) -> None:
        self._enabled = False

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()
            self.n_emitted = 0
            self.n_dropped = 0

    # -- emission --------------------------------------------------------
    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def emit(self, etype: str, name=None, trial=None, **fields):
        """Record one point event; returns the record (or None if disabled).

        ``span``/``parent`` are filled from the calling thread's span
        stack unless passed explicitly in ``fields``.
        """
        if not self._enabled:
            return None
        mono = time.perf_counter()
        stack = self._stack()
        rec = {
            "type": etype,
            "t_mono": mono,
            "t_wall": self._wall0 + (mono - self._mono0),
            "thread": threading.current_thread().name,
        }
        if name is not None:
            rec["name"] = name
        if trial is not None:
            rec["trial"] = trial
        if "span" not in fields and stack:
            rec["span"] = stack[-1]
        rec.update(fields)
        # Ambient trace context (obs.context): events recorded while a
        # cross-process context is bound attach to the originating trial
        # even when the call site doesn't know the tid (fault injections,
        # RPC dispatch, store writes on behalf of a remote caller).
        if _context._armed:
            ctx = getattr(_context._tls, "ctx", None)
            if ctx:
                tid = ctx.get("trace_id")
                if tid is not None and "trace_id" not in rec:
                    rec["trace_id"] = tid
                if rec.get("trial") is None and ctx.get("tid") is not None:
                    rec["trial"] = ctx["tid"]
        with self._lock:
            if len(self._buf) == self.capacity:
                # deque(maxlen=...) silently displaces the oldest record;
                # tally it so coverage claims ("the ring holds the whole
                # run") stay honest in bundles and `show trace`.
                self.n_dropped += 1
            self._buf.append(rec)
            self.n_emitted += 1
        return rec

    @contextmanager
    def span(self, name: str, trial=None, **fields):
        """Nested named span: emits span_begin/span_end with parent links."""
        if not self._enabled:
            yield None
            return
        sid = next(self._span_ids)
        stack = self._stack()
        parent = stack[-1] if stack else None
        self.emit("span_begin", name=name, trial=trial, span=sid, parent=parent, **fields)
        stack.append(sid)
        try:
            yield sid
        finally:
            stack.pop()
            self.emit("span_end", name=name, trial=trial, span=sid, parent=parent)

    # -- readout ---------------------------------------------------------
    def snapshot(self) -> list:
        with self._lock:
            return list(self._buf)

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)

    def dump_jsonl(self, path) -> int:
        """Write one JSON object per line; returns the number of events.

        The first line is a ``{"type": "meta", ...}`` header carrying the
        process identity and wall/mono clock anchor (plus ``skew_s``, the
        heartbeat-estimated offset from the server clock) — the merger's
        clock-normalization input.  Readers that iterate records should
        skip ``type == "meta"``.
        """
        events = self.snapshot()
        with open(path, "w") as fh:
            fh.write(json.dumps({"type": "meta", **self.meta(),
                                 "n_emitted": self.n_emitted,
                                 "n_dropped": self.n_dropped}) + "\n")
            for rec in events:
                fh.write(json.dumps(rec) + "\n")
        return len(events)

    def to_chrome_trace(self, events: list | None = None) -> dict:
        """Render as Chrome ``trace_event`` JSON (Perfetto-loadable).

        Matched span_begin/span_end pairs become ``"ph": "X"`` complete
        events (ts/dur in µs, epoch-anchored); a begin whose end fell
        outside the ring buffer becomes a zero-duration ``"B"``-less
        instant rather than an unclosed nesting error; all other events
        become ``"ph": "i"`` instants.
        """
        if events is None:
            events = self.snapshot()
        out, _ = events_to_chrome(events, pid=os.getpid())
        return {"traceEvents": out, "displayTimeUnit": "ms"}

    def export_chrome_trace(self, path) -> int:
        trace = self.to_chrome_trace()
        with open(path, "w") as fh:
            json.dump(trace, fh)
        return len(trace["traceEvents"])


def events_to_chrome(events: list, pid: int | None = None, ts_fn=None):
    """Convert structured event records into Chrome ``trace_event`` dicts.

    The shared conversion core behind :meth:`EventLog.to_chrome_trace`
    (single process) and ``show.py``'s ``merge_traces`` (many processes):

    * ``pid`` — the lane the events render into (the merger assigns one
      per source process),
    * ``ts_fn`` — optional ``rec -> wall seconds`` override; the merger
      passes each file's own ``wall0 + (t_mono - mono0) - skew_s``
      normalization so lanes from different machines line up.

    Returns ``(trace_events, anchors)``: ``anchors`` is one
    ``(ts_us, pid, tid_lane, trial, type)`` tuple per converted record
    that carries a trial id — the attachment points for the merger's
    per-trial cross-lane flow arrows.  ``meta`` header records are
    skipped so a raw ``loop_events.jsonl`` can be fed directly.
    """
    if pid is None:
        pid = os.getpid()
    if ts_fn is None:
        ts_fn = lambda rec: rec["t_wall"]  # noqa: E731
    tids: dict = {}

    def _tid(thread_name):
        return tids.setdefault(thread_name, len(tids) + 1)

    open_spans: dict = {}
    out = []
    anchors = []

    def _anchor(rec, ts_us, lane):
        if rec.get("trial") is not None:
            anchors.append((ts_us, pid, lane, rec["trial"], rec["type"]))

    for rec in events:
        if rec.get("type") == "meta":
            continue
        ph_args = {
            k: v
            for k, v in rec.items()
            if k not in ("type", "name", "t_mono", "t_wall", "thread")
        }
        ts_us = ts_fn(rec) * 1e6
        if rec["type"] == "span_begin":
            open_spans[rec.get("span")] = rec
        elif rec["type"] == "span_end":
            begin = open_spans.pop(rec.get("span"), None)
            if begin is None:
                continue  # begin fell out of the ring buffer
            lane = _tid(begin["thread"])
            begin_us = ts_fn(begin) * 1e6
            out.append(
                {
                    "name": begin.get("name", "span"),
                    "ph": "X",
                    "ts": begin_us,
                    "dur": max(0.0, (rec["t_mono"] - begin["t_mono"]) * 1e6),
                    "pid": pid,
                    "tid": lane,
                    "cat": "hyperopt_tpu",
                    "args": {
                        k: v
                        for k, v in begin.items()
                        if k not in ("type", "name", "t_mono", "t_wall", "thread")
                    },
                }
            )
            _anchor(begin, begin_us, lane)
        else:
            lane = _tid(rec["thread"])
            out.append(
                {
                    "name": rec.get("name", rec["type"]),
                    "ph": "i",
                    "s": "t",
                    "ts": ts_us,
                    "pid": pid,
                    "tid": lane,
                    "cat": "hyperopt_tpu:" + rec["type"],
                    "args": ph_args,
                }
            )
            _anchor(rec, ts_us, lane)
    # Spans still open when the log was read: emit as zero-length marks
    # so the trace stays loadable.
    for begin in open_spans.values():
        out.append(
            {
                "name": begin.get("name", "span"),
                "ph": "i",
                "s": "t",
                "ts": ts_fn(begin) * 1e6,
                "pid": pid,
                "tid": _tid(begin["thread"]),
                "cat": "hyperopt_tpu:span_open",
                "args": {},
            }
        )
    out.sort(key=lambda e: e["ts"])
    return out, anchors


#: Process-global event log; disabled until a Tracer (or a test) arms it.
EVENTS = EventLog()
