"""Bounded-buffer structured event log with Chrome trace_event export.

One process-global :class:`EventLog` (``EVENTS``) collects typed events
from the loop, the suggest algorithms and the parallel backends.  The
log is **disabled by default** — ``emit()``/``span()`` reduce to a single
attribute check — and is armed either explicitly or by constructing a
:class:`~hyperopt_tpu.obs.trace.Tracer` with a ``trace_dir``.

Event vocabulary (advisory, not enforced — see EVENT_TYPES):

* ``trial_start`` / ``trial_end`` — one pair per trial, carrying the tid
* ``suggest`` — one per suggest call (point event; the wall time lives
  in the enclosing ``span_begin/span_end`` pair emitted by the Tracer)
* ``compile`` — a kernel-cache miss (TPE kernel or device-loop run
  cache); each one is a fresh XLA compilation
* ``store_claim`` / ``store_write`` / ``store_flush`` — trial-store
  claim/result/persistence traffic
* ``worker_up`` / ``worker_down`` — parallel worker lifecycle
* ``transfer_borrow`` / ``transfer_drop`` — ATPE cross-run transfer
  decisions
* ``span_begin`` / ``span_end`` — nested named spans (suggest, evaluate,
  store, save, ...) with per-thread parent links

Each record carries ``t_mono`` (``time.perf_counter()``) and ``t_wall``
(epoch seconds, derived from a single wall/mono anchor pair so the two
clocks never disagree about ordering), the emitting thread, and the
enclosing span id.  Storage is a ``collections.deque(maxlen=capacity)``
ring buffer (``HYPEROPT_TPU_TRACE_BUFFER``, default 65536): a run that
out-lives the buffer keeps the most recent events instead of growing
without bound.

``to_chrome_trace()`` converts span pairs into ``"ph": "X"`` complete
events and everything else into ``"ph": "i"`` instants, microsecond
timestamps, which Perfetto / chrome://tracing load directly; because
``ts`` is epoch-anchored the host spans line up with ``jax.profiler``
device traces captured in the same run.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from contextlib import contextmanager

__all__ = ["EVENTS", "EventLog", "EVENT_TYPES", "DEFAULT_CAPACITY"]

DEFAULT_CAPACITY = 65536

#: Advisory vocabulary for ``type`` — emit() accepts any string so new
#: subsystems can add events without touching this module, but everything
#: the core emits is listed here (tests pin the core set against it).
EVENT_TYPES = frozenset(
    {
        "trial_start",
        "trial_end",
        "suggest",
        "compile",
        "store_claim",
        "store_write",
        "store_flush",
        "store_requeue",
        "worker_up",
        "worker_down",
        "transfer_borrow",
        "transfer_drop",
        "span_begin",
        "span_end",
        "pipeline_dispatch",
        "pipeline_materialize",
        "pipeline_cancel",
        "pipeline_fallback",
        "fault_injected",
        "trial_retry",
    }
)


def _capacity_from_env() -> int:
    raw = os.environ.get("HYPEROPT_TPU_TRACE_BUFFER", "")
    try:
        cap = int(raw) if raw else DEFAULT_CAPACITY
    except ValueError:
        cap = DEFAULT_CAPACITY
    return max(1, cap)


class EventLog:
    """Thread-safe bounded ring buffer of typed telemetry events."""

    def __init__(self, capacity: int | None = None):
        if capacity is None:
            capacity = _capacity_from_env()
        self.capacity = max(1, int(capacity))
        self._buf: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._span_ids = itertools.count(1)
        self._tls = threading.local()
        self._enabled = False
        self.n_emitted = 0  # total ever emitted (buffer may have dropped some)
        # One wall/mono anchor pair: t_wall is always derived from t_mono so
        # the two clocks can never disagree about event ordering.
        self._wall0 = time.time()
        self._mono0 = time.perf_counter()

    # -- arming ----------------------------------------------------------
    @property
    def enabled(self) -> bool:
        return self._enabled

    def enable(self) -> None:
        self._enabled = True

    def disable(self) -> None:
        self._enabled = False

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()
            self.n_emitted = 0

    # -- emission --------------------------------------------------------
    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def emit(self, etype: str, name=None, trial=None, **fields):
        """Record one point event; returns the record (or None if disabled).

        ``span``/``parent`` are filled from the calling thread's span
        stack unless passed explicitly in ``fields``.
        """
        if not self._enabled:
            return None
        mono = time.perf_counter()
        stack = self._stack()
        rec = {
            "type": etype,
            "t_mono": mono,
            "t_wall": self._wall0 + (mono - self._mono0),
            "thread": threading.current_thread().name,
        }
        if name is not None:
            rec["name"] = name
        if trial is not None:
            rec["trial"] = trial
        if "span" not in fields and stack:
            rec["span"] = stack[-1]
        rec.update(fields)
        with self._lock:
            self._buf.append(rec)
            self.n_emitted += 1
        return rec

    @contextmanager
    def span(self, name: str, trial=None, **fields):
        """Nested named span: emits span_begin/span_end with parent links."""
        if not self._enabled:
            yield None
            return
        sid = next(self._span_ids)
        stack = self._stack()
        parent = stack[-1] if stack else None
        self.emit("span_begin", name=name, trial=trial, span=sid, parent=parent, **fields)
        stack.append(sid)
        try:
            yield sid
        finally:
            stack.pop()
            self.emit("span_end", name=name, trial=trial, span=sid, parent=parent)

    # -- readout ---------------------------------------------------------
    def snapshot(self) -> list:
        with self._lock:
            return list(self._buf)

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)

    def dump_jsonl(self, path) -> int:
        """Write one JSON object per line; returns the number written."""
        events = self.snapshot()
        with open(path, "w") as fh:
            for rec in events:
                fh.write(json.dumps(rec) + "\n")
        return len(events)

    def to_chrome_trace(self, events: list | None = None) -> dict:
        """Render as Chrome ``trace_event`` JSON (Perfetto-loadable).

        Matched span_begin/span_end pairs become ``"ph": "X"`` complete
        events (ts/dur in µs, epoch-anchored); a begin whose end fell
        outside the ring buffer becomes a zero-duration ``"B"``-less
        instant rather than an unclosed nesting error; all other events
        become ``"ph": "i"`` instants.
        """
        if events is None:
            events = self.snapshot()
        pid = os.getpid()
        tids: dict = {}

        def _tid(thread_name):
            return tids.setdefault(thread_name, len(tids) + 1)

        open_spans: dict = {}
        out = []
        for rec in events:
            ph_args = {
                k: v
                for k, v in rec.items()
                if k not in ("type", "name", "t_mono", "t_wall", "thread")
            }
            ts_us = rec["t_wall"] * 1e6
            if rec["type"] == "span_begin":
                open_spans[rec.get("span")] = rec
            elif rec["type"] == "span_end":
                begin = open_spans.pop(rec.get("span"), None)
                if begin is None:
                    continue  # begin fell out of the ring buffer
                out.append(
                    {
                        "name": begin.get("name", "span"),
                        "ph": "X",
                        "ts": begin["t_wall"] * 1e6,
                        "dur": max(0.0, (rec["t_mono"] - begin["t_mono"]) * 1e6),
                        "pid": pid,
                        "tid": _tid(begin["thread"]),
                        "cat": "hyperopt_tpu",
                        "args": {
                            k: v
                            for k, v in begin.items()
                            if k not in ("type", "name", "t_mono", "t_wall", "thread")
                        },
                    }
                )
            else:
                out.append(
                    {
                        "name": rec.get("name", rec["type"]),
                        "ph": "i",
                        "s": "t",
                        "ts": ts_us,
                        "pid": pid,
                        "tid": _tid(rec["thread"]),
                        "cat": "hyperopt_tpu:" + rec["type"],
                        "args": ph_args,
                    }
                )
        # Spans still open when the log was read: emit as zero-length marks
        # so the trace stays loadable.
        for begin in open_spans.values():
            out.append(
                {
                    "name": begin.get("name", "span"),
                    "ph": "i",
                    "s": "t",
                    "ts": begin["t_wall"] * 1e6,
                    "pid": pid,
                    "tid": _tid(begin["thread"]),
                    "cat": "hyperopt_tpu:span_open",
                    "args": {},
                }
            )
        out.sort(key=lambda e: e["ts"])
        return {"traceEvents": out, "displayTimeUnit": "ms"}

    def export_chrome_trace(self, path) -> int:
        trace = self.to_chrome_trace()
        with open(path, "w") as fh:
            json.dump(trace, fh)
        return len(trace["traceEvents"])


#: Process-global event log; disabled until a Tracer (or a test) arms it.
EVENTS = EventLog()
