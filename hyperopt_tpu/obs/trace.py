"""Per-run loop tracer: named spans, device traces, trace-dir artifacts.

:class:`Tracer` accumulates wall-clock totals per span name (under a
lock — ``overlap_suggest`` legitimately runs the suggest span on a
worker thread concurrently with evaluate, so the r5 unlocked-defaultdict
version lost increments), mirrors every span into the process-global
structured event log, and optionally drives ``jax.profiler`` device
traces.  Constructing a Tracer with a ``trace_dir`` arms the event log
for the run; ``dump()`` then writes three artifacts:

* ``loop_trace.json`` — per-phase summary (total_s/count/mean_ms per
  span, same schema as r4/r5) plus ``_wall`` attribution metadata
  (run wall time, seconds attributed to depth-0 spans, coverage
  fraction),
* ``loop_events.jsonl`` — the raw structured event log,
* ``chrome_trace.json`` — Chrome ``trace_event`` export of the same
  events (load in Perfetto or chrome://tracing).

:class:`NullTracer` is the disabled path ``fmin`` uses when no trace dir
is configured: its ``span`` is a single shared no-op context manager —
no clock read, no lock, no allocation — which is what keeps disabled
overhead under the <1% ``trials_per_sec`` budget (DESIGN.md §6).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import defaultdict
from contextlib import contextmanager
from typing import Optional

from . import context as _context
from .events import EVENTS

__all__ = ["Tracer", "NullTracer"]


class _NullSpan:
    """Reusable zero-cost context manager for the disabled path."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class Tracer:
    """Accumulates named wall-clock spans; optionally drives jax.profiler."""

    def __init__(self, trace_dir: Optional[str] = None,
                 device_trace: bool = False,
                 events=EVENTS):
        self.trace_dir = trace_dir
        # jax.profiler.start_trace drags in the TF import chain (~5 s) and
        # dominates short CPU runs; HYPEROPT_TPU_DEVICE_TRACE=0 keeps the
        # event/context layer while opting out of the device profiler.
        if os.environ.get("HYPEROPT_TPU_DEVICE_TRACE", "1").lower() in (
                "0", "false", "no"):
            device_trace = False
        self.device_trace = device_trace and trace_dir is not None
        self.events = events
        # Span totals are mutated from the main loop AND the
        # overlap_suggest worker thread — guard them (the old
        # utils/tracing.py defaultdicts were unlocked and racy).
        self._lock = threading.Lock()
        self.totals = defaultdict(float)
        self.counts = defaultdict(int)
        self._top_totals = defaultdict(float)  # depth-0 spans only
        self._depth = threading.local()
        self._started = False
        self._armed_events = False
        self._armed_context = False
        self.trace_id = None
        if trace_dir:
            os.makedirs(trace_dir, exist_ok=True)
            if not self.events.enabled:
                self.events.enable()
                self._armed_events = True
            # Cross-process trace context rides along with the event log:
            # a traced run stamps its RPCs and trial docs so server and
            # worker events attach to this run's trials (obs/context.py).
            if not _context.armed():
                _context.enable()
                self._armed_context = True
            self.trace_id = _context.new_trace_id()
            self.events.set_meta(trace_id=self.trace_id)
        self._t0 = time.perf_counter()
        self._wall_s = None

    # -- spans ---------------------------------------------------------------

    @contextmanager
    def span(self, name: str, trial=None):
        depth = getattr(self._depth, "n", 0)
        self._depth.n = depth + 1
        t0 = time.perf_counter()
        try:
            with self.events.span(name, trial=trial):
                yield
        finally:
            dt = time.perf_counter() - t0
            self._depth.n = depth
            with self._lock:
                self.totals[name] += dt
                self.counts[name] += 1
                if depth == 0:
                    self._top_totals[name] += dt

    # -- device traces -------------------------------------------------------

    def start_device_trace(self):
        if not self.device_trace or self._started:
            return
        try:
            import jax

            jax.profiler.start_trace(self.trace_dir)
            self._started = True
        except Exception:  # profiler unavailable on this backend
            self.device_trace = False

    def stop_device_trace(self):
        if not self._started:
            return
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception:
            pass
        self._started = False

    # -- summary -------------------------------------------------------------

    def summary(self) -> dict:
        out = {}
        with self._lock:
            items = sorted(self.totals.items())
            counts = dict(self.counts)
        for name, total in items:
            n = counts[name]
            out[name] = {"total_s": round(total, 6), "count": n,
                         "mean_ms": round(1e3 * total / max(n, 1), 3)}
        return out

    def set_wall(self, wall_s: float) -> None:
        """Pin the attribution denominator to the measured loop window.

        ``exhaust`` calls this with the wall time of the loop itself so
        observability overhead outside it (``jax.profiler.start_trace``
        alone costs seconds) doesn't dilute span coverage."""
        self._wall_s = float(wall_s)

    def attribution(self) -> dict:
        """Wall-time coverage: fraction attributed to depth-0 named spans.

        Depth-0 spans in the serial loop are disjoint, so their sum is a
        sound numerator; nested spans are excluded to avoid double
        counting.  The ≥95% acceptance check reads ``coverage``.
        """
        wall = self._wall_s
        if wall is None:
            wall = time.perf_counter() - self._t0
        with self._lock:
            attributed = sum(self._top_totals.values())
        return {
            "wall_s": round(wall, 6),
            "attributed_s": round(attributed, 6),
            "coverage": round(attributed / wall, 4) if wall > 0 else 0.0,
        }

    def dump(self) -> Optional[str]:
        if not self.trace_dir:
            return None
        doc = self.summary()
        doc["_wall"] = self.attribution()
        path = os.path.join(self.trace_dir, "loop_trace.json")
        with open(path, "w") as f:
            json.dump(doc, f, indent=2)
        if self.events.enabled:
            self.events.dump_jsonl(
                os.path.join(self.trace_dir, "loop_events.jsonl"))
            self.events.export_chrome_trace(
                os.path.join(self.trace_dir, "chrome_trace.json"))
        if self._armed_events:
            self.events.disable()
            self.events.clear()
            self._armed_events = False
        if self._armed_context:
            _context.disable()
            self._armed_context = False
        return path


class NullTracer(Tracer):
    """No-op tracer (no dir, no device traces, no event mirroring).

    ``span`` returns one preallocated no-op context manager: the
    per-span cost is an attribute load and two trivial ``__enter__`` /
    ``__exit__`` calls.  This is the default tracer on every ``fmin``
    without a trace dir, so it carries the <1% overhead budget.
    """

    def __init__(self):
        super().__init__(trace_dir=None, device_trace=False)

    def span(self, name: str, trial=None):
        return _NULL_SPAN
