"""Self-contained postmortem bundles: freeze telemetry to a directory.

A bundle is one directory written by the flight recorder (or pulled
over the wire via the read-only ``bundle`` verb) that carries everything
needed to reconstruct "what was this process doing when it died":

* ``MANIFEST.json`` — schema version, dump reason, process identity,
  the PR 6 ``trace_id``, event counts (emitted / dropped / captured)
  and the file list;
* ``loop_events.jsonl`` — the event ring **with its meta clock anchor
  header**, byte-compatible with a Tracer's dump, so
  ``hyperopt-tpu-show trace --merge BUNDLE_DIR ...`` splices the bundle
  straight into a fleet trace (same trace ids, same clock frame);
* ``metrics.json`` — full registry snapshot with mergeable histogram
  states; ``device.json`` — device-runtime report; ``costs.json`` —
  the per-kernel cost ledger; ``env.json`` — config snapshot with
  token-bearing values **redacted** before they reach disk;
* provider sections (``series.json`` / ``health.json`` / ``slo.json`` /
  ``wal.json``): a serving process registers callables
  (:func:`register_provider`) contributing its time-series window,
  health verdicts, SLO states and WAL tail offsets + store state hash.

``read_bundle`` loads a directory back into the payload dict;
``write_payload`` writes a payload pulled over RPC, so a remote shard's
flight dump lands on the operator's disk in the identical on-disk form.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading

from . import context as _context
from . import costs as _costs
from . import device as _device
from . import metrics as _metrics
from .events import EVENTS

__all__ = [
    "BUNDLE_SCHEMA",
    "collect_payload",
    "read_bundle",
    "register_provider",
    "unregister_provider",
    "write_bundle",
    "write_payload",
]

BUNDLE_SCHEMA = 1

#: Section name -> zero-arg callable returning a JSON-able payload.
_PROVIDERS: dict = {}
_PROVIDERS_LOCK = threading.Lock()

#: Env keys snapshotted into env.json (config provenance).
_ENV_PREFIXES = ("HYPEROPT_TPU_", "JAX_", "XLA_")
#: Key substrings whose values never reach disk.
_REDACT_MARKERS = ("TOKEN", "SECRET", "PASSWORD", "CREDENTIAL", "APIKEY",
                   "API_KEY", "AUTH")


def register_provider(name: str, fn) -> None:
    """Register a bundle section source (server-owned state the module
    globals can't see: time-series store, SLO monitor, health cache,
    WAL offsets).  Last registration per name wins."""
    with _PROVIDERS_LOCK:
        _PROVIDERS[name] = fn


def unregister_provider(name: str) -> None:
    with _PROVIDERS_LOCK:
        _PROVIDERS.pop(name, None)


def _redacted_env() -> dict:
    out = {}
    for k in sorted(os.environ):
        if not k.startswith(_ENV_PREFIXES):
            continue
        ku = k.upper()
        if any(m in ku for m in _REDACT_MARKERS):
            out[k] = "<redacted>"
        else:
            out[k] = os.environ[k]
    return out


def state_hash(data: bytes) -> str:
    """Short stable content hash for store-state cross-checks."""
    return hashlib.sha256(data).hexdigest()[:16]


def collect_payload(reason: str, extra: dict | None = None) -> dict:
    """Gather every section in-memory (the ``bundle`` verb's reply and
    :func:`write_bundle`'s input)."""
    meta = EVENTS.meta()
    events = EVENTS.snapshot()
    with _PROVIDERS_LOCK:
        providers = dict(_PROVIDERS)
    payload = {
        "manifest": {
            "schema": BUNDLE_SCHEMA,
            "reason": reason,
            "pid": meta.get("pid"),
            "host": meta.get("host"),
            "trace_id": meta.get("trace_id"),
            "n_events": len(events),
            "n_emitted": EVENTS.n_emitted,
            "n_dropped": EVENTS.n_dropped,
            "sections": [],
            "extra": extra or {},
        },
        "events": [{"type": "meta", **meta,
                    "n_dropped": EVENTS.n_dropped}] + events,
        "metrics": _metrics.registry().snapshot(states=True),
        "env": _redacted_env(),
    }
    for name, fn in (("device", _device.report),
                     ("costs", _costs.ledger_report)):
        try:
            payload[name] = fn()
        except Exception as e:   # a sick section must not sink the dump
            payload[name] = {"error": f"{type(e).__name__}: {e}"}
    if not payload["manifest"]["trace_id"] and _context._armed:
        cur = _context.current()
        if cur and cur.get("trace_id"):
            payload["manifest"]["trace_id"] = cur["trace_id"]
    for name, fn in sorted(providers.items()):
        try:
            payload[name] = fn()
        except Exception as e:   # a sick provider must not sink the dump
            payload[name] = {"error": f"{type(e).__name__}: {e}"}
    payload["manifest"]["sections"] = sorted(
        k for k in payload if k != "manifest")
    return payload


def write_payload(out_dir: str, payload: dict) -> str:
    """Write a payload dict as a bundle directory (local dump and the
    client side of a remote ``bundle`` pull share this path)."""
    os.makedirs(out_dir, exist_ok=True)
    events = payload.get("events") or []
    with open(os.path.join(out_dir, "loop_events.jsonl"), "w") as fh:
        for rec in events:
            fh.write(json.dumps(rec) + "\n")
    for name, doc in payload.items():
        if name == "events":
            continue
        fname = "MANIFEST.json" if name == "manifest" else f"{name}.json"
        with open(os.path.join(out_dir, fname), "w") as fh:
            json.dump(doc, fh, indent=1, default=str)
            fh.write("\n")
    return out_dir


def write_bundle(out_dir: str, reason: str,
                 extra: dict | None = None) -> str:
    """Freeze the current telemetry into ``out_dir``; returns it."""
    return write_payload(out_dir, collect_payload(reason, extra=extra))


def read_bundle(bundle_dir: str) -> dict:
    """Load a bundle directory back into its payload dict."""
    payload = {}
    ev_path = os.path.join(bundle_dir, "loop_events.jsonl")
    if os.path.exists(ev_path):
        events = []
        with open(ev_path) as fh:
            for line in fh:
                line = line.strip()
                if line:
                    events.append(json.loads(line))
        payload["events"] = events
    for fname in sorted(os.listdir(bundle_dir)):
        if not fname.endswith(".json"):
            continue
        name = ("manifest" if fname == "MANIFEST.json"
                else fname[:-len(".json")])
        try:
            with open(os.path.join(bundle_dir, fname)) as fh:
                payload[name] = json.load(fh)
        except ValueError:
            payload[name] = None
    if "manifest" not in payload:
        raise FileNotFoundError(
            f"{bundle_dir}: no MANIFEST.json — not a flight bundle")
    return payload
