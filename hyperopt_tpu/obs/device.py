"""Device-runtime telemetry: HBM held by resident history, kernel-cache
occupancy, transfer/donation counts.

The resident-history subsystem (``history.py``) keeps device buffers
alive across suggests — solo rings in ``history._STORE`` and fleet lane
stacks (``BatchedResident``) registered in ``history._BATCHED`` — but
until now nothing reported how much HBM they pin.  ``collect()`` walks
both tables **on demand** under ``history._LOCK`` (zero hot-path
overhead: no allocation or append is instrumented) and publishes:

* ``device.hbm.resident_bytes`` / ``device.hbm.resident_rings`` — live
  bytes and entry count across every solo resident ring, from the
  canonical buffer shapes (``cap × row_bytes(p)`` per ring, the same
  ``_row_bytes`` accounting the upload counters use);
* ``device.hbm.lane_stack_bytes`` / ``device.hbm.lane_stacks`` — the
  fleet twins, ``B × cap × row_bytes(p)`` per stack;
* ``device.kernel_cache.entries`` — distinct compiled-program cache
  keys seen by the always-on kernel-cache tap
  (``metrics.kernel_cache_stats``), i.e. occupancy per
  ``(backend, bucket-tier)`` key space;
* ``device.donated_programs`` (counter, emitted by ``history._fn``) —
  how many in-place-aliasing (donating) programs were built.

Cumulative transfer volume stays where it always was
(``history.upload_bytes``); ``report()`` folds it in so one call
answers "what is the device runtime holding and moving".
"""

from __future__ import annotations

from . import metrics as _metrics

__all__ = ["collect", "report"]


def _ring_bytes():
    """(n_rings, total_bytes, n_stacks, stack_bytes) under history._LOCK."""
    from .. import history as _hist
    rings = 0
    ring_b = 0
    stacks = 0
    stack_b = 0
    with _hist._LOCK:
        for states in list(_hist._STORE.values()):
            for res in list(states.values()):
                p = int(res.bufs[0].shape[-1]) if res.bufs else 0
                rings += 1
                ring_b += int(res.cap) * _hist._row_bytes(p)
        for st in list(_hist._BATCHED):
            stacks += 1
            stack_b += int(st.b) * int(st.cap) * _hist._row_bytes(int(st.p))
    # fmin_fleet's whole-loop lane stacks are plain arrays in the loop
    # frame, not BatchedResident entries — counted via the live handles
    # the loop registers.  sys.modules guard: a process that never
    # imported fleet has no stacks, and report() must not drag the
    # kernel stack in just to say so.
    import sys
    _fleet = sys.modules.get("hyperopt_tpu.fleet")
    if _fleet is not None:
        for h in list(_fleet._LANE_STACKS):
            stacks += 1
            stack_b += int(h.nbytes())
    return rings, ring_b, stacks, stack_b


def report() -> dict:
    """Point-in-time device-runtime report (no gauges touched)."""
    rings, ring_b, stacks, stack_b = _ring_bytes()
    kc = _metrics.kernel_cache_stats()
    return {
        "resident_rings": rings,
        "resident_bytes": ring_b,
        "lane_stacks": stacks,
        "lane_stack_bytes": stack_b,
        "kernel_cache": {
            "entries": len(kc.get("by_key", {})),
            "requests": kc.get("requests", 0),
            "misses": kc.get("misses", 0),
        },
    }


def collect(reg=None) -> dict:
    """Compute :func:`report` and publish it as gauges on ``reg``
    (default: the process registry).  Called by the netstore scrape
    loop so the HBM series land in the time-series store and the
    OpenMetrics exposition."""
    reg = reg if reg is not None else _metrics.registry()
    rep = report()
    reg.gauge("device.hbm.resident_bytes").set(rep["resident_bytes"])
    reg.gauge("device.hbm.resident_rings").set(rep["resident_rings"])
    reg.gauge("device.hbm.lane_stack_bytes").set(rep["lane_stack_bytes"])
    reg.gauge("device.hbm.lane_stacks").set(rep["lane_stacks"])
    reg.gauge("device.kernel_cache.entries").set(
        rep["kernel_cache"]["entries"])
    return rep
