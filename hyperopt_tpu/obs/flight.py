"""Flight recorder: always-on black box with freeze-and-dump triggers.

The event ring (``obs.EVENTS``) and the metrics registry are already
bounded, always-on accumulators — what dies with the process is the
*readout*.  The flight recorder closes that gap: :func:`install` arms a
process-wide dump directory, and any of four triggers freezes the
current telemetry into a self-contained postmortem **bundle**
(:mod:`~hyperopt_tpu.obs.bundle`) on disk:

* an SLO alert fires (:func:`on_slo_fired`, hooked from
  ``slo.SloMonitor``'s firing transition),
* an unhandled exception escapes ``fmin`` / the pipeline executor / a
  server verb dispatch (:func:`on_crash`),
* SIGTERM (the handler chains to whatever was installed before it),
* an explicit :func:`dump` request (``force=True`` bypasses the
  rate limit) — also what the read-only ``bundle`` verb serves.

Automatic triggers are rate-limited (``HYPEROPT_TPU_FLIGHT_MIN_INTERVAL_S``,
default 30 s) so an alert storm or a crash loop cannot fill the disk:
suppressed dumps bump ``flight.suppressed`` instead.  Each dump bumps
``flight.dumps``, emits a ``flight_dump`` event (visible in the very
bundle it triggered, and in later ones), and passes through the
``flight.dump`` fault point so chaos schedules can exercise the
failure path of the failure path.

Cost model: DISARMED (the default) every trigger hook is one
module-global boolean check — same discipline as ``obs.context`` /
``faults.py``, measured in ``benchmarks/obs_health.py``.  Armed cost is
only paid when a trigger actually fires.
"""

from __future__ import annotations

import os
import signal
import threading
import time

from . import bundle as _bundle
from . import context as _context
from . import metrics as _metrics
from .events import EVENTS

__all__ = [
    "armed",
    "dump",
    "install",
    "on_crash",
    "on_slo_fired",
    "uninstall",
]

DEFAULT_MIN_INTERVAL_S = 30.0

#: Module-global fast path: every trigger hook starts with ``if not _armed``.
_armed = False

_LOCK = threading.Lock()
_STATE = {
    "dir": None,
    "min_interval_s": DEFAULT_MIN_INTERVAL_S,
    "last_mono": None,    # monotonic time of the last successful dump
    "seq": 0,             # per-process dump counter (directory naming)
    "prev_sigterm": None,
    "sigterm_installed": False,
}


def armed() -> bool:
    return _armed


def _min_interval_from_env() -> float:
    raw = os.environ.get("HYPEROPT_TPU_FLIGHT_MIN_INTERVAL_S", "")
    try:
        return float(raw) if raw else DEFAULT_MIN_INTERVAL_S
    except ValueError:
        return DEFAULT_MIN_INTERVAL_S


def install(dump_dir: str | None = None, *, sigterm: bool = True,
            min_interval_s: float | None = None,
            arm_events: bool = True) -> str | None:
    """Arm the recorder.  ``dump_dir`` falls back to
    ``HYPEROPT_TPU_FLIGHT_DIR``; with neither set this is a no-op
    returning None (so callers can install unconditionally).

    ``arm_events=True`` enables the event ring if nothing else (a
    Tracer, a test) has — the black box records even in untraced
    processes.  ``sigterm=True`` chains a dump into the process's
    SIGTERM handling (best-effort: only possible from the main thread).
    Idempotent; re-installing updates the directory.
    """
    global _armed
    dump_dir = dump_dir or os.environ.get("HYPEROPT_TPU_FLIGHT_DIR") or None
    if not dump_dir:
        return None
    os.makedirs(dump_dir, exist_ok=True)
    with _LOCK:
        _STATE["dir"] = dump_dir
        _STATE["min_interval_s"] = (
            _min_interval_from_env() if min_interval_s is None
            else float(min_interval_s))
    if arm_events and not EVENTS.enabled:
        EVENTS.enable()
    if arm_events and not _context.armed():
        # Adopt incoming trace contexts too: a postmortem bundle from an
        # otherwise-untraced server still attributes its events to the
        # calling client's trace id (spliceable by id after a crash).
        _context.enable()
    if sigterm:
        _install_sigterm()
    _armed = True
    _metrics.registry().gauge("flight.armed").set(1.0)
    return dump_dir


def uninstall() -> None:
    """Disarm and restore any chained SIGTERM handler (tests)."""
    global _armed
    _armed = False
    _metrics.registry().gauge("flight.armed").set(0.0)
    with _LOCK:
        _STATE["dir"] = None
        _STATE["last_mono"] = None
        prev = _STATE["prev_sigterm"]
        installed = _STATE["sigterm_installed"]
        _STATE["prev_sigterm"] = None
        _STATE["sigterm_installed"] = False
    if installed:
        try:
            signal.signal(signal.SIGTERM,
                          prev if prev is not None else signal.SIG_DFL)
        except ValueError:    # non-main thread
            pass


def _install_sigterm() -> None:
    with _LOCK:
        if _STATE["sigterm_installed"]:
            return
    try:
        prev = signal.getsignal(signal.SIGTERM)
        signal.signal(signal.SIGTERM, _on_sigterm)
    except ValueError:        # not the main thread — skip, stay armed
        return
    with _LOCK:
        _STATE["prev_sigterm"] = prev
        _STATE["sigterm_installed"] = True


def _on_sigterm(signum, frame):
    dump("sigterm")
    prev = _STATE["prev_sigterm"]
    if callable(prev):
        prev(signum, frame)
    elif prev == signal.SIG_DFL:
        raise SystemExit(128 + int(signum))
    # SIG_IGN / None: swallow, matching the pre-install behavior.


def dump(reason: str, *, force: bool = False, extra: dict | None = None):
    """Freeze-and-dump one bundle; returns its directory path.

    Automatic triggers pass ``force=False`` and are rate-limited to one
    dump per ``min_interval_s`` (suppressions return None and bump
    ``flight.suppressed``).  Never raises: a failed dump is counted
    (``flight.errors``) and swallowed — the recorder must not turn a
    crash into a different crash.
    """
    if not _armed:
        return None
    reg = _metrics.registry()
    now = time.monotonic()
    with _LOCK:
        out_dir = _STATE["dir"]
        if out_dir is None:
            return None
        last = _STATE["last_mono"]
        if not force and last is not None and \
                (now - last) < _STATE["min_interval_s"]:
            suppressed = True
        else:
            suppressed = False
            _STATE["last_mono"] = now
            _STATE["seq"] += 1
            seq = _STATE["seq"]
    if suppressed:
        reg.counter("flight.suppressed").inc()
        return None
    try:
        from .. import faults as _faults
        _faults.maybe_fail("flight.dump", reason=reason)
        slug = "".join(c if c.isalnum() or c in "-_" else "-"
                       for c in reason)[:40] or "dump"
        path = os.path.join(out_dir,
                            f"bundle-{os.getpid()}-{seq:03d}-{slug}")
        EVENTS.emit("flight_dump", name=reason, path=path)
        _bundle.write_bundle(path, reason, extra=extra)
        reg.counter("flight.dumps").inc()
        return path
    except Exception:
        reg.counter("flight.errors").inc()
        return None


def on_slo_fired(name: str, **fields) -> None:
    """Trigger hook for ``SloMonitor``'s firing transition."""
    if not _armed:
        return
    dump(f"slo-{name}", extra={"trigger": "slo_alert", "slo": name,
                               **fields})


def on_crash(site: str, exc: BaseException) -> None:
    """Trigger hook for unhandled exceptions escaping ``fmin``, the
    pipeline executor, or a server dispatch.  ``KeyboardInterrupt`` and
    generator/system exits are operator intent, not crashes."""
    if not _armed:
        return
    if isinstance(exc, (KeyboardInterrupt, SystemExit, GeneratorExit)):
        return
    dump(f"crash-{site}",
         extra={"trigger": "crash", "site": site,
                "error": f"{type(exc).__name__}: {exc}"})
