"""Per-kernel cost attribution: compile wall time + XLA cost analysis.

Every kernel-cache compile site (``tpe.get_kernel``, the fleet vmap
tiers, ``backends/gp``, ``backends/es`` — the Pallas-EI variants are
distinct cache keys of the TPE kernel, so they get their own rows)
already feeds :func:`~hyperopt_tpu.obs.metrics.kernel_cache_event`.
This module adds the *cost* side of that accounting: on a cache miss,
an **armed** recorder AOT-lowers and compiles the program's hot entry
(``fn.lower(*shapes).compile()``) and records

* compile wall time,
* XLA ``cost_analysis`` (flops, bytes accessed) and
  ``memory_analysis`` (peak / argument / output / temp bytes) where the
  backend exposes them (best-effort: CPU backends may return nothing),

keyed by the **same** ``repr(key)`` the kernel-cache counters use, so
:func:`ledger_report` can join compile cost with live request counts
(``kernel_cache_stats()["by_key"]``) and per-dispatch wall times into
one ledger answering "ms and bytes per suggestion, by program".

Cost model: DISARMED (the default) every hook is a single module-global
boolean check — the same discipline as ``obs.context`` / ``faults.py``,
measured alongside them in ``benchmarks/obs_health.py`` against the
~66 ns/op budget.  ARMED (``HYPEROPT_TPU_COSTS=1`` or :func:`arm`), a
cache miss pays one extra AOT compile of the program it just built —
the serving compile itself is untouched — and a dispatch pays one
dict update under a lock.  Recording failures are contained: the
ledger must never break the serve path (``cost.errors`` counts them).
"""

from __future__ import annotations

import os
import threading
import time

from . import metrics as _metrics

__all__ = [
    "arm",
    "armed",
    "clear",
    "disarm",
    "ledger_report",
    "observe_dispatch",
    "record_compile",
]

#: Module-global fast path: every hook starts with ``if not _armed``.
_armed = os.environ.get("HYPEROPT_TPU_COSTS", "") in ("1", "on", "true")

_LOCK = threading.Lock()
#: repr(cache key) -> compile-cost entry (see record_compile).
_LEDGER: dict = {}
#: repr(cache key) -> live per-dispatch accumulator (see observe_dispatch).
_LIVE: dict = {}

#: Which shared live histograms attribute to which kernel family —
#: consulted by ledger_report for the "live" join of each entry.
_FAMILY_SERIES = {
    "tpe": ("suggest.upload_ms", "suggest.dispatch_ms",
            "suggest.fetch_sync_ms"),
    "fleet": ("suggest.upload_ms", "suggest.dispatch_ms",
              "suggest.fetch_sync_ms"),
    "gp": ("suggest.upload_ms", "backend.gp.dispatch_ms"),
    "es": ("suggest.upload_ms", "backend.es.dispatch_ms"),
    # Device-loop segments: one dispatch == one compiled scan segment
    # (obs.devtel backfills the histogram at each sync boundary).
    "device": ("device.telemetry.segment_ms",),
}


def armed() -> bool:
    return _armed


def arm() -> None:
    global _armed
    _armed = True


def disarm() -> None:
    global _armed
    _armed = False


def clear() -> None:
    """Drop all recorded entries (tests/benches)."""
    with _LOCK:
        _LEDGER.clear()
        _LIVE.clear()


def _cost_analysis(compiled) -> dict:
    """Best-effort XLA cost/memory analysis off a compiled program.

    ``cost_analysis()`` returns a dict (newer jax) or a list of dicts
    (one per computation, older jax); ``memory_analysis()`` returns an
    object with ``*_size_in_bytes`` attributes.  Either may be missing
    or raise on a given backend — absent numbers stay ``None`` rather
    than poisoning the entry.
    """
    out = {"flops": None, "bytes_accessed": None,
           "peak_memory_bytes": None, "argument_bytes": None,
           "output_bytes": None, "temp_bytes": None,
           "generated_code_bytes": None}
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if ca:
            out["flops"] = ca.get("flops")
            out["bytes_accessed"] = ca.get("bytes accessed")
    except Exception:
        pass
    try:
        ma = compiled.memory_analysis()
        if ma is not None:
            for field, attr in (
                    ("peak_memory_bytes", "temp_size_in_bytes"),
                    ("argument_bytes", "argument_size_in_bytes"),
                    ("output_bytes", "output_size_in_bytes"),
                    ("temp_bytes", "temp_size_in_bytes"),
                    ("generated_code_bytes",
                     "generated_code_size_in_bytes")):
                v = getattr(ma, attr, None)
                if v is not None:
                    out[field] = int(v)
            # Peak = arguments + outputs + temporaries when XLA gives the
            # pieces; keep temp alone if the others are absent.
            parts = [out["argument_bytes"], out["output_bytes"],
                     out["temp_bytes"]]
            if all(p is not None for p in parts):
                out["peak_memory_bytes"] = sum(parts)
    except Exception:
        pass
    return out


def record_compile(kernel: str, key, lower=None, *, n_cap=None, P=None,
                   m=None, tier=None, compile_s=None):
    """Record one kernel-cache **miss**'s compile cost.

    ``kernel`` is the family name (``tpe`` / ``fleet`` / ``gp`` /
    ``es``); ``key`` is the cache-key tuple the site also passed to
    ``kernel_cache_event`` — ``repr(key)`` is the join key.  ``lower``
    is a zero-arg callable performing the AOT lowering
    (``fn.lower(*shapes).compile()``) and returning the compiled
    program; it only runs when armed.  Alternatively a pre-measured
    ``compile_s`` may be passed.  Returns the ledger entry (or None
    when disarmed / on a contained failure).
    """
    if not _armed:
        return None
    reg = _metrics.registry()
    entry = {"kernel": kernel, "key": repr(key), "n_cap": n_cap, "P": P,
             "m": m, "tier": tier, "compile_s": compile_s}
    try:
        if lower is not None:
            t0 = time.perf_counter()
            compiled = lower()
            entry["compile_s"] = time.perf_counter() - t0
            entry.update(_cost_analysis(compiled))
    except Exception:
        reg.counter("cost.errors").inc()
        return None
    with _LOCK:
        _LEDGER[entry["key"]] = entry
        n = len(_LEDGER)
    reg.counter("cost.compiles").inc()
    if entry["compile_s"] is not None:
        reg.histogram("cost.compile_s").observe(entry["compile_s"])
    reg.gauge("cost.entries").set(n)
    return entry


def observe_dispatch(key, ms: float) -> None:
    """Attribute one live dispatch's wall time to its program.

    Called from the suggest hot paths with the same cache key the
    compile site used; disarmed cost is the module-global boolean.
    """
    if not _armed:
        return
    ks = repr(key)
    with _LOCK:
        acc = _LIVE.get(ks)
        if acc is None:
            acc = _LIVE[ks] = {"calls": 0, "total_ms": 0.0,
                               "min_ms": None, "max_ms": None}
        acc["calls"] += 1
        acc["total_ms"] += ms
        if acc["min_ms"] is None or ms < acc["min_ms"]:
            acc["min_ms"] = ms
        if acc["max_ms"] is None or ms > acc["max_ms"]:
            acc["max_ms"] = ms


def ledger_report(reg=None) -> dict:
    """The joined per-kernel cost ledger.

    One row per recorded compile, joined with the always-on kernel-cache
    request counts (same ``repr(key)``), the per-key live dispatch
    accumulator, and the family's shared ``suggest.*_ms`` /
    ``backend.*.dispatch_ms`` histogram summaries.  Derived columns:
    ``ms_per_suggestion`` (mean live dispatch ms / proposals per call)
    and ``bytes_per_suggestion`` (program bytes accessed / proposals).
    """
    reg = reg if reg is not None else _metrics.registry()
    kcs = _metrics.kernel_cache_stats()
    by_key = kcs.get("by_key", {})
    with _LOCK:
        entries = {k: dict(v) for k, v in _LEDGER.items()}
        live = {k: dict(v) for k, v in _LIVE.items()}
    snap = reg.snapshot()
    hists = snap.get("histograms", {})
    rows = []
    for ks in sorted(entries):
        e = entries[ks]
        cache = by_key.get(ks, {})
        e["requests"] = cache.get("requests", 0)
        e["misses"] = cache.get("misses", 0)
        acc = live.get(ks)
        if acc:
            e["dispatches"] = acc["calls"]
            e["dispatch_ms_mean"] = acc["total_ms"] / acc["calls"]
            e["dispatch_ms_min"] = acc["min_ms"]
            e["dispatch_ms_max"] = acc["max_ms"]
        m = e.get("m") or 1
        if acc:
            e["ms_per_suggestion"] = e["dispatch_ms_mean"] / m
        if e.get("bytes_accessed") is not None:
            e["bytes_per_suggestion"] = e["bytes_accessed"] / m
        rows.append(e)
    fams = sorted({e["kernel"] for e in rows} or _FAMILY_SERIES)
    live_series = {}
    for fam in fams:
        for name in _FAMILY_SERIES.get(fam, ()):
            h = hists.get(name)
            if h and h.get("count"):
                live_series[name] = {k: h.get(k) for k in
                                     ("count", "mean", "p50", "p95")}
    return {
        "entries": rows,
        "live_ms": live_series,
        "kernel_cache": {"requests": kcs.get("requests", 0),
                         "misses": kcs.get("misses", 0)},
        "armed": _armed,
    }
