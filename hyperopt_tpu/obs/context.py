"""Cross-process trace context: one trial, followed across every process.

A distributed fmin run spreads one trial's life over three processes —
the driver suggests it, the StoreServer claims/records it, a worker
evaluates it.  Each process has its own :class:`~.events.EventLog`, so
without a shared identity the three event streams cannot be stitched
back into one story.  This module carries that identity:

* ``trace_id`` — one 16-hex-char id per fmin run (the driver mints it),
* ``span`` — the emitting side's current span id (parent-span hint for
  cross-process nesting; informational, never required),
* ``tid`` — the trial id the current work belongs to.

The context is **thread-local** and **disabled by default**.  Arming
happens alongside the event log (a :class:`~.trace.Tracer` with a
``trace_dir`` arms both); when disarmed every entry point returns after
a single module-global boolean check — the same cost model as
``faults.maybe_fail`` (~65 ns/call, DESIGN.md §6) — so the stamping
sites in ``_Rpc.__call__`` and the suggest loop are free in production.

Wire format (documented in docs/API.md): the compact string
``"<trace_id>/<span>/<tid>"`` with empty segments for absent fields,
e.g. ``"9f2c51aa03b47d10//17"``.  It travels in two places:

* the ``ctx`` field of every netstore RPC body (stamped by
  :func:`wire_current` in the client, adopted by ``StoreServer._dispatch``),
* ``doc["misc"]["trace"]`` of every suggested trial document (stamped
  by :func:`stamp_misc` at insert, adopted by workers via
  :func:`bind_doc` before evaluating).

Adopting a context makes :meth:`EventLog.emit` auto-attach ``trace_id``
and ``trial`` to every event the process records while bound — which is
what lets ``hyperopt-tpu-show trace --merge`` draw per-trial flow
arrows across process lanes.
"""

from __future__ import annotations

import threading
import uuid

__all__ = [
    "armed",
    "enable",
    "disable",
    "new_trace_id",
    "current",
    "bind",
    "bind_doc",
    "adopt",
    "to_wire",
    "from_wire",
    "wire_current",
    "stamp_misc",
    "from_misc",
]

#: Module-global fast-path gate: False ⇒ every entry point is a no-op
#: after one boolean check (the disabled-path budget, DESIGN.md §6).
_armed = False

_tls = threading.local()


def armed() -> bool:
    return _armed


def enable() -> None:
    global _armed
    _armed = True


def disable() -> None:
    global _armed
    _armed = False


def new_trace_id() -> str:
    """Mint a run-scoped trace id (16 hex chars; the driver calls this)."""
    return uuid.uuid4().hex[:16]


def current() -> dict | None:
    """The calling thread's bound context, or None (also None when disarmed)."""
    if not _armed:
        return None
    return getattr(_tls, "ctx", None)


class _NullBind:
    """Shared no-op context manager for the disarmed path."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL = _NullBind()


class _Bind:
    """Swap the thread-local context in/out (restores the previous one)."""

    __slots__ = ("_ctx", "_prev")

    def __init__(self, ctx: dict):
        self._ctx = ctx
        self._prev = None

    def __enter__(self):
        self._prev = getattr(_tls, "ctx", None)
        _tls.ctx = self._ctx
        return self._ctx

    def __exit__(self, *exc):
        _tls.ctx = self._prev
        return False


def bind(trace_id=None, span=None, tid=None):
    """Context manager binding (and layering over) the thread's context.

    Fields left None inherit from the currently bound context; a no-op
    shared manager is returned when the subsystem is disarmed.
    """
    if not _armed:
        return _NULL
    ctx = dict(getattr(_tls, "ctx", None) or {})
    if trace_id is not None:
        ctx["trace_id"] = trace_id
    if span is not None:
        ctx["span"] = span
    if tid is not None:
        ctx["tid"] = tid
    return _Bind(ctx)


def bind_doc(doc):
    """Bind the context a trial document carries (worker side).

    Reads ``doc["misc"]["trace"]`` (stamped by the driver at insert) and
    falls back to the doc's own tid, so worker events attach to the
    originating trial even for docs inserted by an untraced driver.
    """
    if not _armed:
        return _NULL
    ctx = from_misc(doc.get("misc") or {}) or {}
    if ctx.get("tid") is None and doc.get("tid") is not None:
        ctx["tid"] = doc["tid"]
    return _Bind(ctx)


def adopt(wire):
    """Bind a context received off the wire (server side); no-op on junk."""
    if not _armed or not wire:
        return _NULL
    ctx = from_wire(wire)
    if not ctx:
        return _NULL
    return _Bind(ctx)


def to_wire(ctx: dict) -> str:
    """``{trace_id, span, tid}`` → ``"<trace_id>/<span>/<tid>"``."""
    span = ctx.get("span")
    tid = ctx.get("tid")
    return "%s/%s/%s" % (ctx.get("trace_id") or "",
                         "" if span is None else span,
                         "" if tid is None else tid)


def from_wire(wire) -> dict | None:
    """Inverse of :func:`to_wire`; None for malformed/empty strings."""
    if not wire:
        return None
    try:
        t, s, d = str(wire).split("/")
    except ValueError:
        return None
    ctx: dict = {}
    if t:
        ctx["trace_id"] = t
    for key, raw in (("span", s), ("tid", d)):
        if raw:
            try:
                ctx[key] = int(raw)
            except ValueError:
                pass
    return ctx or None


def wire_current() -> str | None:
    """The bound context as a wire string, or None (fast when disarmed)."""
    if not _armed:
        return None
    ctx = getattr(_tls, "ctx", None)
    if not ctx:
        return None
    return to_wire(ctx)


def stamp_misc(misc: dict, tid=None, trace_id=None) -> None:
    """Write the wire context into a trial doc's ``misc["trace"]``.

    Explicit ``tid``/``trace_id`` override the ambient context (the
    driver stamps each doc with its own tid).  No-op when disarmed —
    untraced runs produce byte-identical documents.
    """
    if not _armed:
        return
    ctx = dict(getattr(_tls, "ctx", None) or {})
    if trace_id is not None:
        ctx["trace_id"] = trace_id
    if tid is not None:
        ctx["tid"] = tid
    if ctx:
        misc["trace"] = to_wire(ctx)


def from_misc(misc) -> dict | None:
    """Parse a doc's ``misc["trace"]`` stamp; None if absent/malformed."""
    if not isinstance(misc, dict):
        return None
    return from_wire(misc.get("trace"))
