"""Per-experiment optimizer-health verdicts.

Turns raw trial history + backend introspection into one operator-facing
verdict per ``(tenant, exp_key)``:

* ``healthy``      — improving, non-degenerate, acquisition has signal
* ``warn``         — suspicious but not conclusive: high candidate
                     duplication or a degenerate TPE good/bad split
* ``stagnating``   — the best loss has not improved (relative to its
                     own scale) over the last ``window`` completed
                     trials
* ``ei_collapse``  — the surrogate's expected improvement has collapsed
                     to numerical noise relative to the observed loss
                     scale: the optimizer is proposing from a flat
                     acquisition surface (classic cause: a collapsed /
                     duplicated candidate set, or a GP fit to
                     zero-spread losses)

The history checks need only the trial docs.  The model-side checks go
through the **introspection hook** on the PR 10 backends contract: a
suggest callable may expose ``fn.introspect(domain, trials, seed=0)``
returning a diagnostics dict (GP: grid-selected log-marginal-likelihood
and candidate-sweep EI statistics; TPE: good/bad split sizes and
degeneracy).  ``assess()`` applies thresholds here so the hooks stay
pure diagnostics.

Verdicts are surfaced three ways: the read-only ``health`` service verb
(``NetTrials.health()``), ``health.verdict.<store>`` gauges (numeric
``VERDICT_CODE``), and the HEALTH panel in ``show live``.
"""

from __future__ import annotations

import functools
import math

from . import metrics as _metrics

__all__ = ["VERDICTS", "VERDICT_CODE", "assess", "publish"]

#: Severity-ordered verdict names; index = gauge code.
VERDICTS = ("healthy", "warn", "stagnating", "ei_collapse")
VERDICT_CODE = {name: i for i, name in enumerate(VERDICTS)}

_DONE = 2                       # base.JOB_STATE_DONE (no import cycle)


def _finite_losses(docs):
    """(tid-ordered losses of completed trials, n_docs_seen)."""
    done = []
    for d in docs:
        if d.get("state") != _DONE:
            continue
        loss = (d.get("result") or {}).get("loss")
        if loss is None:
            continue
        loss = float(loss)
        if math.isfinite(loss):
            done.append((d.get("tid", 0), loss))
    done.sort()
    return [l for _, l in done]


def _dup_rate(docs, window):
    """Duplicate fraction among the last ``window`` suggested points
    (rounded param fingerprints from ``misc.vals``)."""
    tail = sorted(docs, key=lambda d: d.get("tid", 0))[-window:]
    if len(tail) < 2:
        return None
    prints = []
    for d in tail:
        vals = ((d.get("misc") or {}).get("vals") or {})
        fp = tuple(sorted(
            (k, round(float(v[0]), 9) if v else None)
            for k, v in vals.items()))
        prints.append(fp)
    return 1.0 - len(set(prints)) / len(prints)


def unwrap(fn):
    """Peel keyword-only ``functools.partial`` wrappers (registry
    variants) down to the callable that carries the hook attributes —
    the same unwrapping rule as ``contract.halves_of``."""
    while isinstance(fn, functools.partial):
        fn = fn.func
    return fn


def assess(docs, domain=None, trials=None, suggest_fn=None, *,
           window: int = 20, min_trials: int = 8,
           stagnation_tol: float = 1e-3, dup_tol: float = 0.5,
           ei_tol: float = 1e-3, introspect: bool = True,
           seed: int = 0) -> dict:
    """Health report for one experiment.

    ``docs`` drive the history checks; ``domain``/``trials`` (plus the
    backend's ``suggest_fn``) enable the introspection checks when all
    three are present and ``introspect`` is True.  Thresholds:

    * stagnation — relative best-loss improvement over the trailing
      ``window`` completed trials below ``stagnation_tol`` (evaluated
      once ``len >= min_trials`` and there is pre-window history);
    * duplication — fraction of repeated candidate fingerprints in the
      trailing window above ``dup_tol``;
    * EI collapse — introspected ``ei_rel`` (best candidate EI in raw
      loss units over the observed loss scale) below ``ei_tol``.
    """
    losses = _finite_losses(docs)
    n_done = len(losses)
    report = {
        "n_trials": len(docs),
        "n_done": n_done,
        "best_loss": min(losses) if losses else None,
        "checks": {},
        "introspection": None,
    }
    checks = report["checks"]

    # -- best-loss plateau / stagnation --------------------------------------
    stagnating = None
    if n_done >= max(min_trials, window + 1):
        best_before = min(losses[:-window])
        best_now = min(losses)
        scale = max(abs(best_before), 1e-12)
        improvement = (best_before - best_now) / scale
        checks["improvement_rel"] = improvement
        stagnating = improvement < stagnation_tol
    checks["stagnating"] = stagnating

    # -- candidate-set duplication -------------------------------------------
    dup = _dup_rate(docs, window)
    checks["dup_rate"] = dup
    checks["dup_high"] = None if dup is None else dup > dup_tol

    # -- backend introspection -----------------------------------------------
    ei_collapse = None
    split_degenerate = None
    if introspect and suggest_fn is not None and domain is not None \
            and trials is not None:
        hook = getattr(unwrap(suggest_fn), "introspect", None)
        if hook is not None:
            try:
                info = dict(hook(domain, trials, seed=seed))
            except Exception as e:   # diagnostics must never break serving
                info = {"error": f"{type(e).__name__}: {e}"}
            report["introspection"] = info
            if not info.get("insufficient") and "error" not in info:
                ei_rel = info.get("ei_rel")
                if ei_rel is not None:
                    ei_collapse = ei_rel < ei_tol
                if info.get("split_degenerate") is not None:
                    split_degenerate = bool(info["split_degenerate"])
    checks["ei_collapse"] = ei_collapse
    checks["split_degenerate"] = split_degenerate

    if ei_collapse:
        verdict = "ei_collapse"
    elif stagnating:
        verdict = "stagnating"
    elif checks["dup_high"] or split_degenerate:
        verdict = "warn"
    else:
        verdict = "healthy"
    report["verdict"] = verdict
    report["code"] = VERDICT_CODE[verdict]
    return report


# Bounded live-label set: experiment churn would otherwise grow one
# ``health.verdict.<store>`` gauge per store ever assessed.  Evictions
# bump ``obs.series_evicted`` (HYPEROPT_TPU_SERIES_LABEL_CAP caps it).
_VERDICT_LABELS = _metrics.LabelLru()


def publish(label: str, report: dict, reg=None) -> None:
    """Publish one report as the ``health.verdict.<store>`` gauge
    (value: ``VERDICT_CODE``) and bump ``health.assessments``.  The
    live gauge set is LRU-bounded; the verdict for an evicted store is
    republished on its next assessment."""
    reg = reg if reg is not None else _metrics.registry()
    for old in _VERDICT_LABELS.touch(label):
        reg.remove(f"health.verdict.{old}")
    reg.gauge(f"health.verdict.{label}").set(report["code"])
    reg.counter("health.assessments").inc()
