"""Declarative SLOs with multi-window burn-rate alerting.

An :class:`SloSpec` names an objective over one metric series:

* ``latency_p95`` — at most ``budget`` (default 5%) of observations in
  a window may exceed ``target`` seconds.  Evaluated from *windowed*
  histogram states (cumulative-state differencing in
  ``obs.timeseries``), so the tail fraction is exact at bucket
  resolution with no per-observation cost.
* ``gauge_min`` / ``gauge_max`` — at most ``budget`` of window samples
  may sit below/above ``target`` (worker-liveness fraction, WAL fsync
  lag).

Burn rate is the classic SRE ratio: ``violating fraction / budget`` —
1.0 means the error budget burns exactly as fast as it accrues.  The
monitor evaluates each spec over a **fast** and a **slow** window and

* **fires** when *both* burn rates reach ``burn_threshold`` (the slow
  window proves it's not a blip, the fast window proves it's still
  happening);
* **clears** when the fast-window burn drops back under the threshold
  (the standard asymmetry: recovery is visible in the fast window
  first; no evaluation data leaves the state untouched).

Transitions emit typed ``slo_alert`` events (``state: firing |
resolved``) into the event log — they ride the normal trace dump/merge
pipeline and render as instants in Perfetto and in the ALERTS panel of
``show live`` — and bump ``slo.alerts.fired`` / ``slo.alerts.resolved``.
Continuous state is published as ``slo.<name>.firing`` /
``slo.<name>.burn_fast`` / ``slo.<name>.burn_slow`` /
``slo.<name>.value`` gauges.  A firing transition additionally pokes
the flight recorder (``obs.flight.on_slo_fired``) so an armed process
freezes a postmortem bundle the moment the budget burns.

The declared default specs (``default_slos``) are reconciled against
the docs/API.md catalog by analyzer rules RD009/RD010.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

from . import events as _events
from . import flight as _flight
from . import metrics as _metrics

__all__ = ["SloSpec", "SloMonitor", "default_slos"]

_KINDS = ("latency_p95", "gauge_min", "gauge_max")


@dataclass(frozen=True)
class SloSpec:
    """One service-level objective over one metric series."""

    name: str                   #: catalog key (RD009/RD010 reconciled)
    metric: str                 #: registry series the objective reads
    kind: str = "latency_p95"   #: latency_p95 | gauge_min | gauge_max
    target: float = 1.0         #: threshold in the metric's units
    budget: float = 0.05        #: allowed violating fraction per window
    fast_window: float = 60.0   #: seconds; fires AND clears here
    slow_window: float = 300.0  #: seconds; must corroborate to fire
    burn_threshold: float = 1.0

    def __post_init__(self):
        if self.kind not in _KINDS:
            raise ValueError(f"SloSpec kind {self.kind!r}: want {_KINDS}")
        if not (0.0 < self.budget <= 1.0):
            raise ValueError("SloSpec budget must be in (0, 1]")


def default_slos() -> tuple:
    """The served defaults: suggest-verb tail latency, worker liveness,
    WAL fsync lag — one per failure plane (compute, fleet, durability)."""
    return (
        SloSpec("suggest_p95", metric="netstore.verb.suggest.s",
                kind="latency_p95", target=0.25, budget=0.05),
        SloSpec("worker_liveness", metric="fleet.live_fraction",
                kind="gauge_min", target=0.9, budget=0.1),
        SloSpec("wal_fsync_lag", metric="wal.fsync_lag_s",
                kind="gauge_max", target=1.0, budget=0.1),
    )


class SloMonitor:
    """Evaluates specs against a :class:`~.timeseries.TimeSeriesStore`
    and owns the per-spec alert state machine."""

    def __init__(self, specs, store, reg=None, events=None):
        self.specs = tuple(specs)
        self.store = store
        self._reg = reg
        self._events = events
        self._state = {s.name: {"firing": False, "since": None}
                       for s in self.specs}
        self._last: list = []

    def registry(self):
        return self._reg if self._reg is not None else _metrics.registry()

    def _events_log(self):
        return self._events if self._events is not None else _events.EVENTS

    def _frac_bad(self, spec, window, now):
        if spec.kind == "latency_p95":
            return self.store.window_frac_above(spec.metric, spec.target,
                                                window, now=now)
        samples = self.store.samples(spec.metric, window_s=window, now=now)
        if not samples:
            return None
        if spec.kind == "gauge_min":
            bad = sum(1 for _, v in samples if v < spec.target)
        else:
            bad = sum(1 for _, v in samples if v > spec.target)
        return bad / len(samples)

    def _value(self, spec, now):
        if spec.kind == "latency_p95":
            return self.store.window_quantile(spec.metric, 0.95,
                                              spec.fast_window, now=now)
        samples = self.store.samples(spec.metric,
                                     window_s=spec.fast_window, now=now)
        return samples[-1][1] if samples else None

    def evaluate(self, now: float | None = None) -> list:
        """One evaluation pass; returns the per-spec status list (also
        retrievable via :meth:`status`)."""
        now = time.time() if now is None else float(now)
        reg = self.registry()
        log = self._events_log()
        out = []
        for spec in self.specs:
            st = self._state[spec.name]
            frac_fast = self._frac_bad(spec, spec.fast_window, now)
            frac_slow = self._frac_bad(spec, spec.slow_window, now)
            burn_fast = (None if frac_fast is None
                         else frac_fast / spec.budget)
            burn_slow = (None if frac_slow is None
                         else frac_slow / spec.budget)
            if not st["firing"]:
                if burn_fast is not None and burn_slow is not None and \
                        burn_fast >= spec.burn_threshold and \
                        burn_slow >= spec.burn_threshold:
                    st["firing"] = True
                    st["since"] = now
                    reg.counter("slo.alerts.fired").inc()
                    log.emit("slo_alert", name=spec.name, state="firing",
                             metric=spec.metric, target=spec.target,
                             burn_fast=burn_fast, burn_slow=burn_slow)
                    _flight.on_slo_fired(spec.name, metric=spec.metric,
                                         burn_fast=burn_fast,
                                         burn_slow=burn_slow)
            else:
                if burn_fast is not None and \
                        burn_fast < spec.burn_threshold:
                    st["firing"] = False
                    st["since"] = None
                    reg.counter("slo.alerts.resolved").inc()
                    log.emit("slo_alert", name=spec.name, state="resolved",
                             metric=spec.metric, target=spec.target,
                             burn_fast=burn_fast, burn_slow=burn_slow)
            value = self._value(spec, now)
            reg.gauge(f"slo.{spec.name}.firing").set(
                1.0 if st["firing"] else 0.0)
            if burn_fast is not None:
                reg.gauge(f"slo.{spec.name}.burn_fast").set(burn_fast)
            if burn_slow is not None:
                reg.gauge(f"slo.{spec.name}.burn_slow").set(burn_slow)
            if value is not None:
                reg.gauge(f"slo.{spec.name}.value").set(value)
            out.append({
                "name": spec.name, "kind": spec.kind,
                "metric": spec.metric, "target": spec.target,
                "value": value, "burn_fast": burn_fast,
                "burn_slow": burn_slow, "firing": st["firing"],
                "since": st["since"],
            })
        self._last = out
        return out

    def status(self) -> list:
        """Most recent :meth:`evaluate` result (empty before the first
        pass)."""
        return list(self._last)

    def alerts(self) -> list:
        return [s for s in self._last if s["firing"]]
