"""Device-loop telemetry: sync-boundary backfill into the obs stack.

The device-resident loop (``fmin(mode="device")``, ``fleet.fmin_fleet``)
runs suggest → evaluate → record inside compiled ``lax.scan`` segments,
so between sync boundaries every obs layer is blind — at
``sync_stride=None`` a whole run lands as one opaque fetch.  ISSUE 17
closes that hole with a **telemetry slab**: a small fixed-shape struct
of per-trial aggregates computed inside the compiled segment as a pure
passenger — per-step scan outputs, reduced vectorized after the scan so
the loop body pays three stores, not a carried reduction
(``device._build_segment``):

* best-so-far loss trajectory, downsampled into a ``RESERVOIR``-slot
  ring (slot ``t * R // s`` for segment step ``t`` of ``s``),
* per-segment EI max / mean over TPE steps (winning-score surrogate,
  log density-ratio units — comparable within one run only),
* non-finite-loss count and candidate-argmax tie count
  (``ops/step_ei.py::ei_argmax_stats`` — the flat-acquisition signal),
* per-lane twins under ``fmin_fleet`` (the slab vmaps with the segment).

The slab rides the SAME bulk fetch as the trial slab — zero extra sync
boundaries (``device.fetch_syncs`` deltas are pinned by tests) — and
this module **backfills** it into the hosted layers as if the trials had
run hosted:

* ``obs.events`` — a back-dated ``device_segment`` span plus synthetic
  per-trial ``trial_end`` anchors spread uniformly across the measured
  segment wall window, every record marked ``synthetic=True`` (solo mode
  only; fleet segments emit the span but not B×s per-trial anchors), so
  ``hyperopt-tpu-show trace`` / ``--merge`` Perfetto lanes stay coherent;
* ``obs.metrics`` — ``device.fetch_syncs.<mode>.<stride>`` /
  ``device.segments.<mode>.<stride>`` labeled twins of the unlabeled
  counters (LRU-bounded like every dynamic-label family) plus the slab
  gauges/counters/histograms under ``device.telemetry.*``;
* the time-series store — when a store is registered via
  :func:`set_backfill_store`, each boundary scrapes it at the segment's
  end wall time, so per-segment rows (and therefore SLO burn rates)
  exist for device-mode runs;
* ``obs.health`` — the run's landed docs are assessed at the final
  boundary and published as ``health.verdict.device:<label>``;
* ``obs.costs`` — per-segment dispatch wall times via
  ``observe_dispatch`` (compile rows recorded by the loop on run-cache
  misses) under the ``device`` family;
* flight-recorder bundles — the latest slab per run is served by the
  ``device_telemetry`` bundle provider.

Armed vs. disarmed is **bit-identical** in sampled trials: the slab only
consumes tensors the proposal math already computes
(``tpe._TpeKernel._suggest_one_tel``), never feeds them, and the toggle
(``HYPEROPT_TPU_DEVICE_TELEMETRY``, default on) is keyed into the
segment run caches so flipping it can never serve a stale program.
Everything in this module is host-side, boundary-rate work — nothing
here touches the traced programs.
"""

from __future__ import annotations

import os
import weakref
from collections import OrderedDict
from threading import Lock

import numpy as np

from . import bundle as _bundle
from . import costs as _costs
from . import health as _health
from . import metrics as _metrics
from .events import EVENTS

__all__ = ["RESERVOIR", "enabled", "bump_labeled", "slab_host",
           "backfill_segment", "finish_run", "set_backfill_store",
           "backfill_store", "report"]

#: Slots in the best-so-far trajectory ring carried through each segment.
RESERVOIR = 32


def enabled() -> bool:
    """``HYPEROPT_TPU_DEVICE_TELEMETRY`` — default ON; the device loop
    reads this once per run and keys it into its compiled-segment cache."""
    return os.environ.get("HYPEROPT_TPU_DEVICE_TELEMETRY", "1").lower() \
        not in ("0", "off", "false")


# Labeled-series bookkeeping: <mode>.<stride> labels are caller inputs,
# so the live set is LRU-bounded exactly like health.verdict.<store>.
_LABELS = _metrics.LabelLru()

# Latest slab per (mode, label) for the flight-bundle provider; bounded
# because labels are caller-controlled.
_LAST_CAP = 8
_LAST: "OrderedDict" = OrderedDict()
_LAST_LOCK = Lock()
_PROVIDER_REGISTERED = False

#: Optional weakref to a TimeSeriesStore scraped at each sync boundary.
_STORE_REF = None


def set_backfill_store(store) -> None:
    """Register ``store`` (a :class:`~hyperopt_tpu.obs.timeseries.
    TimeSeriesStore`, or ``None`` to clear) to receive one scrape per
    sync boundary, timestamped at the segment's END wall time — the
    back-dated per-segment rows health/SLO evaluation reads.  Held by
    weakref: a store owned by a server scrape loop dies with it."""
    global _STORE_REF
    _STORE_REF = None if store is None else weakref.ref(store)


def backfill_store():
    return _STORE_REF() if _STORE_REF is not None else None


def bump_labeled(reg, mode: str, stride: str) -> None:
    """Bump the ``<mode>.<stride>``-labeled twins of the unlabeled
    ``device.fetch_syncs`` / ``device.segments`` counters (which keep
    their exact semantics — tests pin their deltas)."""
    label = f"{mode}.{stride}"
    for old in _LABELS.touch(label):
        reg.remove(f"device.fetch_syncs.{old}")
        reg.remove(f"device.segments.{old}")
    reg.counter(f"device.fetch_syncs.{label}").inc()
    reg.counter(f"device.segments.{label}").inc()


def slab_host(slab) -> dict:
    """Fetch a device slab tuple to host scalars/arrays.

    ``slab`` is ``(best, ei_max, ei_sum, n_tpe, n_nonfinite, n_ties,
    bsf[R])`` — scalars per segment, or lane-stacked ``[B]``/``[B, R]``
    under ``fmin_fleet``.  Rides the same device→host sync as the trial
    slab (the program already completed; no extra dispatch).
    """
    best, ei_max, ei_sum, n_tpe, n_bad, n_ties, bsf = (
        np.asarray(x) for x in slab)
    return {"best_loss": best, "ei_max": ei_max, "ei_sum": ei_sum,
            "tpe_steps": n_tpe, "nonfinite": n_bad,
            "argmax_ties": n_ties, "best_trajectory": bsf}


def _emit_backdated(etype, mono, **fields):
    """Emit one event with an explicit back-dated timestamp pair derived
    from the log's own wall/mono anchor (so ordering vs live events stays
    consistent); every synthesized record carries ``synthetic=True``."""
    wall = EVENTS._wall0 + (mono - EVENTS._mono0)
    return EVENTS.emit(etype, t_mono=mono, t_wall=wall, synthetic=True,
                       **fields)


def _aggregate(h: dict) -> dict:
    """Collapse a (possibly lane-stacked) host slab to run-level scalars:
    best = min over lanes, ei_max = max, counts summed, ei mean over all
    TPE steps pooled across lanes."""
    n_tpe = int(h["tpe_steps"].sum())
    ei_sum = float(h["ei_sum"].sum())
    return {
        "best_loss": float(h["best_loss"].min()),
        "ei_max": float(h["ei_max"].max()),
        "ei_mean": (ei_sum / n_tpe) if n_tpe else None,
        "tpe_steps": n_tpe,
        "nonfinite": int(h["nonfinite"].sum()),
        "argmax_ties": int(h["argmax_ties"].sum()),
    }


def backfill_segment(reg, *, mode: str, stride: str, slab_h: dict,
                     n_trials: int, n_lanes: int, t0_mono: float,
                     t1_mono: float, seg_index: int, cost_key=None,
                     tids=None, label=None) -> dict:
    """Backfill ONE segment's slab into events / metrics / costs / the
    time-series store.  ``t0_mono``/``t1_mono`` bracket the segment's
    host wall window (dispatch → fetch landed); ``tids`` (solo mode)
    are the landed trial ids for the synthetic per-trial anchors.
    Returns the aggregated slab summary (also cached for bundles).
    """
    agg = _aggregate(slab_h)
    dur = max(t1_mono - t0_mono, 0.0)
    total = n_trials * max(n_lanes, 1)

    # -- metrics: slab gauges + counters + the per-segment histogram -----
    if np.isfinite(agg["best_loss"]):
        reg.gauge("device.telemetry.best_loss").set(agg["best_loss"])
    if np.isfinite(agg["ei_max"]):
        reg.gauge("device.telemetry.ei_max").set(agg["ei_max"])
    if agg["ei_mean"] is not None and np.isfinite(agg["ei_mean"]):
        reg.gauge("device.telemetry.ei_mean").set(agg["ei_mean"])
    if agg["nonfinite"]:
        reg.counter("device.telemetry.nonfinite").inc(agg["nonfinite"])
    if agg["argmax_ties"]:
        reg.counter("device.telemetry.argmax_ties").inc(
            agg["argmax_ties"])
    reg.histogram("device.telemetry.segment_ms").observe(dur * 1e3)
    if dur > 0:
        reg.gauge("device.telemetry.trials_per_sec").set(total / dur)

    # -- events: back-dated segment span + synthetic trial anchors -------
    if EVENTS.enabled:
        sid = next(EVENTS._span_ids)
        _emit_backdated("span_begin", t0_mono, name="device_segment",
                        span=sid, parent=None, mode=mode, stride=stride,
                        seg=seg_index, n_trials=n_trials,
                        n_lanes=n_lanes)
        if tids is not None and n_trials:
            # Uniform spread across the measured window: the host cannot
            # know per-trial device timing, only the bulk boundary — the
            # "synthetic" mark is the honesty bit readers filter on.
            step = dur / n_trials
            for k, tid in enumerate(tids):
                _emit_backdated("trial_end", t0_mono + (k + 0.5) * step,
                                name="device_trial", trial=int(tid),
                                span=sid, mode=mode, seg=seg_index)
        _emit_backdated("span_end", t1_mono, name="device_segment",
                        span=sid, parent=None)

    # -- costs: per-segment dispatch row under the device family --------
    if cost_key is not None:
        _costs.observe_dispatch(cost_key, dur * 1e3)

    # -- time-series: one back-dated scrape per boundary -----------------
    store = backfill_store()
    if store is not None:
        t1_wall = EVENTS._wall0 + (t1_mono - EVENTS._mono0)
        store.scrape(now=t1_wall)

    # -- bundle cache -----------------------------------------------------
    global _PROVIDER_REGISTERED
    summary = dict(agg)
    summary.update({
        "mode": mode, "stride": stride, "seg": seg_index,
        "n_trials": n_trials, "n_lanes": n_lanes,
        "segment_s": dur,
        "best_trajectory": np.round(
            np.ravel(slab_h["best_trajectory"])[:RESERVOIR].astype(
                np.float64), 6).tolist(),
    })
    with _LAST_LOCK:
        key = (mode, label or mode)
        _LAST.pop(key, None)
        _LAST[key] = summary
        while len(_LAST) > _LAST_CAP:
            _LAST.popitem(last=False)
        if not _PROVIDER_REGISTERED:
            _bundle.register_provider("device_telemetry", report)
            _PROVIDER_REGISTERED = True
    return summary


def finish_run(reg, trials, *, mode: str, label=None) -> dict | None:
    """Run-end health pass over the landed docs (which the slab fetches
    just backfilled): one ``health.assess`` + publish under
    ``device:<label>``.  Boundary-rate work happens per segment; the
    O(n_docs) assessment runs once per run, here."""
    try:
        docs = list(trials.trials)
    except Exception:
        return None
    if not docs:
        return None
    rep = _health.assess(docs)
    _health.publish(f"device:{label or mode}", rep, reg)
    return rep


def report() -> dict:
    """Flight-bundle section: the latest slab summary per live run."""
    with _LAST_LOCK:
        runs = [dict(v) for v in _LAST.values()]
    return {"enabled": enabled(), "reservoir": RESERVOIR, "runs": runs}
