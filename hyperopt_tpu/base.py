"""Core runtime: trial documents, ``Trials``, ``Ctrl``, ``Domain``.

Reference: ``hyperopt/base.py`` (SURVEY.md §2 L4 — ``Trials`` ~L190-620,
``Ctrl`` ~L650, ``Domain`` ~L700-980; mount was empty, anchors from upstream).

The public ``Trials`` API is preserved (the ``trials=`` plugin boundary the
north star requires): ``insert_trial_docs``, ``refresh``, ``new_trial_ids``,
``count_by_state_unsynced``, ``losses``, ``statuses``, ``best_trial``,
``argmin``, ``average_best_error``, attachments, and the trial-doc schema
(``tid``, ``spec``, ``result``, ``misc.idxs/vals``, ``state``).

TPU-first addition: ``Trials`` maintains a **dense struct-of-arrays mirror** of
the trial history (``history()`` → vals f32[N, P], active bool[N, P],
loss f32[N], ok bool[N]) so suggest algorithms ship one contiguous buffer to
the device instead of re-parsing ragged per-trial dicts each step.
"""

from __future__ import annotations


import threading
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .exceptions import (
    AllTrialsFailed,
    InvalidLoss,
    InvalidResultStatus,
    InvalidTrial,
)
from .space import CompiledSpace, compile_space

# ---------------------------------------------------------------------------
# Job states & statuses (reference: hyperopt/base.py ~L60)
# ---------------------------------------------------------------------------

JOB_STATE_NEW = 0
JOB_STATE_RUNNING = 1
JOB_STATE_DONE = 2
JOB_STATE_ERROR = 3
JOB_STATE_CANCEL = 4
JOB_STATES = (JOB_STATE_NEW, JOB_STATE_RUNNING, JOB_STATE_DONE,
              JOB_STATE_ERROR, JOB_STATE_CANCEL)

STATUS_NEW = "new"
STATUS_RUNNING = "running"
STATUS_SUSPENDED = "suspended"
STATUS_OK = "ok"
STATUS_FAIL = "fail"
STATUS_STRINGS = (STATUS_NEW, STATUS_RUNNING, STATUS_SUSPENDED,
                  STATUS_OK, STATUS_FAIL)

_TRIAL_KEYS = ("state", "tid", "spec", "result", "misc", "exp_key",
               "owner", "version", "book_time", "refresh_time")
_MISC_KEYS = ("tid", "cmd", "idxs", "vals")


def coarse_utcnow() -> float:
    """Second-resolution wall-clock timestamp (reference: utils.coarse_utcnow)."""
    return float(int(time.time()))


#: Granularity of :func:`coarse_utcnow`.  Staleness checks that compare a
#: coarse ``book_time``/``refresh_time`` against a clock must allow this
#: much slop, or a doc booked late in a wall second looks up to a full
#: second older than it is and a sub-second timeout requeues it instantly.
COARSE_CLOCK_SLOP_S = 1.0


def validate_trial_docs(docs):
    for doc in docs:
        for k in _TRIAL_KEYS:
            if k not in doc:
                raise InvalidTrial(f"trial missing key {k!r}: {doc}")
        if doc["state"] not in JOB_STATES:
            raise InvalidTrial(f"invalid state {doc['state']!r}")
        misc = doc["misc"]
        for k in _MISC_KEYS:
            if k not in misc:
                raise InvalidTrial(f"trial misc missing key {k!r}")
        if misc["tid"] != doc["tid"]:
            raise InvalidTrial(
                f"tid mismatch: doc {doc['tid']} vs misc {misc['tid']}")
        for label, idxs in misc["idxs"].items():
            vals = misc["vals"].get(label)
            if vals is None or len(idxs) != len(vals):
                raise InvalidTrial(
                    f"idxs/vals length mismatch for label {label!r}")
    return docs


def new_trial_doc(tid, exp_key=None, cmd=None):
    """Blank NEW-state trial document with the reference schema."""
    return {
        "state": JOB_STATE_NEW,
        "tid": tid,
        "spec": None,
        "result": {"status": STATUS_NEW},
        "misc": {"tid": tid, "cmd": cmd, "idxs": {}, "vals": {}},
        "exp_key": exp_key,
        "owner": None,
        "version": 0,
        "book_time": None,
        "refresh_time": None,
    }


# ---------------------------------------------------------------------------
# idxs/vals <-> per-trial conversion (reference: base.py::miscs_to_idxs_vals)
# ---------------------------------------------------------------------------


def miscs_to_idxs_vals(miscs, keys=None):
    """Convert per-trial ``misc['idxs']/['vals']`` into per-variable columns."""
    if keys is None:
        if len(miscs) == 0:
            return {}, {}
        keys = list(miscs[0]["idxs"].keys())
    idxs = {k: [] for k in keys}
    vals = {k: [] for k in keys}
    for misc in miscs:
        for k in keys:
            t_idxs = misc["idxs"].get(k, [])
            t_vals = misc["vals"].get(k, [])
            idxs[k].extend(t_idxs)
            vals[k].extend(t_vals)
    return idxs, vals


def miscs_update_idxs_vals(miscs, idxs, vals, assert_all_vals_used=True):
    """Scatter per-variable columns back into per-trial misc dicts."""
    by_tid = {m["tid"]: m for m in miscs}
    for m in miscs:
        m["idxs"] = {k: [] for k in idxs}
        m["vals"] = {k: [] for k in idxs}
    for k, k_idxs in idxs.items():
        k_vals = vals[k]
        for tid, v in zip(k_idxs, k_vals):
            if tid in by_tid:
                by_tid[tid]["idxs"][k].append(tid)
                by_tid[tid]["vals"][k].append(v)
            elif assert_all_vals_used:
                raise ValueError(f"unknown tid {tid} for label {k!r}")
    return miscs


def spec_from_misc(misc):
    """{label: scalar} point from one trial's misc (active params only)."""
    spec = {}
    for k, v in misc["vals"].items():
        if len(v) == 0:
            continue
        elif len(v) == 1:
            spec[k] = v[0]
        else:
            raise NotImplementedError("multiple values per label in one trial")
    return spec


def docs_from_samples(cs: CompiledSpace, new_ids, vals, active,
                      exp_key=None, cmd=None):
    """Package device sample rows into reference-schema trial docs.

    ``vals``/``active`` are [n, P] host arrays; inactive parameters get empty
    idxs/vals lists (the reference's encoding of unchosen conditional branches).
    """
    vals = np.asarray(vals)
    active = np.asarray(active)
    docs = []
    for row, tid in enumerate(new_ids):
        doc = new_trial_doc(tid, exp_key=exp_key, cmd=cmd)
        idxs_d, vals_d = {}, {}
        for spec in cs.params:
            if active[row, spec.pid]:
                idxs_d[spec.label] = [tid]
                v = vals[row, spec.pid]
                # round() not int(): f32 integer values can sit a ulp below.
                vals_d[spec.label] = [int(round(float(v))) if spec.is_int
                                      else float(v)]
            else:
                idxs_d[spec.label] = []
                vals_d[spec.label] = []
        doc["misc"]["idxs"] = idxs_d
        doc["misc"]["vals"] = vals_d
        docs.append(doc)
    return docs


def _parse_doc_row(tvals, cs, vals, active, i):
    """Fill row ``i`` of dense ``vals``/``active`` from one trial doc's
    ``misc.vals`` (the single value-encoding convention — shared by
    ``Trials.history`` and ``Trials.inflight`` so the two dense views
    cannot diverge)."""
    for spec in cs.params:
        v = tvals.get(spec.label, [])
        if len(v):
            vals[i, spec.pid] = v[0]
            active[i, spec.pid] = True


# ---------------------------------------------------------------------------
# Trials
# ---------------------------------------------------------------------------


class Trials:
    """In-memory trial database (reference: hyperopt/base.py::Trials).

    Synchronous by default (``asynchronous = False``): ``FMinIter`` runs the
    objective in-process.  Subclasses with ``asynchronous = True`` (e.g.
    :class:`hyperopt_tpu.parallel.filestore.FileTrials`) only enqueue docs and
    let external workers evaluate them.
    """

    asynchronous = False

    def __init__(self, exp_key=None, refresh=True):
        self._ids = set()
        self._dynamic_trials: List[dict] = []
        self._trials: List[dict] = []
        self._exp_key = exp_key
        self.attachments: Dict[str, Any] = {}
        self._lock = threading.RLock()
        # SoA mirror cache, invalidated on refresh.
        self._soa_cache = None
        self._best_cache = None
        if refresh:
            self.refresh()

    def __getstate__(self):
        """Picklable state for ``trials_save_file`` checkpointing (the lock
        and the SoA device-array cache are reconstructed on load)."""
        state = self.__dict__.copy()
        state.pop("_lock", None)
        state["_soa_cache"] = None
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._lock = threading.RLock()

    # -- container protocol -------------------------------------------------

    def __len__(self):
        return len(self._trials)

    def __iter__(self):
        return iter(self._trials)

    def __getitem__(self, item):
        return self._trials[item]

    @property
    def trials(self):
        return self._trials

    @property
    def tids(self):
        return [t["tid"] for t in self._trials]

    @property
    def specs(self):
        return [t["spec"] for t in self._trials]

    @property
    def results(self):
        return [t["result"] for t in self._trials]

    @property
    def miscs(self):
        return [t["misc"] for t in self._trials]

    @property
    def idxs_vals(self):
        return miscs_to_idxs_vals(self.miscs)

    @property
    def idxs(self):
        return self.idxs_vals[0]

    @property
    def vals(self):
        return self.idxs_vals[1]

    # -- persistence hooks (overridden by durable subclasses) ----------------

    def _insert_trial_docs(self, docs) -> List[int]:
        self._dynamic_trials.extend(docs)
        return [d["tid"] for d in docs]

    def refresh(self):
        with self._lock:
            if self._exp_key is None:
                self._trials = list(self._dynamic_trials)
            else:
                self._trials = [t for t in self._dynamic_trials
                                if t["exp_key"] == self._exp_key]
            # _soa_cache is NOT cleared here: history() revalidates it by
            # tid-prefix comparison, keeping rebuilds incremental. DONE-trial
            # results are written exactly once, so the prefix cannot go stale.
            # best_trial IS cleared: state flips (NEW→DONE) mutate docs in
            # place, and refresh() is the contract's sync point after any
            # mutation (the same assumption history() already relies on).
            self._best_cache = None

    def insert_trial_doc(self, doc):
        return self.insert_trial_docs([doc])[0]

    def insert_trial_docs(self, docs):
        with self._lock:
            docs = validate_trial_docs(docs)
            for d in docs:
                if d["tid"] in self._ids:
                    raise InvalidTrial(f"duplicate tid {d['tid']}")
                self._ids.add(d["tid"])
            return self._insert_trial_docs(docs)

    def new_trial_ids(self, n):
        with self._lock:
            base = max(
                [t["tid"] for t in self._dynamic_trials] + [len(self._ids) - 1, -1]
            ) + 1
            out = list(range(base, base + n))
            return out

    def delete_all(self):
        with self._lock:
            self._dynamic_trials = []
            self._trials = []
            self._ids = set()
            self.attachments = {}
            self._soa_cache = None
            self._best_cache = None
        # Free any device-resident history buffers now rather than at GC
        # (the tids-prefix check would catch the wipe anyway — this is a
        # memory courtesy, not a correctness requirement).
        from . import history as _rhist

        _rhist.forget(self)

    # -- state bookkeeping ---------------------------------------------------

    def count_by_state_synced(self, job_state, trials=None):
        if trials is None:
            trials = self._trials
        if isinstance(job_state, (tuple, list)):
            states = set(job_state)
        else:
            states = {job_state}
        return sum(1 for t in trials if t["state"] in states)

    def count_by_state_unsynced(self, job_state):
        with self._lock:
            if self._exp_key is not None:
                docs = [t for t in self._dynamic_trials
                        if t["exp_key"] == self._exp_key]
            else:
                docs = self._dynamic_trials
            return self.count_by_state_synced(job_state, trials=docs)

    # -- results ------------------------------------------------------------

    def losses(self, bandit=None):
        return [r.get("loss") for r in self.results]

    def statuses(self, bandit=None):
        return [r.get("status") for r in self.results]

    @property
    def exp_key(self):
        return self._exp_key

    @property
    def best_trial(self):
        # One scan per refresh(): fmin reads this several times per batch
        # (progress postfix, early-stop closures, user callbacks) and the
        # Python-dict scan is O(N) — the cache turns repeat reads into O(1).
        cached = getattr(self, "_best_cache", None)
        if cached is not None:
            return cached
        candidates = [
            t for t in self._trials
            if t["state"] == JOB_STATE_DONE
            and t["result"].get("status") == STATUS_OK
            and t["result"].get("loss") is not None
        ]
        if not candidates:
            raise AllTrialsFailed("no successful trials with a loss yet")
        best = min(candidates, key=lambda t: t["result"]["loss"])
        self._best_cache = best
        return best

    @property
    def argmin(self):
        return spec_from_misc(self.best_trial["misc"])

    def average_best_error(self, bandit=None):
        """Mean loss among best-status trials, variance-weighted like the
        reference (hyperopt/base.py::Trials.average_best_error)."""
        results = [r for r in self.results if r.get("status") == STATUS_OK]
        if not results:
            raise AllTrialsFailed("no ok trials")
        losses = np.asarray([r["loss"] for r in results], dtype=np.float64)
        variances = np.asarray(
            [max(r.get("loss_variance", 0.0), 1e-12) for r in results])
        best = losses.min()
        cutoff = best + np.sqrt(variances[losses.argmin()])
        keep = losses <= cutoff
        return float(np.average(losses[keep], weights=1.0 / variances[keep]))

    # -- attachments ---------------------------------------------------------

    def trial_attachments(self, trial):
        tid = trial["tid"]
        trials_self = self

        class _TrialAttachments:
            def __contains__(self, name):
                return f"ATTACH::{tid}::{name}" in trials_self.attachments

            def __getitem__(self, name):
                return trials_self.attachments[f"ATTACH::{tid}::{name}"]

            def __setitem__(self, name, value):
                trials_self.attachments[f"ATTACH::{tid}::{name}"] = value

            def __delitem__(self, name):
                del trials_self.attachments[f"ATTACH::{tid}::{name}"]

        return _TrialAttachments()

    # -- dense history mirror (TPU-first addition) ---------------------------

    def history(self, cs: CompiledSpace):
        """Dense SoA view of completed trials for device-side suggest kernels.

        Returns dict of host numpy arrays:
          vals   f32[N, P]  parameter matrix (0 where inactive)
          active bool[N, P] liveness mask
          loss   f32[N]     losses (+inf where not ok)
          ok     bool[N]    result status == ok with finite loss
          tids   i64[N]
        Cached until the next ``refresh()``.
        """
        with self._lock:
            done = [t for t in self._trials if t["state"] == JOB_STATE_DONE]
            n, p = len(done), cs.n_params
            new_tids = np.asarray([t["tid"] for t in done], dtype=np.int64)
            # Incremental: trials are append-only in practice, so if the cached
            # prefix still matches we only parse the newly-completed suffix
            # (keeps total host-side work O(N*P) over a run, not O(N^2*P)).
            start = 0
            if (self._soa_cache is not None and self._soa_cache[0] is cs
                    and len(self._soa_cache[1]["tids"]) <= n
                    and np.array_equal(
                        self._soa_cache[1]["tids"],
                        new_tids[: len(self._soa_cache[1]["tids"])])):
                old = self._soa_cache[1]
                start = len(old["tids"])
                if start == n:
                    return old
            vals = np.zeros((n, p), dtype=np.float32)
            active = np.zeros((n, p), dtype=bool)
            loss = np.full((n,), np.inf, dtype=np.float32)
            ok = np.zeros((n,), dtype=bool)
            if start:
                vals[:start] = old["vals"]
                active[:start] = old["active"]
                loss[:start] = old["loss"]
                ok[:start] = old["ok"]
            for i in range(start, n):
                t = done[i]
                r = t["result"]
                if r.get("status") == STATUS_OK and r.get("loss") is not None \
                        and np.isfinite(r["loss"]):
                    loss[i] = r["loss"]
                    ok[i] = True
                _parse_doc_row(t["misc"]["vals"], cs, vals, active, i)
            out = dict(vals=vals, active=active, loss=loss, ok=ok,
                       tids=new_tids)
            self._soa_cache = (cs, out)
            return out

    def inflight(self, cs: CompiledSpace):
        """Dense ``(vals f32[M, P], active bool[M, P])`` of NEW/RUNNING
        trials — the points currently being (or about to be) evaluated.

        ``tpe.suggest_dispatch`` injects these as constant-liar fantasy
        rows so concurrent suggests (overlapped batches, pool workers,
        file-store workers) repel proposals from points already in
        flight instead of duplicating them — a gap the reference's
        parallel backends share (suggest there conditions on completed
        trials only).  In-flight sets are small; no caching.
        """
        with self._lock:
            # _trials, not _dynamic_trials: the exp_key-filtered view —
            # other experiments' in-flight work must not repel this one.
            live = [t for t in self._trials
                    if t["state"] in (JOB_STATE_NEW, JOB_STATE_RUNNING)]
            m, p = len(live), cs.n_params
            vals = np.zeros((m, p), dtype=np.float32)
            active = np.zeros((m, p), dtype=bool)
            for i, t in enumerate(live):
                _parse_doc_row(t["misc"]["vals"], cs, vals, active, i)
            return vals, active

    # -- convenience --------------------------------------------------------

    def fmin(self, fn, space, algo, max_evals, **kwargs):
        from .fmin import fmin as _fmin
        return _fmin(fn, space, algo, max_evals, trials=self,
                     allow_trials_fmin=False, **kwargs)


def trials_from_docs(docs, validate=True, **kwargs):
    """Build a Trials object from a list of trial documents."""
    rval = Trials(**kwargs)
    if validate:
        rval.insert_trial_docs(docs)
    else:
        rval._dynamic_trials.extend(docs)
        rval._ids.update(d["tid"] for d in docs)
    rval.refresh()
    return rval


# ---------------------------------------------------------------------------
# Ctrl
# ---------------------------------------------------------------------------


class Ctrl:
    """Job-to-runtime control handle (reference: hyperopt/base.py::Ctrl ~L650).

    Passed to the objective when ``fmin(..., pass_expr_memo_ctrl=True)``.
    """

    def __init__(self, trials: Trials, current_trial=None, workdir=None):
        self.trials = trials
        self.current_trial = current_trial
        # Per-trial scratch directory, set by distributed workers
        # (parallel.filestore.FileWorker) when configured with workdir=.
        self.workdir = workdir

    @property
    def attachments(self):
        if self.current_trial is None:
            return self.trials.attachments
        return self.trials.trial_attachments(self.current_trial)

    def checkpoint(self, result=None):
        if self.current_trial is not None and result is not None:
            self.current_trial["result"] = result
            self.current_trial["refresh_time"] = coarse_utcnow()

    def should_stop(self) -> bool:
        """Cooperative-cancellation hook: long-running objectives should poll
        this and bail out when it returns True.  Executors that can cancel
        (``parallel.PoolTrials``) rebind it per trial; the default is never.
        (Reference analog: Spark task cancellation, spark.py::_SparkFMinState
        — there the *executor* is killed; a thread pool must cooperate.)"""
        return False


# ---------------------------------------------------------------------------
# Domain
# ---------------------------------------------------------------------------


class Domain:
    """Wraps the user objective + compiled search space.

    Reference: ``hyperopt/base.py::Domain`` (~L700-980): holds the space
    expression, the vectorized sampler, ``memo_from_config`` and ``evaluate``.
    Here the pyll graph + VectorizeHelper are replaced by
    :class:`~hyperopt_tpu.space.CompiledSpace` (compiled once, jitted).
    """

    rec_eval_print_node_on_error = False

    def __init__(self, fn: Callable, expr, workdir=None,
                 pass_expr_memo_ctrl=None, name=None, loss_target=None):
        self.fn = fn
        self.expr = expr
        self.cs = compile_space(expr)
        self.params = {p.label: p for p in self.cs.params}
        self.workdir = workdir
        self.name = name
        self.loss_target = loss_target
        if pass_expr_memo_ctrl is None:
            self.pass_expr_memo_ctrl = getattr(
                fn, "fmin_pass_expr_memo_ctrl", False)
        else:
            self.pass_expr_memo_ctrl = pass_expr_memo_ctrl

    def memo_from_config(self, config: dict):
        """{label: value} assignment → the nested structure the user fn sees."""
        return self.cs.eval_point(config)

    def evaluate(self, config: dict, ctrl: Optional[Ctrl], attach_attachments=True):
        """Run the user objective on one configuration; normalize the result.

        Reference: ``hyperopt/base.py::Domain.evaluate`` (~L850): float results
        become ``{'loss': x, 'status': 'ok'}``; dict results validated.
        """
        from . import faults as _faults

        _faults.maybe_fail("objective.call")
        if self.pass_expr_memo_ctrl:
            rval = self.fn(expr=self.expr,
                           memo=self.memo_from_config(config), ctrl=ctrl)
        else:
            pyll_rval = self.memo_from_config(config)
            rval = self.fn(pyll_rval)

        if isinstance(rval, (float, int, np.floating, np.integer)):
            loss = float(rval)
            if not np.isfinite(loss):
                raise InvalidLoss(f"non-finite loss {loss}")
            dict_rval = {"loss": loss, "status": STATUS_OK}
        elif isinstance(rval, dict):
            dict_rval = dict(rval)
            status = dict_rval.get("status")
            if status not in STATUS_STRINGS:
                raise InvalidResultStatus(f"invalid status {status!r}")
            if status == STATUS_OK:
                try:
                    dict_rval["loss"] = float(dict_rval["loss"])
                except (KeyError, TypeError, ValueError) as exc:
                    raise InvalidLoss(
                        "status ok requires a float 'loss'") from exc
                if not np.isfinite(dict_rval["loss"]):
                    raise InvalidLoss(f"non-finite loss {dict_rval['loss']}")
        else:
            raise InvalidResultStatus(
                f"objective returned {type(rval).__name__}; expected float or dict")

        if attach_attachments and ctrl is not None:
            attachments = dict_rval.pop("attachments", {})
            for k, v in attachments.items():
                ctrl.attachments[k] = v
        return dict_rval

    def short_str(self):
        return f"Domain{{{self.cs!r}}}"

    # Backwards-compat name used by some reference call sites.
    true_loss = staticmethod(lambda result, config=None: result.get("true_loss",
                                                                    result.get("loss")))
