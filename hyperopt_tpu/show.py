"""Experiment inspection CLI.

Reference: ``hyperopt/mongoexp.py::main_show`` / ``main_plot`` utilities
(SURVEY.md §2): summarize a live experiment's state from its store.

Usage::

    python -m hyperopt_tpu.show --root /shared/exp --exp-key e1
    python -m hyperopt_tpu.show --pickle trials.pkl [--plot history.png]
    python -m hyperopt_tpu.show trace /tmp/trace   # per-phase span table
"""

from __future__ import annotations

import argparse
import json
import os
import pickle
import sys
from collections import Counter, defaultdict

from .base import (
    JOB_STATE_CANCEL,
    JOB_STATE_DONE,
    JOB_STATE_ERROR,
    JOB_STATE_NEW,
    JOB_STATE_RUNNING,
    Trials,
)
from .exceptions import AllTrialsFailed

_STATE_NAMES = {JOB_STATE_NEW: "new", JOB_STATE_RUNNING: "running",
                JOB_STATE_DONE: "done", JOB_STATE_ERROR: "error",
                JOB_STATE_CANCEL: "cancel"}


def summarize(trials: Trials, out=None) -> None:
    # Resolve the stream at CALL time: an import-time `out=sys.stdout`
    # default would capture whatever stdout object existed when this module
    # was first imported (possibly a since-closed redirection).
    out = out if out is not None else sys.stdout
    states = Counter(t["state"] for t in trials)
    print(f"trials: {len(trials)}", file=out)
    for s, name in _STATE_NAMES.items():
        if states.get(s):
            print(f"  {name:8s} {states[s]}", file=out)
    try:
        best = trials.best_trial
        print(f"best loss: {best['result']['loss']:.6g} "
              f"(tid {best['tid']})", file=out)
        point = {k: v[0] for k, v in best["misc"]["vals"].items() if v}
        for k in sorted(point):
            print(f"  {k} = {point[k]}", file=out)
    except AllTrialsFailed:
        print("best loss: (no successful trials yet)", file=out)
    owners = Counter(t.get("owner") for t in trials if t.get("owner"))
    if owners:
        print("workers:", file=out)
        for owner, n in owners.most_common():
            print(f"  {owner}: {n}", file=out)
    try:
        n_att = len(trials.attachments)
    except Exception:
        n_att = 0
    if n_att:
        print(f"attachments: {n_att}", file=out)


def summarize_trace(trace_dir: str, out=None) -> None:
    """Render a trace directory (``fmin(..., trace_dir=...)``) as a
    per-phase summary table — the table the bench scripts used to
    hand-roll.  Prefers the aggregated ``loop_trace.json``; falls back to
    re-deriving span totals from ``loop_events.jsonl``."""
    out = out if out is not None else sys.stdout
    summary_path = os.path.join(trace_dir, "loop_trace.json")
    events_path = os.path.join(trace_dir, "loop_events.jsonl")
    wall = None
    phases = {}
    if os.path.exists(summary_path):
        with open(summary_path) as f:
            doc = json.load(f)
        wall = doc.pop("_wall", None)
        phases = {k: v for k, v in doc.items() if isinstance(v, dict)
                  and "total_s" in v}
    elif os.path.exists(events_path):
        begins, totals, counts = {}, defaultdict(float), defaultdict(int)
        with open(events_path) as f:
            for line in f:
                rec = json.loads(line)
                if rec["type"] == "span_begin":
                    begins[rec.get("span")] = rec
                elif rec["type"] == "span_end":
                    b = begins.pop(rec.get("span"), None)
                    if b is not None:
                        totals[b["name"]] += rec["t_mono"] - b["t_mono"]
                        counts[b["name"]] += 1
        phases = {n: {"total_s": t, "count": counts[n],
                      "mean_ms": 1e3 * t / max(counts[n], 1)}
                  for n, t in totals.items()}
    else:
        print(f"no loop_trace.json or loop_events.jsonl in {trace_dir}",
              file=out)
        return
    wall_s = wall["wall_s"] if wall else sum(
        v["total_s"] for v in phases.values()) or 1.0
    print(f"{'phase':<14s} {'total_s':>10s} {'count':>7s} "
          f"{'mean_ms':>9s} {'% wall':>7s}", file=out)
    for name, rec in sorted(phases.items(),
                            key=lambda kv: -kv[1]["total_s"]):
        print(f"{name:<14s} {rec['total_s']:>10.4f} {rec['count']:>7d} "
              f"{rec['mean_ms']:>9.3f} "
              f"{100.0 * rec['total_s'] / max(wall_s, 1e-12):>6.1f}%",
              file=out)
    if wall:
        print(f"wall {wall['wall_s']:.4f}s, attributed "
              f"{wall['attributed_s']:.4f}s "
              f"({100.0 * wall['coverage']:.1f}% coverage)", file=out)
    if os.path.exists(events_path):
        n_events = sum(1 for _ in open(events_path))
        print(f"events: {n_events} in loop_events.jsonl", file=out)
    chrome = os.path.join(trace_dir, "chrome_trace.json")
    if os.path.exists(chrome):
        print(f"chrome trace: {chrome} (load in Perfetto / "
              f"chrome://tracing)", file=out)


def main(argv=None):
    argv = sys.argv[1:] if argv is None else list(argv)
    if argv and argv[0] == "trace":
        # Subcommand form (`hyperopt-tpu-show trace <dir>`); the flag-based
        # trials inspection below keeps its historical interface.
        tp = argparse.ArgumentParser(prog="hyperopt-tpu-show trace",
                                     description="summarize a trace dir")
        tp.add_argument("trace_dir", help="fmin(..., trace_dir=...) output")
        targs = tp.parse_args(argv[1:])
        summarize_trace(targs.trace_dir)
        return 0

    p = argparse.ArgumentParser(description="inspect a hyperopt_tpu "
                                            "experiment")
    src = p.add_mutually_exclusive_group(required=True)
    src.add_argument("--root", help="file-store experiment root")
    src.add_argument("--pickle", help="trials_save_file pickle")
    p.add_argument("--exp-key", default="default")
    p.add_argument("--plot", default=None,
                   help="write a loss-history PNG to this path")
    args = p.parse_args(argv)

    if args.root:
        from .parallel.filestore import FileTrials
        trials = FileTrials(args.root, exp_key=args.exp_key)
    else:
        with open(args.pickle, "rb") as f:
            trials = pickle.load(f)
        trials.refresh()

    summarize(trials)

    if args.plot:
        import matplotlib
        matplotlib.use("Agg", force=True)
        from . import plotting
        ax = plotting.main_plot_history(trials, do_show=False)
        ax.figure.savefig(args.plot, dpi=120)
        print(f"wrote {args.plot}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
