"""Experiment inspection CLI.

Reference: ``hyperopt/mongoexp.py::main_show`` / ``main_plot`` utilities
(SURVEY.md §2): summarize a live experiment's state from its store.

Usage::

    python -m hyperopt_tpu.show --root /shared/exp --exp-key e1
    python -m hyperopt_tpu.show --pickle trials.pkl [--plot history.png]
    python -m hyperopt_tpu.show trace /tmp/trace   # per-phase span table
    python -m hyperopt_tpu.show trace --merge /tmp/driver /tmp/worker0 \
        -o merged_trace.json                       # fleet Perfetto trace
    python -m hyperopt_tpu.show live http://host:8999 [--token ...]
    python -m hyperopt_tpu.show wal /srv/wal-dir    # WAL/snapshot summary
    python -m hyperopt_tpu.show bundle /tmp/bundle-123-000-slo  # postmortem
"""

from __future__ import annotations

import argparse
import json
import os
import pickle
import sys
import time
from collections import Counter, defaultdict

from .base import (
    JOB_STATE_CANCEL,
    JOB_STATE_DONE,
    JOB_STATE_ERROR,
    JOB_STATE_NEW,
    JOB_STATE_RUNNING,
    Trials,
)
from .exceptions import AllTrialsFailed

_STATE_NAMES = {JOB_STATE_NEW: "new", JOB_STATE_RUNNING: "running",
                JOB_STATE_DONE: "done", JOB_STATE_ERROR: "error",
                JOB_STATE_CANCEL: "cancel"}


def summarize(trials: Trials, out=None) -> None:
    # Resolve the stream at CALL time: an import-time `out=sys.stdout`
    # default would capture whatever stdout object existed when this module
    # was first imported (possibly a since-closed redirection).
    out = out if out is not None else sys.stdout
    states = Counter(t["state"] for t in trials)
    print(f"trials: {len(trials)}", file=out)
    for s, name in _STATE_NAMES.items():
        if states.get(s):
            print(f"  {name:8s} {states[s]}", file=out)
    try:
        best = trials.best_trial
        print(f"best loss: {best['result']['loss']:.6g} "
              f"(tid {best['tid']})", file=out)
        point = {k: v[0] for k, v in best["misc"]["vals"].items() if v}
        for k in sorted(point):
            print(f"  {k} = {point[k]}", file=out)
    except AllTrialsFailed:
        print("best loss: (no successful trials yet)", file=out)
    owners = Counter(t.get("owner") for t in trials if t.get("owner"))
    if owners:
        print("workers:", file=out)
        for owner, n in owners.most_common():
            print(f"  {owner}: {n}", file=out)
    try:
        n_att = len(trials.attachments)
    except Exception:
        n_att = 0
    if n_att:
        print(f"attachments: {n_att}", file=out)


def summarize_trace(trace_dir: str, out=None) -> None:
    """Render a trace directory (``fmin(..., trace_dir=...)``) as a
    per-phase summary table — the table the bench scripts used to
    hand-roll.  Prefers the aggregated ``loop_trace.json``; falls back to
    re-deriving span totals from ``loop_events.jsonl``."""
    out = out if out is not None else sys.stdout
    summary_path = os.path.join(trace_dir, "loop_trace.json")
    events_path = os.path.join(trace_dir, "loop_events.jsonl")
    wall = None
    phases = {}
    if os.path.exists(summary_path):
        with open(summary_path) as f:
            doc = json.load(f)
        wall = doc.pop("_wall", None)
        phases = {k: v for k, v in doc.items() if isinstance(v, dict)
                  and "total_s" in v}
    elif os.path.exists(events_path):
        begins, totals, counts = {}, defaultdict(float), defaultdict(int)
        with open(events_path) as f:
            for line in f:
                rec = json.loads(line)
                if rec["type"] == "span_begin":
                    begins[rec.get("span")] = rec
                elif rec["type"] == "span_end":
                    b = begins.pop(rec.get("span"), None)
                    if b is not None:
                        totals[b["name"]] += rec["t_mono"] - b["t_mono"]
                        counts[b["name"]] += 1
        phases = {n: {"total_s": t, "count": counts[n],
                      "mean_ms": 1e3 * t / max(counts[n], 1)}
                  for n, t in totals.items()}
    else:
        print(f"no loop_trace.json or loop_events.jsonl in {trace_dir}",
              file=out)
        return
    wall_s = wall["wall_s"] if wall else sum(
        v["total_s"] for v in phases.values()) or 1.0
    print(f"{'phase':<14s} {'total_s':>10s} {'count':>7s} "
          f"{'mean_ms':>9s} {'% wall':>7s}", file=out)
    for name, rec in sorted(phases.items(),
                            key=lambda kv: -kv[1]["total_s"]):
        print(f"{name:<14s} {rec['total_s']:>10.4f} {rec['count']:>7d} "
              f"{rec['mean_ms']:>9.3f} "
              f"{100.0 * rec['total_s'] / max(wall_s, 1e-12):>6.1f}%",
              file=out)
    if wall:
        print(f"wall {wall['wall_s']:.4f}s, attributed "
              f"{wall['attributed_s']:.4f}s "
              f"({100.0 * wall['coverage']:.1f}% coverage)", file=out)
    if os.path.exists(events_path):
        n_events, n_dropped = 0, 0
        with open(events_path) as fh:
            for line in fh:
                n_events += 1
                if n_events == 1:
                    try:
                        head = json.loads(line)
                    except ValueError:
                        head = {}
                    if isinstance(head, dict) and head.get("type") == "meta":
                        n_dropped = int(head.get("n_dropped") or 0)
        dropped = (f" ({n_dropped} displaced at the ring)"
                   if n_dropped else "")
        print(f"events: {n_events} in loop_events.jsonl{dropped}", file=out)
    chrome = os.path.join(trace_dir, "chrome_trace.json")
    if os.path.exists(chrome):
        print(f"chrome trace: {chrome} (load in Perfetto / "
              f"chrome://tracing)", file=out)


# -- cross-process trace stitching ------------------------------------------

def _load_events_file(path):
    """Read one ``loop_events.jsonl``: returns ``(meta, events)``.

    The ``{"type": "meta"}`` header (process identity + wall/mono clock
    anchor + heartbeat-estimated ``skew_s``) is separated from the event
    records; files written before the header existed yield ``{}``.
    """
    meta, events = {}, []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            if rec.get("type") == "meta":
                meta = rec
            else:
                events.append(rec)
    return meta, events


def merge_traces(dirs, out_path=None, out=None) -> dict:
    """Stitch several processes' ``loop_events.jsonl`` into ONE Chrome
    trace: one ``pid`` lane per source process, clock-normalized, with
    per-trial flow arrows crossing lane boundaries.

    Clock normalization: every record's display timestamp is recomputed
    from its monotonic clock via the file's own meta anchor,
    ``wall0 + (t_mono - mono0) - skew_s``.  ``skew_s`` is the process's
    wall offset relative to the netstore server (estimated from heartbeat
    replies, 0 for the server itself), so all lanes land in the *server's*
    clock frame even when the machines' wall clocks disagree.

    Flow arrows: any trial whose events appear in ≥2 lanes gets a Chrome
    flow (``ph: s/t/f`` sharing ``id``) threaded through its anchors —
    suggest→claim→evaluate→record across process boundaries renders as
    arrows in Perfetto.
    """
    out = out if out is not None else sys.stdout
    sources = []
    for d in dirs:
        path = (d if d.endswith(".jsonl")
                else os.path.join(d, "loop_events.jsonl"))
        meta, events = _load_events_file(path)
        if meta.get("wall0") is None or meta.get("mono0") is None:
            # A file without the {wall0, mono0} meta anchor can't be
            # clock-normalized into the shared frame: its t_wall would
            # land the lane wherever that process's clock happened to
            # be, silently corrupting cross-lane ordering.  Skip it
            # before lane numbering so kept lanes stay contiguous.
            print(f"warning: {path}: missing {{wall0, mono0}} meta "
                  "anchor; skipping (cannot clock-normalize)", file=out)
            continue
        sources.append((path, meta, events))

    from .obs.events import events_to_chrome

    trace_events, all_anchors = [], []
    for i, (path, meta, events) in enumerate(sources):
        pid = i + 1  # one Perfetto lane per source process
        wall0, mono0 = meta["wall0"], meta["mono0"]
        skew = meta.get("skew_s", 0.0) or 0.0

        def ts_fn(rec, _w=wall0, _m=mono0, _s=skew):
            return _w + (rec["t_mono"] - _m) - _s
        evs, anchors = events_to_chrome(events, pid=pid, ts_fn=ts_fn)
        label = (meta.get("worker_id") or meta.get("role")
                 or os.path.basename(os.path.dirname(os.path.abspath(path)))
                 or f"proc{i}")
        if meta.get("pid") is not None:
            label = f"{label} (os pid {meta['pid']})"
        trace_events.append({"name": "process_name", "ph": "M", "pid": pid,
                             "tid": 0, "args": {"name": label}})
        trace_events.extend(evs)
        all_anchors.extend(anchors)

    # Per-trial flow arrows.  Anchors are deduped to one per (lane pid,
    # event type) — the earliest — so a trial retried in one process
    # doesn't spray N arrows; only trials seen in ≥2 lanes get a flow.
    by_trial = defaultdict(dict)
    for ts_us, pid, lane, trial, etype in all_anchors:
        key = (pid, etype)
        cur = by_trial[trial].get(key)
        if cur is None or ts_us < cur[0]:
            by_trial[trial][key] = (ts_us, pid, lane, etype)
    flows, n_flows = [], 0
    for trial in sorted(by_trial, key=str):
        pts = sorted(by_trial[trial].values())
        if len({p[1] for p in pts}) < 2:
            continue  # flow arrows only for cross-process trials
        n_flows += 1
        for j, (ts_us, pid, lane, etype) in enumerate(pts):
            ev = {"name": f"trial {trial}", "cat": "trial_flow",
                  "ph": "s" if j == 0 else
                        ("f" if j == len(pts) - 1 else "t"),
                  "id": str(trial), "ts": ts_us, "pid": pid, "tid": lane}
            if ev["ph"] == "f":
                ev["bp"] = "e"  # bind the arrowhead to the enclosing slice
            flows.append(ev)

    doc = {
        "traceEvents": trace_events + flows,
        "displayTimeUnit": "ms",
        "otherData": {
            "merged_from": [p for p, _, _ in sources],
            "n_lanes": len(sources),
            "n_trial_flows": n_flows,
        },
    }
    if out_path:
        with open(out_path, "w") as fh:
            json.dump(doc, fh)
        print(f"wrote {out_path}", file=out)
    print(f"merged {len(sources)} lane(s), "
          f"{sum(len(e) for _, _, e in sources)} events, "
          f"{n_flows} cross-process trial flow(s)", file=out)
    return doc


# -- live fleet dashboard ---------------------------------------------------

def fetch_metrics(url: str, token=None, timeout: float = 5.0) -> dict:
    """GET ``<url>/metrics`` from a netstore server (token-gated)."""
    import urllib.request

    base = url.rstrip("/")
    if not base.endswith("/metrics"):
        base += "/metrics"
    req = urllib.request.Request(base)
    if token:
        req.add_header("X-Netstore-Token", token)
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def _hist_row(name, h):
    """One per-verb table row from a histogram summary dict (seconds)."""
    if not h or not h.get("count"):
        return None
    ms = lambda v: f"{1e3 * v:8.2f}" if v is not None else "       -"  # noqa: E731
    return (f"  {name:<28s} {int(h['count']):>7d} {ms(h.get('p50'))} "
            f"{ms(h.get('p95'))} {ms(h.get('p99'))}")


def render_live(snap: dict, out=None, prev=None) -> dict:
    """Render one dashboard frame from a ``GET /metrics`` payload.

    ``prev`` is the previous ``(t, counters)`` sample used to derive
    rates (trials/s); returns this frame's sample for the next call.
    """
    out = out if out is not None else sys.stdout
    now = time.monotonic()
    fleet = snap.get("fleet", {})
    merged = fleet.get("merged", {})
    counters = dict(snap.get("counters", {}))
    for k, v in merged.get("counters", {}).items():
        counters[k] = max(counters.get(k, 0), v)  # merged already sums local
    gauges = snap.get("gauges", {})
    m_gauges = merged.get("gauges", {})

    done = counters.get("fmin.trials.done", 0) + counters.get(
        "worker.trials", 0)
    rate = ""
    if prev is not None:
        dt = now - prev[0]
        if dt > 0:
            d_done = done - prev[1]
            rate = f"   {d_done / dt:6.2f} trials/s"
    print(f"fleet: {fleet.get('n_workers', 0)} worker(s)   "
          f"trials done {done}{rate}", file=out)

    # SHARDS: the fleet router's per-shard panel (snap["router"], present
    # only when the URL polled is a service/router.py front).  Latency
    # tails come from the router's own router.shard.<sid>.s forward
    # histograms; a shard that did not answer the metrics pull renders
    # as DOWN with the error instead of failing the frame.
    router = snap.get("router")
    if router is not None:
        shards = router.get("shards", {})
        print(f"router: {router.get('n_shards', len(shards))} shard(s)   "
              f"map v{router.get('version', '?')}   forwarded "
              f"{int(counters.get('router.forwarded', 0))}   failovers "
              f"{int(counters.get('router.failovers', 0))}   rebalances "
              f"{int(counters.get('router.rebalances', 0))}", file=out)
        if shards:
            r_hists = snap.get("histograms", {})
            pct = lambda h, q: (f"{1e3 * h[q]:8.2f}"  # noqa: E731
                                if h and h.get(q) is not None
                                else f"{'-':>8s}")
            print(f"  {'shard':<12s} {'status':<6s} {'workers':>7s} "
                  f"{'calls':>8s} {'fwd':>6s} {'p50ms':>8s} {'p95ms':>8s} "
                  f"{'p99ms':>8s}", file=out)
            for sid in sorted(shards):
                info = shards[sid]
                h = r_hists.get(f"router.shard.{sid}.s") or {}
                fwd = int(h.get("count", 0))
                if info.get("ok"):
                    print(f"  {sid:<12s} {'ok':<6s} "
                          f"{int(info.get('n_workers', 0)):>7d} "
                          f"{int(info.get('verb_calls', 0)):>8d} "
                          f"{fwd:>6d} {pct(h, 'p50')} {pct(h, 'p95')} "
                          f"{pct(h, 'p99')}", file=out)
                else:
                    print(f"  {sid:<12s} {'DOWN':<6s} {'-':>7s} {'-':>8s} "
                          f"{fwd:>6d} {pct(h, 'p50')} {pct(h, 'p95')} "
                          f"{pct(h, 'p99')}  "
                          f"{info.get('error', '?')}", file=out)
    # AUTOSCALE: the control plane's decision-log tail (snap["autoscale"],
    # present when an Autoscaler is attached to the polled router).  Each
    # row is one WAL-durable decision — the runbook's first stop when a
    # topology change needs explaining.
    auto = snap.get("autoscale")
    if auto is not None:
        if auto.get("error"):
            print(f"autoscale: UNAVAILABLE {auto['error']}", file=out)
        else:
            shed = auto.get("shed_level", 0.0)
            print(f"autoscale: {'running' if auto.get('running') else 'idle'}"
                  f"   shed {shed:.0%}   calm {auto.get('calm', 0)}/"
                  f"{auto.get('calm_ticks', '?')}   bounds "
                  f"[{auto.get('min_shards', '?')}, "
                  f"{auto.get('max_shards', '?')}] shards", file=out)
            decisions = auto.get("decisions") or []
            for d in decisions[-6:]:
                when = time.strftime("%H:%M:%S",
                                     time.localtime(d.get("t", 0)))
                ok = ("ok" if d.get("ok")
                      else f"FAILED {d.get('error', '')}")
                print(f"  {when} {d.get('action', '?'):<11s} "
                      f"burn {d.get('burn', 0):>6.2f}  "
                      f"shards {d.get('shards', '?'):>2}  {ok}  "
                      f"{d.get('reason', '')}", file=out)
    occ = gauges.get("pipeline.occupancy", m_gauges.get("pipeline.occupancy"))
    backlog = gauges.get("pipeline.eval_backlog",
                         m_gauges.get("pipeline.eval_backlog"))
    if occ is not None or backlog is not None:
        print(f"pipeline: occupancy {occ if occ is not None else '-'}   "
              f"eval backlog {backlog if backlog is not None else '-'}",
              file=out)
    # Cohort occupancy of the fleet dispatch path: how full the last
    # vmap-batched dispatch ran (real lanes / pow2 tier) and the padding
    # it paid, plus aggregate dispatch/suggestion volume.
    disp = counters.get("fleet.dispatches", 0)
    if disp:
        size = gauges.get("fleet.cohort_size_last",
                          m_gauges.get("fleet.cohort_size_last", 0))
        tier = gauges.get("fleet.cohort_tier_last",
                          m_gauges.get("fleet.cohort_tier_last", 0))
        waste = gauges.get("fleet.padding_waste",
                           m_gauges.get("fleet.padding_waste", 0.0))
        print(f"cohorts: last {int(size)}/{int(tier)} lanes   "
              f"padding {waste:.0%}   dispatches {int(disp)}   "
              f"suggestions {int(counters.get('fleet.suggestions', 0))}",
              file=out)
    # DEVICE: the device-resident loop's sync-boundary view — segment /
    # fetch totals split by (mode, stride) label, plus the in-carry
    # telemetry slab's latest levels (obs.devtel backfill).
    segs = counters.get("device.segments", 0)
    if segs:
        print(f"device:  segments {int(segs)}   fetches "
              f"{int(counters.get('device.fetch_syncs', 0))}   landed "
              f"{int(counters.get('device.trials_landed', 0))}", file=out)
        labeled = {}
        for k, v in counters.items():
            if k.startswith("device.segments."):
                labeled.setdefault(k[len("device.segments."):],
                                   [0, 0])[0] += v
            elif k.startswith("device.fetch_syncs."):
                labeled.setdefault(k[len("device.fetch_syncs."):],
                                   [0, 0])[1] += v
        if labeled:
            print(f"  {'mode.stride':<16s} {'segments':>9s} "
                  f"{'fetches':>8s}", file=out)
            for lab in sorted(labeled):
                sN, fN = labeled[lab]
                print(f"  {lab:<16s} {int(sN):>9d} {int(fN):>8d}",
                      file=out)
        tel_best = gauges.get("device.telemetry.best_loss",
                              m_gauges.get("device.telemetry.best_loss"))
        if tel_best is not None:
            ei_mx = gauges.get("device.telemetry.ei_max",
                               m_gauges.get("device.telemetry.ei_max"))
            ei_mn = gauges.get("device.telemetry.ei_mean",
                               m_gauges.get("device.telemetry.ei_mean"))
            tps = gauges.get(
                "device.telemetry.trials_per_sec",
                m_gauges.get("device.telemetry.trials_per_sec"))
            fmt = lambda v: "-" if v is None else f"{v:.4g}"  # noqa: E731
            print(f"  slab: best {fmt(tel_best)}   ei max {fmt(ei_mx)} "
                  f"mean {fmt(ei_mn)}   {fmt(tps)} trials/s   nonfinite "
                  f"{int(counters.get('device.telemetry.nonfinite', 0))}"
                  f"   ties "
                  f"{int(counters.get('device.telemetry.argmax_ties', 0))}",
                  file=out)
    faults = counters.get("faults.injected", 0)
    requeued = counters.get("store.requeued", 0)
    fenced = (counters.get("store.write.fenced", 0)
              + counters.get("store.heartbeat.fenced", 0))
    print(f"faults injected {faults}   requeued {requeued}   "
          f"fenced {fenced}", file=out)
    pool_hits = counters.get("rpc.pool.hits", 0)
    pool_misses = counters.get("rpc.pool.misses", 0)
    if pool_hits or pool_misses:
        total = pool_hits + pool_misses
        print(f"pool: {int(pool_hits)}/{int(total)} reused "
              f"({pool_hits / total:.0%})   stale reconnects "
              f"{int(counters.get('rpc.pool.stale_reconnects', 0))}   "
              f"evicted {int(counters.get('rpc.pool.evicted', 0))}",
              file=out)
    parked = counters.get("store.longpoll.parked", 0)
    if parked:
        print(f"longpoll: parked {int(parked)}   woken "
              f"{int(counters.get('store.longpoll.woken', 0))}   timeouts "
              f"{int(counters.get('store.longpoll.timeouts', 0))}",
              file=out)

    # Per-verb server-side latency tails (+ merged client-side RPC time).
    hists = dict(snap.get("histograms", {}))
    for k, v in merged.get("histograms", {}).items():
        hists.setdefault(k, v)
    rows = []
    for name in sorted(hists):
        if name.startswith("netstore.verb.") and name.endswith(".s"):
            row = _hist_row(name[len("netstore.verb."):], hists[name])
            if row:
                rows.append(row)
    rpc = _hist_row("client.rpc (merged)", hists.get("netstore.client.rpc.s"))
    if rpc:
        rows.append(rpc)
    if rows:
        print(f"  {'verb':<28s} {'count':>7s} {'p50ms':>8s} "
              f"{'p95ms':>8s} {'p99ms':>8s}", file=out)
        for row in rows:
            print(row, file=out)

    # Per-tenant lane of the suggestion service (netstore.tenant.<t>.*):
    # verb volume, quota refusals and held claims, labeled by tenant.
    tenants = {}
    for k, v in counters.items():
        if not k.startswith("netstore.tenant."):
            continue
        rest = k[len("netstore.tenant."):]
        tname, _, metric = rest.partition(".")
        rec = tenants.setdefault(tname, {"calls": 0, "rate_rej": 0,
                                         "claims_rej": 0})
        if metric.startswith("verb.") and metric.endswith(".calls"):
            rec["calls"] += v
        elif metric == "quota.rate_rejected":
            rec["rate_rej"] += v
        elif metric == "quota.claims_rejected":
            rec["claims_rej"] += v
    if tenants:
        print(f"  {'tenant':<20s} {'calls':>8s} {'claims':>7s} "
              f"{'rate.rej':>9s} {'claim.rej':>10s}", file=out)
        for tname in sorted(tenants):
            rec = tenants[tname]
            held = gauges.get(f"netstore.tenant.{tname}.claims_held",
                              m_gauges.get(
                                  f"netstore.tenant.{tname}.claims_held"))
            print(f"  {tname:<20s} {int(rec['calls']):>8d} "
                  f"{held if held is not None else '-':>7} "
                  f"{int(rec['rate_rej']):>9d} {int(rec['claims_rej']):>10d}",
                  file=out)

    workers = fleet.get("workers", {})
    if workers:
        print("workers:", file=out)
        for wid in sorted(workers):
            w = workers[wid]
            age = w.get("age_s", 0.0)
            wc = w.get("counters", {})
            wg = w.get("gauges", {})
            stale = "  STALE" if age > 30.0 else ""
            print(f"  {wid:<28s} age {age:6.1f}s  trials "
                  f"{int(wc.get('worker.trials', 0)):>5d}  fails "
                  f"{wg.get('worker.consecutive_failures', 0)}{stale}",
                  file=out)

    # HEALTH: per-(tenant, exp_key) optimizer-health verdicts from the
    # server's last assessment pass (snap["health"], the `health` verb's
    # cache) — stagnation / EI-collapse surface here before loss curves
    # make them obvious.
    health = snap.get("health") or {}
    if health:
        print(f"health:  {'store':<26s} {'verdict':<12s} {'done':>5s} "
              f"{'best':>12s}  flags", file=out)
        for label in sorted(health):
            rep = health[label] or {}
            checks = rep.get("checks", {})
            flags = ",".join(k for k in ("stagnating", "ei_collapse",
                                         "dup_high", "split_degenerate")
                             if checks.get(k)) or "-"
            best = rep.get("best_loss")
            best_s = "-" if best is None else f"{best:.5g}"
            print(f"         {label:<26s} {rep.get('verdict', '?'):<12s} "
                  f"{int(rep.get('n_done', 0)):>5d} {best_s:>12s}  {flags}",
                  file=out)

    # ALERTS: SLO burn-rate state from the server's monitor
    # (snap["alerts"]); firing specs are the ones eating error budget
    # faster than it accrues in BOTH windows.
    alerts = snap.get("alerts") or []
    if alerts:
        fmt_b = lambda b: "    -" if b is None else f"{b:5.2f}"  # noqa: E731
        print(f"alerts:  {'slo':<20s} {'state':<8s} {'burn.fast':>9s} "
              f"{'burn.slow':>9s} {'value':>10s} {'target':>10s}", file=out)
        for st in alerts:
            state = "FIRING" if st.get("firing") else "ok"
            val = st.get("value")
            val_s = "-" if val is None else f"{val:.4g}"
            print(f"         {st['name']:<20s} {state:<8s} "
                  f"{fmt_b(st.get('burn_fast')):>9s} "
                  f"{fmt_b(st.get('burn_slow')):>9s} "
                  f"{val_s:>10s} {st.get('target'):>10}", file=out)

    # COST: the per-kernel cost ledger (snap["costs"], populated when the
    # serving process runs with HYPEROPT_TPU_COSTS=1) — compile wall time
    # + XLA flops/bytes per program, joined with live dispatch ms.
    _render_cost_panel(snap.get("costs"), counters, out)
    return (now, done)


def _cost_ledger_rows(costs: dict):
    """Format a cost ledger's ``entries`` into (header, rows) table lines."""
    header = (f"  {'kernel':<7s} {'n_cap':>6s} {'P':>4s} {'m':>5s} "
              f"{'compile_s':>9s} {'Mflops':>8s} {'MiB':>8s} "
              f"{'disp':>6s} {'ms/sugg':>8s}")
    dash = lambda v, w: f"{v:>{w}}" if v is not None else f"{'-':>{w}}"  # noqa: E731
    rows = []
    for e in costs.get("entries") or []:
        cs = e.get("compile_s")
        fl = e.get("flops")
        ba = e.get("bytes_accessed")
        mps = e.get("ms_per_suggestion")
        rows.append(
            f"  {e.get('kernel', '?'):<7s} {dash(e.get('n_cap'), 6)} "
            f"{dash(e.get('P'), 4)} {dash(e.get('m'), 5)} "
            f"{dash(None if cs is None else f'{cs:.3f}', 9)} "
            f"{dash(None if fl is None else f'{fl / 1e6:.2f}', 8)} "
            f"{dash(None if ba is None else f'{ba / 2**20:.2f}', 8)} "
            f"{int(e.get('dispatches', 0)):>6d} "
            f"{dash(None if mps is None else f'{mps:.3f}', 8)}")
    return header, rows


def _render_cost_panel(costs, counters, out) -> None:
    """The ``cost:`` dashboard panel — shared by ``live`` and ``bundle``."""
    costs = costs or {}
    header, rows = _cost_ledger_rows(costs)
    if rows:
        kc = costs.get("kernel_cache", {})
        print(f"cost:    {len(rows)} ledger entr(ies)   kernel-cache "
              f"{int(kc.get('requests', 0))} req / "
              f"{int(kc.get('misses', 0))} miss", file=out)
        print(header, file=out)
        for row in rows:
            print(row, file=out)
        live_ms = costs.get("live_ms") or {}
        for name in sorted(live_ms):
            h = live_ms[name]
            mean = h.get("mean")
            p95 = h.get("p95")
            print(f"  {name:<28s} {int(h.get('count', 0)):>7d} calls  "
                  f"mean {mean if mean is None else f'{mean:.2f}'}ms  "
                  f"p95 {p95 if p95 is None else f'{p95:.2f}'}ms", file=out)
    elif counters.get("cost.compiles"):
        # The recorder is armed somewhere in the fleet but this process
        # holds no ledger rows (compiles happened in another process).
        print(f"cost:    {int(counters['cost.compiles'])} compile(s) "
              f"recorded elsewhere in the fleet (no local ledger rows)",
              file=out)


def live(url: str, token=None, interval: float = 2.0, once: bool = False,
         out=None) -> int:
    """Poll ``GET /metrics`` into a terminal dashboard (ctrl-C to stop)."""
    out = out if out is not None else sys.stdout
    prev = None
    while True:
        try:
            snap = fetch_metrics(url, token=token)
        except Exception as e:
            print(f"fetch failed: {type(e).__name__}: {e}", file=out)
            if once:
                return 1
            time.sleep(interval)
            continue
        if not once and out is sys.stdout and sys.stdout.isatty():
            print("\x1b[2J\x1b[H", end="", file=out)
        print(f"-- {url} --", file=out)
        prev = render_live(snap, out=out, prev=prev)
        if once:
            return 0
        try:
            time.sleep(interval)
        except KeyboardInterrupt:
            return 0


# -- WAL inspection ---------------------------------------------------------

def show_wal(wal_dir: str, as_json: bool = False, out=None) -> int:
    """Offline summary of a :class:`~.service.server.ServiceServer` WAL
    directory: snapshot coverage, unsnapshotted tail records per verb and
    per (tenant, exp_key) store, torn-tail count."""
    out = out if out is not None else sys.stdout
    from .service.wal import inspect as wal_inspect

    info = wal_inspect(wal_dir)
    if as_json:
        json.dump(info, out, indent=2, sort_keys=True)
        print(file=out)
        return 0
    print(f"wal dir: {info['root']}", file=out)
    snap = info["snapshot"]
    if snap is None:
        print("snapshot: (none)", file=out)
    else:
        age = ""
        if snap.get("t_wall"):
            age = f", written {time.time() - snap['t_wall']:.0f}s ago"
        print(f"snapshot: seq {snap['seq']}, {snap['stores']} store(s), "
              f"{snap['idem_entries']} idem entr(ies), "
              f"{snap['bytes']} bytes{age}", file=out)
    rng = info["seq_range"]
    print(f"tail: {info['records']} record(s)"
          + (f" (seq {rng[0]}..{rng[1]})" if rng else "")
          + f", {info['wal_bytes']} bytes", file=out)
    if info["per_verb"]:
        print("  per verb:", file=out)
        for verb, n in sorted(info["per_verb"].items(),
                              key=lambda kv: -kv[1]):
            print(f"    {verb:<16s} {n}", file=out)
    if info["per_store"]:
        print("  per store (tenant/exp_key):", file=out)
        for key, n in sorted(info["per_store"].items(),
                             key=lambda kv: -kv[1]):
            print(f"    {key:<24s} {n}", file=out)
    if info["torn_tail"]:
        print(f"torn tail: {info['torn_tail']} line(s) dropped "
              "(crash mid-append; the verb was never acked)", file=out)
    return 0


# -- flight-recorder bundles -------------------------------------------------

def show_bundle(bundle_dir: str, out=None) -> int:
    """Render a flight-recorder postmortem bundle directory
    (:mod:`hyperopt_tpu.obs.bundle`): manifest, event-ring coverage,
    section inventory, SLO/health verdicts, WAL anchor and the
    per-kernel cost ledger."""
    out = out if out is not None else sys.stdout
    from .obs import bundle as _bundle

    try:
        payload = _bundle.read_bundle(bundle_dir)
    except FileNotFoundError as e:
        print(f"error: {e}", file=out)
        return 1
    man = payload.get("manifest") or {}
    print(f"bundle: {bundle_dir}", file=out)
    print(f"  schema {man.get('schema')}   reason {man.get('reason')!r}   "
          f"pid {man.get('pid')}   host {man.get('host')}", file=out)
    if man.get("trace_id"):
        print(f"  trace_id {man['trace_id']}  (splice into a fleet trace: "
              f"`show trace --merge {bundle_dir} <other dirs...>`)", file=out)
    print(f"  events: {man.get('n_events', 0)} captured, "
          f"{man.get('n_emitted', 0)} emitted, "
          f"{man.get('n_dropped', 0)} displaced at the ring", file=out)
    if man.get("extra"):
        print(f"  extra: {man['extra']}", file=out)
    print(f"  sections: {', '.join(man.get('sections') or [])}", file=out)

    # Event-type census of the captured ring (meta header excluded).
    types = Counter(rec.get("type") for rec in payload.get("events") or []
                    if rec.get("type") not in (None, "meta"))
    if types:
        census = "  ".join(f"{t}:{n}" for t, n in types.most_common(8))
        print(f"  ring: {census}", file=out)

    slo = payload.get("slo")
    if isinstance(slo, list) and slo:
        firing = [st for st in slo if isinstance(st, dict)
                  and st.get("firing")]
        print(f"slo: {len(slo)} spec(s), {len(firing)} firing"
              + (" — " + ", ".join(st.get("name", "?") for st in firing)
                 if firing else ""), file=out)
    health = payload.get("health")
    if isinstance(health, dict) and health and "error" not in health:
        verdicts = Counter((rep or {}).get("verdict", "?")
                           for rep in health.values())
        print("health: " + "  ".join(f"{v}:{n}" for v, n in
                                     sorted(verdicts.items())), file=out)
    wal = payload.get("wal")
    if isinstance(wal, dict) and "error" not in wal:
        print(f"wal: seq {wal.get('seq')}  snap_seq {wal.get('snap_seq')}  "
              f"state_hash {wal.get('state_hash')}", file=out)
    env = payload.get("env")
    if isinstance(env, dict):
        n_red = sum(1 for v in env.values() if v == "<redacted>")
        print(f"env: {len(env)} key(s) captured"
              + (f", {n_red} redacted" if n_red else ""), file=out)

    costs = payload.get("costs")
    if isinstance(costs, dict) and "error" not in costs:
        counters = ((payload.get("metrics") or {}).get("counters") or {})
        _render_cost_panel(costs, counters, out)
    return 0


def show_lint(report, out=None):
    """Render an analyzer report (``python -m hyperopt_tpu.analysis --json``)
    grouped by rule, new findings first, stale/invalid baseline rows last."""
    out = out or sys.stdout
    by_rule = {}
    for key in ("new", "baselined"):
        for f in report.get(key, ()):
            by_rule.setdefault(f["rule"], []).append((key, f))
    for rule in sorted(by_rule):
        rows = by_rule[rule]
        n_new = sum(1 for k, _ in rows if k == "new")
        print(f"{rule}: {len(rows)} finding(s), {n_new} new", file=out)
        for key, f in sorted(rows, key=lambda kf: (kf[0] != "new",
                                                   kf[1]["file"],
                                                   kf[1]["line"])):
            tag = "NEW " if key == "new" else "base"
            print(f"  [{tag}] {f['file']}:{f['line']} "
                  f"[{f['symbol']}] {f['message']}", file=out)
    for e in report.get("stale", ()):
        print(f"stale baseline entry: {e['rule']} {e['file']} "
              f"[{e['symbol']}] — finding no longer fires; delete it",
              file=out)
    for err in report.get("baseline_errors", ()):
        print(f"baseline error: {err}", file=out)
    counts = report.get("counts", {})
    print(f"{sum(counts.values())} finding(s): "
          f"{len(report.get('new', ()))} new, "
          f"{len(report.get('baselined', ()))} baselined, "
          f"{len(report.get('stale', ()))} stale; counts {counts}",
          file=out)
    if report.get("baseline_errors"):
        return 2
    return 1 if (report.get("new") or report.get("stale")) else 0


def main(argv=None):
    argv = sys.argv[1:] if argv is None else list(argv)
    if argv and argv[0] == "trace":
        # Subcommand form (`hyperopt-tpu-show trace <dir>`); the flag-based
        # trials inspection below keeps its historical interface.
        tp = argparse.ArgumentParser(prog="hyperopt-tpu-show trace",
                                     description="summarize a trace dir, or "
                                                 "--merge several into one "
                                                 "Perfetto trace")
        tp.add_argument("trace_dir", nargs="?", default=None,
                        help="fmin(..., trace_dir=...) output")
        tp.add_argument("--merge", nargs="+", metavar="DIR", default=None,
                        help="stitch these processes' loop_events.jsonl "
                             "into one clock-normalized Chrome trace")
        tp.add_argument("-o", "--out", default="merged_trace.json",
                        help="output path for --merge "
                             "(default: merged_trace.json)")
        targs = tp.parse_args(argv[1:])
        if targs.merge:
            merge_traces(targs.merge, out_path=targs.out)
            return 0
        if targs.trace_dir is None:
            tp.error("a trace dir (or --merge DIR...) is required")
        summarize_trace(targs.trace_dir)
        return 0

    if argv and argv[0] == "wal":
        wp = argparse.ArgumentParser(prog="hyperopt-tpu-show wal",
                                     description="summarize a suggestion-"
                                                 "service WAL directory "
                                                 "(snapshot + tail records)")
        wp.add_argument("wal_dir", help="ServiceServer --wal-dir")
        wp.add_argument("--json", action="store_true",
                        help="emit the raw inspect() dict")
        wargs = wp.parse_args(argv[1:])
        return show_wal(wargs.wal_dir, as_json=wargs.json)

    if argv and argv[0] == "bundle":
        bp = argparse.ArgumentParser(prog="hyperopt-tpu-show bundle",
                                     description="render a flight-recorder "
                                                 "postmortem bundle "
                                                 "directory")
        bp.add_argument("bundle_dir", help="bundle directory (a flight-"
                                           "recorder dump or a NetTrials"
                                           ".bundle(out_dir=...) pull)")
        bargs = bp.parse_args(argv[1:])
        return show_bundle(bargs.bundle_dir)

    if argv and argv[0] == "live":
        lp = argparse.ArgumentParser(prog="hyperopt-tpu-show live",
                                     description="poll a netstore server's "
                                                 "fleet metrics into a "
                                                 "terminal dashboard")
        lp.add_argument("url", help="netstore server url, e.g. "
                                    "http://host:8999")
        lp.add_argument("--token", default=None,
                        help="X-Netstore-Token (or env "
                             "HYPEROPT_TPU_NETSTORE_TOKEN)")
        lp.add_argument("--interval", type=float, default=2.0)
        lp.add_argument("--once", action="store_true",
                        help="print a single frame and exit")
        largs = lp.parse_args(argv[1:])
        token = largs.token or os.environ.get(
            "HYPEROPT_TPU_NETSTORE_TOKEN") or None
        return live(largs.url, token=token, interval=largs.interval,
                    once=largs.once)

    if argv and argv[0] == "lint":
        ap = argparse.ArgumentParser(prog="hyperopt-tpu-show lint",
                                     description="render an invariant-"
                                                 "analyzer report (or run "
                                                 "the analyzers now)")
        ap.add_argument("report", nargs="?", default=None,
                        help="saved `python -m hyperopt_tpu.analysis "
                             "--json` output; omit to analyze --root")
        ap.add_argument("--root", default=".",
                        help="repo root to analyze when no report file "
                             "is given (default: cwd)")
        ap.add_argument("--baseline", default=None,
                        help="baseline file (default: the repo's "
                             "hyperopt_tpu/analysis/baseline.json)")
        largs = ap.parse_args(argv[1:])
        if largs.report:
            with open(largs.report, "r", encoding="utf-8") as f:
                report = json.load(f)
        else:
            from .analysis import default_baseline_path
            from .analysis.__main__ import build_report
            root = os.path.abspath(largs.root)
            report = build_report(
                root, largs.baseline or default_baseline_path(root))
        return show_lint(report)

    p = argparse.ArgumentParser(description="inspect a hyperopt_tpu "
                                            "experiment")
    src = p.add_mutually_exclusive_group(required=True)
    src.add_argument("--root", help="file-store experiment root")
    src.add_argument("--pickle", help="trials_save_file pickle")
    p.add_argument("--exp-key", default="default")
    p.add_argument("--plot", default=None,
                   help="write a loss-history PNG to this path")
    args = p.parse_args(argv)

    if args.root:
        from .parallel.filestore import FileTrials
        trials = FileTrials(args.root, exp_key=args.exp_key)
    else:
        with open(args.pickle, "rb") as f:
            trials = pickle.load(f)
        trials.refresh()

    summarize(trials)

    if args.plot:
        import matplotlib
        matplotlib.use("Agg", force=True)
        from . import plotting
        ax = plotting.main_plot_history(trials, do_show=False)
        ax.figure.savefig(args.plot, dpi=120)
        print(f"wrote {args.plot}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
