"""Experiment inspection CLI.

Reference: ``hyperopt/mongoexp.py::main_show`` / ``main_plot`` utilities
(SURVEY.md §2): summarize a live experiment's state from its store.

Usage::

    python -m hyperopt_tpu.show --root /shared/exp --exp-key e1
    python -m hyperopt_tpu.show --pickle trials.pkl [--plot history.png]
"""

from __future__ import annotations

import argparse
import pickle
import sys
from collections import Counter

from .base import (
    JOB_STATE_CANCEL,
    JOB_STATE_DONE,
    JOB_STATE_ERROR,
    JOB_STATE_NEW,
    JOB_STATE_RUNNING,
    Trials,
)
from .exceptions import AllTrialsFailed

_STATE_NAMES = {JOB_STATE_NEW: "new", JOB_STATE_RUNNING: "running",
                JOB_STATE_DONE: "done", JOB_STATE_ERROR: "error",
                JOB_STATE_CANCEL: "cancel"}


def summarize(trials: Trials, out=None) -> None:
    # Resolve the stream at CALL time: an import-time `out=sys.stdout`
    # default would capture whatever stdout object existed when this module
    # was first imported (possibly a since-closed redirection).
    out = out if out is not None else sys.stdout
    states = Counter(t["state"] for t in trials)
    print(f"trials: {len(trials)}", file=out)
    for s, name in _STATE_NAMES.items():
        if states.get(s):
            print(f"  {name:8s} {states[s]}", file=out)
    try:
        best = trials.best_trial
        print(f"best loss: {best['result']['loss']:.6g} "
              f"(tid {best['tid']})", file=out)
        point = {k: v[0] for k, v in best["misc"]["vals"].items() if v}
        for k in sorted(point):
            print(f"  {k} = {point[k]}", file=out)
    except AllTrialsFailed:
        print("best loss: (no successful trials yet)", file=out)
    owners = Counter(t.get("owner") for t in trials if t.get("owner"))
    if owners:
        print("workers:", file=out)
        for owner, n in owners.most_common():
            print(f"  {owner}: {n}", file=out)
    try:
        n_att = len(trials.attachments)
    except Exception:
        n_att = 0
    if n_att:
        print(f"attachments: {n_att}", file=out)


def main(argv=None):
    p = argparse.ArgumentParser(description="inspect a hyperopt_tpu "
                                            "experiment")
    src = p.add_mutually_exclusive_group(required=True)
    src.add_argument("--root", help="file-store experiment root")
    src.add_argument("--pickle", help="trials_save_file pickle")
    p.add_argument("--exp-key", default="default")
    p.add_argument("--plot", default=None,
                   help="write a loss-history PNG to this path")
    args = p.parse_args(argv)

    if args.root:
        from .parallel.filestore import FileTrials
        trials = FileTrials(args.root, exp_key=args.exp_key)
    else:
        with open(args.pickle, "rb") as f:
            trials = pickle.load(f)
        trials.refresh()

    summarize(trials)

    if args.plot:
        import matplotlib
        matplotlib.use("Agg", force=True)
        from . import plotting
        ax = plotting.main_plot_history(trials, do_show=False)
        ax.figure.savefig(args.plot, dpi=120)
        print(f"wrote {args.plot}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
