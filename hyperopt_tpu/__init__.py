"""hyperopt_tpu — a TPU-native hyperparameter-optimization framework.

A ground-up JAX/XLA re-design of the capabilities of the reference
(``jonatasfreitasv/hyperopt``, a fork of hyperopt — see SURVEY.md): the same
public surface (``fmin``, ``hp.*`` search-space DSL, suggest-algorithm and
``Trials`` plugin boundaries, random / TPE / annealing / mixture / ATPE
algorithms, distributed trial stores), with the numeric core compiled to XLA:

* search spaces compile ONCE to batched, jitted samplers (dense vals + masks
  instead of ragged idxs/vals),
* TPE's adaptive-Parzen fitting, GMM log-pdfs and EI scoring are jitted
  batched kernels over a device-resident trial history,
* candidate batches and multi-start posteriors shard across a TPU slice via
  ``jax.sharding`` / ``shard_map``.
"""

from . import (  # noqa: F401
    anneal,
    atpe,
    criteria,
    fleet,
    graphviz,
    hp,
    mix,
    plotting,
    qmc,
    rand,
    rdists,
    tpe,
)
from .base import (  # noqa: F401
    Ctrl,
    Domain,
    JOB_STATE_CANCEL,
    JOB_STATE_DONE,
    JOB_STATE_ERROR,
    JOB_STATE_NEW,
    JOB_STATE_RUNNING,
    JOB_STATES,
    STATUS_FAIL,
    STATUS_NEW,
    STATUS_OK,
    STATUS_RUNNING,
    STATUS_STRINGS,
    STATUS_SUSPENDED,
    Trials,
    trials_from_docs,
)
from .exceptions import (  # noqa: F401
    AllTrialsFailed,
    DuplicateLabel,
    HyperoptTpuError,
    InjectedFault,
    InvalidTrial,
    NetstoreUnavailable,
    TransientEvaluationError,
)
from . import faults  # noqa: F401 — seeded fault-injection registry
from .fmin import (  # noqa: F401
    FMinIter,
    fmin,
    fmin_pass_expr_memo_ctrl,
    generate_trials_to_calculate,
    partial,
    space_eval,
)
from .scope import scope  # noqa: F401
from . import pyll_shim as pyll  # noqa: F401 — reference-compat alias

# Make `import hyperopt_tpu.pyll` / `from hyperopt_tpu.pyll import scope`
# resolve like a real submodule (reference import idiom: hyperopt.pyll).
import sys as _sys

_sys.modules[__name__ + ".pyll"] = pyll
del _sys
from .parallel import FileTrials, PoolTrials  # noqa: F401 — the reference
# exports its distributed Trials at top level too (hyperopt.SparkTrials;
# SURVEY.md §2 package/CLI row): PoolTrials ≙ SparkTrials (local parallel
# evaluation), FileTrials ≙ MongoTrials (durable elastic workers).
from .device import fmin_device  # noqa: F401 — device-resident loop
from .space import Apply, CompiledSpace, compile_space  # noqa: F401
from .utils import parameter_importance  # noqa: F401
from .utils.early_stop import no_progress_loss  # noqa: F401

__version__ = "0.1.0"

__all__ = [
    "fmin", "fmin_device", "FMinIter", "fmin_pass_expr_memo_ctrl",
    "space_eval",
    "generate_trials_to_calculate",
    "partial", "hp", "tpe", "rand", "anneal", "mix", "atpe", "qmc", "fleet",
    "criteria", "rdists", "plotting", "graphviz", "scope", "pyll",
    "Trials", "trials_from_docs", "Domain", "Ctrl",
    "PoolTrials", "FileTrials",
    "Apply", "CompiledSpace", "compile_space", "no_progress_loss",
    "parameter_importance",
    "STATUS_NEW", "STATUS_RUNNING", "STATUS_SUSPENDED", "STATUS_OK",
    "STATUS_FAIL", "STATUS_STRINGS",
    "JOB_STATE_NEW", "JOB_STATE_RUNNING", "JOB_STATE_DONE",
    "JOB_STATE_ERROR", "JOB_STATE_CANCEL", "JOB_STATES",
    "AllTrialsFailed", "DuplicateLabel", "HyperoptTpuError", "InvalidTrial",
    "InjectedFault", "NetstoreUnavailable", "TransientEvaluationError",
    "faults",
]
