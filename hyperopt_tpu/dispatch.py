"""One dispatch substrate: sharding × fleet lanes × pipeline depth × head.

ROADMAP item 1 ("the unlock"): the repo grew four partially overlapping
suggest paths — local (``tpe.suggest_dispatch``), mesh-sharded
(``parallel/sharded.py``), multi-start, and fleet cohorts — that could not
compose.  This module is the single substrate they all route through now:

* **sharding** — the EI candidate axis split over a ``jax.sharding.Mesh``
  (``ShardedTpeKernel``: collective top-k/argmax over ICI), the
  data-parallel accelerator-runtime framing of Tran et al.
  (PAPERS.md, arXiv:1811.02091);
* **fleet lanes** — the vmap axis over experiments
  (``fleet.CohortScheduler`` acquires its kernels here, so a cohort's
  lane stack runs against the mesh-sharded kernel when one is active —
  the population-as-array idiom of evosax, arXiv:2212.04180);
* **pipeline depth** — the substrate returns ordinary ``tpe`` dispatch
  handles (``("pending", cs, new_ids, arrs, exp_key)``), so the four
  async halves (dispatch / materialize / start_transfer / handle_ready)
  and ``fmin``'s depth-D executor compose without knowing a mesh exists;
* **head** — ``tpe`` / ``tpe_quantile`` both enter through
  ``tpe.suggest_dispatch``, which consults :func:`active_mesh` and
  delegates here, so every head registered in ``backends/contract.py``
  that routes through the canonical dispatch inherits sharding.

Mode selection (``HYPEROPT_TPU_DISPATCH``):

* ``auto`` (default) — sharded when a mesh was registered
  (:func:`set_default_mesh`, done by ``parallel.multihost.initialize``)
  or passed explicitly; local otherwise.  Nothing changes for
  single-process CPU runs even though tests fake 8 devices.
* ``sharded`` — build a mesh over all visible devices and shard every
  suggest (the opt-in the CPU parity tests use).
* ``local`` — never shard, even with a registered mesh (kill switch).

The sharded kernel is numerics-preserving (a ``with_sharding_constraint``
on the candidate axis, nothing else), so substrate output is bit-identical
to the local path at the same (seed, n_cand, history) — pinned by
``tests/test_dispatch.py``.

Cache discipline: sharded kernels live in ``cs._dispatch_kernels`` keyed by
the FULL local kernel key (all 15 env-toggle components of
``tpe.get_kernel``) plus the mesh layout — the legacy
``_sharded_tpe_kernels`` cache omitted ``prng_impl``/``HYPEROPT_TPU_EI_*``
toggles and could hand back a stale kernel after an env flip.  Hits/misses
feed the same ``kernel_cache_stats`` counters as the local cache: one
compile per (head, tier, mesh-shape), asserted by the MULTICHIP bench.
"""

from __future__ import annotations

import os
import threading

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import base
from . import history as _rhist
from . import tpe as _tpe
from .obs import kernel_cache_event
from .obs import costs as _costs
from .obs.metrics import registry as _metrics_registry
from .space import CompiledSpace, prng_impl, prng_key

CAND_AXIS = "sp"    # candidate (sequence-like long) axis
START_AXIS = "dp"   # independent-posterior (data-parallel) axis


# ---------------------------------------------------------------------------
# mode + mesh registry
# ---------------------------------------------------------------------------


def mode() -> str:
    """Dispatch-substrate routing mode (``HYPEROPT_TPU_DISPATCH``).

    ``auto`` (default) — sharded iff a mesh is registered or passed;
    ``sharded`` — force a mesh over all visible devices; ``local`` —
    never shard.  Unrecognized spellings fall back to ``auto`` (the
    conservative mode: behavior only changes when a mesh was
    deliberately provided)."""
    env = os.environ.get("HYPEROPT_TPU_DISPATCH", "auto").strip().lower()
    return env if env in ("local", "sharded") else "auto"


_MESH_LOCK = threading.Lock()
_DEFAULT_MESH = None   # registered by multihost.initialize() / tests
_ENV_MESH = None       # lazily built for mode()=="sharded"


def set_default_mesh(mesh):
    """Register the process-wide default mesh (``auto`` mode shards once
    one is registered).  Pass ``None`` to unregister."""
    global _DEFAULT_MESH
    with _MESH_LOCK:
        _DEFAULT_MESH = mesh
    return mesh


def clear_default_mesh():
    """Drop both the registered and the env-built mesh (test hygiene)."""
    global _DEFAULT_MESH, _ENV_MESH
    with _MESH_LOCK:
        _DEFAULT_MESH = None
        _ENV_MESH = None


def active_mesh(mesh=None):
    """Resolve the mesh the substrate should shard over, or ``None`` for
    the local path.  Explicit ``mesh`` wins; ``local`` mode vetoes
    everything; ``sharded`` mode lazily builds (and memoizes) a mesh over
    all visible devices; ``auto`` uses only a registered default."""
    m = mode()
    if m == "local":
        return None
    if mesh is not None:
        return mesh
    with _MESH_LOCK:
        if _DEFAULT_MESH is not None:
            return _DEFAULT_MESH
    if m == "sharded":
        global _ENV_MESH
        with _MESH_LOCK:
            if _ENV_MESH is None:
                _ENV_MESH = default_mesh()
            return _ENV_MESH
    return None


# ---------------------------------------------------------------------------
# mesh helpers (canonical home; parallel.sharded re-exports for compat)
# ---------------------------------------------------------------------------


def _shard_map(f, mesh, in_specs, out_specs):
    """``jax.shard_map`` with a jax-0.4.x fallback.

    ``shard_map`` graduated from ``jax.experimental`` only in jax 0.5;
    on 0.4.x the top-level symbol is absent and the replication-check
    kwarg is still spelled ``check_rep``.  Feature-detect rather than
    version-parse so pre-release builds resolve correctly."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=False)
    from jax.experimental.shard_map import shard_map as sm

    return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
              check_rep=False)


def default_mesh(devices=None, n_starts=1):
    """Build a ``(dp=n_starts, sp=rest)`` mesh over the available devices."""
    devices = np.asarray(devices if devices is not None else jax.devices())
    n = devices.size
    if n % n_starts:
        raise ValueError(f"{n} devices not divisible by n_starts={n_starts}")
    return Mesh(devices.reshape(n_starts, n // n_starts),
                (START_AXIS, CAND_AXIS))


def _mesh_key(mesh):
    """Stable cache key for a mesh — device ids + layout, not ``id(mesh)``
    (a garbage-collected mesh's id can be recycled by a new mesh, handing
    back a kernel bound to the dead mesh's sharding)."""
    return (mesh.axis_names, mesh.devices.shape,
            tuple(d.id for d in mesh.devices.flat))


class ShardedTpeKernel(_tpe._TpeKernel):
    """TPE suggest step with the candidate axis sharded over a mesh.

    Same math as :class:`~hyperopt_tpu.tpe._TpeKernel`; the only difference
    is a ``with_sharding_constraint`` on every candidate-axis array, which
    makes XLA partition the EI sweep across ``mesh[CAND_AXIS]`` and reduce
    the argmax over ICI.
    """

    def __init__(self, cs: CompiledSpace, n_cap, n_cand, lf, mesh,
                 split="sqrt", multivariate=False, cat_prior=None):
        self.mesh = mesh
        n_shards = mesh.shape[CAND_AXIS]
        if n_cand % n_shards:
            raise ValueError(
                f"n_EI_candidates={n_cand} not divisible by the "
                f"{n_shards}-way candidate mesh axis")
        # Chunked scoring would fight the sharding constraint; per-device
        # candidate counts are modest, so score in one block.
        self.score_chunk = n_cand + 1
        super().__init__(cs, n_cap, n_cand, lf, split,
                         multivariate=multivariate, cat_prior=cat_prior)

    def _constrain_cand(self, x, axis=-1):
        spec = [None] * x.ndim
        spec[axis if axis >= 0 else x.ndim + axis] = CAND_AXIS
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(self.mesh, P(*spec)))


# ---------------------------------------------------------------------------
# unified kernel acquisition
# ---------------------------------------------------------------------------


def get_kernel(cs: CompiledSpace, n_cap: int, n_cand: int, lf: int,
               split: str = "sqrt", multivariate: bool = False,
               cat_prior=None, mesh=None, strict: bool = False):
    """The one kernel-acquisition point for every suggest path.

    ``mesh=None`` → exactly ``tpe.get_kernel`` (the local path keeps its
    cache, key, and bit-for-bit numerics).  With a mesh, a
    :class:`ShardedTpeKernel` from ``cs._dispatch_kernels``, keyed by the
    full local toggle key + mesh layout + routing mode, instrumented
    through the same ``kernel_cache_stats`` / cost-ledger hooks.

    Indivisible ``n_cand`` (candidate axis does not split over the mesh):
    ``strict=True`` (the legacy ``parallel.sharded`` surface) raises the
    pinned ValueError; ``strict=False`` (ambient routing) falls back to
    the local kernel and counts ``dispatch.local`` — an env-selected mesh
    must never turn a working config into a crash."""
    if mesh is None:
        return _tpe.get_kernel(cs, n_cap, n_cand, lf, split,
                               multivariate, cat_prior)
    n_shards = mesh.shape[CAND_AXIS]
    if n_cand % n_shards:
        if strict:
            raise ValueError(
                f"n_EI_candidates={n_cand} not divisible by the "
                f"{n_shards}-way candidate mesh axis")
        _metrics_registry().counter("dispatch.fallback_indivisible").inc()
        return _tpe.get_kernel(cs, n_cap, n_cand, lf, split,
                               multivariate, cat_prior)
    from .ops.gmm import _comp_sampler

    with _tpe._KERNELS_LOCK:
        cache = getattr(cs, "_dispatch_kernels", None)
        if cache is None:
            cache = cs._dispatch_kernels = {}
    cat_prior = cat_prior or _tpe._cat_prior_default()
    # Full local key discipline (every toggle baked into the traced
    # program) + the mesh layout: the legacy sharded cache omitted the
    # prng/EI toggles and could serve a stale kernel after an env flip.
    k = (n_cap, n_cand, lf, split, multivariate, cat_prior,
         _tpe._pallas_mode(), _comp_sampler(), _tpe._pallas_tile(),
         _tpe._split_impl(), prng_impl(), _tpe._pallas_ei_impl(),
         _tpe._ei_precision(), _tpe._ei_topm(), _tpe._fused_step(),
         _rhist.enabled(), ("mesh",) + _mesh_key(mesh))
    with _tpe._KERNELS_LOCK:
        hit = k in cache
        if not hit:
            cache[k] = ShardedTpeKernel(cs, n_cap, n_cand, lf, mesh, split,
                                        multivariate=multivariate,
                                        cat_prior=cat_prior)
    kernel_cache_event(k, hit)
    kern = cache[k]
    kern._cost_key = k
    if not hit:
        def _lower(kern=kern):
            import jax.numpy as jnp

            f32 = jnp.float32
            sd = jax.ShapeDtypeStruct
            nc, p = kern.n_cap, kern.cs.n_params
            return kern._fn_seeded.lower(
                sd((), jnp.uint32),
                sd((nc, p), f32), sd((nc, p), jnp.bool_),
                sd((nc,), f32), sd((nc,), jnp.bool_),
                sd((), f32), sd((), f32)).compile()
        _costs.record_compile("tpe_sharded", k, _lower, n_cap=n_cap,
                              P=cs.n_params, m=1)
    return kern


# ---------------------------------------------------------------------------
# the substrate dispatch (sharded twin of tpe.suggest_dispatch)
# ---------------------------------------------------------------------------


def suggest_dispatch(new_ids, domain, trials, seed, mesh=None, strict=False,
                     prior_weight=_tpe._default_prior_weight,
                     n_startup_jobs=_tpe._default_n_startup_jobs,
                     n_EI_candidates=_tpe._default_n_EI_candidates,
                     gamma=_tpe._default_gamma,
                     linear_forgetting=_tpe._default_linear_forgetting,
                     split="sqrt", multivariate=False, startup=None,
                     cat_prior=None, verbose=True):
    """Mesh-sharded twin of :func:`tpe.suggest_dispatch`.

    Identical control flow and numerics (same bucket math, same resident
    feed, same seeded entries, same handle protocol) with the kernel
    acquired through :func:`get_kernel` — so the handle is materialized /
    start-transferred / pipelined by the unchanged ``tpe`` halves, and
    the output is bit-identical to the local path on a fixed seed.

    The resident ring is fed with a mesh-replicated placement
    (``NamedSharding(mesh, P())``) keyed by the mesh layout, so sharded
    suggest inherits the O(P) delta-append upload path; cohort coalescing
    composes in ``fleet.CohortScheduler``, which acquires its batched
    kernel from the same :func:`get_kernel`."""
    mesh = active_mesh(mesh)
    if mesh is None:
        return _tpe.suggest_dispatch(
            new_ids, domain, trials, seed, prior_weight=prior_weight,
            n_startup_jobs=n_startup_jobs, n_EI_candidates=n_EI_candidates,
            gamma=gamma, linear_forgetting=linear_forgetting, split=split,
            multivariate=multivariate, startup=startup, cat_prior=cat_prior,
            verbose=verbose)
    cs = domain.cs
    n = len(new_ids)
    exp_key = getattr(trials, "exp_key", None)
    if n == 0 or cs.n_params == 0:
        return ("ready", cs, list(new_ids),
                (np.zeros((n, cs.n_params), np.float32),
                 np.ones((n, cs.n_params), bool)), exp_key)
    h = trials.history(cs)
    if int(h["ok"].sum()) < n_startup_jobs:
        v, a = _tpe._startup_batch(startup, new_ids, domain, trials, seed)
        if not isinstance(a, np.ndarray):
            v = np.asarray(v)
            a = cs.active_mask_host(v)
        return ("ready", cs, list(new_ids),
                (np.asarray(v), np.asarray(a)), exp_key)
    resident = _rhist.enabled()
    fant = None
    if resident:
        fant = _tpe._inflight_fantasy_rows(h, trials, cs)
        n_rows = h["vals"].shape[0] + (fant[0].shape[0] if fant else 0)
    else:
        h = _tpe._with_inflight_fantasies(h, trials, cs)
        n_rows = h["vals"].shape[0]
    m = _tpe._batch_size_for(n)
    kern = get_kernel(cs, _tpe._bucket(n_rows + (m if n > 1 else 0)),
                      int(n_EI_candidates), int(linear_forgetting), split,
                      multivariate, cat_prior, mesh=mesh, strict=strict)
    sharded = getattr(kern, "mesh", None) is not None
    reg = _metrics_registry()
    if sharded:
        reg.counter("dispatch.sharded").inc()
    else:
        reg.counter("dispatch.local").inc()
    if n_rows >= 0.75 * kern.n_cap:
        _tpe._prewarm_async(
            get_kernel(cs, kern.n_cap * 2, int(n_EI_candidates),
                       int(linear_forgetting), split, multivariate,
                       cat_prior, mesh=mesh, strict=strict), n=m)
        if resident:
            _rhist.pregrow(trials, cs, kern.n_cap * 2)
    from time import perf_counter

    t_feed = perf_counter()
    if resident:
        # Resident history replicated over the mesh (P() = no sharded
        # dims); placement keys the store so a plain-jit path on the same
        # trials keeps its own canonical buffers.
        kw = (dict(sharding=NamedSharding(mesh, P()),
                   shard_key=_mesh_key(mesh)) if sharded else {})
        hv, ha, hl, hok = _rhist.device_history(
            trials, cs, h, kern.n_cap, fantasies=fant, **kw)
    else:
        hv, ha, hl, hok = _tpe._padded_history(h, kern.n_cap)
    _tpe._obs_ms(reg, "suggest.upload_ms", (perf_counter() - t_feed) * 1e3)
    t_disp = perf_counter()
    seed32 = int(seed) % (2 ** 32)
    from contextlib import nullcontext

    with (mesh if sharded else nullcontext()):
        if n == 1:
            arrs = kern.suggest_seeded(seed32, hv, ha, hl, hok,
                                       gamma, prior_weight)
        else:
            arrs = kern.suggest_many_seeded(seed32, m, n_rows, hv, ha,
                                            hl, hok, gamma, prior_weight)
            _tpe._prewarm_async(kern, n=1)
    dms = (perf_counter() - t_disp) * 1e3
    _tpe._obs_ms(reg, "suggest.dispatch_ms", dms)
    _costs.observe_dispatch(getattr(kern, "_cost_key", None), dms)
    return ("pending", cs, list(new_ids), arrs, exp_key)


# ---------------------------------------------------------------------------
# multi-start: K independent posteriors across the mesh (canonical home)
# ---------------------------------------------------------------------------


def _multi_start_fn(kern, mesh):
    """Build the shard_mapped K-start suggest step (cached per kernel;
    shape-polymorphic in the number of starts via jit retracing).

    Each start gets its OWN γ (``gammas`` is sharded like ``keys``): K
    EI-argmax draws against one posterior at a single γ collapse onto the
    same EI peak (the batch-collapse defect tpe._liar_scan fixes
    sequentially), but the sequential liar would serialize the mesh.  A
    per-start γ spread diversifies in parallel instead — different
    below/above splits give genuinely different posteriors, so the K
    argmax winners spread while every start still exploits the history."""

    def one_host(keys, gammas, vals, active, loss, ok, prior_weight):
        # keys/gammas: [local] — this device's share of the K starts.
        return jax.vmap(
            lambda k, g: kern._suggest_one(k, vals, active, loss, ok,
                                           g, prior_weight))(keys, gammas)

    return jax.jit(_shard_map(
        one_host, mesh=mesh,
        in_specs=(P(START_AXIS), P(START_AXIS), P(), P(), P(), P(), P()),
        out_specs=P(START_AXIS)))


def _gamma_spread(gamma, n_starts):
    """Per-start γ ladder: ``γ·2**linspace(-1, 1, K)`` clipped to a sane
    split range; K=1 degenerates to the base γ."""
    if n_starts == 1:
        return np.asarray([gamma], np.float32)
    return np.clip(gamma * np.exp2(np.linspace(-1.0, 1.0, n_starts)),
                   0.05, 0.75).astype(np.float32)


def multi_start_suggest(new_ids, domain, trials, seed, mesh=None,
                        prior_weight=_tpe._default_prior_weight,
                        n_startup_jobs=_tpe._default_n_startup_jobs,
                        n_EI_candidates=_tpe._default_n_EI_candidates,
                        gamma=_tpe._default_gamma,
                        linear_forgetting=_tpe._default_linear_forgetting,
                        split="sqrt", multivariate=False, startup=None,
                        cat_prior=None):
    """``algo=`` callable proposing ``len(new_ids)`` configs in ONE device
    program: each new trial gets its own RNG stream AND its own γ from a
    ``2**linspace(-1,1,K)`` ladder (see ``_gamma_spread``) — the
    mesh-parallel answer to batch collapse, laid out one-per-mesh-slot
    along the ``dp`` axis.

    Use with ``fmin(..., max_queue_len=K)`` (or an async Trials backend) to
    evaluate K proposals in parallel — BASELINE.md config 4.
    """
    from . import rand

    cs = domain.cs
    if mesh is None:
        mesh = Mesh(np.asarray(jax.devices()), (START_AXIS,))
    h = trials.history(cs)
    if cs.n_params == 0:
        return rand.suggest(new_ids, domain, trials, seed)
    if int(h["ok"].sum()) < n_startup_jobs:
        v, a = _tpe._startup_batch(startup, new_ids, domain, trials, seed)
        if not isinstance(a, np.ndarray):
            v = np.asarray(v)
            a = cs.active_mask_host(v)
        return base.docs_from_samples(cs, new_ids, np.asarray(v),
                                      np.asarray(a),
                                      exp_key=getattr(trials, "exp_key",
                                                      None))
    n = len(new_ids)
    resident = _rhist.enabled()
    fant = None
    if resident:
        fant = _tpe._inflight_fantasy_rows(h, trials, cs)
        n_rows = h["vals"].shape[0] + (fant[0].shape[0] if fant else 0)
    else:
        h = _tpe._with_inflight_fantasies(h, trials, cs)
        n_rows = h["vals"].shape[0]
    n_dev = mesh.shape[START_AXIS]
    n_starts = -(-n // n_dev) * n_dev  # round up to fill the mesh axis
    kern = _tpe.get_kernel(cs, _tpe._bucket(n_rows), int(n_EI_candidates),
                           int(linear_forgetting), split,
                           multivariate=multivariate, cat_prior=cat_prior)
    cache = getattr(cs, "_multi_start_fns", None)
    if cache is None:
        cache = cs._multi_start_fns = {}
    ck = (id(kern), _mesh_key(mesh))
    if ck not in cache:
        cache[ck] = _multi_start_fn(kern, mesh)
    fn = cache[ck]

    if resident:
        hv, ha, hl, hok = _rhist.device_history(
            trials, cs, h, kern.n_cap, fantasies=fant,
            sharding=NamedSharding(mesh, P()), shard_key=_mesh_key(mesh))
    else:
        hv, ha, hl, hok = _tpe._padded_history(h, kern.n_cap)
    keys = jax.random.split(prng_key(int(seed) % (2 ** 32)), n_starts)
    with mesh:
        rows, _ = fn(keys, _gamma_spread(gamma, n_starts), hv, ha, hl, hok,
                     np.float32(prior_weight))
    rows = np.asarray(rows)[:n]
    return base.docs_from_samples(cs, new_ids, rows,
                                  cs.active_mask_host(rows),
                                  exp_key=getattr(trials, "exp_key", None))
