"""Device-resident ``fmin``: the whole optimize loop in ONE XLA program.

Beyond-reference capability (the reference's loop is host-Python by
construction — ``hyperopt/fmin.py::FMinIter`` interleaves Python suggest
calls with Python objective calls, so every trial costs at least one
host↔device round trip; through a high-RTT attachment that sync is ~85 ms
— the measured ceiling of the e2e loop regardless of kernel speed).

When the objective itself is JAX-traceable, none of that is necessary:
:func:`fmin_device` compiles startup sampling, every TPE suggest, every
objective evaluation, and every history insert into a single
``lax.fori_loop`` program.  One dispatch, one fetch, ``max_evals``
trials — per-trial cost is pure device compute (microseconds for small
spaces) instead of tunnel RTT.  This is the same total-fusion move as the
constant-liar batch (``tpe._liar_scan``) taken to its limit: the "batch"
is the entire run, and the fantasies are replaced by *real* losses, so
the optimization is exactly sequential TPE — same posterior sequence a
host loop would produce with these draws, not an approximation.

Contract for ``fn``: it is called **under jit** with a flat dict
``{label: f32[] scalar}`` covering every hyperparameter in the space
(quantized/int kinds arrive as their float values) and must return a
scalar loss using jnp ops.  Conditional (``hp.choice``-gated) parameters
are always present in the dict; branch on the choice value with
``jnp.where``/``lax.cond`` rather than Python ``if``.  An optional second
argument receives the activity mask dict ``{label: bool[]}`` when ``fn``
accepts two positionals.

Sharding note: the candidate axis inside each suggest step is the same
one ``parallel.sharded_suggest`` shards over a mesh; a sharded variant of
this loop is the natural composition (run it under ``jax.jit`` with
sharded history constraints).  The single-device path here is the
building block.
"""

from __future__ import annotations

import inspect
import logging
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from collections import OrderedDict

from . import history as _rhist
from .space import CompiledSpace, compile_space, prng_impl, prng_key
from .tpe import (
    _bucket,
    _default_gamma,
    _default_linear_forgetting,
    _default_n_EI_candidates,
    _default_n_startup_jobs,
    _default_prior_weight,
    _insert_row,
    _pallas_tile,
    get_kernel,
)

logger = logging.getLogger(__name__)

# Compiled runs retained per space (LRU): each entry pins its jitted
# program AND the objective closure it traced, so the cache must be
# bounded — a notebook looping over fresh lambdas would otherwise grow
# memory without limit.
_RUN_CACHE_CAP = 8


def _wrap_objective(fn, cs: CompiledSpace):
    """Adapt ``fn`` to ``(row f32[P], act bool[P]) -> f32[]``.

    The activity-mask dict is passed only when the objective declares a
    SECOND required positional parameter.  Parameters with defaults are
    excluded from the count on purpose: ``def obj(p, scale=1.0)`` is a
    one-argument objective with a config knob, and silently feeding the
    mask dict into ``scale`` would corrupt every loss with no error
    (round-4 advisor finding).  Config knobs with defaults therefore stay
    untouched; an objective that wants the mask declares it default-less
    (conventionally named ``active``).
    """
    try:
        n_pos = len([p for p in inspect.signature(fn).parameters.values()
                     if p.kind in (p.POSITIONAL_ONLY,
                                   p.POSITIONAL_OR_KEYWORD)
                     and p.default is p.empty])
    except (TypeError, ValueError):   # builtins / partials without sigs
        n_pos = 1

    def eval_one(row, act):
        params = {p.label: row[p.pid] for p in cs.params}
        if n_pos >= 2:
            active = {p.label: act[p.pid] for p in cs.params}
            out = fn(params, active)
        else:
            out = fn(params)
        return jnp.asarray(out, jnp.float32).reshape(())

    return eval_one


def fmin_device(fn, space, max_evals, seed=0,
                n_startup_jobs=_default_n_startup_jobs,
                n_EI_candidates=_default_n_EI_candidates,
                gamma=_default_gamma,
                prior_weight=_default_prior_weight,
                linear_forgetting=_default_linear_forgetting,
                split="sqrt", multivariate=False, cat_prior=None,
                mesh=None, init=None, n_runs=1, patience=None,
                min_improvement=0.0):
    """Run ``max_evals`` trials of TPE entirely on device; see module doc.

    Returns ``(best, info)`` where ``best`` is the reference-style
    ``{label: python value}`` dict of the best trial's ACTIVE parameters
    and ``info`` carries the full run history as host arrays:
    ``losses f32[max_evals]`` (trial order), ``vals f32[max_evals, P]``,
    ``active bool[max_evals, P]``, ``best_loss`` and ``best_index``.

    ``init`` resumes from a prior run (the host loop's ``trials=``
    analog): pass a previous ``info`` dict (or any
    ``{"vals", "active", "losses"}`` arrays); those trials seed the
    history and the loop continues to ``max_evals`` TOTAL trials.  If the
    prior run is shorter than ``n_startup_jobs``, the startup phase
    samples only the remainder.  The resumed segment uses this call's
    ``seed`` for its key stream.

    ``patience`` enables in-program early stopping (the device analog of
    ``no_progress_loss(patience, min_improvement)``): the loop halts once
    ``patience`` consecutive trials fail to improve the best loss by more
    than ``min_improvement`` (relative, like the host helper's
    ``percent_increase/100``).  Trials never run land as ``inf`` losses
    with ``ok=False`` semantics; ``info["n_trials"]`` reports how many
    actually ran.  Startup trials always run.

    ``n_runs > 1`` vmaps K fully independent restarts (seeds
    ``seed..seed+K-1``) into the same single program — runs are
    embarrassingly parallel, so with a ``mesh`` whose ``dp`` axis divides
    ``n_runs`` the restart axis shards across devices (per-run candidate
    axes stay local; ``mesh``'s ``sp`` sharding applies only to
    single-run calls).  ``best``/``best_loss`` are the best across ALL
    runs; ``info["losses"]``/``vals``/``active`` gain a leading
    ``[n_runs]`` axis and ``best_index`` becomes ``(run, trial)``.
    ``init`` does not compose with ``n_runs > 1``.

    The compiled program is cached on the space per
    ``(max_evals, tuning-kwargs)`` — a second call with the same shape
    reuses it, so steady-state cost is one dispatch + one fetch total.
    """
    cs = space if isinstance(space, CompiledSpace) else compile_space(space)
    max_evals = int(max_evals)
    if max_evals < 1:
        raise ValueError("max_evals must be >= 1")
    if init is not None:
        pv = np.asarray(init["vals"], np.float32)
        pa = np.asarray(init["active"], bool)
        pl = np.asarray(init["losses"], np.float32)
        if pl.ndim != 1:
            raise ValueError(
                f"init['losses'] must be 1-D (trial order), got {pl.shape}")
        n_prev = pl.shape[0]
        if pv.shape != (n_prev, cs.n_params) or pa.shape != pv.shape:
            raise ValueError("init arrays have inconsistent shapes for "
                             f"this space: vals {pv.shape}, active "
                             f"{pa.shape}, losses {pl.shape}")
        if max_evals <= n_prev:
            raise ValueError(
                f"max_evals={max_evals} must exceed the {n_prev} trials "
                "already in init (max_evals is the TOTAL, as in fmin)")
    else:
        n_prev = 0
    # Startup draws still owed after the resumed history (if any).
    n0 = min(max(int(n_startup_jobs) - n_prev, 0), max_evals - n_prev)
    n_cap = _bucket(max_evals)
    n_runs = int(n_runs)
    if n_runs < 1:
        raise ValueError("n_runs must be >= 1")
    if n_runs > 1 and init is not None:
        raise ValueError("init= does not compose with n_runs > 1 "
                         "(restarts are independent fresh runs)")
    from .dispatch import START_AXIS, _mesh_key

    mesh_k = _mesh_key(mesh) if mesh is not None else None
    if mesh is not None and n_runs > 1:
        # The restart axis shards over dp (below); validate up front with
        # the same explicit errors the sharded-kernel path gives.
        if START_AXIS not in mesh.shape:
            raise ValueError(
                f"n_runs > 1 shards restarts over the mesh's "
                f"'{START_AXIS}' axis, but this mesh has axes "
                f"{tuple(mesh.shape)} — build it with "
                "parallel.default_mesh(n_starts=...)")
        n_dp = mesh.shape[START_AXIS]
        if n_runs % n_dp:
            raise ValueError(
                f"n_runs={n_runs} not divisible by the {n_dp}-way "
                f"'{START_AXIS}' mesh axis")
        if n_dp == 1:
            logger.warning(
                "fmin_device: mesh has %s=1, so all %d restarts run on "
                "one device — build parallel.default_mesh(n_starts=%d) "
                "to distribute them", START_AXIS, n_runs, n_runs)
    if mesh is not None and n_runs == 1:
        # Candidate-axis sharding inside every suggest step: the same
        # ShardedTpeKernel constraints parallel.sharded_suggest uses, with
        # the loop still one program — per-step EI sweeps ride ICI, the
        # argmax reduces across devices, and the sequential trial chain
        # stays device-resident.  The kernel comes from the PR-15 dispatch
        # substrate (one acquisition point for every suggest path).
        from .dispatch import CAND_AXIS
        from .dispatch import get_kernel as _dispatch_get_kernel

        # Validate at THIS boundary (round-4 advisor finding): the default
        # n_EI_candidates is rarely divisible by a mesh's candidate axis,
        # and the equivalent raise from deep inside ShardedTpeKernel names
        # neither the kwarg the caller should change nor a workable value.
        if CAND_AXIS in mesh.shape:
            n_sp = mesh.shape[CAND_AXIS]
            if int(n_EI_candidates) % n_sp:
                fixed = -(-int(n_EI_candidates) // n_sp) * n_sp
                raise ValueError(
                    f"fmin_device: n_EI_candidates={n_EI_candidates} is not "
                    f"divisible by the {n_sp}-way '{CAND_AXIS}' mesh axis; "
                    f"pass n_EI_candidates={fixed} (next multiple) or a "
                    f"mesh whose '{CAND_AXIS}' axis divides it")
        kern = _dispatch_get_kernel(cs, n_cap, int(n_EI_candidates),
                                    int(linear_forgetting), split,
                                    multivariate, cat_prior,
                                    mesh=mesh, strict=True)
    else:
        # n_runs > 1 shards the RESTART axis instead; per-run suggests
        # use the plain kernel so the two partitionings can't fight.
        kern = get_kernel(cs, n_cap, int(n_EI_candidates),
                          int(linear_forgetting), split, multivariate,
                          cat_prior)
    eval_one = _wrap_objective(fn, cs)

    cache = getattr(cs, "_device_fmin_cache", None)
    if cache is None:
        cache = cs._device_fmin_cache = OrderedDict()
    # id(fn) is the only semantically safe function key: closures with
    # identical code but different captured values trace to DIFFERENT
    # programs.  The cache entry keeps fn alive, so its id cannot be
    # recycled while the entry exists; eviction (below) releases both.
    patience = None if patience is None else int(patience)
    if patience is not None and patience < 1:
        raise ValueError(f"patience must be >= 1, got {patience}")
    # Irrelevant without patience — normalize so it can't fragment the
    # compile cache with byte-identical programs.
    min_improvement = 0.0 if patience is None else float(min_improvement)
    cache_key = (id(fn), max_evals, n0, n_prev, n_cap,
                 int(n_EI_candidates),
                 float(gamma), float(prior_weight), int(linear_forgetting),
                 split, multivariate, kern.cat_prior, kern.comp_sampler,
                 kern.split_impl, kern.pallas, kern.pallas_ei,
                 kern.ei_precision, kern.ei_topm, kern.fused_step,
                 _pallas_tile(), mesh_k,
                 n_runs, patience, float(min_improvement), prng_impl(),
                 _rhist.enabled())
    run = cache.get(cache_key)
    from .obs import EVENTS, registry as _obs_registry
    _reg = _obs_registry()
    if run is not None:
        cache.move_to_end(cache_key)
        _reg.counter("device.run_cache.hits").inc()
    if run is None:
        _reg.counter("device.run_cache.misses").inc()
        EVENTS.emit("compile", name="fmin_device",
                    max_evals=max_evals, n_runs=n_runs)
        gamma_f = jnp.float32(gamma)
        pw_f = jnp.float32(prior_weight)
        p_dim = cs.n_params

        n_seeded = n_prev + n0   # rows present before the TPE loop starts

        def _run(seed32, pv_, pa_, pl_):
            key = prng_key(seed32)
            k_start, k_loop = jax.random.split(key)
            hv = jnp.zeros((n_cap, p_dim), jnp.float32).at[:n_prev].set(pv_)
            ha = jnp.zeros((n_cap, p_dim), bool).at[:n_prev].set(pa_)
            hl = jnp.full((n_cap,), jnp.inf,
                          jnp.float32).at[:n_prev].set(pl_)
            if n0:
                sv, sa = cs.sample_traced(k_start, n0)
                sl = jax.vmap(eval_one)(sv, sa)
                hv = hv.at[n_prev:n_seeded].set(sv)
                ha = ha.at[n_prev:n_seeded].set(sa)
                hl = hl.at[n_prev:n_seeded].set(sl)
            hok = (jnp.arange(n_cap) < n_seeded)

            def step(i, hv, ha, hl, hok):
                row, act = kern._suggest_one(
                    jax.random.fold_in(k_loop, i), hv, ha, hl, hok,
                    gamma_f, pw_f)
                loss = eval_one(row, act)
                return _insert_row(hv, ha, hl, hok, i, row, act, loss), loss

            if patience is None:
                def body(i, carry):
                    return step(i, *carry)[0]

                hv, ha, hl, hok = jax.lax.fori_loop(
                    n_seeded, max_evals, body, (hv, ha, hl, hok))
                n_done = jnp.int32(max_evals)
            else:
                # In-program no-progress stop (host: no_progress_loss).
                mi = min_improvement    # host float (normalized above)

                def wcond(st):
                    i, since = st[4], st[6]
                    return jnp.logical_and(i < max_evals,
                                           since < patience)

                def wbody(st):
                    hv, ha, hl, hok, i, best, since = st
                    (hv, ha, hl, hok), loss = step(i, hv, ha, hl, hok)
                    if mi > 0:
                        # inf - inf*mi would be NaN; an infinite best
                        # means "anything finite improves".
                        thresh = jnp.where(jnp.isfinite(best),
                                           best - jnp.abs(best) * mi,
                                           best)
                    else:
                        thresh = best
                    improved = loss < thresh
                    # NaN losses neither improve nor poison the tracker
                    # (host analog filters to finite losses).
                    best = jnp.where(jnp.isnan(loss), best,
                                     jnp.minimum(best, loss))
                    since = jnp.where(improved, 0, since + 1)
                    return (hv, ha, hl, hok, i + 1, best, since)

                best0 = jnp.min(jnp.where(
                    hok & ~jnp.isnan(hl), hl, jnp.inf))
                st = (hv, ha, hl, hok, jnp.int32(n_seeded), best0,
                      jnp.int32(0))
                hv, ha, hl, hok, n_done, _, _ = jax.lax.while_loop(
                    wcond, wbody, st)
            return (hv[:max_evals], ha[:max_evals], hl[:max_evals],
                    n_done)

        if n_runs > 1:
            run = jax.jit(jax.vmap(_run, in_axes=(0, None, None, None)))
        else:
            run = jax.jit(_run)
        cache[cache_key] = run
        while len(cache) > _RUN_CACHE_CAP:
            cache.popitem(last=False)

    if init is None:
        pv = np.zeros((0, cs.n_params), np.float32)
        pa = np.zeros((0, cs.n_params), bool)
        pl = np.zeros((0,), np.float32)
    if n_runs > 1:
        seeds = (np.arange(n_runs, dtype=np.uint64)
                 + (int(seed) % (2 ** 32))).astype(np.uint32)
        if mesh is not None:
            # Restarts are embarrassingly parallel: shard the run axis
            # over the mesh's dp axis and let SPMD partition the whole
            # vmapped program (per-run history/candidates stay local).
            # Divisibility/axis presence validated above.
            from jax.sharding import NamedSharding, PartitionSpec

            seeds = jax.device_put(
                seeds, NamedSharding(mesh, PartitionSpec(START_AXIS)))
        vals, active, losses, n_done = run(seeds, pv, pa, pl)
    else:
        vals, active, losses, n_done = run(np.uint32(int(seed) % (2 ** 32)),
                                           pv, pa, pl)
    # ONE host sync for the whole run.
    vals = np.asarray(vals)
    active = np.asarray(active)
    losses = np.asarray(losses)
    # NaN-safe best: non-finite losses lose to any finite one.
    order = np.where(np.isnan(losses), np.inf, losses)
    bi = tuple(int(i) for i in
               np.unravel_index(int(np.argmin(order)), order.shape))
    best_row, best_act = vals[bi], active[bi]
    best = {p.label: cs._param_value(p, best_row[p.pid])
            for p in cs.params if best_act[p.pid]}
    n_done = np.asarray(n_done)
    info = {"losses": losses, "vals": vals, "active": active,
            "best_loss": float(losses[bi]),
            "best_index": bi if n_runs > 1 else bi[0],
            "n_trials": (n_done.astype(int).tolist() if n_runs > 1
                         else int(n_done))}
    return best, info


# ---------------------------------------------------------------------------
# segmented engine — fmin(mode="device") lands results in a Trials
# ---------------------------------------------------------------------------
#
# fmin_device above is the all-or-nothing form: one program, one fetch, an
# info dict.  fmin(mode="device") needs the hosted loop's OBSERVABLE
# contract — results in a Trials, early-stop/progress hooks, resumability —
# without its per-trial fetch sync.  The middle ground is a segmented scan:
# the suggest→evaluate→record chain runs `sync_stride` trials per compiled
# program with the history ring as scan carry, and the host fetches ONE
# [stride]-row slab per segment, lands it in the Trials, and runs the
# hooks.  Per-trial seeds are drawn from the SAME rstate stream the hosted
# loop draws (one integers(2**31-1) per trial), the startup branch is the
# same `sample_traced` program `rand.suggest_batch` jits, and the TPE
# branch is the same `_suggest_one(prng_key(seed), ...)` the hosted
# suggest_seeded entry runs — so at any stride the proposal stream is
# seeded-bit-parity with the hosted loop (pinned at sync_stride=1 by
# tests/test_fmin_device_mode.py for histories within one bucket).


def _build_segment(cs, kern, eval_one, n_startup, gamma, prior_weight,
                   telemetry=False):
    """The per-segment scan: ``(seeds[s], hv, ha, hl, hok, i0) ->
    ((hv, ha, hl, hok, i), (rows[s,P], acts[s,P], losses[s]))``.

    One trial per scan step: startup draws route through
    ``cs.sample_traced`` until ``n_startup`` ok trials exist (the hosted
    gate), TPE draws through ``kern._suggest_one`` — both keyed by
    ``prng_key(seed_t)``, exactly the hosted loop's seeded entries.
    Losses land in the ring with the hosted ``Trials.history`` semantics
    (non-finite → ``ok=False``, ``loss=+inf``) so a resumed or
    mixed-stride run conditions on the same posterior; the raw loss goes
    out in the slab for the Trials doc.

    With ``telemetry=True`` each scan step additionally emits its EI
    stats as plain outputs, reduced VECTORIZED after the scan (still
    inside the compiled segment) into a fixed-shape slab the segment
    returns as a third output ``(best, ei_max, ei_sum, n_tpe,
    n_nonfinite, n_ties, bsf[R])`` — the counters ``obs.devtel``
    backfills at each sync boundary.  The slab is a pure PASSENGER: it
    reads tensors the proposal/evaluate chain already computes (the
    suggest routes through ``_suggest_one_tel`` in BOTH arms — disarmed
    merely drops the stat outputs, so armed/disarmed trace the identical
    proposal subgraph and sampled trials stay bit-identical; pinned by
    the parity tests in tests/test_fmin_device_mode.py), and keeping the
    reductions out of the loop body keeps the armed scan step within
    noise of the disarmed one (the overhead A/B's acceptance bar).
    ``bsf`` is the best-so-far loss after each trial, downsampled to
    ``devtel.RESERVOIR`` slots via slot ``t*R//s`` (segments shorter
    than R fill a prefix; the rest stay ``+inf``).
    """
    gamma_f = jnp.float32(gamma)
    pw_f = jnp.float32(prior_weight)

    def _propose(key, hv, ha, hl, hok, n_ok):
        """One suggest — startup or TPE — plus its passenger EI stats
        (neutral ``(-inf, 0)`` in the startup arm so the ``lax.cond``
        branch signatures match)."""

        def startup(k):
            sv, sa = cs.sample_traced(k, 1)
            return sv[0], sa[0], jnp.float32(-jnp.inf), jnp.int32(0)

        def tpe_step(k):
            return kern._suggest_one_tel(k, hv, ha, hl, hok,
                                         gamma_f, pw_f)

        return jax.lax.cond(n_ok < n_startup, startup, tpe_step, key)

    if not telemetry:
        def segment(seeds, hv, ha, hl, hok, i0):
            def body(carry, seed):
                hv, ha, hl, hok, i = carry
                key = prng_key(seed)
                n_ok = jnp.sum(hok)
                row, act, _eb, _et = _propose(key, hv, ha, hl, hok, n_ok)
                loss = eval_one(row, act)
                lok = jnp.isfinite(loss)
                hv, ha, hl, hok = _insert_row(
                    hv, ha, hl, hok, i, row, act,
                    jnp.where(lok, loss, jnp.inf))
                hok = jax.lax.dynamic_update_slice(
                    hok, lok.reshape((1,)), (i,))
                return (hv, ha, hl, hok, i + 1), (row, act, loss)

            carry = (hv, ha, hl, hok, jnp.asarray(i0, jnp.int32))
            carry, ys = jax.lax.scan(body, carry, seeds)
            return carry, ys

        return segment

    from .obs.devtel import RESERVOIR

    def segment(seeds, hv, ha, hl, hok, i0):
        s = int(seeds.shape[0])

        def body(carry, seed):
            hv, ha, hl, hok, i = carry
            key = prng_key(seed)
            n_ok = jnp.sum(hok)
            is_tpe = n_ok >= n_startup
            row, act, ei_b, ties = _propose(key, hv, ha, hl, hok, n_ok)
            loss = eval_one(row, act)
            lok = jnp.isfinite(loss)
            hv, ha, hl, hok = _insert_row(
                hv, ha, hl, hok, i, row, act,
                jnp.where(lok, loss, jnp.inf))
            hok = jax.lax.dynamic_update_slice(
                hok, lok.reshape((1,)), (i,))
            # The stats leave as plain per-step scan OUTPUTS (three
            # stores); all slab reduction happens vectorized after the
            # scan, keeping the armed loop body within noise of the
            # disarmed one (the overhead A/B's stride-∞ bar).
            return (hv, ha, hl, hok, i + 1), (row, act, loss,
                                              ei_b, ties, is_tpe)

        best0 = jnp.min(jnp.where(hok, hl, jnp.inf))        # run best
        carry = (hv, ha, hl, hok, jnp.asarray(i0, jnp.int32))
        carry, (rows, acts, losses, ei_bs, ties_s, tpe_s) = \
            jax.lax.scan(body, carry, seeds)

        lok = jnp.isfinite(losses)
        traj = jnp.minimum(jax.lax.cummin(
            jnp.where(lok, losses, jnp.inf), axis=0), best0)
        if s <= RESERVOIR:                 # short segment: prefix fill
            bsf = jnp.concatenate(
                [traj, jnp.full((RESERVOIR - s,), jnp.inf, jnp.float32)])
        else:
            # Slot t*R//s keeps the LAST step landing in each slot; the
            # winning step per slot r is floor(((r+1)s - 1)/R) — static,
            # so the downsample is one gather.
            idx = ((np.arange(RESERVOIR) + 1) * s - 1) // RESERVOIR
            bsf = traj[idx]
        slab = (traj[-1],
                jnp.max(ei_bs),            # startup steps emit -inf
                jnp.sum(jnp.where(tpe_s, ei_bs, jnp.float32(0))),
                jnp.sum(tpe_s.astype(jnp.int32)),
                jnp.sum((~lok).astype(jnp.int32)),
                jnp.sum(ties_s),
                bsf)
        return carry, (rows, acts, losses), slab

    return segment


def fmin_trials(fn, space, max_evals, trials, rstate, sync_stride=None,
                early_stop_fn=None, timeout=None, loss_threshold=None,
                show_progressbar=True,
                n_startup_jobs=_default_n_startup_jobs,
                n_EI_candidates=_default_n_EI_candidates,
                gamma=_default_gamma,
                prior_weight=_default_prior_weight,
                linear_forgetting=_default_linear_forgetting,
                split="sqrt", multivariate=False, cat_prior=None,
                mesh=None):
    """Run TPE on-device in ``sync_stride``-trial segments, landing every
    slab into ``trials`` (the engine behind ``fmin(mode='device')``).

    ``sync_stride=None`` (∞) fetches once for the whole run; smaller
    strides trade throughput for hook latency — early-stop, timeout and
    loss-threshold checks run on the landed Trials between segments, so
    they observe the run at stride granularity.  Prior completed trials
    in ``trials`` seed the history ring (resume); the kernel is acquired
    through ``dispatch.get_kernel`` so an ambient mesh
    (``HYPEROPT_TPU_DISPATCH=sharded`` / ``dispatch.set_default_mesh``)
    shards each suggest's candidate axis with no code change here.

    Returns ``trials`` (mutated in place).  Host round trips:
    ``ceil(n_new / sync_stride)`` slab fetches total, counted in the
    ``device.fetch_syncs`` counter — zero per-trial syncs at any stride.

    Telemetry (``HYPEROPT_TPU_DEVICE_TELEMETRY``, default on): each
    segment carries the ``obs.devtel`` slab, fetched in the SAME bulk
    transfer and backfilled into events/metrics/costs/time-series at the
    boundary; sampled trials are bit-identical armed vs. disarmed (the
    slab is a passenger — see ``_build_segment``).
    """
    from time import perf_counter as _perf
    from time import time as _time

    from . import dispatch as _dispatch
    from .base import JOB_STATE_DONE, STATUS_OK, coarse_utcnow
    from .base import docs_from_samples
    from .obs import costs as _costs
    from .obs import devtel as _devtel
    from .obs import metrics as _metrics
    from .utils.progress import default_callback, no_progress_callback

    t_start = _time()
    cs = space if isinstance(space, CompiledSpace) else compile_space(space)
    max_evals = int(max_evals)
    if max_evals < 1:
        raise ValueError("max_evals must be >= 1")
    if sync_stride is not None:
        sync_stride = int(sync_stride)
        if sync_stride < 1:
            raise ValueError(
                f"sync_stride must be >= 1 or None (∞), got {sync_stride}")
    trials.refresh()
    h = trials.history(cs)
    n_prev = int(h["loss"].shape[0])
    exp_key = getattr(trials, "exp_key", None)
    if n_prev >= max_evals:
        return trials

    n_cap = _bucket(max_evals)
    mesh = _dispatch.active_mesh(mesh)
    mesh_k = _mesh_key_of(mesh)
    # One acquisition point for every suggest path: with a mesh the
    # candidate axis shards (collective argmax over ICI); indivisible
    # n_EI_candidates falls back to the bit-identical local kernel.
    kern = _dispatch.get_kernel(cs, n_cap, int(n_EI_candidates),
                                int(linear_forgetting), split,
                                multivariate, cat_prior, mesh=mesh)
    eval_one = _wrap_objective(fn, cs)
    n_startup = int(n_startup_jobs)

    cache = getattr(cs, "_device_fmin_cache", None)
    if cache is None:
        cache = cs._device_fmin_cache = OrderedDict()
    # The telemetry toggle changes the traced program (slab carry +
    # extra outputs), so it MUST key the run cache — flipping the env
    # var can never serve a stale segment.
    telemetry = _devtel.enabled()
    stride_label = "inf" if sync_stride is None else str(sync_stride)
    base_key = ("seg", id(fn), n_cap, n_startup, float(gamma),
                float(prior_weight), int(linear_forgetting),
                int(n_EI_candidates), split, multivariate, kern.cat_prior,
                kern.comp_sampler, kern.split_impl, kern.pallas,
                kern.pallas_ei, kern.ei_precision, kern.ei_topm,
                kern.fused_step, _pallas_tile(), mesh_k, prng_impl(),
                telemetry)
    segment = _build_segment(cs, kern, eval_one, n_startup, gamma,
                             prior_weight, telemetry=telemetry)
    reg = _metrics.registry()
    from .obs import EVENTS

    fresh_strides: set = set()

    def seg_fn(s):
        key = base_key + (s,)
        run = cache.get(key)
        if run is None:
            reg.counter("device.run_cache.misses").inc()
            EVENTS.emit("compile", name="fmin_device_segment", stride=s,
                        max_evals=max_evals)
            fresh_strides.add(s)
            run = cache[key] = jax.jit(segment)
            while len(cache) > _RUN_CACHE_CAP:
                cache.popitem(last=False)
        else:
            cache.move_to_end(key)
            reg.counter("device.run_cache.hits").inc()
        return run

    # Ring seed: prior completed trials (resume), padded to the bucket.
    hv = jnp.zeros((n_cap, cs.n_params), jnp.float32)
    ha = jnp.zeros((n_cap, cs.n_params), bool)
    hl = jnp.full((n_cap,), jnp.inf, jnp.float32)
    hok = jnp.zeros((n_cap,), bool)
    if n_prev:
        hv = hv.at[:n_prev].set(h["vals"])
        ha = ha.at[:n_prev].set(h["active"])
        hl = hl.at[:n_prev].set(h["loss"])
        hok = hok.at[:n_prev].set(h["ok"])

    early_stop_args: list = []
    i = n_prev
    seg_index = 0
    progress_ctx = default_callback if show_progressbar \
        else no_progress_callback
    with progress_ctx(initial=n_prev, total=max_evals) as prog:
        while i < max_evals:
            s = (max_evals - i if sync_stride is None
                 else min(sync_stride, max_evals - i))
            # One scalar draw per trial — the hosted batch cadence, so
            # the seed stream matches fmin's host loop at every stride.
            seeds = np.asarray(
                [rstate.integers(2 ** 31 - 1) for _ in range(s)],
                np.uint32)
            t0_mono = _perf()
            out = seg_fn(s)(seeds, hv, ha, hl, hok, np.int32(i))
            if telemetry:
                (hv, ha, hl, hok, _), (rows, acts, losses), slab = out
            else:
                (hv, ha, hl, hok, _), (rows, acts, losses) = out
                slab = None
            # ONE bulk fetch per segment — the only host sync at this
            # stride; bench.py verifies per-trial round trips are zero
            # by diffing this counter.  The telemetry slab rides the same
            # program output, so fetching it adds no sync boundary.
            rows_h = np.asarray(rows)
            acts_h = np.asarray(acts)
            losses_h = np.asarray(losses)
            t1_mono = _perf()
            reg.counter("device.fetch_syncs").inc()
            reg.counter("device.segments").inc()
            if telemetry:
                _devtel.bump_labeled(reg, "solo", stride_label)
                cost_key = ("device", "solo", s)
                if s in fresh_strides:
                    # First call of a fresh program: its wall time is
                    # dominated by trace+compile — that's the ledger's
                    # compile row (joined by key with the dispatch rows
                    # of every later warm segment).
                    fresh_strides.discard(s)
                    _costs.record_compile(
                        "device", cost_key, compile_s=t1_mono - t0_mono,
                        n_cap=n_cap, P=cs.n_params, m=s)

            new_ids = trials.new_trial_ids(s)
            docs = docs_from_samples(cs, new_ids, rows_h, acts_h,
                                     exp_key=exp_key)
            now = coarse_utcnow()
            for doc, loss in zip(docs, losses_h):
                doc["state"] = JOB_STATE_DONE
                doc["result"] = {"loss": float(loss), "status": STATUS_OK}
                doc["book_time"] = now
                doc["refresh_time"] = now
            trials.insert_trial_docs(docs)
            trials.refresh()
            reg.counter("device.trials_landed").inc(s)
            if slab is not None:
                # Sync-boundary backfill: the slab lands in every hosted
                # obs layer with back-dated (synthetic-marked) stamps.
                _devtel.backfill_segment(
                    reg, mode="solo", stride=stride_label,
                    slab_h=_devtel.slab_host(slab), n_trials=s,
                    n_lanes=1, t0_mono=t0_mono, t1_mono=t1_mono,
                    seg_index=seg_index, cost_key=("device", "solo", s),
                    tids=new_ids, label=exp_key)
            seg_index += 1
            i += s
            prog.update(s)
            fin = losses_h[np.isfinite(losses_h)]
            if len(fin):
                prog.postfix(float(fin.min()))

            # Stride-boundary hooks: they see the landed Trials, i.e. the
            # run at slab granularity (docs/API.md "fmin modes").  The
            # early-stop fn is replayed once per LANDED trial, not once
            # per segment: hosted fmin calls it after every trial and
            # stateful helpers (no_progress_loss) count invocations, so a
            # per-segment call would stretch a patience of 5 trials into
            # 5 segments.  Each replay sees the segment's final Trials —
            # best-so-far only improves within a segment, so the stop
            # lands at the first boundary at/after the hosted trigger.
            if early_stop_fn is not None:
                stop = False
                for _ in range(s):
                    stop, early_stop_args = early_stop_fn(trials,
                                                          *early_stop_args)
                    if stop:
                        break
                if stop:
                    logger.info("early stop triggered (device mode)")
                    break
            if timeout is not None and _time() - t_start >= timeout:
                break
            if loss_threshold is not None:
                try:
                    if trials.best_trial["result"]["loss"] \
                            <= loss_threshold:
                        break
                except Exception:
                    pass
    if telemetry:
        # One O(n_docs) health pass per run, off the docs the segments
        # just landed — stagnation / EI-collapse verdicts for device
        # mode (per-segment series already backfilled above).
        _devtel.finish_run(reg, trials, mode="solo", label=exp_key)
    return trials


def _mesh_key_of(mesh):
    from .dispatch import _mesh_key

    return _mesh_key(mesh) if mesh is not None else None
