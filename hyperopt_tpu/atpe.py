"""Adaptive TPE: self-tuning TPE hyperparameters.

Reference: ``hyperopt/atpe.py`` (~1400 LoC, SURVEY.md §2) — "Adaptive TPE"
(contributed by ElectricBrain) uses **pretrained LightGBM models** + JSON
scaling parameters shipped with the package to predict good TPE
hyperparameters (``gamma``, ``n_EI_candidates``, lockout masks, …) per
problem.

Documented deviation: this environment has no lightgbm and no network to
fetch the reference's model files (SURVEY.md §7 environment facts), and
shipping opaque pretrained artifacts would be contrary to a from-scratch
build anyway.  The same *capability* — per-problem adaptation of the TPE
hyperparameters — is provided by an online **portfolio bandit**:

* a small portfolio of TPE configurations spanning the knobs the reference's
  models predict (γ value and schedule, ``n_EI_candidates``,
  ``prior_weight``), seeded by problem features (dimensionality, categorical
  fraction — the reference's model inputs);
* each suggest call picks a configuration by Thompson sampling over its
  observed improvement record (Beta posterior per arm), so configurations
  that keep finding better losses get chosen more;
* the arm's reward is "the suggested trial improved the best-so-far loss".

This keeps ATPE's plugin signature (``atpe.suggest`` drop-in, same as the
reference) with self-contained, inspectable adaptation.
"""

from __future__ import annotations

import numpy as np

from . import tpe
from .base import JOB_STATE_DONE, JOB_STATE_ERROR, STATUS_OK
from .space import CATEGORICAL


def _portfolio(cs):
    """TPE-configuration arms, scaled by problem features."""
    n_params = max(cs.n_params, 1)
    cat_frac = (sum(1 for p in cs.params if p.kind == CATEGORICAL)
                / n_params)
    # Wider spaces benefit from more EI candidates; heavily categorical
    # spaces from stronger priors (smoothing).
    base_cand = int(np.clip(24 * np.sqrt(n_params), 24, 512))
    pw = 1.0 + cat_frac
    return [
        dict(gamma=0.25, split="sqrt", n_EI_candidates=base_cand,
             prior_weight=pw),
        dict(gamma=0.25, split="quantile", n_EI_candidates=base_cand,
             prior_weight=pw),
        dict(gamma=0.15, split="quantile", n_EI_candidates=base_cand * 2,
             prior_weight=pw),
        dict(gamma=0.5, split="sqrt", n_EI_candidates=base_cand,
             prior_weight=2.0 * pw),   # exploratory arm
    ]


class _BanditState:
    """Per-experiment Thompson-sampling state, attached to the Trials."""

    def __init__(self, n_arms):
        self.wins = np.ones(n_arms)    # Beta(1,1) priors
        self.losses = np.ones(n_arms)
        self.pending = {}              # tid -> (arm, best_loss_at_suggest)

    def pick(self, rng):
        return int(np.argmax(rng.beta(self.wins, self.losses)))

    def settle(self, trials):
        """Score resolved suggestions: did the trial beat the best loss
        recorded when it was proposed?"""
        by_tid = {t["tid"]: t for t in trials}
        for tid in list(self.pending):
            t = by_tid.get(tid)
            if t is None or t["state"] not in (JOB_STATE_DONE,
                                               JOB_STATE_ERROR):
                continue
            arm, best_then = self.pending.pop(tid)
            r = t["result"]
            loss = r.get("loss") if r.get("status") == STATUS_OK else None
            if loss is not None and (best_then is None or loss < best_then):
                self.wins[arm] += 1.0
            else:
                self.losses[arm] += 1.0


def _state(trials, n_arms) -> _BanditState:
    st = getattr(trials, "_atpe_state", None)
    if st is None or len(st.wins) != n_arms:
        st = trials._atpe_state = _BanditState(n_arms)
    return st


def suggest(new_ids, domain, trials, seed,
            n_startup_jobs=tpe._default_n_startup_jobs,
            linear_forgetting=tpe._default_linear_forgetting):
    """Adaptive-TPE suggest (drop-in for ``hyperopt/atpe.py::suggest``)."""
    arms = _portfolio(domain.cs)
    st = _state(trials, len(arms))
    st.settle(trials)
    rng = np.random.default_rng(int(seed) % (2 ** 32))
    arm = st.pick(rng)
    cfg = arms[arm]
    try:
        best = trials.best_trial["result"]["loss"]
    except Exception:
        best = None
    docs = tpe.suggest(new_ids, domain, trials, seed,
                       n_startup_jobs=n_startup_jobs,
                       linear_forgetting=linear_forgetting, **cfg)
    for d in docs:
        st.pending[d["tid"]] = (arm, best)
    return docs
