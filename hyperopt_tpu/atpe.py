"""Adaptive TPE: self-tuning TPE hyperparameters.

Reference: ``hyperopt/atpe.py`` (~1400 LoC, SURVEY.md §2) — "Adaptive TPE"
(contributed by ElectricBrain) uses **pretrained LightGBM models** + JSON
scaling parameters shipped with the package to predict, per problem, good
TPE hyperparameters (``gamma``, ``nEICandidates``, ``priorWeight``), a
**result-filtering mode** (fit the posterior on a subset of the history) and
**per-parameter lockout masks** (freeze "secondary" parameters at the
incumbent's values while the primary ones are searched).

Documented deviation: this environment has no lightgbm and no network to
fetch the reference's model files (SURVEY.md §7 environment facts), and
shipping opaque pretrained artifacts would be contrary to a from-scratch
build anyway.  The same *capabilities* are provided self-contained:

* **portfolio bandit** — a set of TPE configurations spanning the knobs the
  reference's models predict (γ value and schedule, ``n_EI_candidates``,
  ``prior_weight``, ``linear_forgetting`` as the age-filtering analog),
  seeded by problem features (dimensionality, categorical fraction — the
  reference's model inputs).  Each suggest call picks a configuration by
  Thompson sampling over its observed improvement record (Beta posterior
  per arm), so configurations that keep finding better losses get chosen
  more.
* **per-parameter lockout** (reference: secondaryLockingMode) — arms with a
  ``lockout`` fraction freeze the least *important* parameters at the
  incumbent's values and let TPE search the rest.  Importance is estimated
  online from the trial history: |Spearman correlation| with loss for
  numeric columns, between-group variance ratio (η²) for categorical ones —
  the inspectable stand-in for the reference's learned
  secondary-correlation models.
* the arm's reward is "the suggested trial improved the best-so-far loss".
* **transfer memory** (reference: the pretrained models' cross-problem
  knowledge) — arm posteriors persist on disk keyed by the space's
  structural fingerprint, so a new experiment over the same space starts
  from everything previous experiments learned about which TPE
  configurations work there.  An UNSEEN space seeds from the most
  *similar* space on record by structural-feature distance
  (:func:`_space_features` — the generalize-to-new-problems capability
  the reference's pretrained models provide; measured winning both
  starved-budget medians in ``benchmarks/transfer_ab_cross.json``).
  See :class:`_TransferStore`; disable with
  ``HYPEROPT_TPU_ATPE_TRANSFER=0``, relocate with
  ``HYPEROPT_TPU_CACHE_DIR``.

This keeps ATPE's plugin signature (``atpe.suggest`` drop-in, same as the
reference) with self-contained, inspectable adaptation.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading

import numpy as np

from . import base, tpe
from .base import JOB_STATE_DONE, JOB_STATE_ERROR, STATUS_OK
from .obs import metrics as _metrics
from .obs.events import EVENTS
from .space import CATEGORICAL, RANDINT, UNIFORMINT

logger = logging.getLogger(__name__)


def _tiers_on() -> bool:
    """Arm-shape canonicalization toggle (``HYPEROPT_TPU_ATPE_TIERS``).

    On (default): every arm's ``n_EI_candidates`` snaps UP to a
    power-of-two tier before reaching ``tpe.get_kernel``.  The candidate
    count is a compile-shape axis (it sizes the EI broadcast), and the
    un-tiered portfolio derived it continuously from dimensionality
    (``24·√P``), so every distinct space compiled its own arm-shape
    family.  Tiered, all spaces with √P in a ×2 band share one family,
    and an arm pair like (base, max(base, 128)) collapses onto ONE shape
    whenever the base tier reaches 128 — fewer distinct XLA programs per
    process, and a stable shape vocabulary for :func:`_prewarm_arms`.
    ``0`` restores the continuous shapes (A/B:
    ``benchmarks/atpe_profile.py``).  Never changes which γ/split/
    forgetting semantics an arm carries — only how many EI candidates it
    scores, which the bandit treats as part of the arm's identity either
    way.
    """
    return os.environ.get("HYPEROPT_TPU_ATPE_TIERS", "1") != "0"


def _tier(n: int) -> int:
    """Snap a candidate count UP to the next power of two (min 32).

    Rounding up never shrinks an arm's exploration breadth; the extra
    candidates cost a partial tile the EI kernel was padding to anyway.
    """
    return max(32, 1 << (max(int(n), 1) - 1).bit_length())


def _portfolio(cs):
    """TPE-configuration arms, scaled by problem features.

    Spans the reference models' output space: γ (value + schedule),
    n_EI_candidates, prior_weight, age filtering (linear_forgetting) and
    secondary-parameter lockout."""
    n_params = max(cs.n_params, 1)
    cat_frac = (sum(1 for p in cs.params if p.kind == CATEGORICAL)
                / n_params)
    # Wider spaces benefit from more EI candidates; heavily categorical
    # spaces from stronger priors (smoothing).
    base_cand = int(np.clip(24 * np.sqrt(n_params), 24, 512))
    if _tiers_on():
        base_cand = _tier(base_cand)
    pw = 1.0 + cat_frac
    arms = [
        dict(gamma=0.25, split="sqrt", n_EI_candidates=base_cand,
             prior_weight=pw),
        dict(gamma=0.25, split="quantile", n_EI_candidates=base_cand,
             prior_weight=pw),
        dict(gamma=0.15, split="quantile", n_EI_candidates=base_cand * 2,
             prior_weight=pw),
        dict(gamma=0.5, split="sqrt", n_EI_candidates=base_cand,
             prior_weight=2.0 * pw),   # exploratory arm
        # Age-filtering analog (reference resultFilteringMode='age'): a
        # short forgetting horizon fits the posterior on recent trials only.
        dict(gamma=0.25, split="quantile", n_EI_candidates=base_cand,
             prior_weight=pw, linear_forgetting=10),
        # Joint-vector EI (benchmarks/quality.py: wins or ties 8/9 zoo
        # domains) — the bandit learns per-problem whether it helps.
        dict(gamma=0.25, split="quantile", n_EI_candidates=max(base_cand, 128),
             prior_weight=pw, multivariate=True),
    ]
    if n_params >= 3:  # lockout is meaningless on tiny spaces
        arms += [
            # Secondary lockout (reference secondaryLockingMode): freeze the
            # low-importance half / three-quarters at the incumbent.
            dict(gamma=0.25, split="quantile", n_EI_candidates=base_cand,
                 prior_weight=pw, lockout=0.5),
            dict(gamma=0.15, split="quantile", n_EI_candidates=base_cand * 2,
                 prior_weight=pw, lockout=0.75),
        ]
    return arms


def parameter_importance(h, cs):
    """Online per-parameter importance from the trial history.

    Returns ``imp[P]`` in [0, 1]: a bias-adjusted between-group variance
    ratio (η², adjusted like R²) of the loss across value groups — discrete
    columns group by value, numeric columns by quantile bin.  Unlike a rank
    correlation this captures non-monotone (e.g. U-shaped) relations, which
    are the norm for loss-vs-hyperparameter curves.  Columns with too few
    active observations get 1.0 (unknown → never lock).

    Reference analog: atpe.py's pretrained secondary-correlation models —
    here replaced by a transparent statistic over the same signal.
    """
    ok = h["ok"]
    loss = h["loss"]
    P = cs.n_params
    imp = np.ones(P, np.float64)

    def eta2_adj(y, gid, k, n):
        tot = y.var()
        if tot <= 0 or n <= k:
            return 0.0
        within = sum(float(y[gid == g].var()) * int((gid == g).sum())
                     for g in np.unique(gid)) / n
        # adjusted for the k-groups-from-n-samples positive bias
        val = 1.0 - (within / max(n - k, 1)) / (tot / (n - 1))
        return float(np.clip(val, 0.0, 1.0))

    for spec in cs.params:
        m = h["active"][:, spec.pid] & ok
        n = int(m.sum())
        if n < 8:
            continue
        x = h["vals"][m, spec.pid].astype(np.float64)
        y = loss[m].astype(np.float64)
        uniq = np.unique(x)
        if spec.kind in (CATEGORICAL, RANDINT, UNIFORMINT) and \
                len(uniq) <= 32:
            gid = np.searchsorted(uniq, x)
            imp[spec.pid] = eta2_adj(y, gid, len(uniq), n)
        else:
            k = int(np.clip(n // 8, 2, 8))
            edges = np.quantile(x, np.linspace(0, 1, k + 1)[1:-1])
            gid = np.searchsorted(edges, x)
            imp[spec.pid] = eta2_adj(y, gid, k, n)
    return imp


def _apply_lockout(cs, rows, acts, trials, h, frac, rng):
    """Freeze the lowest-importance ``frac`` of parameters at the
    incumbent's values (reference: secondary lockout masks).  Gate
    (choice) columns may flip branches, so the activity mask is recomputed
    after substitution."""
    try:
        best_misc = trials.best_trial["misc"]
    except Exception:
        return rows, acts
    imp = parameter_importance(h, cs)
    # Only parameters the incumbent actually has values for can be locked.
    lockable = []
    for spec in cs.params:
        v = best_misc["vals"].get(spec.label, [])
        if len(v):
            lockable.append((imp[spec.pid], spec.pid, float(v[0])))
    if len(lockable) < 2:
        return rows, acts
    lockable.sort()
    n_lock = int(round(frac * len(lockable)))
    if n_lock == 0:
        return rows, acts
    rows = np.array(rows, copy=True)
    for _, pid, v in lockable[:n_lock]:
        rows[:, pid] = v
    return rows, cs.active_mask_host(rows)


def _space_features(cs) -> list:
    """Structural feature vector for cross-SPACE transfer similarity.

    The reference's pretrained models generalize to unseen problems from
    structural descriptors (atpe.py feeds dimensionality/type statistics
    into its LightGBM predictors, SURVEY.md §2).  This is the analogous
    descriptor here: which TPE configuration wins is driven by the space's
    *shape* — size, distribution-family mix, conditionality — not by its
    labels or exact bounds, so a new space can seed its arm posteriors
    from the most similar space on record (``_TransferStore.load``).

    Components (each in [0, 1] except the first, so L1 distance weights
    size ~= one family fraction):
      ``log1p(P)/log(101)``, fraction of uniform-family / log-family /
      normal-family / quantized / categorical columns, fraction of
      conditional (gated) columns, mean categorical arity / 32.
    """
    from .space import (
        LOGNORMAL,
        LOGUNIFORM,
        NORMAL,
        QLOGNORMAL,
        QLOGUNIFORM,
        QNORMAL,
        QUNIFORM,
        UNIFORM,
    )

    P = max(cs.n_params, 1)
    kinds = [p.kind for p in cs.params]

    def frac(ks):
        return sum(1 for k in kinds if k in ks) / P

    cat_arity = [p.n_options for p in cs.params
                 if p.kind == CATEGORICAL or (p.kind == RANDINT
                                              and p.probs is not None)]
    return [
        float(np.log1p(cs.n_params) / np.log(101.0)),
        frac((UNIFORM, QUNIFORM, UNIFORMINT, RANDINT)),
        frac((LOGUNIFORM, QLOGUNIFORM, LOGNORMAL, QLOGNORMAL)),
        frac((NORMAL, QNORMAL, LOGNORMAL, QLOGNORMAL)),
        sum(1 for p in cs.params if p.q) / P,
        frac((CATEGORICAL,)) + sum(
            1 for p in cs.params
            if p.kind == RANDINT and p.probs is not None) / P,
        sum(1 for p in cs.params if p.conditions) / P,
        float(np.mean(cat_arity) / 32.0) if cat_arity else 0.0,
    ]


def _fingerprint(cs) -> str:
    """Structural fingerprint of a compiled space (stable across processes).

    Built from the compiled column specs — label, distribution kind, bounds,
    quantization, categorical probs and gating conditions — i.e. the same
    identity :func:`hyperopt_tpu.space._freeze` captures for the compile
    cache, but hashed to a short printable key suitable for a JSON store."""
    parts = []
    for p in cs.params:
        parts.append((p.label, p.kind, p.low, p.high, p.mu, p.sigma, p.q,
                      None if p.probs is None else tuple(p.probs),
                      tuple(p.conditions)))
    return hashlib.sha256(repr(parts).encode()).hexdigest()[:24]


class _TransferStore:
    """Cross-experiment arm-posterior persistence (the reference's
    pretrained-model analog, SURVEY.md §2 ``atpe.py`` + ``atpe_models/``).

    One JSON file maps space fingerprints to cumulative arm win/loss counts
    (+ the space's structural :func:`_space_features`).  A new experiment
    seeds its Thompson posteriors from the stored counts, scaled so
    borrowed evidence never exceeds ``EVIDENCE_CAP`` pseudo-trials —
    strong enough to skip the cold-start exploration, weak enough for
    fresh data to override a stale record.

    **Cross-space generalization** (round-3 verdict ask #5 — the actual
    reference capability: its pretrained models predict for *unseen*
    problems): when the exact fingerprint has no record, ``load`` seeds
    from the NEAREST stored space by feature distance — similarity
    ``exp(-L1)`` must clear ``MIN_NEIGHBOR_SIM``, the borrowed evidence is
    additionally discounted by ``NEIGHBOR_DISCOUNT * sim``, and arm counts
    are reconciled by index prefix (the portfolio's arm order is stable;
    lockout arms append at the end).

    Flushes are read-modify-write of per-experiment *deltas* with an
    atomic replace, so concurrent experiments on one machine at worst drop
    a few increments rather than corrupting the file."""

    EVIDENCE_CAP = 30.0
    MIN_NEIGHBOR_SIM = 0.5       # exp(-L1 distance) gate for borrowing
    NEIGHBOR_DISCOUNT = 0.5      # neighbor evidence is worth half exact

    def __init__(self, path):
        self.path = path
        self._lock = threading.Lock()

    @staticmethod
    def default():
        if os.environ.get("HYPEROPT_TPU_ATPE_TRANSFER", "1") == "0":
            return None
        d = os.environ.get("HYPEROPT_TPU_CACHE_DIR") or os.path.join(
            os.path.expanduser("~"), ".cache", "hyperopt_tpu")
        return _TransferStore(os.path.join(d, "atpe_transfer.json"))

    def _read(self):
        try:
            with open(self.path) as f:
                data = json.load(f)
            return data if isinstance(data, dict) else {}
        except (OSError, ValueError):
            return {}

    @staticmethod
    def _counts(rec, n_arms=None):
        """Validated (wins, losses) float arrays from a record, or None.
        ``n_arms`` enforces an exact length; None accepts any length."""
        if not isinstance(rec, dict):
            return None
        w, l = rec.get("wins", ()), rec.get("losses", ())
        if len(w) != len(l) or not len(w):
            return None
        if n_arms is not None and len(w) != n_arms:
            return None
        try:
            w = np.asarray(w, float)
            l = np.asarray(l, float)
        except (TypeError, ValueError):
            return None
        if not np.isfinite(w.sum() + l.sum()):
            return None
        return w, l

    def load(self, fp, n_arms, features=None):
        """Seed posteriors: Beta(1,1) plus capped stored evidence.

        Exact-fingerprint records seed at full ``EVIDENCE_CAP``; with no
        exact record and ``features`` given, the nearest stored space by
        feature similarity seeds at a discounted cap (see class
        docstring).  A malformed record (schema drift, hand edits)
        degrades to the flat prior rather than crashing every experiment
        on that space."""
        data = self._read()
        wins = np.ones(n_arms)
        losses = np.ones(n_arms)
        counts = self._counts(data.get(fp), n_arms)
        cap = self.EVIDENCE_CAP
        _reg = _metrics.registry()
        if counts is not None:
            _reg.counter("atpe.transfer.exact").inc()
            EVENTS.emit("transfer_borrow", name="exact", fp=fp)
        elif fp in data:
            # A record exists for this fingerprint but failed validation.
            _reg.counter("atpe.transfer.dropped").inc()
            EVENTS.emit("transfer_drop", name="malformed", fp=fp)
        if counts is None and features is not None:
            counts, sim = self._nearest(data, fp, features)
            if counts is not None:
                cap *= self.NEIGHBOR_DISCOUNT * sim
                _reg.counter("atpe.transfer.neighbor").inc()
                EVENTS.emit("transfer_borrow", name="neighbor", fp=fp,
                            sim=round(sim, 4))
        if counts is None:
            _reg.counter("atpe.transfer.cold").inc()
            return wins, losses
        w, l = counts
        m = min(n_arms, len(w))       # prefix-map an evolved portfolio
        total = float(w[:m].sum() + l[:m].sum())
        if total > 0:
            s = min(1.0, cap / total)
            wins[:m] += s * w[:m]
            losses[:m] += s * l[:m]
        return wins, losses

    def _nearest(self, data, fp, features):
        """Most similar OTHER record by feature distance, or (None, 0)."""
        feats = np.asarray(features, float)
        best, best_sim = None, 0.0
        for key, rec in data.items():
            if key == fp or not isinstance(rec, dict):
                continue
            f = rec.get("features")
            if not isinstance(f, list) or len(f) != len(feats):
                continue
            counts = self._counts(rec)
            if counts is None:
                continue
            try:
                sim = float(np.exp(-np.abs(np.asarray(f, float)
                                           - feats).sum()))
            except (TypeError, ValueError):
                continue
            if sim > best_sim:
                best, best_sim = counts, sim
        if best is None or best_sim < self.MIN_NEIGHBOR_SIM:
            return None, 0.0
        return best, best_sim

    def flush(self, fp, d_wins, d_losses, n_new_exp=0, features=None):
        """Accumulate this experiment's new outcome deltas into the store."""
        if not (d_wins.any() or d_losses.any() or n_new_exp):
            return
        with self._lock:
            try:
                data = self._read()
                rec = data.get(fp)
                n = len(d_wins)
                try:
                    if (not isinstance(rec, dict)
                            or len(rec.get("wins", ())) != n
                            or len(rec.get("losses", ())) != n):
                        raise ValueError
                    old_w = np.asarray(rec["wins"], float)
                    old_l = np.asarray(rec["losses"], float)
                    if not np.isfinite(old_w.sum() + old_l.sum()):
                        raise ValueError
                except (TypeError, ValueError):   # schema drift → restart
                    rec = {"n_experiments": 0}
                    old_w = np.zeros(n)
                    old_l = np.zeros(n)
                rec["wins"] = (old_w + d_wins).tolist()
                rec["losses"] = (old_l + d_losses).tolist()
                rec["n_experiments"] = int(rec.get("n_experiments", 0)
                                           + n_new_exp)
                if features is not None:   # enables cross-space similarity
                    rec["features"] = list(map(float, features))
                data[fp] = rec
                os.makedirs(os.path.dirname(self.path), exist_ok=True)
                tmp = f"{self.path}.tmp.{os.getpid()}"
                with open(tmp, "w") as f:
                    json.dump(data, f)
                os.replace(tmp, self.path)
                _metrics.registry().counter("atpe.transfer.flushes").inc()
                EVENTS.emit("store_flush", name="atpe_transfer", fp=fp)
            except OSError:   # cache dir unwritable → adapt in-memory only
                _metrics.registry().counter(
                    "atpe.transfer.flush_failed").inc()
                logger.debug("atpe transfer flush failed", exc_info=True)


class _BanditState:
    """Per-experiment Thompson-sampling state, attached to the Trials.

    ``store``/``fp`` wire the cross-experiment transfer memory: posteriors
    start from the store's record for this space and every settled outcome
    is flushed back as a delta."""

    # Outcomes accumulated in memory before a store flush: each flush is
    # a whole-file JSON read-modify-write (+ atomic replace), and doing
    # one per resolved trial put ~N file rewrites on the suggest path of
    # an N-trial run (measured as part of the atpe_s wall-time gap,
    # benchmarks/atpe_profile.py).  Batching trades at most
    # FLUSH_EVERY-1 un-flushed outcomes on a hard kill — the in-process
    # posterior is unaffected, and ``atexit`` drains the remainder on
    # any normal exit (EVIDENCE_CAP=30 makes the loss negligible anyway).
    FLUSH_EVERY = 8

    def __init__(self, n_arms, store=None, fp=None, features=None):
        self.store = store
        self.fp = fp
        if store is not None and fp is not None:
            self.wins, self.losses = store.load(fp, n_arms,
                                                features=features)
            store.flush(fp, np.zeros(n_arms), np.zeros(n_arms), n_new_exp=1,
                        features=features)
        else:
            self.wins = np.ones(n_arms)    # Beta(1,1) priors
            self.losses = np.ones(n_arms)
        self.pending = {}              # tid -> (arm, best_loss_at_suggest)
        self._d_wins = np.zeros(n_arms)     # un-flushed store deltas
        self._d_losses = np.zeros(n_arms)
        if store is not None and fp is not None:
            import atexit
            import weakref

            # weakref: an atexit-held strong ref would pin every Trials
            # (via _atpe_state) for the process lifetime.
            ref = weakref.ref(self)
            atexit.register(lambda: (lambda s: s and s.flush_deltas())(ref()))

    def pick(self, rng):
        return int(np.argmax(rng.beta(self.wins, self.losses)))

    def flush_deltas(self):
        """Drain accumulated outcome deltas to the transfer store."""
        if self.store is None or self.fp is None:
            return
        d_w, d_l = self._d_wins, self._d_losses
        if not (d_w.any() or d_l.any()):
            return
        self._d_wins = np.zeros(len(self.wins))
        self._d_losses = np.zeros(len(self.losses))
        self.store.flush(self.fp, d_w, d_l)

    def settle(self, trials):
        """Score resolved suggestions: did the trial beat the best loss
        recorded when it was proposed?"""
        n = len(self.wins)
        d_wins = np.zeros(n)
        d_losses = np.zeros(n)
        by_tid = {t["tid"]: t for t in trials}
        for tid in list(self.pending):
            t = by_tid.get(tid)
            if t is None or t["state"] not in (JOB_STATE_DONE,
                                               JOB_STATE_ERROR):
                continue
            arm, best_then = self.pending.pop(tid)
            r = t["result"]
            loss = r.get("loss") if r.get("status") == STATUS_OK else None
            if loss is not None and (best_then is None or loss < best_then):
                d_wins[arm] += 1.0
            else:
                d_losses[arm] += 1.0
        self.wins += d_wins
        self.losses += d_losses
        self._d_wins += d_wins
        self._d_losses += d_losses
        if self._d_wins.sum() + self._d_losses.sum() >= self.FLUSH_EVERY:
            self.flush_deltas()


def _prewarm_arms(cs, arms, st, n_trials, linear_forgetting):
    """Background-compile every arm's suggest program for the current
    history bucket — the arm analog of ``tpe._prewarm_async``'s bucket
    prewarm.

    Thompson sampling hops between arms, and each arm whose shape tuple
    (n_EI_candidates tier, linear_forgetting, split, multivariate) differs
    compiles its own XLA program; un-prewarmed, every first hop onto an
    arm stalls a suggest behind that compile.  This kicks all arms'
    single-proposal programs (ATPE suggests are per-trial) into
    ``_prewarm_async``'s daemon threads once per bucket, so hops land on
    warm programs.  Inherits that helper's guards: no-op on 1-core CPU
    hosts (the compile would fight the objective for the core), and
    per-kernel done-marks make re-walks cheap.  Best-effort throughout.
    """
    bucket = tpe._bucket(n_trials)
    if getattr(st, "_prewarmed_bucket", 0) == bucket:
        return
    st._prewarmed_bucket = bucket
    for cfg in arms:
        try:
            kern = tpe.get_kernel(
                cs, bucket, int(cfg["n_EI_candidates"]),
                int(cfg.get("linear_forgetting", linear_forgetting)),
                cfg.get("split", "sqrt"), cfg.get("multivariate", False))
            tpe._prewarm_async(kern, n=1)
        except Exception:   # pragma: no cover - purely opportunistic
            logger.debug("atpe arm prewarm failed", exc_info=True)


def _state(trials, cs, n_arms) -> _BanditState:
    st = getattr(trials, "_atpe_state", None)
    if st is None or len(st.wins) != n_arms:
        store = _TransferStore.default()
        fp = _fingerprint(cs) if store is not None else None
        feats = _space_features(cs) if store is not None else None
        st = trials._atpe_state = _BanditState(n_arms, store=store, fp=fp,
                                               features=feats)
    return st


def suggest(new_ids, domain, trials, seed,
            n_startup_jobs=tpe._default_n_startup_jobs,
            linear_forgetting=tpe._default_linear_forgetting,
            extra_algos=()):
    """Adaptive-TPE suggest (drop-in for ``hyperopt/atpe.py::suggest``).

    ``extra_algos`` widens the bandit's portfolio beyond TPE
    configurations: each entry is a backend-registry name (``"gp"``,
    ``"es"``, anything :func:`hyperopt_tpu.backends.resolve` accepts)
    added as one more arm.  The Thompson bandit then learns per problem
    whether a whole different *head* beats the TPE arms — the adaptive
    analog of ``mix.suggest``'s fixed weights.  Delegated arms skip the
    TPE-specific lockout/prewarm machinery but share the same
    improvement-reward accounting and transfer memory."""
    cs = domain.cs
    arms = _portfolio(cs)
    arms += [dict(algo=str(name)) for name in extra_algos]
    st = _state(trials, cs, len(arms))
    st.settle(trials)
    rng = np.random.default_rng(int(seed) % (2 ** 32))
    arm = st.pick(rng)
    _reg = _metrics.registry()
    _reg.counter("atpe.suggest.calls").inc()
    _reg.counter(f"atpe.arm.{arm}.picked").inc()
    cfg = dict(arms[arm])
    try:
        best = trials.best_trial["result"]["loss"]
    except Exception:
        best = None
    algo_name = cfg.pop("algo", None)
    if algo_name is not None:
        from .backends import contract as _backends

        docs = _backends.resolve(algo_name)(new_ids, domain, trials,
                                            int(seed))
        for d in docs:
            st.pending[d["tid"]] = (arm, best)
        return docs
    lockout = cfg.pop("lockout", None)
    cfg.setdefault("linear_forgetting", linear_forgetting)
    rows, acts = tpe.suggest_batch(new_ids, domain, trials, seed,
                                   n_startup_jobs=n_startup_jobs, **cfg)
    if best is not None and len(trials) >= n_startup_jobs:
        _prewarm_arms(cs, arms, st, len(trials), linear_forgetting)
    if lockout is not None and best is not None:
        h = trials.history(cs)
        if int(h["ok"].sum()) >= n_startup_jobs:
            rows, acts = _apply_lockout(cs, rows, acts, trials, h,
                                        lockout, rng)
    docs = base.docs_from_samples(cs, new_ids, np.asarray(rows),
                                  np.asarray(acts),
                                  exp_key=getattr(trials, "exp_key", None))
    for d in docs:
        st.pending[d["tid"]] = (arm, best)
    return docs


#: registry hook (hyperopt_tpu.backends.contract resolves through this)
BACKENDS = {"atpe": suggest}
