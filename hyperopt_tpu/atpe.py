"""Adaptive TPE: self-tuning TPE hyperparameters.

Reference: ``hyperopt/atpe.py`` (~1400 LoC, SURVEY.md §2) — "Adaptive TPE"
(contributed by ElectricBrain) uses **pretrained LightGBM models** + JSON
scaling parameters shipped with the package to predict, per problem, good
TPE hyperparameters (``gamma``, ``nEICandidates``, ``priorWeight``), a
**result-filtering mode** (fit the posterior on a subset of the history) and
**per-parameter lockout masks** (freeze "secondary" parameters at the
incumbent's values while the primary ones are searched).

Documented deviation: this environment has no lightgbm and no network to
fetch the reference's model files (SURVEY.md §7 environment facts), and
shipping opaque pretrained artifacts would be contrary to a from-scratch
build anyway.  The same *capabilities* are provided self-contained:

* **portfolio bandit** — a set of TPE configurations spanning the knobs the
  reference's models predict (γ value and schedule, ``n_EI_candidates``,
  ``prior_weight``, ``linear_forgetting`` as the age-filtering analog),
  seeded by problem features (dimensionality, categorical fraction — the
  reference's model inputs).  Each suggest call picks a configuration by
  Thompson sampling over its observed improvement record (Beta posterior
  per arm), so configurations that keep finding better losses get chosen
  more.
* **per-parameter lockout** (reference: secondaryLockingMode) — arms with a
  ``lockout`` fraction freeze the least *important* parameters at the
  incumbent's values and let TPE search the rest.  Importance is estimated
  online from the trial history: |Spearman correlation| with loss for
  numeric columns, between-group variance ratio (η²) for categorical ones —
  the inspectable stand-in for the reference's learned
  secondary-correlation models.
* the arm's reward is "the suggested trial improved the best-so-far loss".

This keeps ATPE's plugin signature (``atpe.suggest`` drop-in, same as the
reference) with self-contained, inspectable adaptation.
"""

from __future__ import annotations

import numpy as np

from . import base, tpe
from .base import JOB_STATE_DONE, JOB_STATE_ERROR, STATUS_OK
from .space import CATEGORICAL, RANDINT, UNIFORMINT


def _portfolio(cs):
    """TPE-configuration arms, scaled by problem features.

    Spans the reference models' output space: γ (value + schedule),
    n_EI_candidates, prior_weight, age filtering (linear_forgetting) and
    secondary-parameter lockout."""
    n_params = max(cs.n_params, 1)
    cat_frac = (sum(1 for p in cs.params if p.kind == CATEGORICAL)
                / n_params)
    # Wider spaces benefit from more EI candidates; heavily categorical
    # spaces from stronger priors (smoothing).
    base_cand = int(np.clip(24 * np.sqrt(n_params), 24, 512))
    pw = 1.0 + cat_frac
    arms = [
        dict(gamma=0.25, split="sqrt", n_EI_candidates=base_cand,
             prior_weight=pw),
        dict(gamma=0.25, split="quantile", n_EI_candidates=base_cand,
             prior_weight=pw),
        dict(gamma=0.15, split="quantile", n_EI_candidates=base_cand * 2,
             prior_weight=pw),
        dict(gamma=0.5, split="sqrt", n_EI_candidates=base_cand,
             prior_weight=2.0 * pw),   # exploratory arm
        # Age-filtering analog (reference resultFilteringMode='age'): a
        # short forgetting horizon fits the posterior on recent trials only.
        dict(gamma=0.25, split="quantile", n_EI_candidates=base_cand,
             prior_weight=pw, linear_forgetting=10),
        # Joint-vector EI (benchmarks/quality.py: wins or ties 8/9 zoo
        # domains) — the bandit learns per-problem whether it helps.
        dict(gamma=0.25, split="quantile", n_EI_candidates=max(base_cand, 128),
             prior_weight=pw, multivariate=True),
    ]
    if n_params >= 3:  # lockout is meaningless on tiny spaces
        arms += [
            # Secondary lockout (reference secondaryLockingMode): freeze the
            # low-importance half / three-quarters at the incumbent.
            dict(gamma=0.25, split="quantile", n_EI_candidates=base_cand,
                 prior_weight=pw, lockout=0.5),
            dict(gamma=0.15, split="quantile", n_EI_candidates=base_cand * 2,
                 prior_weight=pw, lockout=0.75),
        ]
    return arms


def parameter_importance(h, cs):
    """Online per-parameter importance from the trial history.

    Returns ``imp[P]`` in [0, 1]: a bias-adjusted between-group variance
    ratio (η², adjusted like R²) of the loss across value groups — discrete
    columns group by value, numeric columns by quantile bin.  Unlike a rank
    correlation this captures non-monotone (e.g. U-shaped) relations, which
    are the norm for loss-vs-hyperparameter curves.  Columns with too few
    active observations get 1.0 (unknown → never lock).

    Reference analog: atpe.py's pretrained secondary-correlation models —
    here replaced by a transparent statistic over the same signal.
    """
    ok = h["ok"]
    loss = h["loss"]
    P = cs.n_params
    imp = np.ones(P, np.float64)

    def eta2_adj(y, gid, k, n):
        tot = y.var()
        if tot <= 0 or n <= k:
            return 0.0
        within = sum(float(y[gid == g].var()) * int((gid == g).sum())
                     for g in np.unique(gid)) / n
        # adjusted for the k-groups-from-n-samples positive bias
        val = 1.0 - (within / max(n - k, 1)) / (tot / (n - 1))
        return float(np.clip(val, 0.0, 1.0))

    for spec in cs.params:
        m = h["active"][:, spec.pid] & ok
        n = int(m.sum())
        if n < 8:
            continue
        x = h["vals"][m, spec.pid].astype(np.float64)
        y = loss[m].astype(np.float64)
        uniq = np.unique(x)
        if spec.kind in (CATEGORICAL, RANDINT, UNIFORMINT) and \
                len(uniq) <= 32:
            gid = np.searchsorted(uniq, x)
            imp[spec.pid] = eta2_adj(y, gid, len(uniq), n)
        else:
            k = int(np.clip(n // 8, 2, 8))
            edges = np.quantile(x, np.linspace(0, 1, k + 1)[1:-1])
            gid = np.searchsorted(edges, x)
            imp[spec.pid] = eta2_adj(y, gid, k, n)
    return imp


def _apply_lockout(cs, rows, acts, trials, h, frac, rng):
    """Freeze the lowest-importance ``frac`` of parameters at the
    incumbent's values (reference: secondary lockout masks).  Gate
    (choice) columns may flip branches, so the activity mask is recomputed
    after substitution."""
    try:
        best_misc = trials.best_trial["misc"]
    except Exception:
        return rows, acts
    imp = parameter_importance(h, cs)
    # Only parameters the incumbent actually has values for can be locked.
    lockable = []
    for spec in cs.params:
        v = best_misc["vals"].get(spec.label, [])
        if len(v):
            lockable.append((imp[spec.pid], spec.pid, float(v[0])))
    if len(lockable) < 2:
        return rows, acts
    lockable.sort()
    n_lock = int(round(frac * len(lockable)))
    if n_lock == 0:
        return rows, acts
    rows = np.array(rows, copy=True)
    for _, pid, v in lockable[:n_lock]:
        rows[:, pid] = v
    acts = np.asarray(cs.active_mask(rows))
    return rows, acts


class _BanditState:
    """Per-experiment Thompson-sampling state, attached to the Trials."""

    def __init__(self, n_arms):
        self.wins = np.ones(n_arms)    # Beta(1,1) priors
        self.losses = np.ones(n_arms)
        self.pending = {}              # tid -> (arm, best_loss_at_suggest)

    def pick(self, rng):
        return int(np.argmax(rng.beta(self.wins, self.losses)))

    def settle(self, trials):
        """Score resolved suggestions: did the trial beat the best loss
        recorded when it was proposed?"""
        by_tid = {t["tid"]: t for t in trials}
        for tid in list(self.pending):
            t = by_tid.get(tid)
            if t is None or t["state"] not in (JOB_STATE_DONE,
                                               JOB_STATE_ERROR):
                continue
            arm, best_then = self.pending.pop(tid)
            r = t["result"]
            loss = r.get("loss") if r.get("status") == STATUS_OK else None
            if loss is not None and (best_then is None or loss < best_then):
                self.wins[arm] += 1.0
            else:
                self.losses[arm] += 1.0


def _state(trials, n_arms) -> _BanditState:
    st = getattr(trials, "_atpe_state", None)
    if st is None or len(st.wins) != n_arms:
        st = trials._atpe_state = _BanditState(n_arms)
    return st


def suggest(new_ids, domain, trials, seed,
            n_startup_jobs=tpe._default_n_startup_jobs,
            linear_forgetting=tpe._default_linear_forgetting):
    """Adaptive-TPE suggest (drop-in for ``hyperopt/atpe.py::suggest``)."""
    cs = domain.cs
    arms = _portfolio(cs)
    st = _state(trials, len(arms))
    st.settle(trials)
    rng = np.random.default_rng(int(seed) % (2 ** 32))
    arm = st.pick(rng)
    cfg = dict(arms[arm])
    lockout = cfg.pop("lockout", None)
    cfg.setdefault("linear_forgetting", linear_forgetting)
    try:
        best = trials.best_trial["result"]["loss"]
    except Exception:
        best = None
    rows, acts = tpe.suggest_batch(new_ids, domain, trials, seed,
                                   n_startup_jobs=n_startup_jobs, **cfg)
    if lockout is not None and best is not None:
        h = trials.history(cs)
        if int(h["ok"].sum()) >= n_startup_jobs:
            rows, acts = _apply_lockout(cs, rows, acts, trials, h,
                                        lockout, rng)
    docs = base.docs_from_samples(cs, new_ids, np.asarray(rows),
                                  np.asarray(acts),
                                  exp_key=getattr(trials, "exp_key", None))
    for d in docs:
        st.pending[d["tid"]] = (arm, best)
    return docs
