def suggest(new_ids, domain, trials, seed):
    raise NotImplementedError('atpe: coming next')
