"""Tracing / profiling instrumentation for the optimization loop.

The reference has no tracing subsystem (SURVEY.md §5.1 — closest: verbose
logging + tqdm postfix).  The TPU build adds the recommended equivalent:
wall-clock spans around the loop phases (suggest / evaluate / store) plus
optional XLA device traces via ``jax.profiler`` for TensorBoard.

Enable with ``fmin(..., trace_dir="/tmp/trace")`` or the
``HYPEROPT_TPU_TRACE_DIR`` environment variable.  The span summary is
written to ``<trace_dir>/loop_trace.json``; device traces (if jax.profiler
is usable) land in the same directory.

Also home to the process-global TPE kernel-cache counters
(:func:`kernel_cache_event` / :func:`kernel_cache_stats`) — compile-shape
accounting for ``tpe.get_kernel``, consumed by ``benchmarks/atpe_profile.py``.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import defaultdict
from contextlib import contextmanager
from typing import Optional

# -- kernel-cache statistics -------------------------------------------------
#
# Process-global request/miss counters for the TPE kernel cache
# (``tpe.get_kernel``).  A miss means a fresh ``_TpeKernel`` was
# constructed — i.e. a new XLA program will be traced and compiled — so
# ``misses`` is the per-process compile-shape count the ATPE arm
# canonicalization work optimizes (``benchmarks/atpe_profile.py`` reads
# these before/after to show arms collapsing onto shared shapes).
# Always on: two dict increments under a lock per suggest are noise next
# to a single device dispatch.

_CACHE_LOCK = threading.Lock()
_CACHE_STATS: dict = {"requests": 0, "misses": 0, "by_key": {}}


def kernel_cache_event(key, hit: bool) -> None:
    """Record one ``get_kernel`` lookup. ``key``: the cache-key tuple."""
    ks = repr(key)
    with _CACHE_LOCK:
        _CACHE_STATS["requests"] += 1
        per = _CACHE_STATS["by_key"].setdefault(
            ks, {"requests": 0, "misses": 0})
        per["requests"] += 1
        if not hit:
            _CACHE_STATS["misses"] += 1
            per["misses"] += 1


def kernel_cache_stats(reset: bool = False) -> dict:
    """Snapshot (and optionally reset) the process-global cache counters.

    Returns ``{"requests": int, "misses": int, "by_key": {repr(key):
    {"requests": int, "misses": int}}}``.  ``misses`` counts distinct
    kernel constructions (compile shapes); ``by_key`` lets callers
    attribute them — e.g. ``benchmarks/atpe_profile.py`` diffing arm
    shapes with tiering on vs off.
    """
    with _CACHE_LOCK:
        out = {"requests": _CACHE_STATS["requests"],
               "misses": _CACHE_STATS["misses"],
               "by_key": {k: dict(v)
                          for k, v in _CACHE_STATS["by_key"].items()}}
        if reset:
            _CACHE_STATS["requests"] = 0
            _CACHE_STATS["misses"] = 0
            _CACHE_STATS["by_key"] = {}
    return out


class Tracer:
    """Accumulates named wall-clock spans; optionally drives jax.profiler."""

    def __init__(self, trace_dir: Optional[str] = None,
                 device_trace: bool = False):
        self.trace_dir = trace_dir
        self.device_trace = device_trace and trace_dir is not None
        self.totals = defaultdict(float)
        self.counts = defaultdict(int)
        self._started = False
        if trace_dir:
            os.makedirs(trace_dir, exist_ok=True)

    # -- spans ---------------------------------------------------------------

    @contextmanager
    def span(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self.totals[name] += dt
            self.counts[name] += 1

    # -- device traces -------------------------------------------------------

    def start_device_trace(self):
        if not self.device_trace or self._started:
            return
        try:
            import jax

            jax.profiler.start_trace(self.trace_dir)
            self._started = True
        except Exception:  # profiler unavailable on this backend
            self.device_trace = False

    def stop_device_trace(self):
        if not self._started:
            return
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception:
            pass
        self._started = False

    # -- summary -------------------------------------------------------------

    def summary(self) -> dict:
        out = {}
        for name, total in sorted(self.totals.items()):
            n = self.counts[name]
            out[name] = {"total_s": round(total, 6), "count": n,
                         "mean_ms": round(1e3 * total / max(n, 1), 3)}
        return out

    def dump(self) -> Optional[str]:
        if not self.trace_dir:
            return None
        path = os.path.join(self.trace_dir, "loop_trace.json")
        with open(path, "w") as f:
            json.dump(self.summary(), f, indent=2)
        return path


class NullTracer(Tracer):
    """No-op tracer (no dir, no device traces); spans still cost ~0."""

    def __init__(self):
        super().__init__(trace_dir=None, device_trace=False)
