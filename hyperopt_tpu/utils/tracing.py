"""Back-compat shim: tracing now lives in :mod:`hyperopt_tpu.obs`.

Round 6 grew this module into the ``hyperopt_tpu/obs/`` subsystem
(structured event log + metrics registry + Tracer).  The four public
names that lived here — :class:`Tracer`, :class:`NullTracer`,
:func:`kernel_cache_event`, :func:`kernel_cache_stats` — are re-exported
unchanged so existing imports keep working; new code should import from
``hyperopt_tpu.obs`` directly.
"""

from __future__ import annotations

from ..obs.metrics import kernel_cache_event, kernel_cache_stats  # noqa: F401
from ..obs.trace import NullTracer, Tracer  # noqa: F401

__all__ = ["Tracer", "NullTracer", "kernel_cache_event", "kernel_cache_stats"]
