"""Tracing / profiling instrumentation for the optimization loop.

The reference has no tracing subsystem (SURVEY.md §5.1 — closest: verbose
logging + tqdm postfix).  The TPU build adds the recommended equivalent:
wall-clock spans around the loop phases (suggest / evaluate / store) plus
optional XLA device traces via ``jax.profiler`` for TensorBoard.

Enable with ``fmin(..., trace_dir="/tmp/trace")`` or the
``HYPEROPT_TPU_TRACE_DIR`` environment variable.  The span summary is
written to ``<trace_dir>/loop_trace.json``; device traces (if jax.profiler
is usable) land in the same directory.
"""

from __future__ import annotations

import json
import os
import time
from collections import defaultdict
from contextlib import contextmanager
from typing import Optional


class Tracer:
    """Accumulates named wall-clock spans; optionally drives jax.profiler."""

    def __init__(self, trace_dir: Optional[str] = None,
                 device_trace: bool = False):
        self.trace_dir = trace_dir
        self.device_trace = device_trace and trace_dir is not None
        self.totals = defaultdict(float)
        self.counts = defaultdict(int)
        self._started = False
        if trace_dir:
            os.makedirs(trace_dir, exist_ok=True)

    # -- spans ---------------------------------------------------------------

    @contextmanager
    def span(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self.totals[name] += dt
            self.counts[name] += 1

    # -- device traces -------------------------------------------------------

    def start_device_trace(self):
        if not self.device_trace or self._started:
            return
        try:
            import jax

            jax.profiler.start_trace(self.trace_dir)
            self._started = True
        except Exception:  # profiler unavailable on this backend
            self.device_trace = False

    def stop_device_trace(self):
        if not self._started:
            return
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception:
            pass
        self._started = False

    # -- summary -------------------------------------------------------------

    def summary(self) -> dict:
        out = {}
        for name, total in sorted(self.totals.items()):
            n = self.counts[name]
            out[name] = {"total_s": round(total, 6), "count": n,
                         "mean_ms": round(1e3 * total / max(n, 1), 3)}
        return out

    def dump(self) -> Optional[str]:
        if not self.trace_dir:
            return None
        path = os.path.join(self.trace_dir, "loop_trace.json")
        with open(path, "w") as f:
            json.dump(self.summary(), f, indent=2)
        return path


class NullTracer(Tracer):
    """No-op tracer (no dir, no device traces); spans still cost ~0."""

    def __init__(self):
        super().__init__(trace_dir=None, device_trace=False)
