"""Early-stopping policies for ``fmin(early_stop_fn=...)``.

Reference: ``hyperopt/early_stop.py::no_progress_loss`` (SURVEY.md §2 L7).
An early-stop fn has signature ``fn(trials, *args) -> (stop: bool, args)``;
the returned args are threaded into the next call.
"""

from __future__ import annotations

import numpy as np


def no_progress_loss(iteration_stop_count=20, percent_increase=0.0):
    """Stop when the best loss hasn't improved by more than
    ``percent_increase`` percent for ``iteration_stop_count`` iterations."""

    def stop_fn(trials, best_loss=None, iteration_no_progress=0):
        losses = [l for l, s in zip(trials.losses(), trials.statuses())
                  if s == "ok" and l is not None and np.isfinite(l)]
        if not losses:
            return False, [best_loss, iteration_no_progress]
        new_loss = min(losses)
        if best_loss is None:
            return False, [new_loss, 0]
        if percent_increase > 0:
            improved = new_loss < best_loss - abs(best_loss) * \
                (percent_increase / 100.0)
        else:
            improved = new_loss < best_loss
        if improved:
            return False, [new_loss, 0]
        iteration_no_progress += 1
        return (iteration_no_progress >= iteration_stop_count,
                [min(new_loss, best_loss), iteration_no_progress])

    return stop_fn
