"""Auxiliary utilities (progress, early stopping, plotting, graphviz, rdists).

Reference: ``hyperopt/early_stop.py``, ``progress.py``, ``plotting.py``,
``graphviz.py``, ``rdists.py``, ``utils.py`` (SURVEY.md §2 L7).
"""

from __future__ import annotations

import numpy as np


def fast_isin(X, X_all):
    """Boolean membership of X in X_all (reference: hyperopt/utils.py::fast_isin)."""
    return np.isin(X, X_all)


def get_most_recent_inds(obj):
    """Indices of the newest version of each tid (reference:
    hyperopt/utils.py::get_most_recent_inds — dedupe refreshed docs by
    (tid, version))."""
    data = np.rec.fromarrays(
        [np.asarray([d["tid"] for d in obj]),
         np.asarray([d.get("version", 0) for d in obj])],
        names=["tid", "version"])
    order = np.argsort(data, order=["tid", "version"])
    sorted_data = data[order]
    keep = np.ones(len(obj), dtype=bool)
    keep[:-1] = sorted_data["tid"][1:] != sorted_data["tid"][:-1]
    return order[keep]


def parameter_importance(trials, space):
    """Per-parameter importance of a finished experiment, ``{label: score}``.

    Scores are the bias-adjusted between-group variance ratio (η²) of the
    loss across value groups (quantile bins for numeric parameters) — the
    statistic ATPE's lockout arms use online (see
    :func:`hyperopt_tpu.atpe.parameter_importance`).  No reference
    equivalent (hyperopt exposes no importance API); provided because the
    question "which hyperparameters mattered?" is the first thing asked of
    a finished sweep.
    """
    from ..atpe import parameter_importance as _imp
    from ..space import compile_space

    cs = compile_space(space)
    h = trials.history(cs)
    imp = _imp(h, cs)
    return {p.label: float(imp[p.pid]) for p in cs.params}
