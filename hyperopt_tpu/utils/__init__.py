"""Auxiliary utilities (progress, early stopping, plotting, graphviz, rdists).

Reference: ``hyperopt/early_stop.py``, ``progress.py``, ``plotting.py``,
``graphviz.py``, ``rdists.py``, ``utils.py`` (SURVEY.md §2 L7).
"""

from __future__ import annotations

import numpy as np


def fast_isin(X, X_all):
    """Boolean membership of X in X_all (reference: hyperopt/utils.py::fast_isin)."""
    return np.isin(X, X_all)


def get_most_recent_inds(obj):
    """Indices of the newest version of each tid (reference:
    hyperopt/utils.py::get_most_recent_inds — dedupe refreshed docs by
    (tid, version))."""
    data = np.rec.fromarrays(
        [np.asarray([d["tid"] for d in obj]),
         np.asarray([d.get("version", 0) for d in obj])],
        names=["tid", "version"])
    order = np.argsort(data, order=["tid", "version"])
    sorted_data = data[order]
    keep = np.ones(len(obj), dtype=bool)
    keep[:-1] = sorted_data["tid"][1:] != sorted_data["tid"][:-1]
    return order[keep]
