"""Progress reporting for the fmin loop.

Reference: ``hyperopt/progress.py`` + ``std_out_err_redirect_tqdm.py``
(SURVEY.md §2 L7): a tqdm bar with ``best loss:`` postfix, and a no-op
variant.  tqdm is optional; without it progress reporting is a silent no-op.
"""

from __future__ import annotations

import contextlib
import sys

try:
    from tqdm import tqdm as _tqdm
except Exception:  # pragma: no cover - tqdm is normally present
    _tqdm = None


class _ProgressHandle:
    def update(self, n):
        raise NotImplementedError

    def postfix(self, best_loss):
        raise NotImplementedError


class _TqdmHandle(_ProgressHandle):
    def __init__(self, bar):
        self.bar = bar

    def update(self, n):
        if n > 0:
            self.bar.update(n)

    def postfix(self, best_loss):
        self.bar.set_postfix_str(f"best loss: {best_loss:.6g}")


class _NullHandle(_ProgressHandle):
    def update(self, n):
        pass

    def postfix(self, best_loss):
        pass


@contextlib.contextmanager
def default_callback(initial=0, total=None):
    """tqdm progress context (reference: progress.py::default_callback)."""
    if _tqdm is None:
        yield _NullHandle()
        return
    with _tqdm(initial=initial, total=total, file=sys.stderr,
               dynamic_ncols=True, disable=not sys.stderr.isatty()) as bar:
        yield _TqdmHandle(bar)


@contextlib.contextmanager
def no_progress_callback(initial=0, total=None):
    """Silent progress context (reference: progress.py::no_progress_callback)."""
    yield _NullHandle()
